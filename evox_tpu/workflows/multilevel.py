"""Hierarchical multi-level ES: an outer meta-ES adapting inner-ES
hyperparameters across island groups.

Design sources (PAPERS.md): "Distributed Evolution Strategies with
Multi-Level Learning" (arXiv 2310.05377) — a two-level scheme where an
outer evolutionary loop adapts the hyperparameters (step size, learning
rate, parent count) of a population of inner ES instances from their
observed progress — and Fiber (arXiv 2003.11164) — elastic worker
membership: member loss is a normal scheduling event that degrades the
pool, never a run-killing failure. The reference has no analog of either
(its Ray layer replicates identical workflows; PARITY row 58).

Structure:

- **Groups** are independent inner ES runs (island semantics: separate
  states, separate PRNG streams, no migration — diversity is the point;
  each group is judged on its OWN phase progress).
- Each **outer generation** samples one hyperparameter vector per group
  from an outer Gaussian (``theta_g = mean + sigma * eps_g`` in the
  specs' transformed space), applies it to the group's inner ES, runs
  ``inner_steps`` inner generations (a *phase*), scores each group by
  its phase improvement (best-so-far before minus after — per-phase
  credit, so a group's standing history doesn't mask a bad proposal),
  and updates the outer mean/sigma CEM-style from the elite fraction.
- **Hyperparameters** (:class:`HyperSpec`) bind two ways: ``kind="attr"``
  rebinds a (dotted) template attribute as a TRACED value — the tenant
  fleet's binding law (:func:`~evox_tpu.workflows.tenancy.
  bind_hyperparams`), so ONE compiled program serves every proposal and
  every outer generation — and ``kind="state"`` overwrites an inner
  STATE leaf at phase start (CMA-family ``sigma`` lives in state, not on
  the template). Integer hyperparameters (``mu``) adapt through their
  continuous carriers: bind the padded weight table / derived scalars as
  attrs (see GUIDE §6); a static shape change is a recompile by
  construction and deliberately unsupported inside a run.

Two inner drive modes:

- **fleet** (jittable problems): the groups ARE a
  :class:`~evox_tpu.workflows.tenancy.VectorizedWorkflow` tenant fleet —
  one fused vmapped dispatch per inner phase, (TENANT, POP) 2-D-mesh
  capable, attr hyperparams rebound by state surgery on the fleet's
  traced hyperparam leaves (no recompile). A ``ShardedES(mesh=None,
  n_shards=k)`` template gives every member the per-shard fold_in
  sampling LAW replicated (vmappable); layout comes from the fleet mesh.
- **sequential** (host/external problems, or ``fleet=False``): groups
  run one at a time through two jitted halves (`ask` / `tell`) whose
  hyperparams are jit OPERANDS — two compiles total for all groups and
  outer generations. This is the mode that composes with a true
  POP-sharded ``ShardedES(mesh=...)`` member (each group's dispatch
  spans the whole mesh — multi-host capable for jittable problems) and
  with :class:`~evox_tpu.problems.neuroevolution.process_farm.
  ProcessRolloutFarm` evaluation: a killed/hung worker re-dispatches
  inside the farm (bit-identical fitness law, PR 2), a farm degraded
  below its floor marks only the AFFECTED GROUP inactive
  (``FarmDegradedError`` caught by name — the group parks, its outer
  score is excluded, the run continues on the survivors) and the farm's
  ``admit()`` re-admission hook runs between phases so replacement
  workers rejoin — Fiber's elastic membership on our substrate.

The outer loop is a HOST boundary (like IPOP): ``step()`` is one outer
generation (phase dispatches inside), ``run()`` a Python loop over it.
Callback-free by construction (pinned in test_no_host_callbacks):
everything device-side is plain jit; all orchestration is host-side
between dispatches, so it runs on the axon backend wherever its problem
does. Multi-objective outer scoring is out of scope (single-objective
inner ES only).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.algorithm import Algorithm
from ..core.monitor import Monitor
from ..core.problem import Problem
from ..core.struct import PyTreeNode, static_field
from ..utils.common import parse_opt_direction
from .tenancy import VectorizedWorkflow, bind_hyperparams

__all__ = ["HyperSpec", "MultiLevelES", "MultiLevelState"]

# farm/pool exhaustion raised by an evaluation backend whose live
# membership fell below its floor — matched by NAME so workflows never
# import the problems package (dependency direction, CLAUDE.md)
_DEGRADED_ERRORS = ("FarmDegradedError",)


def _is_degraded(e: BaseException) -> bool:
    return any(
        c.__name__ in _DEGRADED_ERRORS for c in type(e).__mro__
    )


@dataclasses.dataclass(frozen=True)
class HyperSpec:
    """One adapted inner-ES hyperparameter.

    Args:
        name: template attribute path (``kind="attr"``; dotted paths
            reach through wrappers, e.g. ``"algorithm.noise_stdev"``
            inside a ``ShardedES``) or inner-STATE leaf name
            (``kind="state"``, e.g. the CMA family's ``sigma``).
        init: initial value (external space).
        sigma: outer mutation stdev (in the TRANSFORMED space — for
            ``transform="log"`` a value of 0.3 means ±35%-ish proposals).
        lb / ub: external-space clip bounds of every proposal.
        transform: ``"log"`` (positive scale parameters — the outer
            Gaussian lives on log-theta) or ``"linear"``.
        kind: ``"attr"`` (traced template attribute) or ``"state"``
            (inner state leaf overwritten at phase start).
    """

    name: str
    init: float
    sigma: float = 0.3
    lb: float = 1e-8
    ub: float = 1e8
    transform: str = "log"
    kind: str = "attr"

    def __post_init__(self):
        if self.transform not in ("log", "linear"):
            raise ValueError(f"unknown transform {self.transform!r}")
        if self.kind not in ("attr", "state"):
            raise ValueError(f"unknown kind {self.kind!r}")
        if not (self.lb < self.ub):
            raise ValueError(f"need lb < ub, got [{self.lb}, {self.ub}]")
        if self.transform == "log" and self.lb <= 0:
            raise ValueError("log-transformed specs need lb > 0")
        if not (self.lb <= self.init <= self.ub):
            raise ValueError(
                f"init {self.init} outside [{self.lb}, {self.ub}]"
            )

    def to_internal(self, v):
        return jnp.log(v) if self.transform == "log" else jnp.asarray(v)

    def to_external(self, z):
        v = jnp.exp(z) if self.transform == "log" else z
        return jnp.clip(v, self.lb, self.ub)


class _PhaseBest(Monitor):
    """Minimal vmappable score tracker (internal minimization
    convention): per group, the best candidate so far (reporting /
    threshold readout) and the MEAN fitness of the newest generation
    (the outer score — the mean is the low-variance progress signal;
    a best-ever readout is dominated by sampling luck and cannot rank
    hyperparameter proposals). Deliberately not a TelemetryMonitor —
    two scalars per group, present even on monitor-less inner states."""

    def hooks(self):
        return ("post_eval",)

    def init(self, key=None):
        return (
            jnp.asarray(jnp.inf, dtype=jnp.float32),  # best-so-far
            jnp.asarray(jnp.inf, dtype=jnp.float32),  # newest-gen mean
        )

    def post_eval(self, mstate, cand, fitness):
        # hooks see USER-convention fitness; fold to minimization
        best, _ = mstate
        f = fitness * self.opt_direction[0]
        return (
            jnp.minimum(best, jnp.min(f).astype(jnp.float32)),
            jnp.mean(f).astype(jnp.float32),
        )


class MultiLevelState(PyTreeNode):
    """Outer state. The small outer-distribution leaves replicate (no
    annotations — the workflow-state convention of StdWorkflowState);
    the inner states carry their own per-field annotations through."""

    generation: jax.Array  # OUTER generation counter
    outer_mean: jax.Array = None  # (H,) transformed space
    outer_sigma: jax.Array = None  # (H,)
    theta: jax.Array = None  # (G, H) live proposals
    key: jax.Array = None
    inner: Any = None  # fleet state | (G,)-stacked inner algo states
    prob: Any = None  # sequential mode: shared problem state
    best: jax.Array = None  # (G,) best-so-far (internal min convention)
    score: jax.Array = None  # (G,) newest phase-end mean fitness
    active: jax.Array = None  # (G,) bool
    first_step: bool = static_field(default=True)


class MultiLevelES:
    """Outer meta-ES over a population of inner ES groups.

    Args:
        algorithm: the inner-ES template (any single-objective
            :class:`Algorithm`; ``ShardedES``-wrapped members supported —
            see the module docstring for which mode carries the
            shard_map island). Algorithms declaring init_ask/init_tell
            are rejected in sequential mode.
        problem: shared :class:`Problem` (host problems force sequential
            mode).
        n_groups: inner group count (the outer population size).
        hyper_specs: the adapted hyperparameters (:class:`HyperSpec`).
        inner_steps: inner generations per outer generation (the phase
            length — the outer credit-assignment window).
        outer_lr: CEM interpolation rate of the outer mean/sigma toward
            the elite proposals (0 disables adaptation — with
            ``explore=False`` that is the frozen-hyperparameter control
            the convergence test baselines against).
        elite_frac: top fraction of ACTIVE groups (by phase improvement)
            recombined into the outer update.
        sigma_decay: multiplicative outer-sigma decay per outer
            generation (1.0 = none), applied after the CEM update.
        explore: sample per-group proposals around the outer mean. With
            ``False`` every group runs the mean exactly (paired with
            ``outer_lr=0`` this freezes hyperparameters entirely).
        exploit: at each phase start, restart every group's inner state
            from the BEST group's phase-end state (each group keeps its
            OWN PRNG-stream leaves, so groups stay decorrelated) — the
            outer SELECTION step of the multi-level scheme, and what
            makes phase-end scores directly comparable (same start
            state, different hyperparameters). ``False`` keeps classic
            independent islands; scores then use per-phase improvement
            credit instead of absolute level.
        opt_direction / pop_transforms: as :class:`StdWorkflow` (single
            objective only).
        mesh: fleet mode — a (TENANT, POP) mesh for the vmapped fleet;
            sequential mode — the inner workflow/ShardedES mesh is the
            algorithm's own affair (pass the mesh to ``ShardedES``).
        fleet: force the drive mode (default: fleet iff the problem is
            jittable).
        admit_every: call the problem's ``admit()`` re-admission hook (if
            it has one) every N phases (sequential mode; 0 disables).
    """

    def __init__(
        self,
        algorithm: Algorithm,
        problem: Problem,
        n_groups: int,
        hyper_specs: Sequence[HyperSpec],
        inner_steps: int = 10,
        outer_lr: float = 0.5,
        elite_frac: float = 0.5,
        sigma_decay: float = 1.0,
        explore: bool = True,
        exploit: bool = True,
        opt_direction: Any = "min",
        pop_transforms: Sequence[Callable] = (),
        mesh: Optional[jax.sharding.Mesh] = None,
        fleet: Optional[bool] = None,
        admit_every: int = 1,
        jit_step: bool = True,
    ):
        if n_groups < 2:
            raise ValueError(f"need >= 2 groups, got {n_groups}")
        if not hyper_specs:
            raise ValueError("need at least one HyperSpec")
        if inner_steps < 1:
            raise ValueError("inner_steps must be >= 1")
        if not (0.0 <= outer_lr <= 1.0):
            raise ValueError("outer_lr must be in [0, 1]")
        if not (0.0 < elite_frac <= 1.0):
            raise ValueError("elite_frac must be in (0, 1]")
        names = [s.name for s in hyper_specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate hyperparameter names: {names}")
        self.algorithm = algorithm
        self.problem = problem
        self.n_groups = int(n_groups)
        self.specs = tuple(hyper_specs)
        self.inner_steps = int(inner_steps)
        self.outer_lr = float(outer_lr)
        self.elite_frac = float(elite_frac)
        self.sigma_decay = float(sigma_decay)
        self.explore = bool(explore)
        self.exploit = bool(exploit)
        self.opt_direction = parse_opt_direction(opt_direction)
        self.pop_transforms = tuple(pop_transforms)
        self.mesh = mesh
        self.admit_every = int(admit_every)
        self.jit_step = jit_step
        self.fleet_mode = bool(
            problem.jittable if fleet is None else fleet
        )
        if self.fleet_mode and not problem.jittable:
            raise ValueError(
                "fleet mode needs a jittable problem (a host callback "
                "cannot run under vmap); pass fleet=False for the "
                "sequential drive"
            )
        self._attr_specs = tuple(s for s in self.specs if s.kind == "attr")
        self._state_specs = tuple(s for s in self.specs if s.kind == "state")
        # validate attr paths against the template once (the fleet's rule)
        for s in self._attr_specs:
            obj = algorithm
            for part in s.name.split("."):
                if not hasattr(obj, part):
                    raise ValueError(
                        f"HyperSpec[{s.name!r}]: template "
                        f"{type(obj).__name__} has no attribute {part!r}"
                    )
                obj = getattr(obj, part)
        # membership / adaptation event log (host-side observability;
        # surfaced through report())
        self.events: list = []
        if self.fleet_mode:
            self._score_mon = _PhaseBest()
            self._fleet = VectorizedWorkflow(
                algorithm,
                problem,
                n_tenants=self.n_groups,
                hyperparams={
                    s.name: jnp.full(
                        (self.n_groups,), s.init, dtype=jnp.float32
                    )
                    for s in self._attr_specs
                },
                monitors=[self._score_mon],
                opt_direction=opt_direction,
                pop_transforms=pop_transforms,
                mesh=mesh,
                jit_step=jit_step,
            )
        else:
            if getattr(algorithm, "has_init_ask", False) or getattr(
                algorithm, "has_init_tell", False
            ):
                raise ValueError(
                    "sequential multi-level drive supports steady-state "
                    "ask/tell algorithms only (the ES family); "
                    f"{type(algorithm).__name__} declares init hooks"
                )
            self._fleet = None
            # two jitted halves with the hyperparams as TRACED operands:
            # two compiles serve every group and every outer generation
            self._seq_ask = (
                jax.jit(self._seq_ask_impl) if jit_step
                else self._seq_ask_impl
            )
            self._seq_tell = (
                jax.jit(self._seq_tell_impl) if jit_step
                else self._seq_tell_impl
            )

    # ------------------------------------------------------------- internals
    def _seq_ask_impl(self, astate: Any, hp: Dict[str, jax.Array]):
        algo = bind_hyperparams(self.algorithm, hp)
        pop, astate = algo.ask(astate)
        cand = pop
        for t in self.pop_transforms:
            cand = t(cand)
        return cand, astate

    def _seq_tell_impl(
        self, astate: Any, hp: Dict[str, jax.Array], fitness: jax.Array
    ):
        algo = bind_hyperparams(self.algorithm, hp)
        return algo.tell(astate, fitness * self.opt_direction[0])

    def _theta_to_values(self, theta: jax.Array) -> Dict[str, jax.Array]:
        """(G, H) internal proposals -> {name: (G,) external values}."""
        return {
            s.name: s.to_external(theta[:, i])
            for i, s in enumerate(self.specs)
        }

    def hyper_values(self, state: MultiLevelState) -> Dict[str, np.ndarray]:
        """The CURRENT per-group hyperparameter values (external space,
        host numpy) — what each group's inner ES is actually running."""
        return {
            k: np.asarray(jax.device_get(v))
            for k, v in self._theta_to_values(state.theta).items()
        }

    def _apply_values(
        self, state: MultiLevelState, values: Dict[str, jax.Array]
    ) -> MultiLevelState:
        """Install proposals into the inner states: attr specs rebind the
        TRACED hyperparam leaves (fleet) / are handed to the jitted
        halves (sequential); state specs overwrite the (G,)-stacked
        inner-state leaf."""
        inner = state.inner
        if self.fleet_mode and self._attr_specs:
            hp = dict(inner.tenants.hyperparams)
            for s in self._attr_specs:
                hp[s.name] = values[s.name].astype(hp[s.name].dtype)
            inner = inner.replace(tenants=inner.tenants.replace(hyperparams=hp))
        algo_states = inner.tenants.algo if self.fleet_mode else inner
        if self._state_specs:
            updates = {}
            for s in self._state_specs:
                leaf = getattr(algo_states, s.name)
                updates[s.name] = jnp.broadcast_to(
                    values[s.name].astype(leaf.dtype).reshape(
                        (self.n_groups,) + (1,) * (leaf.ndim - 1)
                    ),
                    leaf.shape,
                )
            algo_states = algo_states.replace(**updates)
            if self.fleet_mode:
                inner = inner.replace(
                    tenants=inner.tenants.replace(algo=algo_states)
                )
            else:
                inner = algo_states
        return state.replace(inner=inner)

    # ------------------------------------------------------------------ init
    def init(self, key: jax.Array) -> MultiLevelState:
        k_outer, k_inner = jax.random.split(key)
        mean = jnp.stack(
            [s.to_internal(jnp.asarray(s.init, jnp.float32)) for s in self.specs]
        ).astype(jnp.float32)
        sigma = jnp.asarray([s.sigma for s in self.specs], dtype=jnp.float32)
        theta = jnp.tile(mean, (self.n_groups, 1))
        if self.fleet_mode:
            inner = self._fleet.init(k_inner)
            prob = None
        else:
            gkeys = jax.random.split(k_inner, self.n_groups + 1)
            inner = jax.vmap(self.algorithm.init)(gkeys[: self.n_groups])
            prob = self.problem.init(gkeys[-1])
        state = MultiLevelState(
            generation=jnp.zeros((), jnp.int32),
            outer_mean=mean,
            outer_sigma=sigma,
            theta=theta,
            key=k_outer,
            inner=inner,
            prob=prob,
            best=jnp.full((self.n_groups,), jnp.inf, dtype=jnp.float32),
            score=jnp.full((self.n_groups,), jnp.inf, dtype=jnp.float32),
            active=jnp.ones((self.n_groups,), dtype=bool),
            first_step=True,
        )
        # the init proposals ARE the means — install them so group state
        # (CMA sigma etc.) starts where the outer distribution says
        return self._apply_values(
            state, self._theta_to_values(theta)
        )

    # ------------------------------------------------------------------ step
    def step(self, state: MultiLevelState) -> MultiLevelState:
        """ONE outer generation: exploit (restart groups from the best
        group's state) → sample proposals → install → run one inner
        phase → score (phase-end mean fitness) → CEM outer update.
        Host-driven between inner dispatches (the IPOP discipline)."""
        if self.exploit and not state.first_step:
            state = self._exploit_best(state)
        key, k_eps = jax.random.split(state.key)
        if self.explore:
            eps = jax.random.normal(
                k_eps, (self.n_groups, len(self.specs)), dtype=jnp.float32
            )
            theta = state.outer_mean + state.outer_sigma * eps
        else:
            theta = jnp.tile(state.outer_mean, (self.n_groups, 1))
        state = self._apply_values(
            state.replace(theta=theta, key=key),
            self._theta_to_values(theta),
        )
        score_before = state.score
        state = self._run_phase(state)
        if self.exploit:
            # groups started this phase from the SAME state: the
            # phase-end mean fitness ranks the proposals directly
            gain = -state.score
        else:
            # independent islands: per-phase improvement credit, so a
            # group's standing history doesn't mask a bad proposal
            gain = jnp.where(
                jnp.isinf(score_before),
                -state.score,
                score_before - state.score,
            )
        gain = jnp.nan_to_num(gain, nan=0.0, posinf=0.0, neginf=0.0)
        state = self._outer_update(state, gain)
        return state.replace(
            generation=state.generation + 1, first_step=False
        )

    def _exploit_best(self, state: MultiLevelState) -> MultiLevelState:
        """Restart every group's inner ALGORITHM state from the current
        best-scoring active group's, preserving each group's own PRNG
        leaves (any leaf whose field name ends in ``key`` — the OpenES
        ``key``/``noise_key`` convention) so group streams stay
        decorrelated. Hyperparam/monitor/problem leaves are untouched."""
        score = np.asarray(jax.device_get(state.score))
        active = np.asarray(jax.device_get(state.active))
        score = np.where(active, score, np.inf)
        if not np.isfinite(score).any():
            return state
        best_g = int(np.argmin(score))

        def pick(path, x):
            if any(
                str(getattr(k, "name", "")).endswith("key") for k in path
            ):
                return x
            if getattr(x, "ndim", 0) >= 1 and x.shape[0] == self.n_groups:
                return jnp.broadcast_to(x[best_g], x.shape)
            return x

        algo_states = (
            state.inner.tenants.algo if self.fleet_mode else state.inner
        )
        algo_states = jax.tree_util.tree_map_with_path(pick, algo_states)
        if self.fleet_mode:
            inner = state.inner.replace(
                tenants=state.inner.tenants.replace(algo=algo_states)
            )
        else:
            inner = algo_states
        return state.replace(inner=inner)

    def run(self, state: MultiLevelState, n_outer: int) -> MultiLevelState:
        for _ in range(int(n_outer)):
            state = self.step(state)
        return state

    # ----------------------------------------------------------- inner phase
    def _run_phase(self, state: MultiLevelState) -> MultiLevelState:
        if self.fleet_mode:
            inner = self._fleet.run(state.inner, self.inner_steps)
            tracker_best, tracker_mean = inner.tenants.monitors[0]
            best = jnp.where(
                state.active,
                jnp.minimum(state.best, tracker_best.astype(jnp.float32)),
                state.best,
            )
            score = jnp.where(
                state.active, tracker_mean.astype(jnp.float32), state.score
            )
            return state.replace(inner=inner, best=best, score=score)
        return self._run_phase_sequential(state)

    def _run_phase_sequential(self, state: MultiLevelState) -> MultiLevelState:
        values = self._theta_to_values(state.theta)
        active = np.asarray(jax.device_get(state.active)).copy()
        best = np.asarray(jax.device_get(state.best)).copy()
        score = np.asarray(jax.device_get(state.score)).copy()
        inner = state.inner
        pstate = state.prob
        phase_idx = int(state.generation)
        if (
            self.admit_every
            and phase_idx % self.admit_every == 0
            and hasattr(self.problem, "admit")
        ):
            admitted = self.problem.admit()
            if admitted:
                self.events.append(
                    {"event": "admit", "phase": phase_idx, "workers": admitted}
                )
        for g in range(self.n_groups):
            if not active[g]:
                continue
            hp_g = {
                s.name: values[s.name][g] for s in self._attr_specs
            }
            astate = jax.tree.map(lambda x: x[g], inner)
            try:
                for _ in range(self.inner_steps):
                    cand, astate = self._seq_ask(astate, hp_g)
                    fitness, pstate = self.problem.evaluate(pstate, cand)
                    f_int = np.asarray(
                        jax.device_get(fitness), dtype=np.float32
                    ) * float(self.opt_direction[0])
                    best[g] = min(best[g], float(f_int.min()))
                    score[g] = float(f_int.mean())
                    astate = self._seq_tell(astate, hp_g, fitness)
            except Exception as e:
                if not _is_degraded(e):
                    raise
                # elastic membership: the evaluation pool fell below its
                # floor mid-phase — THIS group parks (its partial phase
                # is discarded from the outer score), the run continues
                # on the remaining groups; a later admit() can only help
                # future phases, the parked group stays parked (its inner
                # state is no longer comparable to its proposal)
                active[g] = False
                self.events.append(
                    {
                        "event": "group_lost",
                        "phase": phase_idx,
                        "group": g,
                        "error": f"{type(e).__name__}: {e}",
                    }
                )
                continue
            inner = jax.tree.map(
                lambda full, new, _g=g: full.at[_g].set(new)
                if hasattr(full, "at")
                else full,
                inner,
                astate,
            )
        if not active.any():
            raise RuntimeError(
                "multi-level ES: every group lost its evaluation backend "
                f"(events: {self.events[-self.n_groups:]})"
            )
        return state.replace(
            inner=inner,
            prob=pstate,
            best=jnp.asarray(best, dtype=jnp.float32),
            score=jnp.asarray(score, dtype=jnp.float32),
            active=jnp.asarray(active, dtype=bool),
        )

    # ---------------------------------------------------------- outer update
    def _outer_update(
        self, state: MultiLevelState, gain: jax.Array
    ) -> MultiLevelState:
        if self.outer_lr == 0.0:
            return state
        active = np.asarray(jax.device_get(state.active))
        n_active = int(active.sum())
        if n_active < 2:
            return state  # nothing to rank against
        k = max(1, int(round(self.elite_frac * n_active)))
        g = np.asarray(jax.device_get(gain))
        g = np.where(active, g, -np.inf)  # parked groups never elite
        elite = np.argsort(-g)[:k]
        theta = np.asarray(jax.device_get(state.theta))
        elite_theta = theta[elite]
        lr = self.outer_lr
        mean = (1 - lr) * np.asarray(
            jax.device_get(state.outer_mean)
        ) + lr * elite_theta.mean(axis=0)
        # the outer sigma stays FIXED (modulo the explicit decay knob):
        # a CEM-style shrink toward the elite std collapses exploration
        # within a few outer generations whenever the elites cluster
        # (measured: adaptation froze mid-run with best-so-far pinned at
        # an early lucky draw), and a frozen outer sigma is exactly the
        # (1, λ)-ES-with-fixed-step outer loop of the multi-level paper
        sigma = np.maximum(
            np.asarray(jax.device_get(state.outer_sigma))
            * self.sigma_decay,
            1e-4,
        )
        return state.replace(
            outer_mean=jnp.asarray(mean, jnp.float32),
            outer_sigma=jnp.asarray(sigma, jnp.float32),
        )

    # --------------------------------------------------------------- readout
    def best_fitness(self, state: MultiLevelState) -> Tuple[Any, Any]:
        """(per-group best-so-far, overall best) in the USER convention."""
        sign = float(self.opt_direction[0])
        per_group = np.asarray(jax.device_get(state.best)) * sign
        overall = (
            per_group.min() if sign > 0 else per_group.max()
        )
        return per_group, float(overall)

    def report(self, state: Optional[MultiLevelState] = None) -> dict:
        """Host-side observability: outer distribution, per-group scores,
        membership events (run_report picks this up via ``extra=``)."""
        out = {
            "mode": "fleet" if self.fleet_mode else "sequential",
            "n_groups": self.n_groups,
            "inner_steps": self.inner_steps,
            "hyperparams": [s.name for s in self.specs],
            "events": list(self.events),
        }
        if state is not None:
            per_group, overall = self.best_fitness(state)
            out.update(
                {
                    "outer_generation": int(state.generation),
                    "active_groups": int(
                        np.asarray(jax.device_get(state.active)).sum()
                    ),
                    "best_per_group": per_group.tolist(),
                    "best_overall": overall,
                    "outer_mean_external": {
                        s.name: float(
                            s.to_external(state.outer_mean[i])
                        )
                        for i, s in enumerate(self.specs)
                    },
                }
            )
        return out
