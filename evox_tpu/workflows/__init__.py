from .std import StdWorkflow, StdWorkflowState
from .islands import IslandWorkflow, IslandWorkflowState
from .pipelined import run_host_pipelined

__all__ = [
    "StdWorkflow",
    "StdWorkflowState",
    "IslandWorkflow",
    "IslandWorkflowState",
    "run_host_pipelined",
]
