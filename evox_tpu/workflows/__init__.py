from .std import StdWorkflow, StdWorkflowState
from .islands import IslandWorkflow, IslandWorkflowState

__all__ = [
    "StdWorkflow",
    "StdWorkflowState",
    "IslandWorkflow",
    "IslandWorkflowState",
]
