from .checkpoint import WorkflowCheckpointer
from .std import StdWorkflow, StdWorkflowState
from .islands import IslandWorkflow, IslandWorkflowState
from .pipelined import run_host_pipelined

__all__ = [
    "StdWorkflow",
    "StdWorkflowState",
    "IslandWorkflow",
    "IslandWorkflowState",
    "WorkflowCheckpointer",
    "run_host_pipelined",
]
