from .checkpoint import (
    CheckpointConfigError,
    WorkflowCheckpointer,
    restore_layouts,
)
from .std import StdWorkflow, StdWorkflowState
from .surrogate import SurrogateWorkflow, SurrogateWorkflowState
from .islands import IslandWorkflow, IslandWorkflowState
from .pipelined import run_host_pipelined
from .journal import JournalIntegrityError, RunJournal
from .flightrec import (
    FlightRecorder,
    MetricsStream,
    merge_pod_streams,
    read_stream,
)
from .fleet_health import FleetHealthPolicy, fleet_health_signals
from .tenancy import (
    RunQueue,
    TenantSpec,
    VectorizedWorkflow,
    VectorizedWorkflowState,
    bind_hyperparams,
)
from .multilevel import HyperSpec, MultiLevelES, MultiLevelState
from .elastic import (
    BucketError,
    BucketShape,
    BucketTable,
    ElasticServer,
    ElasticSpec,
    ElasticWorkflow,
    PopAutoscaler,
    warm_fleet_cache,
)
from .supervisor import (
    DispatchDeadlineError,
    RunAbortedError,
    RunSupervisor,
    classify_error,
)
from .control_plane import (
    ControlLedger,
    ControlPlane,
    PodAutoscaler,
)

__all__ = [
    "StdWorkflow",
    "StdWorkflowState",
    "SurrogateWorkflow",
    "SurrogateWorkflowState",
    "IslandWorkflow",
    "IslandWorkflowState",
    "VectorizedWorkflow",
    "VectorizedWorkflowState",
    "bind_hyperparams",
    "HyperSpec",
    "MultiLevelES",
    "MultiLevelState",
    "RunQueue",
    "TenantSpec",
    "BucketError",
    "BucketShape",
    "BucketTable",
    "ElasticServer",
    "ElasticSpec",
    "ElasticWorkflow",
    "PopAutoscaler",
    "warm_fleet_cache",
    "WorkflowCheckpointer",
    "CheckpointConfigError",
    "restore_layouts",
    "RunJournal",
    "JournalIntegrityError",
    "FlightRecorder",
    "MetricsStream",
    "merge_pod_streams",
    "read_stream",
    "FleetHealthPolicy",
    "fleet_health_signals",
    "run_host_pipelined",
    "RunSupervisor",
    "RunAbortedError",
    "DispatchDeadlineError",
    "classify_error",
    "ControlLedger",
    "ControlPlane",
    "PodAutoscaler",
]
