from .std import StdWorkflow, StdWorkflowState

__all__ = ["StdWorkflow", "StdWorkflowState"]
