"""StdWorkflow — the single-program, mesh-native orchestration loop.

Capability parity with the reference's ``StdWorkflow`` (reference:
src/evox/workflows/std_workflow.py) **and** its ``RayDistributedWorkflow``
(reference: src/evox/workflows/distributed.py), redesigned for TPU:

- The whole ask → evaluate → tell generation is ONE jitted function over a
  global ``jax.sharding.Mesh``. No pmap, no per-rank slicing, no Ray RPC.
- The candidate population is constrained to a ``NamedSharding`` over the
  ``"pop"`` mesh axis before evaluation; GSPMD partitions the (vmapped)
  evaluation across all devices and inserts the fitness all-gather over ICI
  where the algorithm's ``tell`` consumes it globally — this replaces the
  reference's ``lax.dynamic_slice_in_dim`` + ``lax.all_gather`` pmap dance
  (std_workflow.py:160,189-200) and the entire Ray object-store path.
- Multi-host: initialize ``jax.distributed`` (core/distributed.py), build the
  mesh over all pod devices, run the same program — collectives ride
  ICI within a slice, DCN across slices.
- Host-side (non-jittable) problems run through ``jax.pure_callback`` with a
  declared fitness shape, same contract as the reference's
  ``external_problem=True`` (std_workflow.py:146-158).
- Monitors follow the reference's 8-hook spec but their state is an
  on-device pytree threaded through the step (core/monitor.py).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.algorithm import Algorithm
from ..core.monitor import Monitor
from ..core.problem import Problem
from ..core.struct import PyTreeNode, static_field, field
from ..core.distributed import (
    POP_AXIS as _POP_AXIS_NAME,
    all_gather,
    shard_pop,
)
from ..core.dtype_policy import DtypePolicy, apply_compute, apply_storage
from ..utils.common import parse_opt_direction
from .checkpoint import (
    WorkflowCheckpointer,
    checkpointed_run,
    enter_run,
)
from .common import (
    build_hook_table,
    callback_evaluate,
    finish_step,
    fused_run,
    ingest_fitness,
    make_run_loop,
    quarantine_nonfinite,
    run_hooks,
)


class StdWorkflowState(PyTreeNode):
    generation: jax.Array
    algo: Any
    prob: Any
    monitors: Tuple[Any, ...]
    first_step: bool = static_field(default=True)


class StdWorkflow:
    """Compose algorithm + problem + monitors into a jitted, sharded step.

    Args:
        algorithm: an :class:`~evox_tpu.core.Algorithm`.
        problem: a :class:`~evox_tpu.core.Problem`.
        monitors: monitors implementing the 8-hook spec.
        opt_direction: ``"min"`` / ``"max"`` or a per-objective list; fitness
            is multiplied by the resulting ±1 vector before ``tell`` so
            algorithms always minimize.
        pop_transforms: applied to candidates before evaluation (e.g.
            ``TreeAndVector.batched_to_tree`` for neuroevolution).
        fit_transforms: applied to the sign-flipped fitness before ``tell``
            (e.g. ``rank_based_fitness``).
        mesh: a ``jax.sharding.Mesh`` with a ``"pop"`` axis. When given, the
            candidate batch and fitness are sharded over it.
        external_problem: force the ``pure_callback`` evaluation path;
            defaults to ``not problem.jittable``.
        num_objectives: fitness arity used to declare callback output shapes.
        jit_step: disable to debug eagerly.
        migrate_helper: optional jittable callable ``() -> (do_migrate,
            foreign_pop, foreign_fitness)`` polled once per generation; when
            ``do_migrate`` is True the algorithm's ``migrate(state, pop,
            fitness) -> state`` ingests the foreign individuals under a
            ``lax.cond`` (the reference's human-in-the-loop migration slot,
            std_workflow.py:230-244). For live injection the helper should
            pull data through ``io_callback``/``pure_callback`` internally —
            a plain closure is traced once and its values baked into the
            compiled step.
        eval_shard_map: evaluate inside an explicit ``jax.shard_map`` island
            — each device scores only its population shard, then the fitness
            is ``all_gather``-ed (tiled) over ICI. Semantically identical to
            the default GSPMD-constraint path (asserted in tests) but the
            collective is explicit; useful when XLA's auto-partitioning of an
            exotic ``evaluate`` is poor. Requires a mesh, a jittable problem
            and a problem state that is replicated-safe (stateless or pure).
        allow_uneven_shards: with a mesh, a population not divisible by the
            ``"pop"`` axis size normally raises at construction (uneven GSPMD
            layouts silently unbalance devices; the reference hard-errors
            too, std_workflow.py:189-193). Set True to accept the uneven
            layout anyway (GSPMD pads internally; shard_map mode still
            requires divisibility).
        quarantine_nonfinite: replace NaN/±Inf fitness entries with the
            worst FINITE value of their generation (per objective) after
            the sign flip and before ``fit_transforms``/``tell`` — a
            poison candidate then loses cleanly instead of corrupting
            argmin/ranking (NaN poisons every comparison-based selection).
            Monitors' ``post_eval`` (including TelemetryMonitor's NaN/Inf
            counters) still observe the RAW fitness, so quarantined
            candidates remain visible in telemetry.
        dtype_policy: an optional :class:`~evox_tpu.core.dtype_policy.
            DtypePolicy` (e.g. ``BF16_STORAGE``). ``field(storage=True)``-
            annotated float leaves of the state are held in the policy's
            storage dtype between generations (halving the memory-bound
            legs' loop-carry HBM traffic) and upcast to the compute dtype
            at step entry, so every reduction/mean/covariance update runs
            full-precision. ``None`` (default) is bit-identical to the
            pre-policy behavior. Checkpoints snapshot the storage-dtype
            leaves; resume with the same policy (the config-fingerprint
            guard records leaf dtypes and refuses cross-policy restores).
        donate_carries: donate the fused ``run`` loop's state carry and
            the pipelined ``tell``'s ask-context (``jax.jit``
            ``donate_argnums``), eliminating the per-dispatch state copy —
            donation shows up as ``alias_bytes`` in the roofline report's
            memory analysis. Caller-visible semantics are preserved:
            ``run()`` advances caller-owned states one non-donating
            ``step`` first and only donates its own intermediates, and
            checkpoint snapshots are always taken from never-donated
            states (snapshot-before-donate). Sharp edges, and why the
            default is False: (a) ``pipeline_ask``'s returned ctx is
            consumed-and-invalidated by ``pipeline_tell`` — don't reuse a
            ctx across tells (``run_host_pipelined`` never does); (b)
            donation changes XLA's fusion clustering inside the run loop,
            which perturbs float results at the last ulp (measured: CSO
            loser rows differ by 1 ulp on the CPU backend) — so the
            default stays off to keep the fused run bit-identical to a
            ``step`` loop (the repo's equivalence laws), and donation is
            the explicit perf knob the bench legs turn on.
    """

    def __init__(
        self,
        algorithm: Algorithm,
        problem: Problem,
        monitors: Sequence[Monitor] = (),
        opt_direction: Any = "min",
        pop_transforms: Sequence[Callable] = (),
        fit_transforms: Sequence[Callable] = (),
        mesh: Optional[jax.sharding.Mesh] = None,
        external_problem: Optional[bool] = None,
        num_objectives: int = 1,
        jit_step: bool = True,
        eval_shard_map: bool = False,
        allow_uneven_shards: bool = False,
        migrate_helper: Optional[Callable] = None,
        quarantine_nonfinite: bool = False,
        dtype_policy: Optional[DtypePolicy] = None,
        donate_carries: bool = False,
    ):
        self.algorithm = algorithm
        self.problem = problem
        self.monitors = tuple(monitors)
        self.opt_direction = parse_opt_direction(opt_direction)
        self.pop_transforms = tuple(pop_transforms)
        self.fit_transforms = tuple(fit_transforms)
        self.mesh = mesh
        self.num_objectives = num_objectives
        self.external = (not problem.jittable) if external_problem is None else external_problem
        self.eval_shard_map = eval_shard_map
        self.migrate_helper = migrate_helper
        self.quarantine_nonfinite = quarantine_nonfinite
        self.dtype_policy = dtype_policy
        self.donate_carries = bool(donate_carries) and jit_step
        # migration stores raw (sign-flipped) fitness into the algorithm
        # state; population-relative shaped fitness cannot coexist with it
        # (the stored conventions would mix) — see Algorithm.migrate
        if migrate_helper is not None and fit_transforms:
            raise ValueError(
                "migrate_helper cannot be combined with fit_transforms: "
                "migrants carry raw fitness while tell stores shaped values"
            )
        if eval_shard_map and (mesh is None or self.external):
            raise ValueError(
                "eval_shard_map requires a mesh and a jittable problem"
            )
        from ..core.distributed import mesh_spans_processes

        if self.external and mesh_spans_processes(mesh):
            # explicit refusal, not silent corruption: under a mesh that
            # SPANS processes, the pure_callback would run problem.evaluate
            # on EVERY process against its own population shard and an
            # unsynchronized host-side problem object (reference's Ray path
            # existed precisely to own this; SURVEY §7 "host callbacks").
            # A mesh-less workflow — or a process-LOCAL mesh in a
            # multi-process run — stays legal multi-controller JAX: each
            # process owns its whole population locally.
            raise ValueError(
                "external (host) problems are single-process: under "
                "multi-process SPMD each process would invoke the host "
                "evaluate on its own shard against unsynchronized host "
                "state. Scale host rollouts across machines with "
                "ProcessRolloutFarm (problems/neuroevolution/"
                "process_farm.py), or use a jittable problem for mesh "
                "parallelism."
            )
        if mesh is not None:
            n_shards = mesh.shape[_POP_AXIS_NAME]
            pop_size = getattr(algorithm, "pop_size", None)
            if pop_size is not None and pop_size % n_shards != 0:
                if eval_shard_map or not allow_uneven_shards:
                    raise ValueError(
                        f"pop_size {pop_size} is not divisible by the mesh's "
                        f"'pop' axis ({n_shards} shards); pad the population, "
                        "resize the mesh, or pass allow_uneven_shards=True "
                        "to accept an unbalanced GSPMD layout"
                    )
        # everything but the algorithm, for clone_with_algorithm (the IPOP
        # driver rebuilds the workflow around a grown population). Built
        # from the NORMALIZED attributes, not the raw arguments: a caller's
        # one-shot iterable (monitors=iter([...])) is already exhausted by
        # the tuple() above and would silently clone to an empty sequence
        self._ctor_args = dict(
            problem=self.problem,
            monitors=self.monitors,
            opt_direction=opt_direction,
            pop_transforms=self.pop_transforms,
            fit_transforms=self.fit_transforms,
            mesh=self.mesh,
            external_problem=self.external,
            num_objectives=self.num_objectives,
            jit_step=jit_step,
            eval_shard_map=self.eval_shard_map,
            allow_uneven_shards=allow_uneven_shards,
            migrate_helper=self.migrate_helper,
            quarantine_nonfinite=self.quarantine_nonfinite,
            dtype_policy=self.dtype_policy,
            donate_carries=donate_carries,
        )
        for m in self.monitors:
            m.set_opt_direction(self.opt_direction)
        self._hook_table = build_hook_table(self.monitors)
        self.jit_step = jit_step
        self._step = jax.jit(self._step_impl) if jit_step else self._step_impl
        # dynamic trip count: ONE compile covers every n_steps; the carry
        # is donated (fused_run only feeds it internally-produced states)
        self._run_loop = make_run_loop(self._step_impl, donate=self.donate_carries)
        # jitted step halves for the host-overlap driver (pipelined.py);
        # tell consumes-and-invalidates ask's ctx (argnum 1) when donating
        self._p_ask = jax.jit(self._pipeline_ask_impl) if jit_step else self._pipeline_ask_impl
        self._p_tell = (
            jax.jit(
                self._pipeline_tell_impl,
                donate_argnums=(1,) if self.donate_carries else (),
            )
            if jit_step
            else self._pipeline_tell_impl
        )

    def clone_with_algorithm(self, algorithm: Algorithm) -> "StdWorkflow":
        """A new workflow identical to this one but driving ``algorithm``
        (shared problem/monitor OBJECTS, fresh compiled closures) — the
        host-boundary rebuild point for IPOP population growth
        (workflows/ipop.py)."""
        return StdWorkflow(algorithm, **self._ctor_args)

    def analysis_targets(self, state: "StdWorkflowState") -> dict:
        """Entry-point programs for AOT cost/memory analysis
        (core/xla_cost.py): ``{name: (jitted_callable, example_args)}``,
        the exact compiled programs the workflow dispatches.

        The steady state (``first_step=False``) is analyzed — that is
        what every generation after the init peel runs, and what the
        fused ``run`` loop carries. ``run``'s trip count is a traced
        operand and XLA's cost analysis counts a dynamic-trip-count loop
        body once, so its static FLOPs/bytes are PER GENERATION. For
        external (host) problems the jitted step embeds a
        ``pure_callback`` — untraceable on the axon backend — so the
        pipelined halves (what ``run_host_pipelined`` actually
        dispatches) are analyzed instead; the host ``evaluate`` between
        them is outside XLA and outside this analysis by construction.
        """
        if not self.jit_step:
            return {}
        steady = state.replace(first_step=False) if state.first_step else state
        if self.external:
            cand_sds, ctx_sds = jax.eval_shape(self._p_ask, steady)
            pop = jax.tree.leaves(cand_sds)[0].shape[0]
            if self.num_objectives > 1:
                fit_shape: Tuple[int, ...] = (pop, self.num_objectives)
            else:
                fit_shape = self.problem.fit_shape(pop)
            fit_sds = jax.ShapeDtypeStruct(
                fit_shape, jnp.dtype(self.problem.fit_dtype)
            )
            return {
                "pipeline_ask": (self._p_ask, (steady,)),
                "pipeline_tell": (
                    self._p_tell,
                    (steady, ctx_sds, fit_sds, steady.prob),
                ),
            }
        return {
            "step": (self._step, (steady,)),
            "run": (self._run_loop, (steady, jnp.asarray(1, jnp.int32))),
        }

    # ------------------------------------------------------------------ init
    def init(self, key: jax.Array) -> StdWorkflowState:
        keys = jax.random.split(key, 2 + len(self.monitors))
        state = StdWorkflowState(
            generation=jnp.zeros((), dtype=jnp.int32),
            algo=self.algorithm.init(keys[0]),
            prob=self.problem.init(keys[1]),
            monitors=tuple(m.init(k) for m, k in zip(self.monitors, keys[2:])),
            first_step=True,
        )
        # storage-annotated leaves rest in the policy's storage dtype from
        # the very first state, so the step signature never changes
        state = apply_storage(state, self.dtype_policy)
        # pod meshes: the eager init above computed identical host values
        # on every process (same key); assemble them into GLOBAL arrays
        # (per-process make_array_from_single_device_arrays over the
        # field-annotation layout) so the global-mesh jit can consume the
        # state — no-op on single-process meshes (core/distributed.py)
        from ..core.distributed import ensure_global_state

        return ensure_global_state(state, self.mesh)

    # ------------------------------------------------------------------ step
    def step(self, state: StdWorkflowState) -> StdWorkflowState:
        return self._step(state)

    def run(
        self,
        state: StdWorkflowState,
        n_steps: int,
        checkpointer: Optional[WorkflowCheckpointer] = None,
        resume_from: Any = None,
        restarts: Any = None,
    ) -> StdWorkflowState:
        """Run ``n_steps`` generations as ONE compiled program.

        TPU-first: a Python ``for`` loop over ``step`` pays a host dispatch
        per generation; ``run`` fuses generations into a single on-device
        ``lax.fori_loop`` (the reference has no analog — its per-step host
        loop is the cost its Ray pipelining tries to hide). The trip count is
        a traced operand, so one compilation covers every ``n_steps``. The
        first generation is peeled off eagerly (``first_step`` is static so
        the loop carry stays type-stable across the init_ask/init_tell
        dispatch). With ``jit_step=False`` this falls back to an eager
        Python loop for debugging. External (host) problems route through
        the :class:`~evox_tpu.core.executor.GenerationExecutor` host
        pipeline instead (bit-identical to a ``step`` loop and axon-legal
        — a ``pure_callback`` inside a fused ``fori_loop`` is not); use
        :func:`~evox_tpu.workflows.pipelined.run_host_pipelined` directly
        for ``on_generation``/``eval_chunk``/``max_staleness`` control.

        Crash safety (axon-safe, no host callbacks — see
        workflows/checkpoint.py): ``checkpointer=`` chunks the fused loop
        at the checkpoint cadence and snapshots the state between
        dispatches — final state identical to the unchunked run.
        ``resume_from=`` (a :class:`WorkflowCheckpointer` or directory)
        restores the newest intact snapshot first; ``n_steps`` then counts
        TOTAL generations, so a crashed run re-invoked with identical
        arguments completes the remaining generations and reproduces the
        straight run's final state.

        ``restarts=`` (an :class:`~evox_tpu.core.guardrail.IPOPRestarts`,
        requires the algorithm to be a ``GuardedAlgorithm``) adds
        host-boundary IPOP population doubling: the run is chunked at the
        policy's ``check_every`` cadence, the guarded wrapper's on-device
        health counters are read between dispatches, and a triggered
        restart rebuilds the workflow around a doubled population (one
        recompile per doubling, best-so-far carried across; see
        workflows/ipop.py). Composes with ``checkpointer``/``resume_from``
        — a resumed run rebuilds the snapshot's population size first.
        """
        if restarts is not None:
            if self.external:
                # host problems take the executor pipeline for IPOP too —
                # an ipop segment through fused_run would trace the
                # pure_callback step the executor routing exists to avoid
                from .pipelined import run_host_pipelined

                return run_host_pipelined(
                    self, state, n_steps, checkpointer=checkpointer,
                    resume_from=resume_from, restarts=restarts,
                )
            from .ipop import ipop_run

            return ipop_run(
                self,
                state,
                n_steps,
                restarts,
                segment=lambda w, s, c, ck: (
                    checkpointed_run(w, s, c, ck)
                    if ck is not None
                    else fused_run(w, s, c)
                ),
                checkpointer=checkpointer,
                resume_from=resume_from,
            )
        # shared prologue (workflows/checkpoint.py enter_run): resolve a
        # resume into (restored state, REMAINING steps) with the
        # config-fingerprint guard armed on the caller's live state, and
        # default the checkpointer to the resumed directory
        state, n_steps, checkpointer = enter_run(
            state, n_steps, checkpointer, resume_from, expect_like=state
        )
        if self.external:
            # host-problem path: since PR 8 the fused callback loop is
            # replaced by the executor's double-buffered host pipeline
            # (bit-identical to a step loop — the run==step law — and,
            # unlike a pure_callback fori_loop, legal on the callback-less
            # axon backend); checkpoint snapshots ride its background lane
            from .pipelined import run_host_pipelined

            return run_host_pipelined(
                self, state, n_steps, checkpointer=checkpointer
            )
        if checkpointer is not None:
            return checkpointed_run(self, state, n_steps, checkpointer)
        return fused_run(self, state, n_steps)

    def resume(
        self,
        checkpointer: WorkflowCheckpointer,
        n_steps: int,
        fallback_state: Optional[StdWorkflowState] = None,
        state_sharding: Any = None,
        allow_config_mismatch: bool = False,
    ) -> StdWorkflowState:
        """Continue an interrupted checkpointed run to ``n_steps`` TOTAL
        generations: restore ``checkpointer``'s newest intact snapshot
        (falling back to ``fallback_state`` — e.g. a fresh ``wf.init`` —
        when no snapshot exists yet) and run the remaining generations
        with checkpointing still on. ``resume()`` of an already-complete
        run returns its final snapshot unchanged.

        Topology portability: snapshots hold mesh-free host arrays, so a
        run checkpointed on one mesh resumes on THIS workflow's mesh —
        however many devices it has (the device-loss recovery path:
        checkpoint on 8 chips, restart on 4 or 1, keep the trajectory).
        The restored leaves are eagerly re-placed by the state's own
        ``field(sharding=...)`` annotations on ``self.mesh``
        (:func:`~evox_tpu.workflows.checkpoint.restore_layouts`); pass
        ``state_sharding=`` (a pytree of shardings, e.g. from
        :func:`~evox_tpu.core.distributed.state_sharding`) to override
        the placement explicitly.

        Config guard: a snapshot written under a different algorithm /
        population size / monitor set raises
        :class:`~evox_tpu.workflows.checkpoint.CheckpointConfigError`
        instead of restoring into a program compiled for other shapes;
        ``allow_config_mismatch=True`` overrides."""
        expect_like = fallback_state
        if expect_like is None:
            try:
                # structure-only init+step: eval_shape never runs the
                # program, and snapshots are written at step boundaries —
                # one traced step materializes any lazily-sized monitor
                # buffers (LineageMonitor's width-discovered rings), so
                # the reference has the SNAPSHOT's structure. For
                # structure-stable states this equals the init structure.
                expect_like = jax.eval_shape(
                    lambda k: self.step(self.init(k)), jax.random.PRNGKey(0)
                )
            except Exception:
                try:
                    expect_like = jax.eval_shape(
                        self.init, jax.random.PRNGKey(0)
                    )
                except Exception:
                    expect_like = None  # exotic init: guard disarms
        state = checkpointer.latest(
            expect_like=expect_like,
            allow_config_mismatch=allow_config_mismatch,
        )
        if state is None:
            if fallback_state is None:
                raise FileNotFoundError(
                    f"no usable checkpoint under {checkpointer.directory}; "
                    "pass fallback_state=wf.init(key) to start fresh"
                )
            state = fallback_state
        else:
            from .checkpoint import restore_layouts

            state = restore_layouts(
                state, mesh=self.mesh, state_sharding=state_sharding
            )
        return self.run(
            state,
            max(n_steps - int(state.generation), 0),
            checkpointer=checkpointer,
        )

    def _dispatch_ask(self, state: StdWorkflowState) -> Tuple[bool, Any, Any]:
        """First-step-aware ask: ``(use_init, pop, astate)``. The single
        dispatch point shared by the step and the sample/validate previews,
        so they can never drift apart."""
        use_init = state.first_step and (
            self.algorithm.has_init_ask or self.algorithm.has_init_tell
        )
        if use_init:
            pop, astate = self.algorithm.init_ask(state.algo)
        else:
            pop, astate = self.algorithm.ask(state.algo)
        return use_init, pop, astate

    def _ask_preview(self, state: StdWorkflowState) -> Any:
        # previews see the same compute-dtype view the step itself asks on
        return self._dispatch_ask(apply_compute(state, self.dtype_policy))[1]

    def sample(self, state: StdWorkflowState) -> Any:
        """The population the algorithm would propose next, without
        advancing the workflow (the Ray workflow's ``sample`` path,
        reference distributed.py:156,384-386)."""
        return self._ask_preview(state)

    def validate(
        self,
        state: StdWorkflowState,
        problem: Optional[Problem] = None,
        key: Optional[jax.Array] = None,
        problem_state: Any = None,
    ) -> jax.Array:
        """Score the current population on ``problem`` without ``tell``.

        The mesh-native analog of the Ray workflow's ``valid`` path
        (reference distributed.py:145-156,381-383): ask, transform,
        evaluate — no algorithm-state advance, no fitness sign flip.
        ``problem`` defaults to the training problem; pass a
        validation-mode problem (e.g. ``DatasetProblem.valid()``) to score
        on held-out data. Eager utility: the validation problem's state is
        created ad hoc — seed it with ``key`` (for keyed/stochastic
        validation problems: rollout seeds, noisy benchmarks) or hand in a
        pre-built ``problem_state`` to reuse running statistics
        (e.g. observation-normalizer moments from training).

        Caveat: a training problem that consumes a host stream during
        ``evaluate`` (``DatasetProblem``, host env loops) still consumes
        one draw when validated on — pass a validation problem to keep the
        training stream untouched.
        """
        problem = problem if problem is not None else self.problem
        cand = self._ask_preview(state)
        for t in self.pop_transforms:
            cand = t(cand)
        cand = shard_pop(cand, self.mesh)
        if problem_state is not None and problem is self.problem:
            raise ValueError(
                "problem_state is only meaningful with an explicit "
                "validation problem"
            )
        if problem is self.problem:
            fitness, _ = self._evaluate(state.prob, cand)
        else:
            pstate = (
                problem_state
                if problem_state is not None
                else (problem.init(key) if key is not None else problem.init())
            )
            fitness, _ = problem.evaluate(pstate, cand)
        return fitness

    def _run_hooks(self, name: str, mstates: list, *args: Any) -> None:
        run_hooks(self.monitors, self._hook_table, name, mstates, *args)

    def _flip(self, fitness: jax.Array) -> jax.Array:
        if fitness.ndim == 1:
            return fitness * self.opt_direction[0]
        return fitness * self.opt_direction

    def _evaluate(self, pstate: Any, cand: Any) -> Tuple[jax.Array, Any]:
        if not self.external:
            if self.eval_shard_map:
                return self._evaluate_shard_map(pstate, cand)
            return self.problem.evaluate(pstate, cand)
        return callback_evaluate(self.problem, pstate, cand, self.num_objectives)

    def _evaluate_shard_map(self, pstate: Any, cand: Any) -> Tuple[jax.Array, Any]:
        """Explicit-collective evaluation: each device scores its local
        population shard, then all-gathers the fitness over ICI (the
        modernized form of the reference's per-rank dynamic_slice +
        lax.all_gather pmap scheme, std_workflow.py:160,189-200). The
        problem state is replicated in and must come back replicated —
        every shard computes the same update or none."""
        from jax.sharding import PartitionSpec as P

        n_cand = jax.tree.leaves(cand)[0].shape[0]
        n_shards = self.mesh.shape[_POP_AXIS_NAME]
        if n_cand % n_shards != 0:
            # catches algorithms whose evaluated batch differs from pop_size
            # (e.g. CSO's half-pop offspring) — the constructor check can't
            raise ValueError(
                f"eval_shard_map: the evaluated candidate batch ({n_cand}) "
                f"is not divisible by the mesh's 'pop' axis ({n_shards} "
                "shards); use the default GSPMD evaluation path for this "
                "algorithm or resize the population/mesh"
            )

        def island(ps, c):
            fit, new_ps = self.problem.evaluate(ps, c)
            return all_gather(fit), new_ps

        from ..utils.compat import shard_map

        # check_vma=False: the gathered fitness and pass-through state ARE
        # replicated after the tiled all_gather, but the static analyzer
        # cannot prove it for arbitrary problem code
        return shard_map(
            island,
            mesh=self.mesh,
            in_specs=(P(), P(_POP_AXIS_NAME)),
            out_specs=(P(), P()),
            check_vma=False,
        )(pstate, cand)

    # ----------------------------------------------- pipelined step halves
    # _step_impl split at the evaluation boundary, for run_host_pipelined
    # (workflows/pipelined.py): the host problem's evaluate runs eagerly in
    # a worker thread between the two jitted halves. Hook order, transforms
    # and state threading are identical to _step_impl, so a pipelined run
    # produces bit-identical states to a wf.step loop.

    def pipeline_ask(self, state: StdWorkflowState):
        """(candidates, ctx): everything before evaluation, jitted."""
        return self._p_ask(state)

    def pipeline_tell(
        self, state: StdWorkflowState, ctx, fitness: jax.Array, pstate: Any
    ) -> StdWorkflowState:
        """Everything after evaluation, jitted; consumes pipeline_ask's ctx
        plus the host-computed (fitness, problem state)."""
        return self._p_tell(state, ctx, fitness, pstate)

    def _pipeline_ask_impl(self, state: StdWorkflowState):
        # storage -> compute at the step boundary: ask's math (and the
        # ctx it hands to tell) runs full-precision
        state = apply_compute(state, self.dtype_policy)
        mstates = list(state.monitors)
        self._run_hooks("pre_step", mstates)
        self._run_hooks("pre_ask", mstates)
        _, pop, astate = self._dispatch_ask(state)
        self._run_hooks("post_ask", mstates, pop)
        cand = pop
        for t in self.pop_transforms:
            cand = t(cand)
        cand = shard_pop(cand, self.mesh)
        self._run_hooks("pre_eval", mstates, cand)
        return cand, (astate, tuple(mstates), cand)

    def _pipeline_tell_impl(
        self, state: StdWorkflowState, ctx, fitness: jax.Array, pstate: Any
    ) -> StdWorkflowState:
        astate, mstates_t, cand = ctx
        mstates = list(mstates_t)
        fitness = shard_pop(fitness, self.mesh)
        self._run_hooks("post_eval", mstates, cand, fitness)
        fitness = self._flip(fitness)
        if self.quarantine_nonfinite:
            fitness = quarantine_nonfinite(fitness)
        use_init = state.first_step and (
            self.algorithm.has_init_ask or self.algorithm.has_init_tell
        )
        # shared tell half (workflows/common.py): fit_transforms ->
        # pre_tell -> tell dispatch -> migrate cond -> constrain_state
        astate = ingest_fitness(self, astate, mstates, fitness, use_init)
        self._run_hooks("post_tell", mstates)
        new_state = state.replace(
            generation=state.generation + 1,
            algo=astate,
            prob=pstate,
            monitors=tuple(mstates),
            first_step=False,
        )
        return finish_step(self.monitors, self._hook_table, new_state)

    def _step_impl(self, state: StdWorkflowState) -> StdWorkflowState:
        # storage -> compute upcast at step entry: every reduction, mean
        # and covariance update below runs in the compute dtype; only the
        # state carried OUT of the step (constrain_state below) is narrow
        state = apply_compute(state, self.dtype_policy)
        mstates = list(state.monitors)
        self._run_hooks("pre_step", mstates)
        self._run_hooks("pre_ask", mstates)

        use_init, pop, astate = self._dispatch_ask(state)
        self._run_hooks("post_ask", mstates, pop)

        cand = pop
        for t in self.pop_transforms:
            cand = t(cand)
        cand = shard_pop(cand, self.mesh)

        self._run_hooks("pre_eval", mstates, cand)
        fitness, pstate = self._evaluate(state.prob, cand)
        fitness = shard_pop(fitness, self.mesh)
        self._run_hooks("post_eval", mstates, cand, fitness)

        fitness = self._flip(fitness)
        if self.quarantine_nonfinite:
            # poison (NaN/Inf) rows get the generation's worst-finite value
            # AFTER monitors saw the raw fitness (telemetry still counts
            # them) and BEFORE fit_transforms/tell (ranking stays sane)
            fitness = quarantine_nonfinite(fitness)
        # shared tell half (workflows/common.py): fit_transforms ->
        # pre_tell -> tell dispatch -> migrate cond -> constrain_state
        astate = ingest_fitness(self, astate, mstates, fitness, use_init)
        self._run_hooks("post_tell", mstates)

        new_state = state.replace(
            generation=state.generation + 1,
            algo=astate,
            prob=pstate,
            monitors=tuple(mstates),
            first_step=False,
        )
        return finish_step(self.monitors, self._hook_table, new_state)
