"""Per-tenant fault isolation for vmapped fleets: signals + policy.

A ``VectorizedWorkflow`` fuses N tenants into ONE dispatch — which means
one tenant whose state goes non-finite (a NaN covariance, a collapsed
sigma) used to keep riding in every subsequent fused step, wasting its
slot's compute forever and polluting the fleet's reports, with no
per-tenant recovery story. This module closes that hole at the serving
layer's natural boundary (between dispatch chunks, where the
``RunQueue`` already retires/admits):

- :func:`fleet_health_signals` reads the per-tenant health signals that
  are ALREADY on device — a NaN scan over each tenant's algorithm
  leaves, the stacked :class:`~evox_tpu.core.guardrail.GuardedState`
  trigger bitmask / restart / stagnation counters when the fleet wraps a
  ``GuardedAlgorithm``, and the stacked TelemetryMonitor stagnation and
  non-finite-fitness counters when one is attached — as one jitted
  computation and ONE small host fetch (a handful of ``(N,)`` arrays;
  on the tunnel, bytes and round-trips are the cost).
- :class:`FleetHealthPolicy` maps those signals to per-slot actions,
  evaluated by ``RunQueue.step_chunk`` at every chunk boundary:

  * ``"freeze"`` — mask the tenant's tell (``jnp.where`` on the frozen
    mask inside the fused step) so its state stops advancing; the slot
    parks with a forensic checkpoint and the fleet keeps its shape.
  * ``"evict"`` — checkpoint the tenant via the existing
    ``extract_tenant`` surgery and backfill the slot from the pending
    queue (or park it when pending is empty).
  * ``"restart"`` — restart in place: a fresh ``init_tenant`` re-centered
    on the tenant's best-so-far via the guardrail's
    :func:`~evox_tpu.core.guardrail.recenter_state` path, budget counter
    preserved so a permanently-poisoned tenant still terminates; after
    ``max_restarts_per_slot`` the action escalates to ``"freeze"``.

Isolation law (tests/test_serving_chaos.py): healthy tenants'
trajectories are BITWISE-unchanged under any mix of actions on other
slots — vmapped per-tenant math is row-independent, ``insert_tenant``
writes exactly one row, and the freeze select is an elementwise
``where`` that returns the computed row unchanged for unfrozen tenants.
Entirely callback-free (host work happens between dispatches), pinned by
tests/test_no_host_callbacks.py.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.guardrail import GuardedState, recenter_state

__all__ = ["FleetHealthPolicy", "fleet_health_signals"]

ACTIONS = ("freeze", "evict", "restart")


def _per_tenant_nan(tree: Any) -> jax.Array:
    """(N,) bool: any NaN in a floating leaf of each tenant's slice.
    Inf is deliberately NOT counted — +Inf sentinels are idiomatic here
    (DE's unevaluated rows, the guardrail's initial best_fitness)."""
    flags = None
    for leaf in jax.tree.leaves(tree):
        x = jnp.asarray(leaf)
        if not jnp.issubdtype(x.dtype, jnp.floating) or x.ndim < 1:
            continue
        bad = jnp.any(
            jnp.isnan(x), axis=tuple(range(1, x.ndim))
        ) if x.ndim > 1 else jnp.isnan(x)
        flags = bad if flags is None else flags | bad
    if flags is None:
        raise ValueError(
            "fleet state has no floating tenant-stacked leaves to scan"
        )
    return flags


def _has_fields(state: Any, *names: str) -> bool:
    fields = getattr(state, "__dataclass_fields__", {})
    return all(n in fields for n in names)


def _signals_impl(tenants: Any) -> Dict[str, jax.Array]:
    """Jittable per-tenant signal vector over the tenant-stacked state.
    Structure-dependent branches (guarded? telemetry attached?) resolve
    at trace time, so the compiled program carries only the signals this
    fleet actually has."""
    out: Dict[str, jax.Array] = {
        "generation": jnp.asarray(tenants.generation, jnp.int32),
        "nonfinite": _per_tenant_nan(tenants.algo),
    }
    algo = tenants.algo
    if isinstance(algo, GuardedState):
        # per-tenant trigger export (core/guardrail.py): the stacked
        # wrapper counters ARE the device-side detector's verdicts
        out["guard_trigger"] = jnp.asarray(algo.last_trigger, jnp.int32)
        out["guard_restarts"] = jnp.asarray(algo.restarts, jnp.int32)
        out["guard_stagnation"] = jnp.asarray(algo.stagnation, jnp.int32)
    for ms in tenants.monitors:
        if _has_fields(ms, "stagnation", "nan_fitness", "nan_candidates"):
            out["stagnation"] = jnp.asarray(ms.stagnation, jnp.int32)
            out["nan_fitness"] = jnp.asarray(ms.nan_fitness, jnp.int32)
            out["nan_candidates"] = jnp.asarray(ms.nan_candidates, jnp.int32)
            break
    return out


_signals_jit = jax.jit(_signals_impl)


def fleet_health_signals(state: Any) -> Dict[str, np.ndarray]:
    """Per-tenant health signals of a ``VectorizedWorkflowState``, as
    host numpy arrays (one jitted computation + one small fetch). Keys
    always present: ``generation``, ``nonfinite``; plus
    ``guard_trigger``/``guard_restarts``/``guard_stagnation`` for
    guarded fleets and ``stagnation``/``nan_fitness``/``nan_candidates``
    when a TelemetryMonitor rides along."""
    device = _signals_jit(state.tenants)
    return {k: np.asarray(v) for k, v in jax.device_get(device).items()}


@dataclasses.dataclass
class FleetHealthPolicy:
    """Chunk-boundary policy mapping per-tenant signals to slot actions.

    Args:
        on_nonfinite: action when a tenant's algorithm state carries NaN
            (``"freeze"`` / ``"evict"`` / ``"restart"`` / None to
            ignore). This is the poisoned-tenant isolation knob.
        on_trigger: action when a guarded fleet's trigger bitmask is
            nonzero (the on-device detector already restarted the inner
            state same-shape; the policy can additionally evict or
            freeze the slot at the serving layer). Default None — the
            guardrail's own restart is usually the right response.
        stagnation_limit: generations without best-so-far improvement
            (TelemetryMonitor's counter, else the guardrail's) before
            ``on_stagnation`` fires. None disables.
        on_stagnation: action for stagnated tenants (default
            ``"restart"`` — re-center on best-so-far and keep spending
            the budget exploring).
        max_restarts_per_slot: in-place restarts per slot before a
            ``"restart"`` decision escalates to ``"freeze"`` (a tenant
            that re-poisons after every restart must not restart
            forever; freezing parks it with its budget unspent).

    ``decide`` returns ``(action, reason)`` or None per tenant; severity
    order is nonfinite > trigger > stagnation (a NaN state is beyond
    what a stagnation restart could help).
    """

    on_nonfinite: Optional[str] = "evict"
    on_trigger: Optional[str] = None
    stagnation_limit: Optional[int] = None
    on_stagnation: Optional[str] = "restart"
    max_restarts_per_slot: int = 2
    # serving-plane flight recorder (PR 16): when attached (RunQueue
    # auto-threads its recorder), every verdict counts into the metrics
    # plane by reason (`fleet_health.<reason-class>`); excluded from
    # comparison/repr — the policy's identity is its thresholds
    metrics: Any = dataclasses.field(
        default=None, compare=False, repr=False
    )

    def __post_init__(self) -> None:
        for name in ("on_nonfinite", "on_trigger", "on_stagnation"):
            action = getattr(self, name)
            if action is not None and action not in ACTIONS:
                raise ValueError(
                    f"{name} must be one of {ACTIONS} or None, got "
                    f"{action!r}"
                )
        if self.max_restarts_per_slot < 0:
            raise ValueError(
                "max_restarts_per_slot must be >= 0, got "
                f"{self.max_restarts_per_slot}"
            )

    def may_freeze(self) -> bool:
        """Whether any decision path can freeze a slot — the RunQueue
        materializes the fleet's frozen mask up front iff so (adding the
        mask later would change the compiled program mid-run)."""
        actions = {self.on_nonfinite, self.on_trigger, self.on_stagnation}
        return "freeze" in actions or "restart" in actions  # escalation

    def _resolve(self, action: str, slot_restarts: int) -> str:
        if action == "restart" and slot_restarts >= self.max_restarts_per_slot:
            return "freeze"
        return action

    def decide(
        self, row: Dict[str, Any], slot_restarts: int = 0
    ) -> Optional[Tuple[str, str]]:
        """One tenant's verdict. ``row``: that tenant's slice of
        :func:`fleet_health_signals` (python scalars); ``slot_restarts``:
        in-place restarts this slot has already had (queue-tracked)."""
        if self.on_nonfinite is not None and bool(row.get("nonfinite")):
            return self._verdict(
                self._resolve(self.on_nonfinite, slot_restarts),
                "nonfinite_state",
            )
        if self.on_trigger is not None and int(row.get("guard_trigger", 0)):
            return self._verdict(
                self._resolve(self.on_trigger, slot_restarts),
                f"guard_trigger:{int(row['guard_trigger'])}",
            )
        if self.stagnation_limit is not None and self.on_stagnation is not None:
            stag = row.get("stagnation", row.get("guard_stagnation"))
            if stag is not None and int(stag) >= self.stagnation_limit:
                return self._verdict(
                    self._resolve(self.on_stagnation, slot_restarts),
                    f"stagnation:{int(stag)}",
                )
        return None

    def _verdict(self, action: str, reason: str) -> Tuple[str, str]:
        if self.metrics is not None:
            # reason class only (strip the per-tenant numeric suffix):
            # metric names must be low-cardinality for the stream's
            # monotonic-counter law to stay meaningful
            self.metrics.count(
                f"fleet_health.{action}.{reason.split(':', 1)[0]}"
            )
        return (action, reason)

    def report(self) -> dict:
        """Static policy config for ``run_report``'s ``fleet_health``."""
        return {
            "on_nonfinite": self.on_nonfinite,
            "on_trigger": self.on_trigger,
            "stagnation_limit": self.stagnation_limit,
            "on_stagnation": self.on_stagnation,
            "max_restarts_per_slot": self.max_restarts_per_slot,
        }


def restarted_tenant(wf: Any, old_tenant: Any, spec_key: jax.Array,
                     fleet_generation: int, hyperparams: Dict[str, Any]):
    """Build the in-place-restart replacement for a slot: a fresh tenant
    from a deterministic new stream (``fold_in`` of the spec's key with
    the fleet generation — replayable by recovery), re-centered on the
    old tenant's best-so-far via the guardrail's
    :func:`~evox_tpu.core.guardrail.recenter_state` path when the fleet
    is guarded (best/restart bookkeeping carried across, restart counter
    incremented — the host-boundary analog of the wrapper's own
    ``lax.cond`` restart). The tenant's OWN generation counter is
    preserved so its budget keeps counting down."""
    key = jax.random.fold_in(jnp.asarray(spec_key), int(fleet_generation))
    fresh = wf.init_tenant(key, hyperparams)
    if wf.algorithm.has_init_ask or wf.algorithm.has_init_tell:
        fresh = wf._solo_peel(fresh)  # static-shape law, as admission does
    old_algo = old_tenant.algo
    if isinstance(old_algo, GuardedState) and isinstance(
        fresh.algo, GuardedState
    ):
        inner = recenter_state(fresh.algo.inner, jnp.asarray(old_algo.best_x))
        fresh = fresh.replace(
            algo=fresh.algo.replace(
                inner=inner,
                best_x=jnp.asarray(old_algo.best_x),
                best_fitness=jnp.asarray(old_algo.best_fitness),
                restarts=jnp.asarray(old_algo.restarts) + 1,
            )
        )
    return fresh.replace(
        generation=jnp.asarray(old_tenant.generation, jnp.int32)
    )
