"""Durable serving journal: a hash-chained write-ahead log for RunQueue.

PR 2 healed the evaluation farm, PR 3 the algorithm numerics, PR 5 the
dispatch layer — but the SERVING layer (``RunQueue`` over a
``VectorizedWorkflow``, PR 7/8) kept its entire sweep bookkeeping
(pending specs, slot assignments, budgets, results) in Python memory: a
SIGKILL'd driver lost everything the fleet had not individually
checkpointed. Fiber (PAPERS.md) treats member failure and re-admission
as NORMAL scheduling events; this module gives the queue the durable
ledger that makes driver death one too.

:class:`RunJournal` is an append-only JSON-lines file where every queue
transition (``submit`` / ``start`` / ``admit`` / ``chunk_complete`` /
``retire`` / ``evict`` / ``health`` / ``recover``) is one fsynced
record. Records are **hash-chained**: each carries ``prev`` (the SHA-256
of the previous record's canonical serialization) and ``sha`` (its own),
so the journal is tamper-evident end to end — a modified or deleted
MIDDLE record breaks the chain of everything after it and raises
:class:`JournalIntegrityError` loudly, while a torn TAIL (the one
partial line a crash mid-append can leave, given per-record fsync) is
skipped with a warning and physically truncated so later appends keep
the file well-formed — the same corrupt-skip discipline as
``WorkflowCheckpointer.latest()``.

Crash-consistency contract (tests/test_serving_chaos.py): the journal is
written BEFORE (submits, close-outs) or AT (chunk barriers) the
transitions it describes, and every ``chunk_complete`` record embeds the
queue's full host-side bookkeeping (pending spec seqs, slot table,
counters, results length) next to the fleet-snapshot path it refers to.
``RunQueue.recover`` therefore rebuilds the exact queue from the newest
barrier whose snapshot is intact and REPLAYS the lost stretch
deterministically — no spec lost, none admitted twice, per-tenant
results and telemetry fingerprints identical to the uncrashed run.
Everything here is host-side file I/O between dispatches — no callbacks,
axon-safe (pinned by tests/test_no_host_callbacks.py).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import warnings
from pathlib import Path
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = ["ChainedLog", "RunJournal", "JournalIntegrityError"]

_SCHEMA = "evox_tpu.run_journal/v1"
_GENESIS = "0" * 64

# every queue transition the journal records; append() rejects anything
# else so a typo'd kind cannot silently create an event class the
# recovery replay and the run_report validator do not know about
EVENT_KINDS = (
    "submit",
    "start",
    "admit",
    "chunk_complete",
    "retire",
    "evict",
    "freeze",
    "health",
    "recover",
    # SLA serving (PR 12): a deadline-driven preemption close-out, and
    # an elastic-autoscale close-out (the tenant grew into the next pop
    # bucket; its continuation is a `submit` with `resume_from` in the
    # TARGET bucket's journal)
    "preempt",
    "autoscale",
    # cross-pod work-stealing (ISSUE 18, workflows/control_plane.py): a
    # parked continuation (or still-pending spec) released from THIS
    # queue because the gateway re-placed it on another pod — the moved
    # work is already durable in the target pod's journal (same WAL
    # ordering as the elastic-growth handoff), so recovery must NOT
    # requeue the stolen seq here
    "steal",
    # pod membership transitions (ISSUE 14, core/pod_supervisor.py —
    # process-0-writes, the checkpoint commit discipline): a member
    # joining a pod epoch, a classified pod fault (worker_dead /
    # hung_collective / coordinator_loss + detection latency), a
    # coordinated SIGTERM drain close-out, a re-formation onto the
    # survivor set, and the barrier-snapshot resume that completes it
    "pod_join",
    "pod_failure",
    "pod_drain",
    "pod_reform",
    "pod_resume",
    # compute integrity (ISSUE 20, core/attest.py): `attest` pins a
    # state digest at a generation (the bisect_divergence replay input);
    # `integrity` records a detected digest violation and the healing
    # action taken (barrier fallback, voted re-dispatch, quarantine) —
    # corruption is never silently retried into acceptance
    "attest",
    "integrity",
)


class JournalIntegrityError(RuntimeError):
    """The journal's hash chain is broken somewhere BEFORE its tail — a
    middle record was edited, replaced, or deleted. Unlike a torn tail
    (the expected crash artifact, skipped with a warning), a broken
    middle means the ledger can no longer be trusted as a whole, so the
    error is loud instead of self-healing."""


def jsonable(obj: Any) -> Any:
    """Coerce numpy/jax scalars and arrays into plain JSON types so
    journal payloads (hyperparams, health signals) serialize without a
    custom encoder. Non-finite floats become None (the
    ``sanitize_json`` rule — the journal is strict RFC 8259 JSON)."""
    if isinstance(obj, dict):
        return {str(k): jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [jsonable(v) for v in obj]
    if isinstance(obj, (bool, np.bool_)):
        return bool(obj)
    if isinstance(obj, (int, np.integer)):
        return int(obj)
    if isinstance(obj, (float, np.floating)):
        f = float(obj)
        return f if np.isfinite(f) else None
    if hasattr(obj, "tolist"):  # numpy / jax arrays
        return jsonable(np.asarray(obj).tolist())
    return obj


def _canonical(record: Dict[str, Any]) -> bytes:
    """The byte string the record's ``sha`` commits to: the record
    without its own ``sha`` field, serialized with sorted keys and no
    whitespace — independent of dict insertion order."""
    body = {k: v for k, v in record.items() if k != "sha"}
    return json.dumps(
        body, sort_keys=True, separators=(",", ":"), allow_nan=False
    ).encode()


def _fsync_dir(directory: Path) -> None:
    """fsync a directory so a rename/unlink inside it is durable."""
    fd = os.open(str(directory), os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class ChainedLog:
    """Append-only, fsynced, hash-chained JSON-lines event log — the
    reusable half of :class:`RunJournal` (PR 16 refactor: the metrics
    stream in ``workflows/flightrec.py`` shares the identical adoption,
    torn-tail-repair, and tamper-evidence machinery, so the discipline
    lives once). Subclasses pin three class attributes:

    - ``FILENAME``: the JSON-lines file inside the directory,
    - ``SCHEMA``: the per-record ``schema`` tag,
    - ``KINDS``: the closed event-kind whitelist (``None`` = any kind).

    Args:
        directory: log directory (created if missing). An existing file
            is ADOPTED: the chain is verified, a torn tail is truncated
            with a warning, and appends continue the chain — that is
            the crash-recovery path.
        max_segment_bytes: when set, the ACTIVE file is rotated once it
            reaches this size: it is renamed to ``FILENAME.NNNNNN`` (the
            next closed-segment ordinal) under the append lock, the
            directory entry is fsynced, and the next append re-creates
            the active file. The hash chain carries straight across the
            boundary (``prev`` of the first record in the new segment is
            the sha of the last record in the old one), so adoption and
            :meth:`verify` check ONE chain over all segments. Because a
            segment is only ever closed by renaming a fully-fsynced
            file, a torn tail can exist ONLY in the active file — a torn
            record inside a closed segment is tamper, not crash damage.
        retain_segments: opt-in retention — keep at most this many
            closed segments, dropping the oldest. A durable
            ``retention.json`` sidecar recording the dropped prefix's
            last seq/sha is committed BEFORE any unlink, so adoption can
            verify a chain whose head is not genesis. The segment
            holding the newest record of a :attr:`PIN_KINDS` kind (the
            newest intact barrier) is never dropped, nor is anything
            newer than it. ``None`` (default) = keep everything;
            :class:`RunJournal` refuses retention outright — recovery
            needs every ``submit``.

    Thread safety: ``append`` takes an internal lock, so the caller
    thread and the executor's background lanes may interleave appends;
    each record is written and fsynced atomically under the lock, so
    the chain stays valid in submission order.
    """

    FILENAME = "chain.jsonl"
    SCHEMA = _SCHEMA
    KINDS: Optional[tuple] = None
    #: record kinds whose newest instance pins its segment against
    #: retention (the "never drop the newest intact barrier" rule)
    PIN_KINDS: tuple = ()
    _RETENTION = "retention.json"

    def __init__(
        self,
        directory: str,
        max_segment_bytes: Optional[int] = None,
        retain_segments: Optional[int] = None,
    ):
        if max_segment_bytes is not None and max_segment_bytes < 1:
            raise ValueError(
                f"max_segment_bytes must be >= 1, got {max_segment_bytes}"
            )
        if retain_segments is not None and retain_segments < 1:
            raise ValueError(
                f"retain_segments must be >= 1, got {retain_segments}"
            )
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.path = self.directory / self.FILENAME
        self.max_segment_bytes = max_segment_bytes
        self.retain_segments = retain_segments
        self._lock = threading.Lock()
        self.torn_tail_dropped = 0
        self.rotations = 0
        self.segments_dropped = 0
        self._records: List[Dict[str, Any]] = []
        self._last_sha = _GENESIS
        self._next_seq = 0
        self._next_ordinal = 1
        self._active_bytes = 0
        if self.path.exists() or self._segment_paths():
            self._adopt()

    # ------------------------------------------------------------------ read
    def _segment_paths(self) -> List[Path]:
        """Closed segments, oldest -> newest (6-digit ordinal order)."""
        return sorted(self.directory.glob(self.FILENAME + ".[0-9]*"))

    def _read_retention(self) -> Optional[dict]:
        try:
            with open(self.directory / self._RETENTION) as f:
                side = json.load(f)
            return side if isinstance(side, dict) else None
        except (OSError, ValueError):
            return None

    def _adopt(self) -> None:
        """Verify the full chain over closed segments + the active file;
        truncate a torn ACTIVE tail (the only damage a single-writer
        fsync-per-record log can suffer from a crash) and raise on
        anything deeper — including any damage inside a closed segment,
        which by construction cannot be a crash artifact."""
        segs = self._segment_paths()
        retention = self._read_retention()
        records: List[Dict[str, Any]] = []
        last_sha = _GENESIS
        first = True
        for seg in segs:
            recs, last_sha, first = self._adopt_file(
                seg, last_sha, retention, first, allow_torn=False
            )
            records.extend(recs)
        if self.path.exists():
            recs, last_sha, first = self._adopt_file(
                self.path, last_sha, retention, first, allow_torn=True
            )
            records.extend(recs)
        self._records = records
        self._last_sha = last_sha
        self._next_seq = (
            records[-1]["seq"] + 1
            if records
            else (
                int(retention["dropped_through_seq"]) + 1
                if retention
                else 0
            )
        )
        if segs:
            self._next_ordinal = (
                max(int(p.name.rsplit(".", 1)[1]) for p in segs) + 1
            )
        elif retention is not None:
            self._next_ordinal = int(
                retention.get("dropped_through_ordinal", 0)
            ) + 1
        self._active_bytes = (
            self.path.stat().st_size if self.path.exists() else 0
        )

    def _adopt_file(
        self,
        path: Path,
        last_sha: str,
        retention: Optional[dict],
        first: bool,
        allow_torn: bool,
    ) -> tuple:
        """Adopt one file of the chain. ``first`` marks the oldest file
        on disk: its head record may chain from genesis, or — when a
        retention sidecar committed a dropped prefix — from the
        sidecar's recorded sha."""
        raw = path.read_bytes()
        lines = raw.split(b"\n")
        # byte offset where each line starts, for physical truncation
        offsets, pos = [], 0
        for line in lines:
            offsets.append(pos)
            pos += len(line) + 1
        records: List[Dict[str, Any]] = []
        bad_index: Optional[int] = None
        bad_reason = ""
        chain_break = False
        nonempty = [i for i, ln in enumerate(lines) if ln.strip()]
        for i in nonempty:
            line = lines[i]
            try:
                record = json.loads(line)
                if not isinstance(record, dict):
                    raise ValueError("record is not a JSON object")
                sha = hashlib.sha256(_canonical(record)).hexdigest()
                if record.get("sha") != sha:
                    raise ValueError(
                        f"sha {str(record.get('sha'))[:12]}… does not match "
                        f"recomputed {sha[:12]}…"
                    )
                prev = record.get("prev")
                if first:
                    # the head of the on-disk chain: genesis, or the
                    # committed retention cut (older segments dropped)
                    if prev != last_sha and not (
                        retention is not None
                        and prev == retention.get("dropped_through_sha")
                        and record.get("seq")
                        == int(retention["dropped_through_seq"]) + 1
                    ):
                        chain_break = True
                        raise ValueError(
                            f"head prev {str(prev)[:12]}… is neither "
                            "genesis nor the committed retention cut"
                        )
                    first = False
                elif prev != last_sha:
                    # a torn append can never COMPLETE a record (the sha
                    # field closes the line), so a self-consistent record
                    # whose prev doesn't chain means a predecessor was
                    # edited or deleted — tamper, wherever it sits
                    chain_break = True
                    raise ValueError(
                        f"prev {str(prev)[:12]}… does not "
                        f"chain from {last_sha[:12]}…"
                    )
            except ValueError as e:
                bad_index = i
                bad_reason = str(e)
                break
            records.append(record)
            last_sha = record["sha"]
        if bad_index is not None:
            if not allow_torn:
                # a closed segment was rotated only after every record
                # in it was fsynced — ANY invalid line inside one is
                # tamper, never a crash artifact
                raise JournalIntegrityError(
                    f"closed journal segment {path} record {len(records)} "
                    f"is invalid ({bad_reason}) — closed segments cannot "
                    "tear; the chain was tampered with. Restore the "
                    "journal from a copy or start a fresh directory."
                )
            if chain_break or bad_index != nonempty[-1]:
                # valid-looking records FOLLOW the bad one: a torn append
                # cannot produce that (each record is fsynced before the
                # next is written) — the middle of the ledger was changed
                raise JournalIntegrityError(
                    f"journal {path} record {len(records)} is invalid "
                    f"({bad_reason}) but later records exist — the chain "
                    "was tampered with mid-file; refusing to adopt. "
                    "Restore the journal from a copy or start a fresh "
                    "directory."
                )
            warnings.warn(
                f"journal {path}: dropping torn tail record "
                f"{len(records)} ({bad_reason}) — the expected artifact of "
                "a crash mid-append",
                stacklevel=2,
            )
            self.torn_tail_dropped += 1
            with open(path, "r+b") as f:
                f.truncate(offsets[bad_index])
                f.flush()
                os.fsync(f.fileno())
        return records, last_sha, first

    def records(self, kind: Optional[str] = None) -> List[Dict[str, Any]]:
        """All adopted+appended records (a copy), optionally filtered."""
        with self._lock:
            recs = list(self._records)
        if kind is not None:
            recs = [r for r in recs if r.get("kind") == kind]
        return recs

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for r in self.records():
            out[r["kind"]] = out.get(r["kind"], 0) + 1
        return out

    @classmethod
    def verify(cls, directory: str) -> int:
        """Re-read a log from disk, raising
        :class:`JournalIntegrityError` on a broken chain; returns the
        number of intact records. (Adoption already verifies — this is
        the standalone audit entry point.)"""
        return len(cls(directory).records())

    # ----------------------------------------------------------------- write
    def append(self, kind: str, **payload: Any) -> Dict[str, Any]:
        """Append one event record and fsync it before returning — the
        WAL guarantee: once ``append`` returns, the transition is
        durable. ``payload`` values are coerced to strict JSON."""
        if self.KINDS is not None and kind not in self.KINDS:
            raise ValueError(
                f"unknown {type(self).__name__} event kind {kind!r}; "
                f"expected one of {self.KINDS}"
            )
        with self._lock:
            record: Dict[str, Any] = {
                "schema": self.SCHEMA,
                "seq": self._next_seq,
                "kind": kind,
                "t": round(time.time(), 6),
                "prev": self._last_sha,
            }
            record.update(jsonable(payload))
            record["sha"] = hashlib.sha256(_canonical(record)).hexdigest()
            line = json.dumps(
                record, sort_keys=True, separators=(",", ":"),
                allow_nan=False,
            )
            with open(self.path, "ab") as f:
                f.write(line.encode() + b"\n")
                f.flush()
                os.fsync(f.fileno())
            self._records.append(record)
            self._last_sha = record["sha"]
            self._next_seq += 1
            self._active_bytes += len(line) + 1
            if (
                self.max_segment_bytes is not None
                and self._active_bytes >= self.max_segment_bytes
            ):
                self._rotate_locked()
            return record

    def _rotate_locked(self) -> None:
        """Close the active file: rename it to the next segment ordinal
        and fsync the directory entry. The rename happens AFTER the last
        record's fsync (append just did it), so a closed segment can
        never carry a torn tail; the in-memory chain head is untouched,
        so the next append continues the chain in a fresh active file."""
        seg = self.directory / f"{self.FILENAME}.{self._next_ordinal:06d}"
        os.rename(self.path, seg)
        _fsync_dir(self.directory)
        self._next_ordinal += 1
        self._active_bytes = 0
        self.rotations += 1
        if self.retain_segments is not None:
            self._apply_retention_locked()

    def _apply_retention_locked(self) -> None:
        """Drop the oldest closed segments past ``retain_segments``,
        never dropping the segment that holds the newest record of a
        :attr:`PIN_KINDS` kind (or anything newer). The cut is committed
        to the ``retention.json`` sidecar — durably, BEFORE any unlink —
        so adoption can verify the shortened chain's head against it."""
        segs = self._segment_paths()
        excess = len(segs) - self.retain_segments
        if excess <= 0:
            return
        droppable = segs[:excess]
        if self.PIN_KINDS:
            pinned = [
                r["seq"]
                for r in self._records
                if r.get("kind") in self.PIN_KINDS
            ]
            if pinned:
                pin_seq = max(pinned)
                kept = []
                for seg in droppable:
                    # the segment's last record bounds its seq range: a
                    # segment whose bound reaches the pinned seq holds
                    # it (or something newer) — stop dropping there
                    tail = seg.read_bytes().strip().split(b"\n")[-1]
                    last = json.loads(tail)
                    if int(last["seq"]) >= pin_seq:
                        break
                    kept.append(seg)
                droppable = kept
        if not droppable:
            return
        cut_path = droppable[-1]
        tail = json.loads(cut_path.read_bytes().strip().split(b"\n")[-1])
        side = {
            "schema": self.SCHEMA,
            "dropped_through_seq": int(tail["seq"]),
            "dropped_through_sha": tail["sha"],
            "dropped_through_ordinal": int(
                cut_path.name.rsplit(".", 1)[1]
            ),
        }
        tmp = self.directory / (self._RETENTION + ".tmp")
        with open(tmp, "wb") as f:
            f.write(json.dumps(side, sort_keys=True).encode())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.directory / self._RETENTION)
        _fsync_dir(self.directory)
        for seg in droppable:
            seg.unlink()
        _fsync_dir(self.directory)
        cut_seq = int(tail["seq"])
        self._records = [r for r in self._records if r["seq"] > cut_seq]
        self.segments_dropped += len(droppable)

class RunJournal(ChainedLog):
    """The serving queue's durable WAL (module docstring): the
    :class:`ChainedLog` machinery under the ``journal.jsonl`` name with
    the queue-transition kind whitelist — ``append()`` rejects anything
    outside :data:`EVENT_KINDS` so a typo'd kind cannot silently create
    an event class the recovery replay and the run_report validator do
    not know about."""

    FILENAME = "journal.jsonl"
    SCHEMA = _SCHEMA
    KINDS = EVENT_KINDS
    PIN_KINDS = ("chunk_complete",)

    def __init__(
        self,
        directory: str,
        max_segment_bytes: Optional[int] = None,
        retain_segments: Optional[int] = None,
    ):
        if retain_segments is not None:
            # recover() replays EVERY submit — a retained-away prefix
            # would silently lose accepted work, so the queue's WAL may
            # rotate (bounded files) but never forget
            raise ValueError(
                "RunJournal does not support retention: recovery replays "
                "the full submit history; use max_segment_bytes alone"
            )
        super().__init__(directory, max_segment_bytes=max_segment_bytes)

    # ---------------------------------------------------------------- report
    def report(self) -> dict:
        """The ``tenancy.queue.journal`` section of ``run_report()``
        (schema v6, validated by tools/check_report.py): per-kind event
        counters, the chain head, and whether this journal has ever been
        recovered from."""
        counts = self.counts()
        return {
            "path": str(self.path),
            "records": len(self._records),
            "last_seq": len(self._records) - 1,
            "events": counts,
            "recovered": counts.get("recover", 0) > 0,
            "torn_tail_dropped": self.torn_tail_dropped,
        }
