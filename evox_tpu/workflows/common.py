"""Plumbing shared by the workflow implementations."""

from __future__ import annotations

from typing import Any, Callable, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.monitor import HOOK_NAMES, Monitor
from ..core.problem import Problem


def build_hook_table(monitors: Sequence[Monitor]) -> Dict[str, Tuple[int, ...]]:
    """name -> indices of the monitors implementing that hook."""
    return {
        name: tuple(i for i, m in enumerate(monitors) if name in m.hooks())
        for name in HOOK_NAMES
    }


def run_hooks(
    monitors: Sequence[Monitor],
    table: Dict[str, Tuple[int, ...]],
    name: str,
    mstates: list,
    *args: Any,
) -> None:
    """Dispatch one hook across monitors, updating ``mstates`` in place."""
    for i in table[name]:
        mstates[i] = getattr(monitors[i], name)(mstates[i], *args)


def finish_step(
    monitors: Sequence[Monitor],
    table: Dict[str, Tuple[int, ...]],
    new_state: Any,
) -> Any:
    """Run the ``post_step`` hooks against the otherwise-final workflow
    state (so monitors observe exactly what the step returns), then fold
    their updated states back in."""
    mstates = list(new_state.monitors)
    run_hooks(monitors, table, "post_step", mstates, new_state)
    return new_state.replace(monitors=tuple(mstates))


def make_run_loop(step_impl: Callable, donate: bool = False) -> Callable:
    """Jitted ``(state, n) -> state`` running ``step_impl`` n times in one
    on-device ``fori_loop``; the trip count is a traced operand, so one
    compilation covers every ``n``.

    ``donate=True`` donates the state carry (``donate_argnums=0``): XLA
    aliases the input state's buffers into the loop carry and output
    instead of double-buffering them across the program boundary — the
    aliasing shows up as ``alias_bytes`` in ``memory_analysis()`` and as
    reduced peak bytes in ``run_report()["roofline"]``. The donated input
    is INVALIDATED after the call. Default False (matching the
    workflows' ``donate_carries`` default): whoever turns it on owns the
    snapshot-before-donate contract — the loop must only ever be fed
    states its driver produced itself. :func:`fused_run` (the driver
    behind ``StdWorkflow.run``/``IslandWorkflow.run``) honors it by
    advancing caller-owned states one non-donating ``wf.step`` first, so
    checkpoints are always taken from states the loop never donates."""
    return jax.jit(
        lambda s, n: jax.lax.fori_loop(0, n, lambda _, x: step_impl(x), s),
        donate_argnums=(0,) if donate else (),
    )


def fused_run(wf: Any, state: Any, n_steps: int) -> Any:
    """Shared ``run()`` body: peel the first generation eagerly through
    the non-donating ``wf.step`` — both for the init_ask dispatch (the
    loop carry stays type-stable) and so the CALLER's state buffers are
    never donated (the step's output is a fresh intermediate owned by
    this function; jax 0.4.x does not forward unchanged inputs to
    outputs, verified in tests/test_dtype_policy.py) — then hand the rest
    to the donated ``wf._run_loop`` (or an eager Python loop when
    ``wf.jit_step=False``)."""
    if n_steps <= 0:
        return state
    # the peel is mandatory when the loop donates: without it a warm
    # caller state would be handed straight to the donated loop and the
    # caller's arrays (bench re-timing loops, checkpointer snapshots,
    # test fixtures) would be invalidated under it
    if state.first_step or getattr(wf, "donate_carries", False):
        state = wf.step(state)
        n_steps -= 1
    if not wf.jit_step:
        for _ in range(n_steps):
            state = wf._step_impl(state)
        return state
    if n_steps > 0:
        state = wf._run_loop(state, jnp.asarray(n_steps, dtype=jnp.int32))
    return state


def ingest_fitness(
    wf: Any,
    astate: Any,
    mstates: list,
    fitness: jax.Array,
    use_init: bool,
) -> Any:
    """The tell half every workflow variant shares once the fitness is
    FINAL (sign-flipped, quarantined/filled): fit_transforms → pre_tell
    hook → ``init_tell``/``tell`` dispatch → the ``migrate_helper``
    ``lax.cond`` → the end-of-step ``constrain_state`` boundary. One
    body (used by StdWorkflow's step and pipelined tell and by
    SurrogateWorkflow's screened variants) so a change to any of these
    steps cannot silently drift between the copies."""
    from ..core.distributed import constrain_state

    for t in wf.fit_transforms:
        fitness = t(fitness)
    run_hooks(wf.monitors, wf._hook_table, "pre_tell", mstates, fitness)
    if use_init:
        astate = wf.algorithm.init_tell(astate, fitness)
    else:
        astate = wf.algorithm.tell(astate, fitness)
    if wf.migrate_helper is not None:
        do_migrate, foreign_pop, foreign_fit = wf.migrate_helper()
        # foreign fitness arrives in the user's convention: sign-flip it
        # to the internal minimization key, but never fit_transforms —
        # population-relative shaping over a lone migrant batch is
        # meaningless/NaN (see StdWorkflow.migrate_helper docs)
        foreign_fit = wf._flip(foreign_fit)
        astate = jax.lax.cond(
            do_migrate,
            lambda a: wf.algorithm.migrate(a, foreign_pop, foreign_fit),
            lambda a: a,
            astate,
        )
    # declared sharding + storage-dtype downcast in one fused walk: the
    # loop-carried algorithm state leaves the step at storage width
    return constrain_state(astate, wf.mesh, wf.dtype_policy)


def quarantine_nonfinite(fitness: jax.Array) -> jax.Array:
    """Replace non-finite fitness entries with the worst FINITE value of
    the batch (internal minimization convention: the per-objective max),
    so a poison candidate loses every comparison cleanly instead of
    corrupting argmin/sorting/ranking — NaN propagates through every
    comparison-based selection op. Multi-objective fitness is quarantined
    per objective column. A column with NO finite entry falls back to the
    dtype's max finite value. Jittable, shape-preserving."""
    finite = jnp.isfinite(fitness)
    worst = jnp.max(jnp.where(finite, fitness, -jnp.inf), axis=0)
    worst = jnp.where(
        jnp.isfinite(worst), worst, jnp.finfo(fitness.dtype).max
    )
    return jnp.where(finite, fitness, worst)


def callback_evaluate(
    problem: Problem, pstate: Any, cand: Any, num_objectives: int = 1
) -> Tuple[jax.Array, Any]:
    """Host-side evaluation through ``jax.pure_callback`` with a declared
    fitness signature (the reference's ``external_problem=True`` contract,
    std_workflow.py:146-158). External problems are stateless from the jit
    program's point of view: the state operand passes through and any host
    update lives on the problem object itself."""
    leaves = jax.tree.leaves(cand)
    pop_size = leaves[0].shape[0]
    if num_objectives > 1:
        shape: Tuple[int, ...] = (pop_size, num_objectives)
    else:
        shape = problem.fit_shape(pop_size)
    result_sds = jax.ShapeDtypeStruct(shape, jnp.dtype(problem.fit_dtype))

    def host_eval(ps, c):
        fit, _ = problem.evaluate(ps, c)
        return np.asarray(fit, dtype=problem.fit_dtype)

    fitness = jax.pure_callback(host_eval, result_sds, pstate, cand)
    return fitness, pstate
