"""Elastic serving: bucketed fleet shapes, warm admission, pop autoscaling.

``VectorizedWorkflow``/``RunQueue`` (PRs 7/8/11) serve FIXED fleet
shapes: a tenant whose (pop, dim, fleet-width) doesn't match the
compiled shape triggers a full XLA retrace on the critical path — the
one cost the PR-4 detector can only report. This module hides XLA's
static-shape world behind a small lattice of canonical shapes (Fiber's
elastic-membership serving model, PAPERS.md arXiv 2003.11164):

- :class:`BucketTable` quantizes a request's ``pop`` and fleet ``width``
  UP to powers-of-two rungs (user-overridable); ``dim`` is an exact key
  component, never padded — padding the population adds candidates whose
  fitness can be made inert, but padding the search space changes the
  objective itself (a separable problem's padded coordinates shift every
  fitness value), so each distinct dim is its own bucket.
- :class:`ElasticWorkflow` pads admission: a tenant requesting
  ``pop=p`` into a ``pop=B`` bucket runs the bucket shape with its last
  ``B − p`` fitness rows replaced by the worst FINITE fitness of its
  live rows (:func:`pad_inert_rows` — the quarantine fill law from
  PR 2), so the inert rows lose every comparison, never become
  best-so-far, and never perturb telemetry. The per-tenant live-row
  count rides as the reserved traced hyperparam ``ACTIVE_ROWS``, so ONE
  compiled bucket program serves every requested pop ≤ B. Width padding
  is idle filler slots (vmap rows are independent — asserted).
- :class:`ElasticServer` owns the bucket map: get-or-create a bucket's
  :class:`ElasticWorkflow` + :class:`~evox_tpu.workflows.tenancy.
  RunQueue` per canonical shape, AOT-warm its executables through
  :class:`~evox_tpu.core.exec_cache.ExecutableCache`
  (:func:`warm_fleet_cache` — memory/disk/compile), and route every
  submitted :class:`ElasticSpec` to its bucket. Admitting a tenant into
  a WARM bucket is pure state surgery (``insert_tenant``) against a
  cached executable — never a retrace (asserted with
  ``DispatchRecorder(strict_retrace=True)``); a COLD PROCESS warm-starts
  its buckets by deserializing executables from the cache directory in
  milliseconds instead of recompiling.
- :class:`PopAutoscaler` re-targets IPOP's increasing-population
  machinery (PAPERS.md arXiv 2409.11765; ``workflows/ipop.py``) as a
  SERVING policy: a guarded tenant showing the restart/stagnation
  escalation signal grows into the next pop rung's bucket when that
  bucket has capacity — the same :func:`~evox_tpu.workflows.ipop.
  grow_guarded` surgery the host-boundary doubling uses (pure in
  pop_size, so recovery re-derives it; the PR-10 handoff precedent),
  journaled as an ``autoscale`` close-out plus a continuation admit in
  the target bucket's journal.

Correctness contract (tests/test_elastic.py): a padded tenant ≡ its
:meth:`ElasticWorkflow.solo_workflow` run at the exact bucket shape with
the same inert-row mask (allclose(1e-5), the PR-7 tenancy contract);
inert rows and filler neighbours never change a healthy tenant's
telemetry ring fingerprint (bitwise); a serialized executable reloaded
in a fresh process reproduces the compiling process's trajectory
bitwise; stale-topology cache entries refuse loudly
(:class:`~evox_tpu.core.exec_cache.ExecCacheError`).

No reference analog (the reference has no serving layer; SURVEY §5):
design sources are Fiber and the IPOP-CMA-ES paper, see PARITY row 57.
Everything here is host-side orchestration + AOT compilation between
dispatches — no callbacks, axon-safe (pinned by
tests/test_no_host_callbacks.py).
"""

from __future__ import annotations

import dataclasses
import hashlib
import re
import warnings
from functools import partial
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.exec_cache import ExecutableCache
from .tenancy import RunQueue, TenantSpec, VectorizedWorkflow

__all__ = [
    "ACTIVE_ROWS",
    "BucketError",
    "BucketShape",
    "BucketTable",
    "ElasticServer",
    "ElasticSpec",
    "ElasticWorkflow",
    "PopAutoscaler",
    "pad_inert_rows",
    "warm_fleet_cache",
]

# reserved per-tenant hyperparam: the tenant's LIVE population rows
# (requested pop ≤ bucket pop). Traced like any hyperparam — one
# compiled bucket program serves every value — but never bound onto the
# algorithm template (ElasticWorkflow strips it before _bind)
ACTIVE_ROWS = "_elastic_active_rows"


def pad_inert_rows(fitness: jax.Array, active: Any) -> jax.Array:
    """Replace fitness rows at index ``>= active`` with the worst FINITE
    fitness among the live rows (per objective column — the
    ``quarantine_nonfinite`` fill law), so padded candidates lose every
    comparison-based selection cleanly: never top-k, never best-so-far,
    never a telemetry best. A live-row set with no finite entry falls
    back to the dtype's max finite value. ``active`` may be a traced
    scalar (the fleet path) or a python int (the solo fit_transform).
    Jittable, shape-preserving; ``active == pop`` is a bitwise
    identity."""
    n = fitness.shape[0]
    live = jnp.arange(n) < active
    live_b = live if fitness.ndim == 1 else live[:, None]
    finite_live = jnp.isfinite(fitness) & live_b
    worst = jnp.max(jnp.where(finite_live, fitness, -jnp.inf), axis=0)
    worst = jnp.where(
        jnp.isfinite(worst), worst, jnp.finfo(fitness.dtype).max
    )
    return jnp.where(live_b, fitness, worst)


# ------------------------------------------------------------------ buckets


class BucketError(ValueError):
    """A request cannot be mapped onto the bucket lattice (beyond the
    top rung, or a non-positive shape)."""


@dataclasses.dataclass(frozen=True)
class BucketShape:
    """One canonical compiled fleet shape: every tenant in the bucket
    runs ``pop`` candidates over ``dim`` dimensions in a ``width``-wide
    vmapped fleet."""

    pop: int
    dim: int
    width: int

    @property
    def key(self) -> str:
        return f"pop{self.pop}_dim{self.dim}_w{self.width}"

    def as_tuple(self) -> Tuple[int, int, int]:
        return (self.pop, self.dim, self.width)


def _pow2_rungs(lo: int, hi: int) -> Tuple[int, ...]:
    rungs, v = [], max(int(lo), 1)
    while v < hi:
        rungs.append(v)
        v *= 2
    rungs.append(int(hi))
    return tuple(rungs)


class BucketTable:
    """The lattice of canonical shapes requests are rounded UP onto.

    Args:
        pop_rungs: explicit sorted pop rungs; default powers of two from
            ``min_pop`` to ``max_pop``.
        width_rungs: explicit sorted fleet-width rungs; default powers
            of two from 1 to ``max_width``.
        min_pop / max_pop / max_width: lattice bounds for the defaults.

    ``dim`` has no rungs: it keys buckets exactly (see module
    docstring). A request beyond the top rung raises
    :class:`BucketError` — elastic serving rounds up, it never silently
    truncates a search."""

    def __init__(
        self,
        pop_rungs: Optional[Sequence[int]] = None,
        width_rungs: Optional[Sequence[int]] = None,
        min_pop: int = 8,
        max_pop: int = 1 << 16,
        max_width: int = 256,
    ):
        self.pop_rungs = (
            tuple(sorted(int(r) for r in pop_rungs))
            if pop_rungs
            else _pow2_rungs(min_pop, max_pop)
        )
        self.width_rungs = (
            tuple(sorted(int(r) for r in width_rungs))
            if width_rungs
            else _pow2_rungs(1, max_width)
        )
        if any(r < 1 for r in self.pop_rungs + self.width_rungs):
            raise BucketError("bucket rungs must be positive")

    @staticmethod
    def _round_up(value: int, rungs: Tuple[int, ...], what: str) -> int:
        if value < 1:
            raise BucketError(f"requested {what} must be >= 1, got {value}")
        for r in rungs:
            if r >= value:
                return r
        raise BucketError(
            f"requested {what}={value} exceeds the lattice's top rung "
            f"{rungs[-1]}; extend the {what} rungs (BucketTable("
            f"{what}_rungs=...)) or shrink the request"
        )

    def bucket_for(self, pop: int, dim: int, width: int = 1) -> BucketShape:
        """Quantize a (pop, dim, width) request onto the lattice: pop and
        width round UP to their rungs, dim passes through exactly."""
        if dim < 1:
            raise BucketError(f"requested dim must be >= 1, got {dim}")
        return BucketShape(
            pop=self._round_up(int(pop), self.pop_rungs, "pop"),
            dim=int(dim),
            width=self._round_up(int(width), self.width_rungs, "width"),
        )

    def next_pop_rung(self, pop: int) -> Optional[int]:
        """The smallest rung strictly above ``pop`` (the autoscaler's
        growth target), or None at the top of the lattice."""
        for r in self.pop_rungs:
            if r > pop:
                return r
        return None

    def report(self) -> dict:
        return {
            "pop_rungs": list(self.pop_rungs),
            "width_rungs": list(self.width_rungs),
            "dim": "exact",
        }


# ----------------------------------------------------------- padded fleets


class ElasticWorkflow(VectorizedWorkflow):
    """A :class:`VectorizedWorkflow` that understands the reserved
    ``ACTIVE_ROWS`` hyperparam: each tenant's fitness rows beyond its
    requested pop are replaced by the inert worst-finite fill
    (:func:`pad_inert_rows`) between the quarantine stage and the
    fit transforms — the bucket's padded-admission mechanism. Tenants
    without the binding behave exactly like the parent class."""

    def _check_hp_name(self, name: str) -> None:
        if name == ACTIVE_ROWS:
            return  # reserved: consumed by the workflow, never bound
        super()._check_hp_name(name)

    def _bind(self, hp: Dict[str, Any]):
        if ACTIVE_ROWS in hp:
            hp = {k: v for k, v in hp.items() if k != ACTIVE_ROWS}
        return super()._bind(hp)

    def _filter_fitness(self, t, fitness: jax.Array) -> jax.Array:
        active = t.hyperparams.get(ACTIVE_ROWS)
        if active is None:
            return fitness
        return pad_inert_rows(fitness, active)

    def solo_workflow(
        self,
        index: Optional[int] = None,
        hyperparams: Optional[Dict[str, Any]] = None,
        mesh: Optional[jax.sharding.Mesh] = None,
        state: Any = None,
    ):
        """The solo reference/resume workflow for a PADDED tenant: the
        parent's :class:`~evox_tpu.workflows.std.StdWorkflow` at the
        exact bucket shape, with the tenant's inert-row mask prepended
        to ``fit_transforms`` — the same pipeline position the fleet
        applies it at (after quarantine, before the user transforms), so
        the padded-tenant ≡ solo law holds with the mask on both
        sides."""
        if hyperparams is None:
            hyperparams = (
                self.tenant_hyperparams(index, state=state)
                if index is not None
                else {}
            )
        hp = dict(hyperparams)
        active = hp.pop(ACTIVE_ROWS, None)
        wf = super().solo_workflow(hyperparams=hp, mesh=mesh)
        if active is not None:
            wf.fit_transforms = (
                partial(pad_inert_rows, active=int(np.asarray(active))),
            ) + wf.fit_transforms
        return wf


# --------------------------------------------------------------- AOT warm


def _value_digest(v: Any) -> str:
    """Value identity for a BAKED constant (a closure cell, a partial's
    bound argument, an instance attribute). Arrays hash by
    dtype/shape/BYTES — ``repr`` truncates past 1000 elements, so two
    big constants differing in one element would collide — containers
    recurse element-wise, callables defer to
    :func:`_transform_identity`, the rest use an address-stripped
    repr."""
    if isinstance(v, (list, tuple)):
        return "[" + ",".join(_value_digest(x) for x in v) + "]"
    if isinstance(v, dict):
        return "{" + ",".join(
            f"{k!r}:{_value_digest(x)}"
            for k, x in sorted(v.items(), key=lambda kv: repr(kv[0]))
        ) + "}"
    if callable(v) and not isinstance(v, type):
        return _transform_identity(v)
    try:
        arr = np.asarray(v)
        if arr.dtype == object:
            raise TypeError
        return (
            f"ndarray({arr.dtype},{arr.shape})#"
            + hashlib.sha256(arr.tobytes()).hexdigest()[:16]
        )
    except Exception:
        return re.sub(r" at 0x[0-9a-f]+", "", repr(v))


def _transform_identity(t: Any) -> str:
    """A content-addressed identity for a pop/fit transform. Bare
    ``__name__`` is not enough: two different lambdas both print
    ``<lambda>`` (two fleets sharing a cache directory would silently
    serve each other's compiled program), while ``repr`` of a partial
    embeds a ``0x`` address that changes every process (silently
    defeating the on-disk warm start). Functions key by module+qualname
    plus a digest of their BYTECODE and closure values; partials recurse
    into their func and key their bound arguments by value
    (:func:`_value_digest` — array bytes, never truncated repr)."""
    if isinstance(t, partial):
        args = ",".join(_value_digest(a) for a in t.args)
        kw = ",".join(
            f"{k}={_value_digest(v)}"
            for k, v in sorted(t.keywords.items())
        )
        return (
            f"partial({_transform_identity(t.func)},"
            f"args=({args}),kw=({kw}))"
        )
    code = getattr(t, "__code__", None)
    if code is not None:
        body = hashlib.sha256(
            code.co_code + repr(code.co_consts).encode()
        ).hexdigest()[:16]
        cells = []
        for c in getattr(t, "__closure__", None) or ():
            try:
                cells.append(_value_digest(c.cell_contents))
            except ValueError:  # empty cell
                cells.append("<empty>")
        name = getattr(t, "__qualname__", getattr(t, "__name__", "?"))
        return (
            f"{getattr(t, '__module__', '?')}.{name}"
            f"#{body}({','.join(cells)})"
        )
    # callable object: type identity + an address-stripped repr (the
    # config a __call__ object carries is in its repr by convention)
    return (
        f"{type(t).__module__}.{type(t).__qualname__}:"
        + re.sub(r" at 0x[0-9a-f]+", "", repr(t))
    )


def _instance_identity(obj: Any, depth: int = 0) -> str:
    """A content digest of an algorithm/problem instance's constructor
    config. The traced program BAKES closed-over constants (PSO's
    lb/ub, coefficients, a problem's parameters) that appear in neither
    the class name nor the abstract argument signature — two fleets
    differing only in those values must key distinct executables, or a
    shared cache directory silently serves one fleet the other's
    compiled program (the same hazard :func:`_transform_identity`
    guards for transforms). Public attributes hash by VALUE: arrays by
    bytes, nested objects (GuardedAlgorithm's inner algorithm) by
    recursion, callables by :func:`_transform_identity`, the rest by
    address-stripped repr."""
    name = f"{type(obj).__module__}.{type(obj).__qualname__}"
    if depth > 4 or not hasattr(obj, "__dict__"):
        return name
    h = hashlib.sha256(name.encode())
    for k, v in sorted(vars(obj).items()):
        if k.startswith("_"):
            continue
        h.update(k.encode())
        if callable(v) and not hasattr(v, "__dict__"):
            h.update(_transform_identity(v).encode())
            continue
        try:
            arr = np.asarray(v)
            if arr.dtype == object:
                raise TypeError
            h.update(
                str(arr.dtype).encode()
                + str(arr.shape).encode()
                + arr.tobytes()
            )
        except Exception:
            if hasattr(v, "__dict__") and not callable(v):
                h.update(_instance_identity(v, depth + 1).encode())
            else:
                # containers/callables/scalars: by VALUE, never by a
                # (truncating) repr — see _value_digest
                h.update(_value_digest(v).encode())
    return f"{name}#{h.hexdigest()[:16]}"


def fleet_fingerprint(wf: VectorizedWorkflow) -> str:
    """The static-config half of the executable cache key: everything
    that changes the TRACED fleet program without changing the abstract
    argument signature — algorithm/problem/monitor instance CONFIG
    (baked constants included, by value), fleet width, opt direction,
    quarantine/policy/donation flags, transform identities, hyperparam
    names. Leaf shapes/dtypes are keyed separately by the abstract
    signature."""
    parts = [
        type(wf).__qualname__,
        _instance_identity(wf.algorithm),
        _instance_identity(wf.problem),
        f"n={wf.n_tenants}",
        f"dir={np.asarray(wf.opt_direction).tolist()}",
        f"q={wf.quarantine_nonfinite}",
        f"donate={wf.donate_carries}",
        f"policy={wf.dtype_policy}",
        "pt:" + ",".join(_transform_identity(t) for t in wf.pop_transforms),
        "ft:" + ",".join(_transform_identity(t) for t in wf.fit_transforms),
        "mon:" + ",".join(_instance_identity(m) for m in wf.monitors),
        "hp:" + ",".join(sorted(wf.hyperparams)),
    ]
    return hashlib.sha256("|".join(parts).encode()).hexdigest()


def warm_fleet_cache(
    wf: VectorizedWorkflow,
    cache: ExecutableCache,
    bucket: Optional[BucketShape] = None,
    seed_key: Any = None,
    planned: bool = True,
) -> Dict[str, Any]:
    """AOT-compile (or reload from ``cache``) the fleet's four serving
    executables and swap them onto the workflow, so every subsequent
    dispatch runs a cached program:

    - ``fleet_step_first`` — the ``first_step=True`` init_ask peel,
    - ``fleet_step`` — the steady vmapped step,
    - ``fleet_run_loop`` — the fused fori_loop (trip count is a traced
      operand: ONE executable covers every chunk length),
    - ``fleet_solo_peel`` — the single-tenant admission peel (bindings
      are traced operands: one executable serves every admitted spec).

    Lowering uses ``jax.eval_shape`` abstract states — zero FLOPs, no
    state materialized. Idempotent: re-warming reuses the originals
    captured on first warm (a cache hit, not a recompile). The cache is
    advertised as ``wf._exec_cache`` so ``run_report`` surfaces the
    ``serving.cache`` section.

    Mesh caveat: executables are exact about input placement; a meshed
    fleet must be warmed AND driven with states placed the same way the
    lowering example was (``wf.init`` → dispatch, the normal serving
    path). Returns ``{"fingerprint", "entries"}``."""
    if not wf.jit_step:
        raise ValueError(
            "warm_fleet_cache requires jit_step=True: an eager fleet has "
            "no executable to cache"
        )
    fp = fleet_fingerprint(wf)
    originals = getattr(wf, "_exec_cache_originals", None)
    if originals is None:
        originals = {
            "step": wf._step,
            "run_loop": wf._run_loop,
            "solo_peel": wf._solo_peel,
        }
        wf._exec_cache_originals = originals
    key = (
        seed_key if seed_key is not None else jax.random.PRNGKey(0)
    )
    bt = bucket.as_tuple() if bucket is not None else None
    state0 = jax.eval_shape(wf.init, key)
    steady = state0.replace(first_step=False)
    hp0 = {k: v[0] for k, v in wf.hyperparams.items()}
    tenant0 = jax.eval_shape(lambda k: wf.init_tenant(k, hp0), key)
    n_sds = jax.ShapeDtypeStruct((), jnp.int32)

    get = partial(
        cache.get_or_compile,
        bucket=bt,
        mesh=wf.mesh,
        planned=planned,
    )
    step_first = get("fleet_step_first", fp, originals["step"], (state0,))
    step = get("fleet_step", fp, originals["step"], (steady,))
    run_loop = get("fleet_run_loop", fp, originals["run_loop"], (steady, n_sds))
    solo_peel = get("fleet_solo_peel", fp, originals["solo_peel"], (tenant0,))

    from ..core.exec_cache import _CachedDispatch

    def _step_dispatch(state):
        # first_step is STATIC pytree metadata: the designed init peel is
        # its own executable, the steady step another — exactly the two
        # programs jit would hold, now pinned to cached binaries
        return (step_first if state.first_step else step)(state)

    _step_dispatch.lower = originals["step"].lower  # roofline analyzer path
    wf._step = _step_dispatch
    wf._run_loop = _CachedDispatch(run_loop, originals["run_loop"])
    wf._solo_peel = _CachedDispatch(solo_peel, originals["solo_peel"])
    wf._exec_cache = cache
    return {
        "fingerprint": fp,
        "entries": ["fleet_step_first", "fleet_step", "fleet_run_loop",
                    "fleet_solo_peel"],
    }


# ------------------------------------------------------------- autoscaler


@dataclasses.dataclass
class PopAutoscaler:
    """IPOP-as-serving-policy (PAPERS.md arXiv 2409.11765): grow a
    struggling run into the next pop rung's bucket when capacity frees
    up. Requires the bucket factory to produce
    :class:`~evox_tpu.core.guardrail.GuardedAlgorithm` templates — the
    growth TRIGGER is the wrapper's on-device escalation signal
    (``restarts`` advanced past ``checked_restarts``, optionally a
    stagnation floor), the same rule ``workflows/ipop.py`` doubles on.

    Args:
        stagnation_limit: additionally trigger when a tenant's guarded
            ``stagnation`` counter reaches this (None: restart signal
            only — the IPOP default).
        max_grows: rungs a single run may climb (bounds the compile
            surface the autoscaler can create).
    """

    stagnation_limit: Optional[int] = None
    max_grows: int = 1

    def triggered(self, restarts: int, checked: int, stagnation: int) -> bool:
        trig = restarts > checked
        if self.stagnation_limit is not None:
            trig = trig or stagnation >= self.stagnation_limit
        return trig

    def report(self) -> dict:
        return {
            "stagnation_limit": self.stagnation_limit,
            "max_grows": self.max_grows,
        }


# ----------------------------------------------------------------- server


@dataclasses.dataclass
class ElasticSpec:
    """One elastic search request: any (pop, dim) — the server rounds it
    onto the bucket lattice. ``deadline`` is the SLA bound in the
    bucket's fleet generations (see :class:`~evox_tpu.workflows.tenancy.
    TenantSpec`)."""

    seed: Any
    n_steps: int
    pop: int
    dim: int
    hyperparams: Dict[str, Any] = dataclasses.field(default_factory=dict)
    tag: Optional[str] = None
    deadline: Optional[int] = None


@dataclasses.dataclass
class _Bucket:
    shape: BucketShape
    workflow: ElasticWorkflow
    queue: RunQueue
    fillers: int = 0


class ElasticServer:
    """The elastic serving front end: submit any (pop, dim) search; the
    server buckets it, warms the bucket's executables through the AOT
    cache, pads admission, and drives every bucket's
    :class:`~evox_tpu.workflows.tenancy.RunQueue` (SLA ordering,
    preemption, journal durability included — they are queue features).

    Args:
        factory: ``factory(bucket: BucketShape) -> ElasticWorkflow`` —
            builds the bucket's fleet at the canonical shape. The
            returned workflow must be an :class:`ElasticWorkflow` with
            ``n_tenants == bucket.width`` and the reserved
            ``ACTIVE_ROWS`` hyperparam in its constructor stack (see
            GUIDE.md §6 for the three-line recipe).
        table: the :class:`BucketTable` lattice (default powers of two).
        cache: an :class:`~evox_tpu.core.exec_cache.ExecutableCache`
            (or ``cache_dir`` to build one). A shared on-disk cache is
            what makes a cold process start in milliseconds.
        width: fleet-width request quantized per bucket (how many
            co-resident tenants a bucket serves).
        chunk: generations per dispatch chunk (RunQueue granularity).
        journal_dir / checkpoint_dir: per-bucket subdirectories are
            created under these (``<dir>/<bucket.key>``) — the PR-11
            durability story applies per bucket.
        autoscaler: a :class:`PopAutoscaler`, evaluated after every
            serve round.
        supervisor: optional RunSupervisor shared by every bucket queue.
        strict_after_warm: freeze the cache once a bucket is warmed —
            any later unplanned compile raises
            :class:`~evox_tpu.core.exec_cache.ExecCacheMissError`.
    """

    def __init__(
        self,
        factory: Callable[[BucketShape], ElasticWorkflow],
        table: Optional[BucketTable] = None,
        cache: Optional[ExecutableCache] = None,
        cache_dir: Optional[str] = None,
        width: int = 4,
        chunk: int = 5,
        journal_dir: Optional[str] = None,
        checkpoint_dir: Optional[str] = None,
        autoscaler: Optional[PopAutoscaler] = None,
        supervisor: Any = None,
        strict_after_warm: bool = False,
        metrics: Any = None,
        executor: Any = None,
    ):
        self.factory = factory
        self.table = table if table is not None else BucketTable()
        self.cache = (
            cache
            if cache is not None
            else ExecutableCache(directory=cache_dir)
        )
        self.width = width
        self.chunk = chunk
        self.journal_dir = Path(journal_dir) if journal_dir else None
        self.checkpoint_dir = Path(checkpoint_dir) if checkpoint_dir else None
        self.autoscaler = autoscaler
        self.supervisor = supervisor
        self.strict_after_warm = strict_after_warm
        # one GenerationExecutor can serve every bucket queue (the
        # background lanes are per-instance, so sharing keeps ONE
        # checkpoint lane for the whole server); None lets each RunQueue
        # build its own, as before
        self.executor = executor
        # serving-plane flight recorder (PR 16): ONE recorder spans the
        # whole lattice — threaded into every bucket RunQueue (whose
        # samples then share one SLO ledger across buckets) and the
        # shared executable cache. A str/Path builds a stream-backed
        # recorder; None (default) changes nothing.
        if isinstance(metrics, (str, Path)):
            from .flightrec import FlightRecorder

            metrics = FlightRecorder(directory=str(metrics))
        self.metrics = metrics
        if metrics is not None and getattr(self.cache, "metrics", None) is None:
            self.cache.metrics = metrics
        self._buckets: Dict[str, _Bucket] = {}
        self._filler_seq = 0
        self.autoscale_events: List[dict] = []

    # ------------------------------------------------------------- buckets
    def bucket_for(self, spec: ElasticSpec) -> BucketShape:
        return self.table.bucket_for(spec.pop, spec.dim, self.width)

    def _get_bucket(
        self, shape: BucketShape, recover: bool = False
    ) -> _Bucket:
        b = self._buckets.get(shape.key)
        if b is not None:
            return b
        wf = self.factory(shape)
        if not isinstance(wf, ElasticWorkflow):
            raise TypeError(
                "ElasticServer factory must return an ElasticWorkflow "
                f"(got {type(wf).__name__}) — the padded-admission mask "
                "lives there"
            )
        if wf.n_tenants != shape.width:
            raise ValueError(
                f"factory built a {wf.n_tenants}-wide fleet for bucket "
                f"{shape.key} (width {shape.width})"
            )
        if ACTIVE_ROWS not in wf.hyperparams:
            raise ValueError(
                f"bucket workflow must declare the reserved {ACTIVE_ROWS!r} "
                "hyperparam in its constructor stack (e.g. hyperparams={"
                f"{ACTIVE_ROWS!r}: jnp.full((width,), pop, jnp.int32)}}) — "
                "it carries each tenant's live-row count"
            )
        if self.autoscaler is not None and not hasattr(
            wf.algorithm, "health_report"
        ):
            raise ValueError(
                "PopAutoscaler needs the guarded escalation signal: the "
                "bucket factory must wrap its algorithm in "
                "GuardedAlgorithm (core/guardrail.py)"
            )
        warm_fleet_cache(wf, self.cache, bucket=shape, planned=True)
        wf._bucket_table = self.table  # run_report serving pickup
        if recover:
            # graft a journal-recovered queue in place of a fresh one:
            # same factory/warm/validation path, but the queue's
            # pending/slots/results come back from the bucket's journal
            # (RunQueue.recover — the PR-11 replay law). The multi-pod
            # control plane rebuilds dead or killed pods through this.
            if self.journal_dir is None:
                raise ValueError(
                    "recovering a bucket needs journal_dir — there is "
                    "no journal to replay without one"
                )
            q = RunQueue.recover(
                wf,
                str(self.journal_dir / shape.key),
                supervisor=self.supervisor,
                metrics=self.metrics,
                executor=self.executor,
            )
        else:
            q = RunQueue(
                wf,
                chunk=self.chunk,
                supervisor=self.supervisor,
                journal=(
                    str(self.journal_dir / shape.key)
                    if self.journal_dir is not None
                    else None
                ),
                checkpoint_dir=(
                    str(self.checkpoint_dir / shape.key)
                    if self.checkpoint_dir is not None
                    else None
                ),
                metrics=self.metrics,
                executor=self.executor,
            )
        b = _Bucket(shape=shape, workflow=wf, queue=q)
        self._buckets[shape.key] = b
        if self.strict_after_warm:
            self.cache.freeze()
        return b

    # -------------------------------------------------------------- submit
    def submit(self, spec: ElasticSpec) -> BucketShape:
        """Route a request onto the lattice and queue it in its bucket.
        Admission into an already-warm bucket is state surgery against a
        cached executable — no retrace."""
        shape = self.bucket_for(spec)
        b = self._get_bucket(shape)
        tspec = TenantSpec(
            seed=spec.seed,
            n_steps=spec.n_steps,
            hyperparams={
                **spec.hyperparams,
                ACTIVE_ROWS: jnp.asarray(int(spec.pop), jnp.int32),
            },
            tag=spec.tag,
            pop=shape.pop,
            deadline=spec.deadline,
        )
        b.queue.submit(tspec)
        return shape

    def _filler_spec(self, b: _Bucket) -> TenantSpec:
        """An inert width-padding tenant: full live rows (the mask is an
        identity), one-generation budget, result discarded. Fills the
        fleet to its static width when fewer real tenants are pending —
        the width half of padded admission."""
        self._filler_seq += 1
        b.fillers += 1
        hp0 = {
            name: jnp.asarray(stack[0])
            for name, stack in b.workflow.hyperparams.items()
        }
        hp0[ACTIVE_ROWS] = jnp.asarray(b.shape.pop, jnp.int32)
        return TenantSpec(
            seed=1_000_003 + self._filler_seq,
            n_steps=1,
            hyperparams=hp0,
            tag=f"_pad_{self._filler_seq:04d}",
            pop=b.shape.pop,
        )

    def _ensure_started(self, b: _Bucket) -> None:
        q = b.queue
        if q.state is not None:
            return
        if not q.pending and not q.continuations:
            return
        # continuations fill slots too (start() draws from both): only
        # top up the REAL shortfall, or continuation-fed buckets carry
        # surplus fillers that each cost an admission + serve rounds
        while (
            len(q.pending) + len(q.continuations) < b.workflow.n_tenants
        ):
            q.submit(self._filler_spec(b))
        q.start()

    # --------------------------------------------------------------- serve
    def _has_work(self) -> bool:
        for b in self._buckets.values():
            q = b.queue
            if q.pending or q.continuations:
                return True
            if q.state is not None and not q.finished:
                return True
        return False

    def has_work(self) -> bool:
        """Public face of the scheduling loop's continue condition —
        the multi-pod control plane polls it per pod."""
        return self._has_work()

    def serve_round(self) -> None:
        """ONE scheduling quantum: every bucket with work advances one
        chunk, then the autoscale pass runs. ``serve()`` is this in a
        loop; the multi-pod control plane calls it directly so the
        gateway can interleave rounds across pods (and kill/steal/
        recover between them at chunk granularity)."""
        for b in list(self._buckets.values()):
            self._ensure_started(b)
            q = b.queue
            if q.state is None:
                continue
            if q.finished and not (q.pending or q.continuations):
                continue
            q.step_chunk()
        self._autoscale_pass()

    def serve(self, max_rounds: Optional[int] = None) -> List[dict]:
        """Drive every bucket to completion (round-robin, one chunk per
        bucket per round; autoscale decisions between rounds). Returns
        the merged real-tenant results."""
        rounds = 0
        while self._has_work():
            self.serve_round()
            rounds += 1
            if max_rounds is not None and rounds >= max_rounds:
                break
        return self.results()

    # ------------------------------------------------------------- recover
    def recover_bucket(self, shape: BucketShape) -> "_Bucket":
        """Rebuild one bucket from its journal: the factory re-creates
        the workflow (same validation + cache warm as a fresh bucket),
        then :meth:`RunQueue.recover` replays the bucket's journal to
        the newest intact barrier. Driving the server afterwards
        completes the sweep with per-tenant results identical to the
        uncrashed run — the PR-11 law, lifted to the lattice. Raises if
        the bucket is already live (recovery is for dead processes, not
        running ones)."""
        if shape.key in self._buckets:
            raise RuntimeError(
                f"bucket {shape.key} is already live in this server — "
                "recover_bucket rebuilds dead buckets, it cannot replace "
                "a running queue"
            )
        return self._get_bucket(shape, recover=True)

    # ----------------------------------------------------------- autoscale
    def _autoscale_pass(self) -> None:
        """Grow triggered tenants into the next pop rung's bucket. The
        decision reads the guarded wrapper's on-device counters (one
        tiny per-fleet fetch); the move is the shared IPOP surgery
        (:func:`~evox_tpu.workflows.ipop.grow_guarded`) + a continuation
        submit to the target queue — pure state surgery on both sides,
        journaled on both sides."""
        if self.autoscaler is None:
            return
        for b in list(self._buckets.values()):
            q = b.queue
            if q.state is None:
                continue
            astate = q.state.tenants.algo
            if not hasattr(astate, "restarts"):
                continue
            sig = jax.device_get(
                {
                    "restarts": astate.restarts,
                    "checked": astate.checked_restarts,
                    "stagnation": astate.stagnation,
                }
            )
            for i, slot in enumerate(q.slots):
                if slot is None or not slot.active or slot.frozen:
                    continue
                spec = slot.spec
                if (spec.tag or "").startswith("_pad_"):
                    continue
                grows = getattr(spec, "_elastic_grows", 0)
                if grows >= self.autoscaler.max_grows:
                    continue
                if not self.autoscaler.triggered(
                    int(sig["restarts"][i]),
                    int(sig["checked"][i]),
                    int(sig["stagnation"][i]),
                ):
                    continue
                new_pop = self.table.next_pop_rung(b.shape.pop)
                if new_pop is None:
                    continue
                target_shape = BucketShape(
                    pop=new_pop, dim=b.shape.dim, width=b.shape.width
                )
                tb = self._get_bucket(target_shape)
                if not self._has_capacity(tb):
                    continue
                self._grow(b, i, tb, grows)

    @staticmethod
    def _has_capacity(tb: _Bucket) -> bool:
        """'When slots free up': an unstarted bucket always has room; a
        started one needs a parked (inactive, unfrozen) slot and an
        empty pending queue that would otherwise claim it."""
        q = tb.queue
        if q.state is None:
            return True
        if q.pending or q.continuations:
            return False
        return any(
            s is None or (not s.active and not s.frozen) for s in q.slots
        )

    def _grow(
        self, b: _Bucket, index: int, tb: _Bucket, grows: int
    ) -> None:
        from .checkpoint import WorkflowCheckpointer
        from .ipop import grow_guarded

        q, twf = b.queue, tb.workflow
        slot = q.slots[index]
        spec = slot.spec
        # 1) build the grown tenant at the target rung: fresh init from
        #    the tenant's deterministic growth stream, re-centered on
        #    the old best, counters carried (the IPOP surgery — pure in
        #    pop_size, so recovery re-derives the same state from the
        #    same spec + old snapshot). The source slot is closed out
        #    LAST: the WAL discipline demands the continuation be
        #    durable in the target journal BEFORE the source journal
        #    retires the tenant, or a crash between the two appends
        #    loses acknowledged work (duplicates heal — recovery dedups
        #    continuations by parked checkpoint — lost work cannot)
        old = jax.device_get(
            jax.tree.map(lambda x: x[index], q.state.tenants)
        )
        hp2 = {
            **spec.hyperparams,
            ACTIVE_ROWS: jnp.asarray(tb.shape.pop, jnp.int32),
        }
        fresh = twf.init_tenant(
            jax.random.fold_in(spec.key(), grows + 1), hp2
        )
        fresh = fresh.replace(algo=grow_guarded(fresh.algo, old.algo))
        if twf.algorithm.has_init_ask or twf.algorithm.has_init_tell:
            # algorithms with a distinct first generation peel it SOLO
            # at the target rung AFTER the re-center (the _fresh_tenant
            # admission law; ipop_run's first_step=True analog) — the
            # steady vmapped step must never ingest fitness against an
            # un-initialized archive/parent state
            fresh = twf._solo_peel(fresh)
        # monitor state may be POP-SHAPED (EvalMonitor's (K, pop)
        # history ring): it cannot cross a rung — carry the ring only
        # when its shapes are pop-independent, else keep the target
        # rung's fresh monitors and say so (losing ring continuity must
        # not kill the serve sweep)
        def _sig(t):
            return [
                (getattr(x, "shape", ()), getattr(x, "dtype", None))
                for x in jax.tree.leaves(t)
            ]

        if _sig(old.monitors) == _sig(fresh.monitors):
            mon2 = old.monitors  # ring continuity across the rung
        else:
            warnings.warn(
                f"autoscale growth {b.shape.key} -> {tb.shape.key}: "
                "monitor state is population-shaped and cannot cross "
                "the rung; the grown tenant starts a fresh ring "
                "(telemetry continuity lost for this tenant)"
            )
            mon2 = fresh.monitors
        grown = fresh.replace(
            generation=jnp.asarray(old.generation, jnp.int32),
            monitors=mon2,
        )
        # 2) durable continuation + admit in the TARGET queue. Deadlines
        #    are measured on the OWNING queue's fleet clock: carry the
        #    REMAINING slack onto the target clock, never the raw number
        #    (a fresh bucket would grant ~source_gen extra slack, an old
        #    one would mark an on-schedule run doomed on arrival);
        #    clamped to the submit-time feasibility floor (n_steps)
        deadline2 = spec.deadline
        if deadline2 is not None:
            sgen = int(q.state.generation)
            tgen = (
                int(tb.queue.state.generation)
                if tb.queue.state is not None
                else 0
            )
            deadline2 = max(tgen + (spec.deadline - sgen), spec.n_steps)
        spec2 = dataclasses.replace(
            spec,
            pop=tb.shape.pop,
            hyperparams=hp2,
            deadline=deadline2,
        )
        spec2._elastic_grows = grows + 1
        cont_dir = None
        if tb.queue.checkpoint_dir is not None:
            cont_dir = Path(tb.queue.checkpoint_dir) / (
                f"{spec.tag or 'tenant'}_grown{grows + 1}"
            )
            ckpt = WorkflowCheckpointer(
                str(cont_dir),
                every=max(int(old.generation), 1),
                keep=tb.queue.keep,
            )
            from .std import StdWorkflowState

            ckpt.save(
                StdWorkflowState(
                    generation=grown.generation,
                    algo=grown.algo,
                    prob=grown.prob,
                    monitors=grown.monitors,
                    first_step=False,
                )
            )
        tb.queue.submit_resume(
            spec2,
            checkpoint=str(cont_dir) if cont_dir is not None else None,
            state=grown,
            done=int(old.generation),
        )
        # 3) only NOW close the source slot out (forensic checkpoint +
        #    source-journal `autoscale` record + refill): the handoff is
        #    already durable on the target side
        q.counters["grown"] = q.counters.get("grown", 0) + 1
        entry = q._close_out(index, status="grown")
        if self.metrics is not None:
            self.metrics.count("elastic.grows")
            self.metrics.event(
                "elastic.grow",
                tag=spec.tag,
                from_bucket=b.shape.key,
                to_bucket=tb.shape.key,
            )
        self.autoscale_events.append(
            {
                "tag": spec.tag,
                "from": b.shape.key,
                "to": tb.shape.key,
                "generation": int(old.generation),
                "grows": grows + 1,
                "source_entry": {
                    k: entry.get(k) for k in ("status", "generations")
                },
            }
        )

    # -------------------------------------------------------------- results
    def results(self) -> List[dict]:
        """Merged per-tenant results across buckets, filler tenants
        dropped, each entry annotated with its bucket key."""
        out = []
        for key, b in self._buckets.items():
            for r in b.queue.results:
                if (r.get("tag") or "").startswith("_pad_"):
                    continue
                out.append({**r, "bucket": key})
        return out

    def report(self) -> dict:
        """The server-level serving summary: the lattice, per-bucket
        queue reports, autoscale events, and the shared cache."""
        return {
            "table": self.table.report(),
            "buckets": {
                key: b.queue.report() for key, b in self._buckets.items()
            },
            "autoscale": {
                "policy": (
                    self.autoscaler.report()
                    if self.autoscaler is not None
                    else None
                ),
                "events": list(self.autoscale_events),
            },
            "cache": self.cache.report(),
        }
