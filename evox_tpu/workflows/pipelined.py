"""Overlapped driver loop for host (non-jittable) problems.

The reference's Ray workflow gains throughput from its async dispatch
queue (reference workflows/distributed.py:361-369): the driver processes
monitor output while the workers' ``tell`` (step2) futures are still in
flight. This module is the single-process TPU-native analog for
``StdWorkflow`` with an external problem:

- the device ``tell``/``ask`` work is *dispatched* asynchronously (JAX's
  async dispatch) and computes while the host thread hands the next
  candidate batch to the rollout pool;
- the host problem's ``evaluate`` for generation ``g+1`` runs in a worker
  thread concurrently with the user's per-generation host work
  (``on_generation``: logging, plotting, metric computation, checkpoint
  saves) for generation ``g`` — the two dominant host-side costs overlap
  instead of serializing.

The data-dependency chain eval -> tell -> ask -> eval is untouched, so
results are bit-identical to ``wf.step`` loops (asserted in
tests/test_pipelined.py); only wall-clock changes. For jittable problems
use ``wf.run`` — a fused device loop beats any host pipelining.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Optional

import jax
import numpy as np

from .checkpoint import WorkflowCheckpointer, _as_checkpointer, resolve_resume


def chunked_evaluate(problem, pstate, cand, eval_chunk: Optional[int]):
    """``problem.evaluate`` over row slices of at most ``eval_chunk``
    candidates, fitness concatenated — the degradation the supervisor
    applies when a full-batch host evaluation dies with OOM / HTTP 413
    (CLAUDE.md: big tunneled payloads are the 413 trigger).

    Bit-equivalence contract: chunking is invisible exactly when the
    host ``evaluate`` scores rows independently of their batch (true for
    deterministic per-candidate problems; NOT for farms that draw one
    seed per evaluate() CALL — those re-seed per chunk, see GUIDE.md §6).
    The problem state threads through the chunks in order and the LAST
    chunk's returned state is kept, matching the unchunked call for
    pass-through states."""
    if eval_chunk is None:
        return problem.evaluate(pstate, cand)
    leaves = jax.tree.leaves(cand)
    n = leaves[0].shape[0]
    if eval_chunk < 1:
        raise ValueError(f"eval_chunk must be >= 1, got {eval_chunk}")
    if eval_chunk >= n:
        return problem.evaluate(pstate, cand)
    fits = []
    for lo in range(0, n, eval_chunk):
        hi = min(lo + eval_chunk, n)
        part = jax.tree.map(lambda x: x[lo:hi], cand)
        fit, pstate = problem.evaluate(pstate, part)
        fits.append(np.asarray(fit))
    return np.concatenate(fits, axis=0), pstate


def run_host_pipelined(
    wf,
    state,
    n_steps: int,
    on_generation: Optional[Callable[[int, Any, jax.Array], None]] = None,
    checkpointer: Optional[WorkflowCheckpointer] = None,
    resume_from: Any = None,
    restarts: Any = None,
    eval_chunk: Optional[int] = None,
):
    """Run ``n_steps`` generations of ``wf`` (a :class:`StdWorkflow` whose
    problem is external/host-side), overlapping host evaluation with
    device dispatch and with ``on_generation(gen_index, state, fitness)``
    host work of the previous generation. Returns the final state —
    identical to ``for _ in range(n_steps): state = wf.step(state)``.

    Crash safety: ``checkpointer=`` snapshots the state whenever
    ``state.generation`` crosses a multiple of its cadence (host-side,
    between dispatches — the next generation's evaluate is already in
    flight while the snapshot pickles, and the final state is always
    snapshotted). ``resume_from=`` (a
    :class:`~evox_tpu.workflows.checkpoint.WorkflowCheckpointer` or a
    directory) restores the newest intact snapshot and reinterprets
    ``n_steps`` as the TOTAL generation target. Note the snapshot holds
    only the workflow-state pytree: a host problem that draws
    per-generation seeds from its own RNG (the rollout farms) re-seeds
    fresh after a resume — resume bit-equivalence holds for host problems
    whose evaluate is deterministic (see GUIDE.md §6).

    Observability: ``instrument(wf)`` covers this loop — it wraps
    ``wf.pipeline_ask``/``wf.pipeline_tell``, which this driver calls
    through the workflow object, so per-half dispatch timings, retrace
    flags, and (with ``analyze=True``) the AOT roofline of both jitted
    halves land in ``run_report()`` exactly as for ``wf.run``; a
    :class:`~evox_tpu.problems.neuroevolution.process_farm.
    ProcessRolloutFarm` problem additionally contributes worker-health
    counter tracks to ``write_chrome_trace(extra_counters=
    farm.counter_tracks())``.

    ``eval_chunk=``: evaluate the candidate batch in host-side row
    slices of at most this many candidates (see :func:`chunked_evaluate`
    for the bit-equivalence contract) — the payload-size degradation the
    :class:`~evox_tpu.workflows.supervisor.RunSupervisor` halves on
    OOM / HTTP 413, also usable directly to keep tunneled request sizes
    bounded.
    """
    if not wf.external:
        raise ValueError(
            "run_host_pipelined is for external (host) problems; jittable "
            "problems should use wf.run()'s fused device loop"
        )
    if restarts is not None:
        # host-boundary IPOP (workflows/ipop.py): chunk the pipelined loop
        # at the policy cadence; each chunk is a plain pipelined run, the
        # doubling decision happens between chunks on the guarded counters
        from .ipop import ipop_run

        return ipop_run(
            wf,
            state,
            n_steps,
            restarts,
            segment=lambda w, s, c, ck: run_host_pipelined(
                w, s, c, on_generation=on_generation, checkpointer=ck,
                eval_chunk=eval_chunk,
            ),
            checkpointer=checkpointer,
            resume_from=resume_from,
        )
    if resume_from is not None:
        # expect_like=state: refuse a snapshot from a different config
        state, n_steps = resolve_resume(
            resume_from, state, n_steps, expect_like=state
        )
        if checkpointer is None:
            # a resumed run must stay crash-safe (and must record its own
            # completion, or a second resume would re-run generations):
            # default to checkpointing into the directory we resumed from,
            # the same policy as StdWorkflow.resume()
            checkpointer = _as_checkpointer(resume_from)
    if n_steps <= 0:
        # nothing left to run (e.g. resuming an already-complete run) —
        # return BEFORE dispatching ask/eval: a stray background evaluate
        # would waste a full generation and race the caller on the
        # problem's sockets/state
        return state
    # on_generation receives the GLOBAL 0-based generation index (loop
    # offset + the state's generation at entry), so logs and metric sinks
    # stay consistent when a run is resumed mid-way instead of restarting
    # from 0 (identical to the old loop index for fresh states)
    gen0 = int(state.generation)
    eval_pool = ThreadPoolExecutor(max_workers=1)
    hook_pool = ThreadPoolExecutor(max_workers=1)
    try:
        cand, ctx = wf.pipeline_ask(state)
        fut = eval_pool.submit(
            chunked_evaluate, wf.problem, state.prob, cand, eval_chunk
        )
        hook_fut = None
        for g in range(n_steps):
            fitness, _ = fut.result()
            if hook_fut is not None:
                # surface on_generation errors from generation g-1 BEFORE
                # advancing the state or submitting generation g+1's eval
                # (the hook still overlapped generation g's evaluate, which
                # just completed above — the dominant host-side cost)
                hook_fut.result()
                hook_fut = None
            # discard the problem's returned state, exactly like the
            # wf.step external path does (common.py callback_evaluate):
            # host problems keep generation-to-generation state host-side
            state = wf.pipeline_tell(state, ctx, fitness, state.prob)
            if g + 1 < n_steps:
                # async dispatch: returns while the device still computes;
                # the eval thread blocks on cand materialization, not us
                cand, ctx = wf.pipeline_ask(state)
                fut = eval_pool.submit(
                    chunked_evaluate, wf.problem, state.prob, cand, eval_chunk
                )
            if checkpointer is not None:
                # between dispatches: the next eval is already in flight
                # and the state is immutable, so the snapshot only costs
                # the device->host copy at the checkpoint cadence
                checkpointer.maybe_save(state)
            if on_generation is not None:
                hook_fut = hook_pool.submit(
                    on_generation, gen0 + g, state, fitness
                )
        if hook_fut is not None:
            hook_fut.result()
        if checkpointer is not None:
            if int(state.generation) % checkpointer.every != 0:
                checkpointer.save(state)  # final state is always durable
        return state
    finally:
        eval_pool.shutdown(wait=False)
        hook_pool.shutdown(wait=False)
