"""Overlapped driver loop for host (non-jittable) problems.

The reference's Ray workflow gains throughput from its async dispatch
queue (reference workflows/distributed.py:361-369): the driver processes
monitor output while the workers' ``tell`` (step2) futures are still in
flight. This module is the single-process TPU-native analog for
``StdWorkflow`` with an external problem:

- the device ``tell``/``ask`` work is *dispatched* asynchronously (JAX's
  async dispatch) and computes while the host thread hands the next
  candidate batch to the rollout pool;
- the host problem's ``evaluate`` for generation ``g+1`` runs in a worker
  thread concurrently with the user's per-generation host work
  (``on_generation``: logging, plotting, metric computation, checkpoint
  saves) for generation ``g`` — the two dominant host-side costs overlap
  instead of serializing.

The data-dependency chain eval -> tell -> ask -> eval is untouched, so
results are bit-identical to ``wf.step`` loops (asserted in
tests/test_pipelined.py); only wall-clock changes. For jittable problems
use ``wf.run`` — a fused device loop beats any host pipelining.

Since PR 8 the loop itself lives in
:class:`~evox_tpu.core.executor.GenerationExecutor` (one executor, five
policies — see GUIDE.md §6): this module keeps the host-problem policy
entry point (``run_host_pipelined``), the IPOP recursion, and
``chunked_evaluate``, and adds the opt-in ``max_staleness=K`` stale-tell
mode the executor implements.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.executor import GenerationExecutor
from .checkpoint import WorkflowCheckpointer


def chunked_evaluate(problem, pstate, cand, eval_chunk: Optional[int]):
    """``problem.evaluate`` over row slices of at most ``eval_chunk``
    candidates, fitness concatenated — the degradation the supervisor
    applies when a full-batch host evaluation dies with OOM / HTTP 413
    (CLAUDE.md: big tunneled payloads are the 413 trigger).

    Bit-equivalence contract: chunking is invisible exactly when the
    host ``evaluate`` scores rows independently of their batch (true for
    deterministic per-candidate problems; NOT for farms that draw one
    seed per evaluate() CALL — those re-seed per chunk, see GUIDE.md §6).
    The problem state threads through the chunks in order and the LAST
    chunk's returned state is kept, matching the unchunked call for
    pass-through states.

    Return contract: device/dtype-consistent with the unchunked path. A
    problem returning device arrays gets a device concatenation (the
    old code forced every chunk to host via ``np.asarray`` and returned
    NumPy fitness while the unchunked path returned whatever ``evaluate``
    produced — a silent device→host→device round trip per chunk on the
    tunnel); a NumPy-returning host problem still gets NumPy. The caller
    (``pipeline_tell``) accepts either — nothing fetches until someone
    actually needs host values."""
    if eval_chunk is None:
        return problem.evaluate(pstate, cand)
    leaves = jax.tree.leaves(cand)
    n = leaves[0].shape[0]
    if eval_chunk < 1:
        raise ValueError(f"eval_chunk must be >= 1, got {eval_chunk}")
    if eval_chunk >= n:
        return problem.evaluate(pstate, cand)
    fits = []
    for lo in range(0, n, eval_chunk):
        hi = min(lo + eval_chunk, n)
        part = jax.tree.map(lambda x: x[lo:hi], cand)
        fit, pstate = problem.evaluate(pstate, part)
        fits.append(fit)
    if any(isinstance(f, jax.Array) for f in fits):
        # mirror the unchunked path's device residency: concatenate on
        # device instead of round-tripping every chunk through the host
        return jnp.concatenate([jnp.asarray(f) for f in fits], axis=0), pstate
    return np.concatenate([np.asarray(f) for f in fits], axis=0), pstate


def run_host_pipelined(
    wf,
    state,
    n_steps: int,
    on_generation: Optional[Callable[[int, Any, jax.Array], None]] = None,
    checkpointer: Optional[WorkflowCheckpointer] = None,
    resume_from: Any = None,
    restarts: Any = None,
    eval_chunk: Optional[int] = None,
    max_staleness: Optional[int] = None,
    executor: Optional[GenerationExecutor] = None,
):
    """Run ``n_steps`` generations of ``wf`` (a :class:`StdWorkflow` whose
    problem is external/host-side), overlapping host evaluation with
    device dispatch and with ``on_generation(gen_index, state, fitness)``
    host work of the previous generation. Returns the final state —
    identical to ``for _ in range(n_steps): state = wf.step(state)``.

    Crash safety: ``checkpointer=`` snapshots the state whenever
    ``state.generation`` crosses a multiple of its cadence (host-side,
    between dispatches — the next generation's evaluate is already in
    flight while the snapshot pickles, and the final state is always
    snapshotted). ``resume_from=`` (a
    :class:`~evox_tpu.workflows.checkpoint.WorkflowCheckpointer` or a
    directory) restores the newest intact snapshot and reinterprets
    ``n_steps`` as the TOTAL generation target. Note the snapshot holds
    only the workflow-state pytree: a host problem that draws
    per-generation seeds from its own RNG (the rollout farms) re-seeds
    fresh after a resume — resume bit-equivalence holds for host problems
    whose evaluate is deterministic (see GUIDE.md §6).

    Observability: ``instrument(wf)`` covers this loop — it wraps
    ``wf.pipeline_ask``/``wf.pipeline_tell``, which this driver calls
    through the workflow object, so per-half dispatch timings, retrace
    flags, and (with ``analyze=True``) the AOT roofline of both jitted
    halves land in ``run_report()`` exactly as for ``wf.run``; a
    :class:`~evox_tpu.problems.neuroevolution.process_farm.
    ProcessRolloutFarm` problem additionally contributes worker-health
    counter tracks to ``write_chrome_trace(extra_counters=
    farm.counter_tracks())``.

    ``eval_chunk=``: evaluate the candidate batch in host-side row
    slices of at most this many candidates (see :func:`chunked_evaluate`
    for the bit-equivalence contract) — the payload-size degradation the
    :class:`~evox_tpu.workflows.supervisor.RunSupervisor` halves on
    OOM / HTTP 413, also usable directly to keep tunneled request sizes
    bounded.

    ``max_staleness=K`` (opt-in; ``None`` — the default — defers to the
    passed ``executor``'s configured bound, else 0): admit tells up to
    ``K`` generations stale — up to ``K+1`` host evaluations in flight, each
    tell grafted onto the newest told state with its own matched
    (ask-artifacts, fitness) pair (stale-gradient ES; see
    :class:`~evox_tpu.core.executor.GenerationExecutor`). ``K=0``
    stays bit-identical to a ``wf.step`` loop; ``K>0`` trades
    per-update freshness for throughput when host evaluations can run
    concurrently and is gated by convergence tests, not equivalence.

    ``executor=``: the :class:`~evox_tpu.core.executor.
    GenerationExecutor` to drive (counters/overlap spans accumulate on
    it and surface in ``run_report()["executor"]``); a private default
    executor is created per call otherwise.
    """
    if not wf.external:
        raise ValueError(
            "run_host_pipelined is for external (host) problems; jittable "
            "problems should use wf.run()'s fused device loop"
        )
    if restarts is not None:
        # host-boundary IPOP (workflows/ipop.py): chunk the pipelined loop
        # at the policy cadence; each chunk is a plain pipelined run, the
        # doubling decision happens between chunks on the guarded counters
        from .ipop import ipop_run

        return ipop_run(
            wf,
            state,
            n_steps,
            restarts,
            segment=lambda w, s, c, ck: run_host_pipelined(
                w, s, c, on_generation=on_generation, checkpointer=ck,
                eval_chunk=eval_chunk, max_staleness=max_staleness,
                executor=executor,
            ),
            checkpointer=checkpointer,
            resume_from=resume_from,
        )
    ex = executor if executor is not None else GenerationExecutor(
        max_staleness=max_staleness or 0
    )
    # the executor owns the loop (double-buffered dispatch, background
    # checkpoint/hook lanes, resume resolution, stale window); this
    # function is the host-problem POLICY entry point kept for API
    # stability and the IPOP recursion above
    return ex.run_host(
        wf,
        state,
        n_steps,
        on_generation=on_generation,
        checkpointer=checkpointer,
        resume_from=resume_from,
        eval_chunk=eval_chunk,
        max_staleness=max_staleness,
    )
