"""Overlapped driver loop for host (non-jittable) problems.

The reference's Ray workflow gains throughput from its async dispatch
queue (reference workflows/distributed.py:361-369): the driver processes
monitor output while the workers' ``tell`` (step2) futures are still in
flight. This module is the single-process TPU-native analog for
``StdWorkflow`` with an external problem:

- the device ``tell``/``ask`` work is *dispatched* asynchronously (JAX's
  async dispatch) and computes while the host thread hands the next
  candidate batch to the rollout pool;
- the host problem's ``evaluate`` for generation ``g+1`` runs in a worker
  thread concurrently with the user's per-generation host work
  (``on_generation``: logging, plotting, metric computation, checkpoint
  saves) for generation ``g`` — the two dominant host-side costs overlap
  instead of serializing.

The data-dependency chain eval -> tell -> ask -> eval is untouched, so
results are bit-identical to ``wf.step`` loops (asserted in
tests/test_pipelined.py); only wall-clock changes. For jittable problems
use ``wf.run`` — a fused device loop beats any host pipelining.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Optional

import jax


def run_host_pipelined(
    wf,
    state,
    n_steps: int,
    on_generation: Optional[Callable[[int, Any, jax.Array], None]] = None,
):
    """Run ``n_steps`` generations of ``wf`` (a :class:`StdWorkflow` whose
    problem is external/host-side), overlapping host evaluation with
    device dispatch and with ``on_generation(gen_index, state, fitness)``
    host work of the previous generation. Returns the final state —
    identical to ``for _ in range(n_steps): state = wf.step(state)``.
    """
    if not wf.external:
        raise ValueError(
            "run_host_pipelined is for external (host) problems; jittable "
            "problems should use wf.run()'s fused device loop"
        )
    eval_pool = ThreadPoolExecutor(max_workers=1)
    hook_pool = ThreadPoolExecutor(max_workers=1)
    try:
        cand, ctx = wf.pipeline_ask(state)
        fut = eval_pool.submit(wf.problem.evaluate, state.prob, cand)
        hook_fut = None
        for g in range(n_steps):
            fitness, _ = fut.result()
            if hook_fut is not None:
                # surface on_generation errors from generation g-1 BEFORE
                # advancing the state or submitting generation g+1's eval
                # (the hook still overlapped generation g's evaluate, which
                # just completed above — the dominant host-side cost)
                hook_fut.result()
                hook_fut = None
            # discard the problem's returned state, exactly like the
            # wf.step external path does (common.py callback_evaluate):
            # host problems keep generation-to-generation state host-side
            state = wf.pipeline_tell(state, ctx, fitness, state.prob)
            if g + 1 < n_steps:
                # async dispatch: returns while the device still computes;
                # the eval thread blocks on cand materialization, not us
                cand, ctx = wf.pipeline_ask(state)
                fut = eval_pool.submit(wf.problem.evaluate, state.prob, cand)
            if on_generation is not None:
                hook_fut = hook_pool.submit(on_generation, g, state, fitness)
        if hook_fut is not None:
            hook_fut.result()
        return state
    finally:
        eval_pool.shutdown(wait=False)
        hook_pool.shutdown(wait=False)
