"""Multi-tenant run serving: vmapped strategy fleets and a RunQueue.

The "millions of users" workload (ROADMAP north star) is thousands of
*small independent searches*, not one big one — and a Python loop of
solo :class:`~evox_tpu.workflows.std.StdWorkflow` runs pays a dispatch,
a compile cache lookup, and (on the tunneled axon backend) a 45-100 ms
round-trip PER RUN PER CHUNK. evosax (PAPERS.md, arXiv 2212.04180)
proved the fix for JAX ES: ``vmap`` whole strategies so N runs become
ONE fused XLA program; Fiber (PAPERS.md) showed population-of-runs
serving is the shape PBT/RL fleets need. evox_tpu's frozen-``PyTreeNode``
states stack trivially under ``vmap``, so this module makes fleets a
first-class workflow:

- :class:`VectorizedWorkflow` — N instances of the SAME algorithm class
  (stacked hyperparameters, seeds, and per-tenant problem states with a
  shared shape) vmapped into one jitted ``step`` and one fused ``run``
  dispatch. Reuses the existing machinery wholesale: the
  ``make_run_loop``/``fused_run`` fori-loop (one compile covers every
  trip count, carry donation via ``donate_carries=``), ``DtypePolicy``
  bf16 storage, ``quarantine_nonfinite``, monitors (vmapped per-tenant
  rings), checkpointer/supervisor chunking, and ``GuardedAlgorithm``
  (the wrapper's ask/tell vmap like any algorithm's).
- A (TENANT, POP) 2-D mesh layout: the per-field
  ``field(sharding=...)`` annotations are reused unchanged —
  ``constrain_state(axis_prefix=TENANT_AXIS)`` shifts each spec one
  axis right under the tenant axis (``P("pop")`` → ``P("tenant",
  "pop")``, ``P()`` → ``P("tenant")``), and regex ``rules=`` (the
  ``match_partition_rules`` pattern, SNIPPETS.md [2]) override leaves
  the annotations don't describe. No reference analog; this is the
  refactor unlock for ROADMAP items 4 (tenants × big pops) and 5 (PBT).
- :class:`RunQueue` — the service layer on top: submit
  :class:`TenantSpec` jobs beyond the fleet capacity, run in supervised
  dispatch chunks (:class:`~evox_tpu.workflows.supervisor.RunSupervisor`
  deadlines/retry/restore apply to the whole fleet dispatch), retire
  tenants when their generation budget completes, admit pending specs
  into the freed slot WITHOUT recompiling (state surgery at fixed
  shapes), and evict mid-run — an eviction yields a single-tenant
  checkpoint that a solo ``StdWorkflow`` resumes
  (:meth:`VectorizedWorkflow.extract_tenant` /
  :meth:`VectorizedWorkflow.solo_workflow`).

Correctness contract (tests/test_tenancy.py): tenant ``i`` of a fleet
reproduces a solo run of the same (algorithm, seed, hyperparams) —
bitwise where vmap preserves XLA codegen, else within a documented
tolerance (vmap batches matmuls/reductions, which can re-associate at
the last ulp) plus the standard convergence-threshold gates; an evicted
tenant's checkpoint resumed solo reproduces the remaining trajectory;
supervisor chaos laws (retry/restore are replays of immutable states)
hold through the fleet path.

Scope: fleets require a JITTABLE problem (a host-callback ``evaluate``
cannot run under ``vmap``; serve host problems with
``run_host_pipelined`` per run, or wrap them jittable). Hyperparameters
are bound as attributes on a shallow copy of the template algorithm
inside the traced step, so only values the algorithm reads in
``init``/``ask``/``tell`` can vary per tenant — derived quantities baked
at construction (optax optimizer closures, CMA recombination weights)
do not re-derive; shapes (``pop_size``, ``dim``) must be shared.
"""

from __future__ import annotations

import copy
import dataclasses
from functools import partial
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as _SpecP

from ..core.algorithm import Algorithm
from ..core.attest import IntegrityError
from ..core.distributed import (
    POP_AXIS as _POP,
    TENANT_AXIS as _TENANT,
    constrain_state,
)
from ..core.dtype_policy import DtypePolicy, apply_compute, apply_storage
from ..core.monitor import Monitor
from ..core.problem import Problem
from ..core.struct import PyTreeNode, field, static_field
from ..utils.common import parse_opt_direction
from .checkpoint import (
    CheckpointConfigError,
    WorkflowCheckpointer,
    checkpointed_run,
    enter_run,
)
from .common import (
    build_hook_table,
    fused_run,
    make_run_loop,
    quarantine_nonfinite,
    run_hooks,
)
from .std import StdWorkflow, StdWorkflowState

__all__ = [
    "TenantState",
    "VectorizedWorkflow",
    "VectorizedWorkflowState",
    "TenantSpec",
    "RunQueue",
]


class TenantState(PyTreeNode):
    """One tenant's slice of the fleet (every leaf is tenant-stacked in
    the live :class:`VectorizedWorkflowState`). Mirrors
    ``StdWorkflowState``'s (generation, algo, prob, monitors) plus the
    tenant's traced hyperparameter bindings. ``generation`` is the
    tenant's OWN counter — it differs from the fleet's lockstep counter
    for tenants a RunQueue admitted mid-run, and it is what generation-
    gated monitor hooks and eviction checkpoints see."""

    generation: jax.Array = field(sharding=_SpecP())
    algo: Any = None
    prob: Any = None
    monitors: Tuple[Any, ...] = ()
    hyperparams: Dict[str, Any] = field(default_factory=dict)


class VectorizedWorkflowState(PyTreeNode):
    generation: jax.Array  # scalar: the fleet steps in lockstep
    tenants: TenantState  # leaves carry a leading (n_tenants,) axis
    # optional (n_tenants,) bool mask: a frozen tenant's post-tell state
    # is discarded via an elementwise where-select inside the fused step,
    # so a poisoned slot stops advancing WITHOUT surgery or recompile
    # (FleetHealthPolicy's "freeze" action, workflows/fleet_health.py).
    # None (the default) compiles the step without the select at all —
    # pre-policy fleets keep their exact program; materializing the mask
    # later changes the carry structure (one designed retrace)
    frozen: Any = field(sharding=_SpecP(), default=None)
    first_step: bool = static_field(default=True)


def bind_hyperparams(template: Any, hp: Dict[str, Any]) -> Any:
    """A shallow copy of ``template`` with ``hp``'s (possibly dotted)
    attribute paths bound as TRACED values — the one hyperparameter-
    binding law shared by the vmapped tenant fleet (each tenant's slice
    under vmap) and the multi-level ES's jitted inner halves (each
    group's proposal as a jit operand). Dotted paths copy-on-write each
    intermediate object, so a ``GuardedAlgorithm``'s inner algorithm is
    copied before its attribute is rebound; the template itself is never
    mutated."""
    if not hp:
        return template
    root = copy.copy(template)
    fresh: Dict[str, Any] = {}
    for name, value in hp.items():
        obj = root
        parts = name.split(".")
        for depth, part in enumerate(parts[:-1]):
            prefix = ".".join(parts[: depth + 1])
            child = fresh.get(prefix)
            if child is None:
                child = copy.copy(getattr(obj, part))
                fresh[prefix] = child
                setattr(obj, part, child)
            obj = child
        setattr(obj, parts[-1], value)
    return root


def _tenant_keys(key: jax.Array, n: int) -> jax.Array:
    """Accept one key (split per tenant) or an already-stacked (n, ...)
    key batch — the stacked form is how fleet-vs-solo equivalence tests
    hand tenant ``i`` exactly the key its solo run would get."""
    key = jnp.asarray(key)
    typed = jnp.issubdtype(key.dtype, jax.dtypes.prng_key)
    if (typed and key.ndim >= 1) or (not typed and key.ndim >= 2):
        if key.shape[0] != n:
            raise ValueError(
                f"stacked key batch has leading axis {key.shape[0]}, "
                f"expected n_tenants={n}"
            )
        return key
    return jax.random.split(key, n)


class VectorizedWorkflow:
    """Vmap N instances of one algorithm class into ONE fused dispatch.

    Args:
        algorithm: the template :class:`Algorithm`. Static shape
            hyperparameters (``pop_size``, ``dim``) are shared by every
            tenant; per-tenant variation comes from ``hyperparams`` and
            the per-tenant PRNG keys.
        problem: a JITTABLE :class:`Problem`, shared evaluate; each
            tenant gets its own problem STATE (vmapped ``init``), so
            keyed/stochastic problems differ per tenant.
        n_tenants: fleet width. Static — a different width is a new
            compiled program (exactly like a different pop_size).
        hyperparams: ``{name: stacked_value}`` — each value's leading
            axis is ``n_tenants`` and ``name`` is an attribute (or
            dotted path, e.g. ``"algorithm.noise_stdev"`` through a
            :class:`~evox_tpu.core.guardrail.GuardedAlgorithm`) on the
            template. Inside the traced step each tenant's slice is
            bound onto a shallow copy of the template, so the value
            flows through the tenant's ``init``/``ask``/``tell`` math
            as a traced operand. Only attributes the algorithm READS in
            those methods take effect (constructor-derived closures,
            e.g. an optax optimizer's baked learning rate, do not).
        monitors: shared monitor OBJECTS whose states are vmapped —
            each tenant gets its own TelemetryMonitor ring / EvalMonitor
            device archive. Monitors that stream through host callbacks
            (CheckpointMonitor, StepTimerMonitor, PopMonitor,
            EvoXVisMonitor, EvalMonitor full histories) are REJECTED at
            construction — a callback cannot run inside the vmapped
            step on any backend.
        opt_direction / pop_transforms / fit_transforms /
        quarantine_nonfinite: as :class:`StdWorkflow`, applied PER
            TENANT (a rank transform ranks within each tenant's batch).
        mesh: a mesh carrying a ``"tenant"`` axis (and usually a
            ``"pop"`` axis): ``create_mesh((TENANT_AXIS, POP_AXIS),
            shape=(t, p))``. Tenant-stacked state lays out by the
            per-field annotations shifted under the tenant axis
            (``constrain_state(axis_prefix=TENANT_AXIS)``); candidates
            and fitness are sharded ``P(TENANT_AXIS, POP_AXIS)`` /
            ``P(TENANT_AXIS)``.
        rules: optional ``[(regex, PartitionSpec), ...]`` overriding the
            annotation-derived layout per leaf path
            (:func:`~evox_tpu.core.distributed.match_partition_rules`
            semantics; matched against the TENANT-STACKED state's key
            paths, e.g. ``r"\\.algo\\.population$"``).
        dtype_policy / donate_carries / jit_step: as
            :class:`StdWorkflow` — the policy's storage downcast and
            the donated fused-run carry apply to the whole stacked
            state (the bytes win multiplies by N).
    """

    def __init__(
        self,
        algorithm: Algorithm,
        problem: Problem,
        n_tenants: int,
        hyperparams: Optional[Dict[str, Any]] = None,
        monitors: Sequence[Monitor] = (),
        opt_direction: Any = "min",
        pop_transforms: Sequence[Callable] = (),
        fit_transforms: Sequence[Callable] = (),
        mesh: Optional[jax.sharding.Mesh] = None,
        rules: Optional[Sequence[Tuple[str, Any]]] = None,
        num_objectives: int = 1,
        jit_step: bool = True,
        quarantine_nonfinite: bool = False,
        dtype_policy: Optional[DtypePolicy] = None,
        donate_carries: bool = False,
    ):
        if n_tenants < 1:
            raise ValueError(f"n_tenants must be >= 1, got {n_tenants}")
        if not problem.jittable:
            raise ValueError(
                "VectorizedWorkflow requires a jittable problem: a host "
                "pure_callback cannot run under vmap. Serve host problems "
                "one run at a time (run_host_pipelined), or wrap the "
                "evaluation jittable."
            )
        self.algorithm = algorithm
        self.problem = problem
        self.n_tenants = n_tenants
        self.monitors = tuple(monitors)
        self._opt_direction_arg = opt_direction
        self.opt_direction = parse_opt_direction(opt_direction)
        self.pop_transforms = tuple(pop_transforms)
        self.fit_transforms = tuple(fit_transforms)
        self.mesh = mesh
        self.rules = tuple(rules) if rules else None
        self.num_objectives = num_objectives
        self.quarantine_nonfinite = quarantine_nonfinite
        self.dtype_policy = dtype_policy
        self.jit_step = jit_step
        self.donate_carries = bool(donate_carries) and jit_step
        self.external = False  # fused_run/instrument duck-typing parity
        for m in self.monitors:
            if getattr(m, "uses_host_callbacks", False):
                raise ValueError(
                    f"{type(m).__name__} streams through host callbacks, "
                    "which cannot run inside the vmapped fleet step on ANY "
                    "backend; use the callback-free monitors for per-tenant "
                    "history (TelemetryMonitor rings, "
                    "EvalMonitor(history_capacity=K))"
                )
        self.hyperparams = self._check_hyperparams(hyperparams or {})
        if mesh is not None:
            if _TENANT not in mesh.axis_names:
                raise ValueError(
                    f"VectorizedWorkflow mesh must carry a '{_TENANT}' "
                    f"axis (got axes {tuple(mesh.axis_names)}); build it "
                    "with create_mesh((TENANT_AXIS, POP_AXIS), shape=(t, p))"
                )
            t_shards = mesh.shape[_TENANT]
            if n_tenants % t_shards != 0:
                raise ValueError(
                    f"n_tenants {n_tenants} is not divisible by the mesh's "
                    f"'{_TENANT}' axis ({t_shards} shards)"
                )
            pop_size = getattr(algorithm, "pop_size", None)
            p_shards = mesh.shape.get(_POP, 1)
            if pop_size is not None and pop_size % p_shards != 0:
                raise ValueError(
                    f"pop_size {pop_size} is not divisible by the mesh's "
                    f"'{_POP}' axis ({p_shards} shards)"
                )
        for m in self.monitors:
            m.set_opt_direction(self.opt_direction)
        self._hook_table = build_hook_table(self.monitors)
        self._step = jax.jit(self._step_impl) if jit_step else self._step_impl
        self._run_loop = make_run_loop(self._step_impl, donate=self.donate_carries)
        # single-tenant first-generation peel for RunQueue admission:
        # hyperparams are TRACED leaves of the TenantState operand, so
        # ONE compile serves every admitted spec regardless of its
        # bindings (a per-admission solo StdWorkflow would recompile)
        self._solo_peel = (
            jax.jit(self._solo_peel_impl) if jit_step else self._solo_peel_impl
        )

    # ------------------------------------------------------------ hyperparams
    def _check_hp_name(self, name: str) -> None:
        """Validate a (possibly dotted) hyperparam attribute path against
        the template — the one resolution rule shared by the constructor
        stack, ``init_tenant``, and RunQueue admission."""
        obj = self.algorithm
        for part in name.split("."):
            if not hasattr(obj, part):
                raise ValueError(
                    f"hyperparams[{name!r}]: template "
                    f"{type(obj).__name__} has no attribute {part!r}"
                )
            obj = getattr(obj, part)

    def _check_hyperparams(self, hp: Dict[str, Any]) -> Dict[str, Any]:
        checked = {}
        for name, value in hp.items():
            self._check_hp_name(name)
            value = jnp.asarray(value)
            if value.ndim < 1 or value.shape[0] != self.n_tenants:
                raise ValueError(
                    f"hyperparams[{name!r}] must be stacked with leading "
                    f"axis n_tenants={self.n_tenants}, got shape "
                    f"{value.shape}"
                )
            checked[name] = value
        return checked

    def _bind(self, hp: Dict[str, Any]) -> Algorithm:
        """A shallow copy of the template with this tenant's hyperparam
        slices bound as attributes (:func:`bind_hyperparams` — shared
        with the multi-level ES's traced inner binding,
        workflows/multilevel.py)."""
        return bind_hyperparams(self.algorithm, hp)

    def tenant_hyperparams(
        self, index: int, state: Optional[VectorizedWorkflowState] = None
    ) -> Dict[str, Any]:
        """Tenant ``index``'s concrete hyperparam bindings (host values).
        Reads the LIVE state's bindings when given (a RunQueue rebinds
        slots on admission), else the constructor stack."""
        source = (
            state.tenants.hyperparams if state is not None else self.hyperparams
        )
        return {
            name: jax.device_get(value)[index]
            for name, value in source.items()
        }

    # ------------------------------------------------------------------ init
    def init(
        self, key: jax.Array, hyperparams: Optional[Dict[str, Any]] = None
    ) -> VectorizedWorkflowState:
        """Build the fleet state. ``key``: one key (split per tenant) or
        a stacked ``(n_tenants, ...)`` key batch. Each tenant's slice is
        initialized EXACTLY like ``StdWorkflow.init`` with that tenant's
        key (same split discipline), so tenant ``i`` starts bit-identical
        to a solo run seeded with key ``i``. ``hyperparams=`` overrides
        the constructor stack (same names/shapes) — the RunQueue's
        admission path."""
        hp = (
            self.hyperparams
            if hyperparams is None
            else self._check_hyperparams(hyperparams)
        )
        keys = _tenant_keys(key, self.n_tenants)
        tenants = jax.vmap(self._build_tenant)(keys, hp)
        state = VectorizedWorkflowState(
            generation=jnp.zeros((), dtype=jnp.int32),
            tenants=tenants,
            first_step=True,
        )
        state = apply_storage(state, self.dtype_policy)
        # pod meshes: assemble the tenant-stacked state into global
        # arrays under the tenant-prefixed annotation layout (no-op on
        # single-process meshes; see core/distributed.ensure_global_state)
        from ..core.distributed import ensure_global_state

        return ensure_global_state(
            state, self.mesh, rules=self.rules, axis_prefix=_TENANT
        )

    def _build_tenant(self, k: jax.Array, h: Dict[str, Any]) -> TenantState:
        """The single-tenant constructor shared by the vmapped fleet
        ``init`` and ``init_tenant`` — ONE key-split discipline (matching
        ``StdWorkflow.init``), so the fleet-vs-solo and admission
        equivalence contracts cannot drift apart."""
        algo = self._bind(h)
        ks = jax.random.split(k, 2 + len(self.monitors))
        return TenantState(
            generation=jnp.zeros((), dtype=jnp.int32),
            algo=algo.init(ks[0]),
            prob=self.problem.init(ks[1]),
            monitors=tuple(
                m.init(kk) for m, kk in zip(self.monitors, ks[2:])
            ),
            hyperparams=h,
        )

    # ------------------------------------------------------------------ step
    def step(self, state: VectorizedWorkflowState) -> VectorizedWorkflowState:
        return self._step(state)

    def run(
        self,
        state: VectorizedWorkflowState,
        n_steps: int,
        checkpointer: Optional[WorkflowCheckpointer] = None,
        resume_from: Any = None,
    ) -> VectorizedWorkflowState:
        """Run ``n_steps`` generations of the WHOLE fleet as one fused
        ``fori_loop`` dispatch (see :meth:`StdWorkflow.run` — same
        checkpointer/resume laws, applied to the fleet state; the
        supervisor drives this entry point for chunked healing)."""
        state, n_steps, checkpointer = enter_run(
            state, n_steps, checkpointer, resume_from, expect_like=state
        )
        if checkpointer is not None:
            return checkpointed_run(self, state, n_steps, checkpointer)
        return fused_run(self, state, n_steps)

    def analysis_targets(self, state: VectorizedWorkflowState) -> dict:
        """AOT cost/memory analysis targets (core/xla_cost.py): the
        steady vmapped step and the fused fleet run (dynamic trip count
        ⇒ statics are per fleet-generation), so
        ``run_report()["roofline"]`` attributes the FUSED FLEET dispatch
        — N tenants' achieved rates in one verdict."""
        if not self.jit_step:
            return {}
        steady = state.replace(first_step=False) if state.first_step else state
        return {
            "step": (self._step, (steady,)),
            "run": (self._run_loop, (steady, jnp.asarray(1, jnp.int32))),
        }

    # ------------------------------------------------------------- internals
    def _filter_fitness(self, t: TenantState, fitness: jax.Array) -> jax.Array:
        """Per-tenant fitness filter applied after quarantine, before the
        fit transforms. Identity here; ``ElasticWorkflow`` overrides it
        with the inert-row padding mask."""
        return fitness

    def _flip(self, fitness: jax.Array) -> jax.Array:
        if fitness.ndim == 1:
            return fitness * self.opt_direction[0]
        return fitness * self.opt_direction

    def _shard_stacked(self, tree: Any, inner_pop: bool) -> Any:
        """Constrain tenant-stacked candidate/fitness batches:
        ``P(tenant, pop)`` for (N, B, ...) candidates, ``P(tenant)``
        when the inner axis doesn't shard (scalar fitness rows)."""
        if self.mesh is None:
            return tree
        from jax.sharding import NamedSharding, PartitionSpec as P

        has_pop = _POP in self.mesh.axis_names

        def constrain(x):
            if x.ndim >= 2 and has_pop and inner_pop:
                spec = P(_TENANT, _POP)
            else:
                spec = P(_TENANT)
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(self.mesh, spec)
            )

        return jax.tree.map(constrain, tree)

    def _tenant_ask(self, t: TenantState, use_init: bool):
        mstates = list(t.monitors)
        run_hooks(self.monitors, self._hook_table, "pre_step", mstates)
        run_hooks(self.monitors, self._hook_table, "pre_ask", mstates)
        algo = self._bind(t.hyperparams)
        ask = algo.init_ask if use_init else algo.ask
        pop, astate = ask(t.algo)
        run_hooks(self.monitors, self._hook_table, "post_ask", mstates, pop)
        cand = pop
        for tr in self.pop_transforms:
            cand = tr(cand)
        run_hooks(self.monitors, self._hook_table, "pre_eval", mstates, cand)
        return cand, (astate, tuple(mstates))

    def _tenant_tell(
        self,
        t: TenantState,
        ctx,
        cand: Any,
        fitness: jax.Array,
        pstate: Any,
        use_init: bool,
    ) -> TenantState:
        astate, mstates_t = ctx
        mstates = list(mstates_t)
        run_hooks(
            self.monitors, self._hook_table, "post_eval", mstates, cand, fitness
        )
        fitness = self._flip(fitness)
        if self.quarantine_nonfinite:
            fitness = quarantine_nonfinite(fitness)
        # per-tenant fitness filter hook (identity here): the elastic
        # layer (workflows/elastic.py) overrides it to make padded
        # population rows inert — between the quarantine stage and the
        # user fit transforms, the same pipeline position its solo
        # reference applies the mask at
        fitness = self._filter_fitness(t, fitness)
        for tr in self.fit_transforms:
            fitness = tr(fitness)
        run_hooks(self.monitors, self._hook_table, "pre_tell", mstates, fitness)
        algo = self._bind(t.hyperparams)
        tell = algo.init_tell if use_init else algo.tell
        astate = tell(astate, fitness)
        run_hooks(self.monitors, self._hook_table, "post_tell", mstates)
        # post_step sees the documented workflow-state shape — a solo
        # view with the tenant's OWN .generation (not the fleet's
        # lockstep counter, which runs ahead for queue-admitted tenants)
        # plus .algo/.prob/.monitors — so monitors written against
        # StdWorkflow's contract (generation-gated savers, the guardrail
        # mirror) trace identically per tenant
        generation = t.generation + 1
        hook_state = StdWorkflowState(
            generation=generation,
            algo=astate,
            prob=pstate,
            monitors=tuple(mstates),
            first_step=False,
        )
        ms = list(mstates)
        run_hooks(self.monitors, self._hook_table, "post_step", ms, hook_state)
        return TenantState(
            generation=generation,
            algo=astate,
            prob=pstate,
            monitors=tuple(ms),
            hyperparams=t.hyperparams,
        )

    def _step_impl(
        self, state: VectorizedWorkflowState
    ) -> VectorizedWorkflowState:
        # storage -> compute upcast at the fleet step boundary, exactly
        # like StdWorkflow: all per-tenant math runs in the compute dtype
        state = apply_compute(state, self.dtype_policy)
        use_init = state.first_step and (
            self.algorithm.has_init_ask or self.algorithm.has_init_tell
        )
        tenants = state.tenants
        cand, ctx = jax.vmap(partial(self._tenant_ask, use_init=use_init))(
            tenants
        )
        # the whole fleet's candidates are ONE (N, B, ...) batch laid out
        # over (TENANT, POP) — GSPMD partitions the vmapped evaluation
        # across both axes from this single constraint
        cand = self._shard_stacked(cand, inner_pop=True)
        fitness, pstate = jax.vmap(self.problem.evaluate)(tenants.prob, cand)
        fitness = self._shard_stacked(fitness, inner_pop=True)
        told = jax.vmap(partial(self._tenant_tell, use_init=use_init))(
            tenants, ctx, cand, fitness, pstate
        )
        if state.frozen is not None:
            # fault isolation (fleet_health.py "freeze"): a frozen slot
            # keeps its PRE-step tenant slice — elementwise select, so
            # unfrozen rows pass through the computed values bitwise
            # unchanged (the isolation law's mechanism)
            frozen = state.frozen

            def keep_frozen(old, new):
                mask = frozen.reshape(frozen.shape + (1,) * (new.ndim - 1))
                return jnp.where(mask, old.astype(new.dtype), new)

            told = jax.tree.map(keep_frozen, tenants, told)
        tenants = told
        # end-of-step boundary, fleet-wide: the per-field annotations are
        # applied SHIFTED under the tenant axis (P("pop") -> P("tenant",
        # "pop"), P() -> P("tenant")) with regex rules overriding, and an
        # active dtype policy downcasts storage leaves in the same walk
        tenants = constrain_state(
            tenants,
            self.mesh,
            self.dtype_policy,
            rules=self.rules,
            axis_prefix=_TENANT,
        )
        return state.replace(
            generation=state.generation + 1,
            tenants=tenants,
            first_step=False,
        )

    def init_tenant(
        self, key: jax.Array, hyperparams: Optional[Dict[str, Any]] = None
    ) -> TenantState:
        """A fresh SINGLE tenant (unstacked :class:`TenantState`) with
        concrete ``hyperparams`` bound — the RunQueue admission path.
        Key-split discipline matches :meth:`init`'s per-tenant splits
        (and therefore ``StdWorkflow.init``), so an admitted tenant is
        trajectory-equivalent to a solo run of its (seed, bindings)."""
        hp = {}
        for name, value in (hyperparams or {}).items():
            self._check_hp_name(name)
            hp[name] = jnp.asarray(value)
        return self._build_tenant(jnp.asarray(key), hp)

    def _solo_peel_impl(self, t: TenantState) -> TenantState:
        """One un-vmapped first generation of a single tenant (the
        init_ask/init_tell dispatch the fleet's steady vmapped step must
        never issue for one slot only). Hook order mirrors the vmapped
        step exactly."""
        cand, ctx = self._tenant_ask(t, use_init=True)
        fitness, pstate = self.problem.evaluate(t.prob, cand)
        return self._tenant_tell(t, ctx, cand, fitness, pstate, use_init=True)

    def place_restored(self, state: VectorizedWorkflowState) -> Any:
        """Eagerly re-place a host-restored FLEET snapshot on this
        workflow's mesh using the tenant-prefixed layout (the fleet
        analog of :func:`~evox_tpu.workflows.checkpoint.restore_layouts`
        — the un-prefixed annotations would shard a stacked leaf's
        TENANT axis over the ``pop`` mesh axis). The supervisor's
        restore rung picks this up duck-typed."""
        from ..core.distributed import place_state

        if self.mesh is None:
            return state
        return place_state(
            state, self.mesh, rules=self.rules, axis_prefix=_TENANT
        )

    # ------------------------------------------------- eviction / admission
    def solo_workflow(
        self,
        index: Optional[int] = None,
        hyperparams: Optional[Dict[str, Any]] = None,
        mesh: Optional[jax.sharding.Mesh] = None,
        state: Optional[VectorizedWorkflowState] = None,
    ) -> StdWorkflow:
        """A single-tenant :class:`StdWorkflow` equivalent to fleet slot
        ``index`` (or to explicit concrete ``hyperparams``): the template
        algorithm with that tenant's bindings baked in, the same problem,
        monitors, transforms and dtype policy. This is the resume target
        for an evicted tenant's checkpoint — and the reference
        implementation the fleet's per-tenant trajectory is tested
        against. Pass ``state=`` with ``index`` to read the LIVE slot
        bindings (a RunQueue rebinds slots on admission, so the
        constructor stack can be stale for queue-driven fleets)."""
        if hyperparams is None:
            hyperparams = (
                self.tenant_hyperparams(index, state=state)
                if index is not None
                else {}
            )
        algo = self._bind(
            {k: jnp.asarray(v) for k, v in hyperparams.items()}
        )
        return StdWorkflow(
            algo,
            self.problem,
            monitors=self.monitors,
            opt_direction=self._opt_direction_arg,
            pop_transforms=self.pop_transforms,
            fit_transforms=self.fit_transforms,
            mesh=mesh,
            num_objectives=self.num_objectives,
            jit_step=self.jit_step,
            quarantine_nonfinite=self.quarantine_nonfinite,
            dtype_policy=self.dtype_policy,
            donate_carries=self.donate_carries,
        )

    def extract_tenant(
        self,
        state: VectorizedWorkflowState,
        index: int,
        generation: Optional[int] = None,
    ) -> StdWorkflowState:
        """Slice tenant ``index`` out of the fleet as a SOLO
        ``StdWorkflowState`` (host-side ``device_get`` + slice, eager —
        call between dispatches). The result is exactly what
        ``solo_workflow(index)`` would be carrying at this generation:
        checkpoint it with a :class:`WorkflowCheckpointer` and the solo
        workflow's ``resume_from=`` completes the run — the mid-fleet
        eviction contract. ``generation`` overrides the tenant's own
        counter (rarely needed — the state tracks it per tenant)."""
        # slice ON DEVICE first: fetching the whole stacked fleet to
        # discard N-1 tenants would cost N× the bytes per eviction (the
        # tunnel charges ~6.6 s/256 MB, CLAUDE.md)
        t = jax.device_get(
            jax.tree.map(lambda x: x[index], state.tenants)
        )
        gen = int(t.generation) if generation is None else int(generation)
        return StdWorkflowState(
            generation=jnp.asarray(gen, dtype=jnp.int32),
            algo=t.algo,
            prob=t.prob,
            monitors=t.monitors,
            first_step=False,
        )

    def insert_tenant(
        self,
        state: VectorizedWorkflowState,
        index: int,
        solo_state: Any,
        hyperparams: Optional[Dict[str, Any]] = None,
    ) -> VectorizedWorkflowState:
        """Write a solo tenant state into fleet slot ``index`` (state
        surgery at fixed shapes — NO recompile: the fleet program only
        sees different leaf values). ``solo_state``: a
        ``StdWorkflowState`` (from ``solo_workflow(...).init`` or an
        eviction checkpoint) or an unstacked :class:`TenantState` (from
        :meth:`init_tenant`); it must match the fleet's per-tenant
        structure (same algorithm class, pop size, monitor set).
        ``hyperparams``: the slot's new concrete bindings (default: a
        TenantState's own, else the slot's current ones). A solo state's
        ``generation`` is the tenant's — the caller (RunQueue) tracks
        the offset against the fleet's lockstep counter."""
        if hyperparams is not None:
            slot_hp = {
                name: jnp.asarray(value)
                for name, value in hyperparams.items()
            }
        elif isinstance(solo_state, TenantState):
            slot_hp = solo_state.hyperparams
        else:
            slot_hp = jax.tree.map(
                lambda x: x[index], state.tenants.hyperparams
            )
        new_t = TenantState(
            generation=jnp.asarray(solo_state.generation, dtype=jnp.int32),
            algo=solo_state.algo,
            prob=solo_state.prob,
            monitors=solo_state.monitors,
            hyperparams=slot_hp,
        )
        new_t = apply_storage(new_t, self.dtype_policy)
        # shape guard BEFORE the scatter: a solo state carrying another
        # population size would either raise an opaque broadcasting error
        # deep inside `.at[index].set` or — worse, for a pop that happens
        # to broadcast — silently corrupt the slot. Mismatched shapes are
        # a routing bug (e.g. a checkpoint from a different bucket); name
        # it and point at the elastic router.
        slot_leaves = jax.tree_util.tree_flatten_with_path(state.tenants)[0]
        new_leaves = jax.tree_util.tree_flatten_with_path(new_t)[0]
        if len(slot_leaves) == len(new_leaves):
            for (path, stacked), (_, new) in zip(slot_leaves, new_leaves):
                want = tuple(jnp.asarray(stacked).shape[1:])
                got = tuple(jnp.asarray(new).shape)
                if want != got:
                    raise ValueError(
                        f"insert_tenant: solo state leaf "
                        f"{jax.tree_util.keystr(path)} has shape {got} but "
                        f"fleet slot {index} holds {want} — the tenant was "
                        "built for a different shape (population size, dim, "
                        "or monitor capacity). Shapes are compiled into the "
                        "fleet program; route mismatched requests through "
                        "the bucket lattice (workflows/elastic.py "
                        "ElasticServer) instead."
                    )

        def put(stacked, new):
            stacked = jnp.asarray(stacked)
            return stacked.at[index].set(
                jnp.asarray(new, dtype=stacked.dtype)
            )

        return state.replace(
            tenants=jax.tree.map(put, state.tenants, new_t)
        )

    # --------------------------------------------------------------- freezing
    def with_freeze_mask(
        self, state: VectorizedWorkflowState
    ) -> VectorizedWorkflowState:
        """Materialize the per-tenant frozen mask (all False). Changes
        the carry structure, so do it BEFORE the first dispatch — the
        RunQueue does when its health policy can freeze."""
        if state.frozen is not None:
            return state
        return state.replace(
            frozen=jnp.zeros((self.n_tenants,), dtype=bool)
        )

    def set_frozen(
        self, state: VectorizedWorkflowState, index: int, flag: bool
    ) -> VectorizedWorkflowState:
        """Flip one slot's frozen bit (mask must be materialized)."""
        if state.frozen is None:
            raise ValueError(
                "fleet state has no frozen mask; materialize it with "
                "with_freeze_mask(state) before the first dispatch"
            )
        return state.replace(frozen=state.frozen.at[index].set(flag))

    # -------------------------------------------------------------- reporting
    def monitor_reports(self, mstates: Tuple[Any, ...]) -> List[dict]:
        """One monitor's ``report()`` per reporting monitor for a single
        tenant's monitor states — the shared assembly behind the tenancy
        section and the RunQueue's per-tenant results."""
        reports = []
        for j, mon in enumerate(self.monitors):
            if hasattr(mon, "report"):
                r = mon.report(mstates[j])
                r["monitor"] = type(mon).__name__
                reports.append(r)
        return reports

    def tenancy_report(self, state: VectorizedWorkflowState) -> dict:
        """The ``tenancy`` section of ``run_report()``: fleet shape,
        measured leading axes (the validator cross-checks them against
        ``n_tenants``), and each tenant's monitor reports (per-tenant
        telemetry rings). Host-side, strict JSON."""
        from ..core.instrument import sanitize_json

        # leading axes need SHAPES only (zero transfer); only the
        # monitor states — the small rings — are fetched, never the
        # stacked populations/covariances (tunnel bytes are the cost)
        leading = {
            int(x.shape[0])
            for x in jax.tree.leaves(state.tenants.algo)
            if getattr(x, "ndim", 0) >= 1
        }
        host_monitors = jax.device_get(state.tenants.monitors)
        per_tenant = []
        for i in range(self.n_tenants):
            entry: dict = {"tenant": i}
            reports = self.monitor_reports(
                tuple(
                    jax.tree.map(lambda x: x[i], ms) for ms in host_monitors
                )
            )
            if reports:
                entry["monitors"] = reports
            per_tenant.append(entry)
        report = {
            "n_tenants": self.n_tenants,
            "generation": int(state.generation),
            "tenant_axis": _TENANT if self.mesh is not None else None,
            "leading_axes": sorted(leading),
            "per_tenant": per_tenant,
        }
        queue = getattr(self, "_run_queue", None)
        if queue is not None and hasattr(queue, "report"):
            report["queue"] = queue.report()
        # fault-isolation actions (fleet_health.py) are a first-class
        # section of the tenancy report: run_report()["tenancy"]
        # ["fleet_health"] is where a poisoned tenant's freeze/evict/
        # restart verdict is surfaced (validated by check_report v6)
        if queue is not None and hasattr(queue, "health_report"):
            health = queue.health_report()
            if health is not None:
                report["fleet_health"] = health
        return sanitize_json(report)


# --------------------------------------------------------------------- queue


@dataclasses.dataclass
class TenantSpec:
    """One queued search: seed (int or PRNG key), concrete hyperparam
    bindings (must use the fleet's hyperparam names), a generation
    budget, and an optional tag for the results table.

    ``pop`` (optional) declares the population size the spec was built
    for: admission validates it against the fleet's compiled pop at
    ``submit()`` — a mismatch is a routing error named there, not a
    shape error deep inside the fused vmapped step (route ragged pops
    through ``workflows/elastic.py`` instead).

    ``deadline`` (optional) is the SLA bound, measured in FLEET
    generations since the queue started (``state.generation`` — a
    deterministic clock, so journal recovery replays every scheduling
    decision identically; wall-clock deadlines would not). A deadlined
    spec is admitted in EDF order ahead of deadline-free work, and the
    queue may PREEMPT the running tenant with the most remaining budget
    (parked via the standard eviction checkpoint, auto-resubmitted as a
    continuation) when waiting one more chunk would miss the deadline."""

    seed: Any
    n_steps: int
    hyperparams: Dict[str, Any] = dataclasses.field(default_factory=dict)
    tag: Optional[str] = None
    pop: Optional[int] = None
    deadline: Optional[int] = None

    def key(self) -> jax.Array:
        import numpy as np

        if isinstance(self.seed, (int, np.integer)):
            return jax.random.PRNGKey(int(self.seed))
        return jnp.asarray(self.seed)


@dataclasses.dataclass
class _Slot:
    spec: TenantSpec
    active: bool = True
    # frozen: the slot's tenant was quarantined in place (fleet_health
    # "freeze" action) — it stays in the fleet at fixed shape but its
    # tell is masked and the slot is never refilled
    frozen: bool = False


def _spec_from_record(rec: dict) -> TenantSpec:
    """Rebuild a :class:`TenantSpec` from its journal ``submit`` record
    (the recovery path). Seeds round-trip as ints or key data; a TYPED
    key seed is re-wrapped with its recorded impl — recovery must hand
    ``init_tenant`` the same key dtype the original driver did, or the
    config fingerprint (and the fleet's key leaves) would diverge."""
    import numpy as np

    if rec.get("seed") is not None:
        seed: Any = int(rec["seed"])
    else:
        seed = np.asarray(
            rec["seed_key"], dtype=rec.get("seed_key_dtype", "uint32")
        )
        impl = rec.get("seed_key_impl")
        if impl is not None:
            seed = jax.random.wrap_key_data(jnp.asarray(seed), impl=impl)
    spec = TenantSpec(
        seed=seed,
        n_steps=int(rec["n_steps"]),
        hyperparams=dict(rec.get("hyperparams") or {}),
        tag=rec.get("tag"),
        pop=int(rec["pop"]) if rec.get("pop") is not None else None,
        deadline=(
            int(rec["deadline"]) if rec.get("deadline") is not None else None
        ),
    )
    spec._journal_seq = int(rec["spec_seq"])
    if rec.get("grows"):
        # restore the elastic grow count (bounds PopAutoscaler.max_grows
        # across recovery — a scheduling input like pop/deadline)
        spec._elastic_grows = int(rec["grows"])
    return spec


class RunQueue:
    """Admit/evict tenants through a fixed-width vmapped fleet.

    The fleet's width is static (a compiled-program shape); the queue
    serves MORE searches than that by running the fleet in dispatch
    chunks and swapping retired tenants for pending specs between
    chunks — state surgery at fixed shapes, no recompile. With a
    :class:`~evox_tpu.workflows.supervisor.RunSupervisor`, every chunk
    dispatch runs under its deadline/retry/restore ladder (the fleet is
    one workflow to the supervisor).

    Args:
        workflow: a :class:`VectorizedWorkflow`. Its constructor
            hyperparam stack is only a default — each admitted spec's
            bindings overwrite its slot. A workflow already driven by an
            UNFINISHED RunQueue is refused (the backref would silently
            rewire ``run_report``'s ``tenancy.queue`` pickup mid-sweep);
            once a queue's sweep completes, a new queue may adopt the
            workflow.
        chunk: generations per dispatch chunk (the admission/eviction
            granularity). A tenant's budget is honored exactly: the
            chunk is shortened when any active tenant would overshoot.
        supervisor: optional :class:`RunSupervisor` driving each chunk.
        checkpoint_dir: when given, every retirement/eviction/freeze
            writes a resumable single-tenant snapshot under
            ``<dir>/<tag-or-tenant_K>/`` (a
            :class:`WorkflowCheckpointer`; ``solo_workflow(...)``
            resumes it). Defaults to ``<journal_dir>/tenants`` when a
            journal is configured.
        keep: snapshots kept per tenant directory.
        journal: a :class:`~evox_tpu.workflows.journal.RunJournal` (or a
            directory path) making the whole sweep DURABLE: every queue
            transition is appended to the hash-chained WAL before (or
            at the barrier of) the mutation it describes, and every
            chunk ends with a fleet-level snapshot written through the
            executor's background checkpoint lane plus a
            ``chunk_complete`` barrier record embedding the queue's full
            bookkeeping. A driver SIGKILL'd at ANY point is resumed by
            :meth:`recover` with per-tenant results identical to the
            uncrashed run.
        health_policy: a :class:`~evox_tpu.workflows.fleet_health.
            FleetHealthPolicy` evaluated at every chunk boundary; maps
            per-tenant health signals to freeze/evict/restart slot
            actions (healthy tenants stay bitwise-untouched).

    Lifecycle: ``submit()`` specs (at least ``n_tenants`` before the
    first ``start()``), then ``run()`` to completion — or ``start()`` +
    repeated ``step_chunk()`` for between-chunk control (the legal
    window for :meth:`evict`). Results accumulate in ``results``;
    :meth:`report` is the ``tenancy.queue`` section of ``run_report``.

    Durability note: a MANUAL :meth:`evict` between chunks is journaled
    for audit, but recovery replays from the last chunk barrier — a
    crash in the narrow window between a manual eviction and the next
    barrier rolls the slot swap back (the eviction checkpoint on disk
    stays valid; the tenant simply continues in the fleet). Policy-driven
    actions are deterministic in the restored state and replay exactly.
    """

    def __init__(
        self,
        workflow: VectorizedWorkflow,
        chunk: int = 10,
        supervisor: Any = None,
        checkpoint_dir: Optional[str] = None,
        keep: int = 2,
        executor: Any = None,
        journal: Any = None,
        health_policy: Any = None,
        metrics: Any = None,
        attest: Any = None,
    ):
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        from ..core.executor import GenerationExecutor
        from .journal import RunJournal

        prev = getattr(workflow, "_run_queue", None)
        if prev is not None and prev is not self and not getattr(
            prev, "finished", True
        ):
            raise RuntimeError(
                "this VectorizedWorkflow is already driven by an "
                "unfinished RunQueue — constructing a second one would "
                "silently rewire run_report's tenancy.queue pickup and "
                "interleave two sweeps over one fleet state. Drive the "
                "existing queue to completion (or build a second "
                "workflow) first."
            )
        self.workflow = workflow
        self.chunk = chunk
        self.supervisor = supervisor
        # every serving chunk dispatches through ONE GenerationExecutor
        # (queue scheduling is a thin policy over it): the supervisor
        # ladder becomes an executor hook, and with a journal the fleet
        # snapshot rides the executor's background checkpoint lane.
        # Eviction/retirement snapshots stay SYNCHRONOUS on the caller
        # thread — they happen between chunks and their result is handed
        # out immediately
        self.executor = (
            executor if executor is not None else GenerationExecutor()
        )
        if isinstance(journal, (str, Path)):
            journal = RunJournal(str(journal))
        self.journal = journal
        if checkpoint_dir is None and journal is not None:
            checkpoint_dir = str(journal.directory / "tenants")
        self.checkpoint_dir = (
            Path(checkpoint_dir) if checkpoint_dir is not None else None
        )
        self.keep = keep
        self._fleet_ckpt = (
            WorkflowCheckpointer(
                str(journal.directory / "fleet"), every=1,
                # recovery falls back one barrier when the newest
                # snapshot is torn (a kill mid-background-fsync), so at
                # least two snapshots must survive pruning
                keep=max(2, keep),
            )
            if journal is not None
            else None
        )
        self.health_policy = health_policy
        # serving-plane flight recorder (PR 16): `metrics=None` is an
        # exact no-op — every producer call site below is gated, reads
        # only already-fetched host values, and writes only host memory/
        # files, so states stay bit-identical and no stream file exists.
        # A str/Path builds a stream-backed recorder in that directory.
        if isinstance(metrics, (str, Path)):
            from .flightrec import FlightRecorder

            metrics = FlightRecorder(directory=str(metrics))
        self.metrics = metrics
        if metrics is not None:
            # one recorder serves the whole serving stack: the executor
            # mirrors its dispatch telemetry, the exec cache its
            # hit/miss/compile-ms, the health policy its verdicts; the
            # workflow backref is run_report's `metrics`/`slo` pickup
            workflow._flight_recorder = metrics
            if getattr(self.executor, "metrics", None) is None:
                self.executor.metrics = metrics
            cache = getattr(workflow, "_exec_cache", None)
            if cache is not None and getattr(cache, "metrics", None) is None:
                cache.metrics = metrics
            if (
                health_policy is not None
                and getattr(health_policy, "metrics", None) is None
            ):
                health_policy.metrics = metrics
        # compute-integrity (PR 20): an attestor pins a digest of the
        # fleet state onto every chunk_complete barrier record, so
        # recover() can verify a restored snapshot's BITS against the
        # journal — a corrupt-but-sha256-consistent snapshot is refused
        # and recovery falls back one barrier. `attest=None` is an exact
        # no-op; `attest=True` builds the default StateAttestor.
        if attest is True:
            from ..core.attest import StateAttestor

            attest = StateAttestor()
        self.attest = attest
        self.integrity_events: List[dict] = []
        self.health_events: List[dict] = []
        self._slot_restarts: List[int] = [0] * workflow.n_tenants
        self._config_sha: Optional[str] = None
        self._spec_seq = 0
        self.finished = False
        self.pending: List[TenantSpec] = []
        # parked continuations: specs whose tenant resumes from a
        # checkpoint (preemption, elastic growth) instead of a fresh
        # init — admitted ahead of deadline-free pending work
        self.continuations: List[dict] = []
        self._used_dirs: set = set()
        self.slots: List[Optional[_Slot]] = [None] * workflow.n_tenants
        self.state: Optional[VectorizedWorkflowState] = None
        self.results: List[dict] = []
        self.counters = {
            "submitted": 0,
            "admitted": 0,
            "retired": 0,
            "evicted": 0,
            "frozen": 0,
            "restarted": 0,
            "preempted": 0,
            "readmitted": 0,
            "chunks": 0,
        }
        workflow._run_queue = self  # run_report pickup (tenancy.queue)

    # ------------------------------------------------------------- lifecycle
    def _spec_record(self, spec: TenantSpec, seq: int) -> dict:
        import numpy as np

        rec: dict = {
            "spec_seq": seq,
            "n_steps": int(spec.n_steps),
            "tag": spec.tag,
            "pop": int(spec.pop) if spec.pop is not None else None,
            "deadline": (
                int(spec.deadline) if spec.deadline is not None else None
            ),
            "hyperparams": {
                k: np.asarray(v) for k, v in spec.hyperparams.items()
            },
        }
        # the elastic layer's grow count is a SCHEDULING input (it
        # bounds PopAutoscaler.max_grows): journal it like pop/deadline
        # or a recovered queue would let a grown tenant grow forever
        grows = getattr(spec, "_elastic_grows", 0)
        if grows:
            rec["grows"] = int(grows)
        seed = spec.seed
        if isinstance(seed, (int, np.integer)):
            rec["seed"] = int(seed)
        else:
            arr = jnp.asarray(seed)
            if jnp.issubdtype(arr.dtype, jax.dtypes.prng_key):
                rec["seed_key_impl"] = str(jax.random.key_impl(arr))
                arr = jax.random.key_data(arr)
            arr = np.asarray(arr)
            rec["seed_key"] = arr
            rec["seed_key_dtype"] = str(arr.dtype)
        return rec

    def _validate_spec(self, spec: TenantSpec) -> None:
        if spec.n_steps < 1:
            raise ValueError(
                f"TenantSpec.n_steps must be >= 1, got {spec.n_steps}"
            )
        fleet_pop = getattr(self.workflow.algorithm, "pop_size", None)
        if (
            spec.pop is not None
            and fleet_pop is not None
            and int(spec.pop) != int(fleet_pop)
        ):
            # the pre-elastic failure mode was a shape error deep inside
            # the fused vmapped step, generations after the bad spec was
            # accepted — reject it AT the submission boundary instead
            raise ValueError(
                f"TenantSpec.pop={spec.pop} does not match this fleet's "
                f"compiled pop_size={fleet_pop}. A fleet program is "
                "compiled at ONE population shape; admitting a mismatched "
                "spec would fail (or silently mis-broadcast) inside the "
                "fused step. Route ragged pops through the bucket lattice "
                "(workflows/elastic.py ElasticServer) or build a fleet at "
                "the requested pop."
            )
        if spec.deadline is not None:
            if spec.deadline < spec.n_steps:
                raise ValueError(
                    f"TenantSpec.deadline={spec.deadline} is infeasible: "
                    f"the spec needs n_steps={spec.n_steps} fleet "
                    "generations even if admitted at generation 0"
                )
            if self.checkpoint_dir is None:
                raise ValueError(
                    "deadlined specs need a checkpoint_dir (or a journal): "
                    "meeting a deadline may preempt a running tenant, and "
                    "preemption parks the victim as a resumable eviction "
                    "checkpoint — without a directory its work would be "
                    "lost"
                )
        if set(spec.hyperparams) != set(self.workflow.hyperparams):
            raise ValueError(
                f"spec hyperparams {sorted(spec.hyperparams)} must use "
                f"exactly the fleet's hyperparam names "
                f"{sorted(self.workflow.hyperparams)}"
            )
        for name in spec.hyperparams:
            self.workflow._check_hp_name(name)

    def _journal_submit(self, spec: TenantSpec, **extra: Any) -> None:
        seq = self._spec_seq
        if self.journal is not None:
            self.journal.append(
                "submit", **self._spec_record(spec, seq), **extra
            )
        spec._journal_seq = seq
        self._spec_seq += 1
        self.counters["submitted"] += 1
        self.finished = False

    def submit(self, spec: TenantSpec) -> None:
        """Queue a spec. Validated HERE — a bad spec must be rejected at
        the submission boundary, not discovered mid-sweep after it was
        popped (which would lose it and leave the queue half-updated).
        With a journal, the spec is durable before it is queued (WAL
        discipline: an acknowledged submit survives a crash)."""
        self._validate_spec(spec)
        self._journal_submit(spec)
        self.pending.append(spec)

    def submit_resume(
        self,
        spec: TenantSpec,
        checkpoint: Optional[str] = None,
        state: Any = None,
        done: Optional[int] = None,
    ) -> None:
        """Queue a CONTINUATION: a spec whose tenant resumes from a
        parked solo state (a preemption/eviction/growth checkpoint, or
        an in-memory state) instead of a fresh init. Continuations are
        admitted ahead of deadline-free pending work — they were
        displaced to make room, so they return before new arrivals.
        ``done`` records the generations already completed at park time
        (the SLA pass uses it to compute the continuation's REAL
        remaining work instead of assuming the whole ``n_steps``).
        With a journal a durable ``checkpoint`` is required: an
        in-memory state would not survive the crash the journal exists
        for. The journal records the submit with its ``resume_from``
        path, so recovery rebuilds the continuation queue."""
        self._validate_spec(spec)
        if checkpoint is None and state is None:
            raise ValueError(
                "submit_resume needs a checkpoint directory or an "
                "in-memory solo state to resume from"
            )
        if self.journal is not None and checkpoint is None:
            raise ValueError(
                "a journaled queue requires continuations to name a "
                "durable checkpoint (resume_from) — an in-memory state "
                "cannot be replayed after a crash"
            )
        self._journal_submit(
            spec,
            resume_from=checkpoint,
            done=int(done) if done is not None else None,
        )
        self.continuations.append(
            {
                "spec": spec,
                "seq": getattr(spec, "_journal_seq", None),
                "checkpoint": checkpoint,
                "state": state,
                "done": int(done) if done is not None else None,
            }
        )

    def release_continuation(self, seq: int) -> dict:
        """Release QUEUED work — a parked continuation, or a still-
        pending spec — because it was stolen: the multi-pod control
        plane (:mod:`~evox_tpu.workflows.control_plane`) re-placed it on
        another pod, where its submit is already durable. Same WAL
        ordering as the elastic-growth handoff: the caller makes the
        work durable in the TARGET journal first, then releases it
        here — a crash between the two leaves a duplicate (healed by
        the control plane's checkpoint/tag dedup at recovery), never a
        loss. The journal records a ``steal`` so recovery of THIS queue
        never requeues the moved seq. Returns a descriptor of the
        released work ({seq, tag, checkpoint, done}). Raises
        ``KeyError`` when no queued work carries ``seq`` — an ACTIVE
        slot cannot be stolen directly (preempt it first; the
        preemption parks a continuation)."""
        seq = int(seq)
        for i, c in enumerate(self.continuations):
            if c.get("seq") is not None and int(c["seq"]) == seq:
                if self.journal is not None and c.get("checkpoint") is None:
                    raise ValueError(
                        "a journaled queue cannot release an in-memory "
                        "continuation — nothing durable exists for the "
                        "target pod to resume from"
                    )
                self.continuations.pop(i)
                desc = {
                    "seq": seq,
                    "tag": c["spec"].tag,
                    "checkpoint": c.get("checkpoint"),
                    "done": c.get("done"),
                }
                break
        else:
            for i, spec in enumerate(self.pending):
                if getattr(spec, "_journal_seq", None) == seq:
                    self.pending.pop(i)
                    desc = {
                        "seq": seq,
                        "tag": spec.tag,
                        "checkpoint": None,
                        "done": None,
                    }
                    break
            else:
                raise KeyError(
                    f"no queued work (continuation or pending spec) "
                    f"carries journal seq {seq}"
                )
        self.counters["stolen"] = self.counters.get("stolen", 0) + 1
        if self.journal is not None:
            self.journal.append(
                "steal",
                spec_seq=seq,
                tag=desc["tag"],
                checkpoint=desc["checkpoint"],
            )
        if self.metrics is not None:
            self.metrics.event(
                "queue.stolen", tag=desc["tag"], seq=seq
            )
        return desc

    def start(self) -> VectorizedWorkflowState:
        """Fill every slot and init the fleet. Slots draw from pending
        specs AND parked continuations under the ``_refill`` priority
        ladder — a recovered queue whose remaining work is (mostly)
        continuations (a cross-journal elastic-growth handoff crashed
        before its target bucket ever started) must be startable, not
        stuck behind a pending-only guard."""
        wf = self.workflow
        if self.state is not None:
            raise RuntimeError("RunQueue already started")
        total = len(self.pending) + len(self.continuations)
        if total < wf.n_tenants:
            raise ValueError(
                f"need at least n_tenants={wf.n_tenants} pending specs or "
                f"parked continuations to fill the fleet, have {total}; "
                "submit more or build a narrower fleet"
            )
        units = [self._take_next_unit() for _ in range(wf.n_tenants)]
        specs = [u if k == "spec" else u["spec"] for k, u in units]
        keys = jnp.stack([s.key() for s in specs])
        hp = self._stack_hp([s.hyperparams for s in specs])
        state = wf.init(keys, hyperparams=hp)
        if self.health_policy is not None and self.health_policy.may_freeze():
            # the mask must exist from the FIRST dispatch: adding it
            # mid-run changes the carry structure (a designed retrace
            # this avoids)
            state = wf.with_freeze_mask(state)
        from .checkpoint import state_config_fingerprint

        self._config_sha = state_config_fingerprint(state)
        if self.journal is not None:
            # journaled BEFORE the queue adopts the fleet: a crash here
            # leaves a start record without barriers, which recovery
            # treats as never-started (every submitted spec re-queued)
            self.journal.append(
                "start",
                config_sha=self._config_sha,
                n_tenants=wf.n_tenants,
                chunk=self.chunk,
                keep=self.keep,
                freeze_mask=state.frozen is not None,
                # the policy CONFIG is part of the sweep: recover() must
                # keep isolating poisoned tenants through the replay, or
                # a crashed run's verdicts would diverge from the
                # uncrashed run's (crash-equivalence law)
                health_policy=(
                    self.health_policy.report()
                    if self.health_policy is not None
                    and hasattr(self.health_policy, "report")
                    else None
                ),
                checkpoint_dir=(
                    str(self.checkpoint_dir)
                    if self.checkpoint_dir is not None
                    else None
                ),
                slots=[getattr(s, "_journal_seq", None) for s in specs],
            )
        self.state = state
        self.slots = [_Slot(spec=s) for s in specs]
        fresh = [i for i, (k, _) in enumerate(units) if k == "spec"]
        self.counters["admitted"] += len(fresh)
        if self.metrics is not None and fresh:
            # start()'s batch seating bypasses _install for fresh specs
            # (one vmapped init instead of N surgeries) — mirror it, or
            # the SLO ledger under-counts exactly the first fleet-full
            # of admissions and the coherence validator flags every run
            self.metrics.count("slo.admissions", len(fresh))
        if self.journal is not None:
            for i in fresh:
                self.journal.append(
                    "admit",
                    slot=i,
                    spec_seq=getattr(specs[i], "_journal_seq", None),
                    fleet_generation=0,
                )
        # continuation slots: the fresh-init state above is a shape
        # donor only — replace it with the parked tenant by the standard
        # surgery (which journals its own resumed admit and counts it)
        for i, (k, u) in enumerate(units):
            if k == "cont":
                self._install(
                    i, u["spec"], self._continuation_state(u), resumed=True
                )
        return self.state

    def _stack_hp(self, hp_dicts: List[Dict[str, Any]]) -> Dict[str, Any]:
        names = set(self.workflow.hyperparams)
        for d in hp_dicts:
            if set(d) != names:
                raise ValueError(
                    f"spec hyperparams {sorted(d)} must use exactly the "
                    f"fleet's hyperparam names {sorted(names)}"
                )
        return {
            name: jnp.stack([jnp.asarray(d[name]) for d in hp_dicts])
            for name in names
        }

    def _dispatch(self, n: int) -> None:
        wf = self.workflow
        running = sum(1 for s in self.slots if s is not None and s.active)
        self.state = self.executor.run_fused(
            wf, self.state, n, supervisor=self.supervisor
        )
        self.counters["chunks"] += 1
        if self.metrics is not None:
            # tenant-generations actually SERVED this chunk: n fused
            # generations × tenants doing real work (parked/frozen rows
            # step in lockstep but serve nobody) — the SLO ledger's
            # numerator, accumulated at the dispatch boundary
            self.metrics.count("slo.tenant_gens", n * running)
            self.metrics.count("queue.chunks")

    def _tenant_generations(self):
        """Per-slot OWN generation counters, read from the state (one
        tiny (N,) int32 fetch — the authoritative ledger the budgets are
        checked against)."""
        import numpy as np

        return np.asarray(jax.device_get(self.state.tenants.generation))

    def _sweep(self):
        """Retire every active tenant at/over budget, refill idle slots
        from the pending queue (covers specs submitted after a previous
        ``run()`` drained the fleet). Loops until stable: a freshly
        admitted tenant whose solo peel already met a 1-generation
        budget retires in the next pass instead of forcing a
        zero-length dispatch. Returns the final per-slot generation
        ledger so the caller doesn't refetch it."""
        changed = True
        gens = self._tenant_generations()
        while changed:
            changed = False
            for i, slot in enumerate(self.slots):
                if (
                    slot is not None
                    and slot.active
                    and gens[i] >= slot.spec.n_steps
                ):
                    self._retire(i, status="completed")
                    changed = True
            for i, slot in enumerate(self.slots):
                if (
                    (slot is None or not slot.active)
                    and not (slot is not None and slot.frozen)
                    and (self.pending or self.continuations)
                ):
                    self._refill(i)
                    changed = True
            if changed:
                # surgery/retirement changed the ledger; refresh once
                # per pass (the fetch is a tiny (N,) int32, but on the
                # tunnel every round-trip counts)
                gens = self._tenant_generations()
        return gens

    def step_chunk(self) -> bool:
        """Run one dispatch chunk, retire/refill finished tenants, apply
        the health policy, and (with a journal) write the chunk barrier:
        fleet snapshot on the executor's background checkpoint lane plus
        a ``chunk_complete`` journal record. Returns True while work
        remains (active tenants or pending specs). Between calls is the
        legal window for :meth:`evict`."""
        if self.state is None:
            self.start()
        gens = self._sweep()
        # SLA pass BEFORE sizing the chunk: an urgent deadlined spec may
        # preempt its way in, and the chunk length must honor the
        # freshly admitted tenant's budget
        gens = self._apply_sla(gens)
        active = [
            (i, s) for i, s in enumerate(self.slots)
            if s is not None and s.active
        ]
        if not active:
            self._finish()
            return False
        # int(): the budget term is np.int32 (the generation ledger) and
        # the chunk term a python int — left mixed, the dispatched
        # operand's abstract type flips between weak and strong int32
        # depending on which term wins, which reads as a retrace to the
        # strict detector watching the run entry
        n = int(
            min(
                self.chunk,
                min(s.spec.n_steps - gens[i] for i, s in active),
            )
        )
        self._dispatch(n)
        self._sweep()
        self._apply_health_policy()
        self._barrier()
        if self.metrics is not None:
            # the per-chunk sample: queue-depth gauges plus one durable
            # full-registry snapshot whose embedded `queue` counters are
            # the validator's coherence referee (check_report re-checks
            # slo.* against them on every sample record)
            m = self.metrics
            m.set("queue.pending", len(self.pending))
            m.set("queue.continuations", len(self.continuations))
            m.set(
                "queue.running",
                sum(1 for s in self.slots if s is not None and s.active),
            )
            m.sample(
                queue=dict(self.counters),
                generation=int(self.state.generation),
            )
        more = (
            any(s is not None and s.active for s in self.slots)
            or bool(self.pending)
            or bool(self.continuations)
        )
        if not more:
            self._finish()
        return more

    def run(self) -> List[dict]:
        """Drive everything submitted so far to completion."""
        if self.state is None:
            self.start()
        while self.step_chunk():
            pass
        return self.results

    def _finish(self) -> None:
        """Sweep complete: flush the background snapshot lane (a failed
        background fsync must fail the run, not vanish) and mark the
        queue finished — the point at which a NEW RunQueue may adopt
        this workflow (the backref detach contract)."""
        if self.journal is not None:
            self.executor.drain_lane("fleet_snapshot")
        self.finished = True

    # ----------------------------------------------------- durability barrier
    def _barrier(self) -> None:
        """The per-chunk durability barrier: snapshot the whole fleet on
        the executor's background checkpoint lane, then append a
        ``chunk_complete`` record embedding the queue's complete host
        bookkeeping (pending, slots, counters, results length). Recovery
        restores the newest barrier whose snapshot is intact and replays
        the lost stretch deterministically; the journal append is
        synchronous (WAL) while the snapshot pickles in the background —
        a barrier whose snapshot never landed is skipped at recovery."""
        if self.journal is None:
            return
        state, ckpt = self.state, self._fleet_ckpt
        self.executor.submit_background(
            "fleet_snapshot",
            lambda: ckpt.save(state),
            counter="bg_checkpoint",
        )
        gen = int(state.generation)
        # the attestation is computed BEFORE the background pickle runs:
        # the journal pins the digest of the bits the barrier describes,
        # not whatever the snapshot file ends up holding (one jitted
        # dispatch; only the digest words are fetched)
        extra = {}
        if self.attest is not None:
            att_rec = self.attest.attestation(state)
            att_rec["generation"] = gen
            extra["attest"] = att_rec
        self.journal.append(
            "chunk_complete",
            generation=gen,
            snapshot=str(ckpt.directory / f"ckpt_{gen:08d}.pkl"),
            config_sha=self._config_sha,
            pending=[getattr(s, "_journal_seq", None) for s in self.pending],
            continuations=[
                {
                    "seq": c.get("seq"),
                    "checkpoint": c.get("checkpoint"),
                    "done": c.get("done"),
                }
                for c in self.continuations
            ],
            slots=[
                None
                if s is None
                else {
                    "seq": getattr(s.spec, "_journal_seq", None),
                    "active": s.active,
                    "frozen": s.frozen,
                }
                for s in self.slots
            ],
            counters=dict(self.counters),
            results_len=len(self.results),
            health_len=len(self.health_events),
            slot_restarts=list(self._slot_restarts),
            **extra,
        )

    # ------------------------------------------------------- health policy
    def _apply_health_policy(self) -> None:
        """Evaluate the fleet health policy at the chunk boundary and
        apply per-slot actions. Pure function of the (restored) state
        and slot table, so crash recovery replays identical verdicts."""
        if self.health_policy is None:
            return
        from .fleet_health import fleet_health_signals

        signals = fleet_health_signals(self.state)
        for i, slot in enumerate(self.slots):
            if slot is None or not slot.active:
                continue
            row = {k: v[i] for k, v in signals.items()}
            verdict = self.health_policy.decide(row, self._slot_restarts[i])
            if verdict is None:
                continue
            action, reason = verdict
            event = {
                "health_seq": len(self.health_events),
                "chunk": self.counters["chunks"],
                "slot": i,
                "tag": slot.spec.tag,
                "action": action,
                "reason": reason,
                "generation": int(row["generation"]),
            }
            if self.journal is not None:
                self.journal.append("health", **event)
            self.health_events.append(event)
            if self.metrics is not None:
                self.metrics.count(f"health.{action}")
            if action == "freeze":
                self._freeze(i)
            elif action == "evict":
                self.counters["evicted"] += 1
                self._close_out(i, status="evicted")
                # the evicted tenant was by definition unhealthy: if the
                # slot parked (pending empty), mask its rows too
                self._mask_parked(i)
            elif action == "restart":
                self._restart_slot(i)

    def _freeze(self, index: int) -> None:
        """Quarantine a slot in place: close it out (forensic checkpoint
        + result entry, status ``"frozen"``), mask its tell inside the
        fused step, and park the slot — never refilled, so the poisoned
        state stays inspectable at fixed fleet shape."""
        slot = self.slots[index]
        self.counters["frozen"] += 1
        self._close_out(index, status="frozen", refill=False)
        slot.frozen = True
        self.state = self.workflow.set_frozen(self.state, index, True)

    def _restart_slot(self, index: int) -> None:
        """Restart a slot in place (the guardrail ``recenter_state``
        path, budget preserved): deterministic in (spec, fleet
        generation), so recovery replays the identical restart."""
        from .fleet_health import restarted_tenant

        slot = self.slots[index]
        old = jax.device_get(
            jax.tree.map(lambda x: x[index], self.state.tenants)
        )
        fresh = restarted_tenant(
            self.workflow,
            old,
            slot.spec.key(),
            int(self.state.generation),
            slot.spec.hyperparams,
        )
        self.state = self.workflow.insert_tenant(self.state, index, fresh)
        self._slot_restarts[index] += 1
        self.counters["restarted"] += 1

    # ------------------------------------------------------- retire / evict
    def _tenant_dir(self, slot: _Slot, index: int) -> Optional[Path]:
        if self.checkpoint_dir is None:
            return None
        name = slot.spec.tag or f"tenant_{self.counters['retired'] + self.counters['evicted']:04d}_slot{index}"
        # never share a snapshot directory between two close-outs: the
        # config fingerprint cannot tell two same-shape searches apart,
        # so a reused tag would let one tenant's snapshot silently
        # shadow the other's on resume
        if name in self._used_dirs:
            seq = 2
            while f"{name}_{seq}" in self._used_dirs:
                seq += 1
            name = f"{name}_{seq}"
        self._used_dirs.add(name)
        return self.checkpoint_dir / name

    def _extract(self, index: int) -> StdWorkflowState:
        # the tenant's own generation counter rides in the state itself
        return self.workflow.extract_tenant(self.state, index)

    def _close_out(self, index: int, status: str, refill: bool = True) -> dict:
        slot = self.slots[index]
        solo = self._extract(index)
        entry: dict = {
            "tag": slot.spec.tag,
            "slot": index,
            "status": status,
            "generations": int(solo.generation),
            "budget": slot.spec.n_steps,
        }
        tenant_dir = self._tenant_dir(slot, index)
        if tenant_dir is not None:
            ckpt = WorkflowCheckpointer(
                str(tenant_dir), every=max(int(solo.generation), 1),
                keep=self.keep,
            )
            ckpt.save(solo)
            entry["checkpoint"] = str(tenant_dir)
        reports = self.workflow.monitor_reports(solo.monitors)
        if reports:
            entry["monitors"] = reports
        # the crash law's referee: any monitor exposing fingerprint()
        # (TelemetryMonitor's ring digest) stamps the close-out, so
        # recovered and uncrashed sweeps are comparable record-for-record
        prints = [
            mon.fingerprint(solo.monitors[j])
            for j, mon in enumerate(self.workflow.monitors)
            if hasattr(mon, "fingerprint")
        ]
        if prints:
            entry["fingerprints"] = prints
        entry["hyperparams"] = {
            k: jnp.asarray(v).tolist()
            for k, v in self.workflow.tenant_hyperparams(
                index, state=self.state
            ).items()
        }
        if self.metrics is not None:
            fleet_gen = int(self.state.generation)
            deadline = slot.spec.deadline
            if deadline is not None and status in (
                "completed", "evicted", "frozen",
            ):
                # the SLO ledger's verdict column: a deadlined spec is
                # settled ONLY at a terminal close-out (preemption and
                # growth park continuations — the contract still stands)
                if status == "completed" and fleet_gen <= int(deadline):
                    self.metrics.count("slo.deadline_hits")
                else:
                    self.metrics.count("slo.deadline_misses")
            self.metrics.event(
                f"queue.{status}",
                tag=slot.spec.tag,
                slot=index,
                generations=entry["generations"],
            )
            if status in ("evicted", "frozen"):
                # every queue post-mortem carries the black-box tape
                entry["flight_recorder"] = self.metrics.tail(20)
        if self.journal is not None:
            kind = {
                "evicted": "evict",
                "frozen": "freeze",
                "preempted": "preempt",
                "grown": "autoscale",
            }.get(status, "retire")
            self.journal.append(
                kind,
                result_seq=len(self.results),
                spec_seq=getattr(slot.spec, "_journal_seq", None),
                config_sha=self._config_sha,
                entry=entry,
            )
        slot.active = False
        self.results.append(entry)
        if refill:
            self._refill(index)
        return entry

    def _retire(self, index: int, status: str) -> dict:
        self.counters["retired"] += 1
        return self._close_out(index, status)

    def evict(self, index: int) -> dict:
        """Evict slot ``index`` mid-run (between chunks): its state is
        extracted as a solo snapshot (checkpointed when a directory is
        configured — the RESUMABLE artifact), the result is recorded
        with status ``"evicted"``, and the slot is refilled from the
        pending queue (or parked as inactive when pending is empty —
        never an error). Resume the evicted search with
        ``workflow.solo_workflow(hyperparams=...).run(...,
        resume_from=<checkpoint>)``. Legal only between chunks of a
        STARTED queue: evicting before ``start()`` (or a bogus slot
        index) raises instead of corrupting the slot table."""
        if self.state is None:
            raise RuntimeError(
                "RunQueue.evict before start(): there is no fleet state "
                "to extract a tenant from — the legal eviction window is "
                "between step_chunk() calls"
            )
        if not 0 <= index < len(self.slots):
            raise ValueError(
                f"slot index {index} out of range for a "
                f"{len(self.slots)}-wide fleet"
            )
        slot = self.slots[index]
        if slot is None or not slot.active:
            raise ValueError(f"slot {index} has no active tenant to evict")
        self.counters["evicted"] += 1
        entry = self._close_out(index, status="evicted")
        self._mask_parked(index)
        return entry

    def _mask_parked(self, index: int) -> None:
        """After an eviction whose slot could NOT be refilled (pending
        empty), the parked slot may still hold a poisoned tenant that
        would keep churning NaNs through the fused step — with a freeze
        mask available, stop its rows. Unlike a health-policy freeze,
        the SLOT stays refillable: the mask bit (not ``slot.frozen``) is
        set, and the next admission clears it — a late ``submit()``
        still admits into the parked slot."""
        slot = self.slots[index]
        if (
            slot is not None
            and not slot.active
            and not slot.frozen
            and self.state.frozen is not None
        ):
            self.state = self.workflow.set_frozen(self.state, index, True)

    @staticmethod
    def _edf_key(spec: TenantSpec):
        return (spec.deadline, getattr(spec, "_journal_seq", 0))

    def _fresh_tenant(self, spec: TenantSpec) -> TenantState:
        wf = self.workflow
        solo = wf.init_tenant(spec.key(), spec.hyperparams)
        if wf.algorithm.has_init_ask or wf.algorithm.has_init_tell:
            # algorithms with a distinct first generation peel it SOLO:
            # the fleet's steady vmapped step must never dispatch
            # init_ask/init_tell for one slot only (static shape law).
            # The peel is the fleet's own jitted single-tenant step with
            # the bindings as traced operands — one compile serves every
            # admission (and advances the tenant's own generation to 1)
            solo = wf._solo_peel(solo)
        return solo

    def _continuation_state(self, cont: dict) -> Any:
        if cont.get("state") is not None:
            return cont["state"]
        from .checkpoint import _as_checkpointer

        solo = _as_checkpointer(cont["checkpoint"]).latest()
        if solo is None:
            raise RuntimeError(
                f"continuation checkpoint {cont['checkpoint']} holds no "
                "intact snapshot — the parked tenant cannot be resumed"
            )
        return solo

    def _refill(self, index: int) -> None:
        """Admit the next unit of work into a freed slot, or park the
        slot (it keeps stepping in lockstep; its results are ignored).
        Priority: deadlined work in EDF order — pending specs AND parked
        deadlined continuations compete in one EDF ladder (a preempted
        deadlined victim keeps its SLA standing; exempting it would let
        fresh deadlined arrivals starve it) — then parked continuations
        (they were displaced to make room — they return before new FIFO
        arrivals), then FIFO pending."""
        if not self.pending and not self.continuations:
            return
        kind, unit = self._take_next_unit()
        if kind == "spec":
            self._install(index, unit, self._fresh_tenant(unit), resumed=False)
        else:
            self._install(
                index,
                unit["spec"],
                self._continuation_state(unit),
                resumed=True,
            )

    def _take_next_unit(self) -> Tuple[str, Any]:
        """Remove and return the next admissible unit of work under the
        ``_refill`` priority ladder: EDF across ALL deadlined work
        (pending specs and parked continuations), then parked
        continuations FIFO, then pending FIFO. Returns
        ``("spec", TenantSpec)`` or ``("cont", continuation_dict)``."""
        dl_cont = [
            c for c in self.continuations
            if c["spec"].deadline is not None
        ]
        best_c = (
            min(dl_cont, key=lambda c: self._edf_key(c["spec"]))
            if dl_cont
            else None
        )
        dl_pend = [s for s in self.pending if s.deadline is not None]
        best_p = min(dl_pend, key=self._edf_key) if dl_pend else None
        if best_c is not None and (
            best_p is None
            or self._edf_key(best_c["spec"]) < self._edf_key(best_p)
        ):
            self.continuations.remove(best_c)
            return ("cont", best_c)
        if self.pending and (best_p is not None or not self.continuations):
            if best_p is not None:
                self.pending.remove(best_p)
                return ("spec", best_p)
            return ("spec", self.pending.pop(0))
        return ("cont", self.continuations.pop(0))

    def _install(
        self, index: int, spec: TenantSpec, solo: Any, resumed: bool
    ) -> None:
        wf = self.workflow
        hp = (
            {k: jnp.asarray(v) for k, v in spec.hyperparams.items()}
            if resumed
            else None  # fresh TenantState carries its own bindings
        )
        self.state = wf.insert_tenant(self.state, index, solo, hyperparams=hp)
        if self.state.frozen is not None:
            self.state = wf.set_frozen(self.state, index, False)
        self.slots[index] = _Slot(spec=spec)
        self._slot_restarts[index] = 0
        self.counters["admitted"] += 1
        if resumed:
            self.counters["readmitted"] += 1
        if self.metrics is not None:
            # EDF admissions land here too (the SLA pass installs its
            # urgent spec through _install) — one site keeps the SLO
            # ledger coherent with counters["admitted"] by construction
            self.metrics.count("slo.admissions")
            if resumed:
                self.metrics.count("queue.readmissions")
        if self.journal is not None:
            self.journal.append(
                "admit",
                slot=index,
                spec_seq=getattr(spec, "_journal_seq", None),
                fleet_generation=int(self.state.generation),
                resumed=resumed,
            )
        # restore coherence: the supervisor's newest snapshot must
        # contain the ADMITTED tenant — its restore rung would otherwise
        # resurrect a pre-admission fleet (structurally identical, so
        # the config guard cannot object) and silently attribute the old
        # tenant's trajectory to this spec
        ckpt = getattr(self.supervisor, "checkpointer", None)
        if ckpt is not None:
            ckpt.save(self.state)

    # ------------------------------------------------------ SLA scheduling
    def _apply_sla(self, gens):
        """Deadline-weighted admission + preemption, evaluated before
        each chunk dispatch. Every quantity is measured in fleet
        generations or journal order — never wall clock — so recovery
        replays the identical decisions (the PR-11 determinism law).

        Rule: a pending deadlined spec that could NOT meet its deadline
        after waiting one more chunk (``fleet_gen + chunk + n_steps >
        deadline``) must be admitted now. If no slot is free, preempt
        the "most over-budget" running tenant — the one holding its slot
        longest (max remaining generations) among tenants that are not
        deadline-tight themselves. The victim parks as a standard
        eviction checkpoint and is auto-resubmitted as a continuation
        (:meth:`submit_resume`): preemption trades the victim's latency,
        never its work. Returns the refreshed generation ledger."""
        # a deadlined tenant parked as a preemption continuation keeps
        # competing under the same SLA contract as fresh deadlined
        # arrivals: exempting it would let a stream of new deadlined
        # specs starve it past its deadline with no escalation,
        # contradicting "latency traded, never work"
        units = sorted(
            [("pending", s, s) for s in self.pending
             if s.deadline is not None]
            + [("cont", c, c["spec"]) for c in self.continuations
               if c["spec"].deadline is not None],
            key=lambda u: self._edf_key(u[2]),
        )
        if not units:
            return gens
        # ONE fetch for the whole pass: nothing below advances the
        # fleet generation (preemption/admission are state surgery), and
        # on the tunneled TPU every fetch is a 45-100 ms round trip
        fleet_gen = int(self.state.generation)
        for kind, unit, spec in units:
            # remaining work: exact for a fresh spec, and for a parked
            # continuation whose park-time progress was recorded
            # (``done``); only a done-less continuation (a pre-PR-12
            # journal) falls back to the n_steps upper bound with a
            # 1-generation lower bound for the doomed test — err urgent
            # on the wait side, only skip when provably lost
            if kind == "pending":
                remaining_hi = remaining_lo = spec.n_steps
            elif unit.get("done") is not None:
                remaining_hi = remaining_lo = max(
                    spec.n_steps - int(unit["done"]), 1
                )
            else:
                remaining_hi, remaining_lo = spec.n_steps, 1
            if fleet_gen + remaining_lo > spec.deadline:
                continue  # provably doomed: preemption cannot save it —
                # it stays queued best-effort in EDF order; parking a
                # healthy victim for a guaranteed miss is pure thrash
            if fleet_gen + self.chunk + remaining_hi <= spec.deadline:
                continue  # can still afford to wait one chunk
            # a parked (refillable) slot admits without preemption —
            # _sweep already refilled those in SLA order, so reaching
            # here means every slot is busy (or frozen)
            victim = self._preempt_victim(spec, gens, fleet_gen)
            if victim is None:
                continue  # nothing preemptible: best-effort, no thrash
            self._preempt(victim)
            if kind == "pending":
                self.pending.remove(unit)
                self._install(
                    victim, spec, self._fresh_tenant(spec), resumed=False
                )
            else:
                self.continuations.remove(unit)
                self._install(
                    victim, spec,
                    self._continuation_state(unit), resumed=True,
                )
            # refresh the ledger NOW: a later unit's victim scan must
            # see the just-installed tenant's (zero/resumed) progress,
            # not the preempted tenant's — a stale count would let unit
            # B immediately preempt unit A at zero generations of
            # progress (pure thrash, A tight by construction)
            gens = self._tenant_generations()
        return gens

    def _preempt_victim(
        self, spec: TenantSpec, gens, fleet_gen: int
    ) -> Optional[int]:
        best, best_remaining = None, 0
        for i, slot in enumerate(self.slots):
            if slot is None or not slot.active or slot.frozen:
                continue
            remaining = int(slot.spec.n_steps - gens[i])
            if remaining <= 0:
                continue
            d = slot.spec.deadline
            if d is not None and fleet_gen + self.chunk + remaining > d:
                continue  # itself deadline-tight: preempting it just
                # moves the miss, never removes it
            if remaining > best_remaining:
                best, best_remaining = i, remaining
        return best

    def _preempt(self, index: int) -> None:
        slot = self.slots[index]
        self.counters["preempted"] += 1
        if self.metrics is not None:
            # the discrete event itself rides the _close_out status
            # record (`queue.preempted`); only the ledger counter here
            self.metrics.count("slo.preemptions")
        entry = self._close_out(index, status="preempted", refill=False)
        ckpt_dir = entry.get("checkpoint")
        if ckpt_dir is None:
            # _validate_spec guarantees a checkpoint_dir whenever a
            # deadlined spec (the only preemption trigger) is accepted
            raise RuntimeError(
                "preempted a tenant without a checkpoint directory — "
                "its work would be lost (this is a bug: deadlined specs "
                "require checkpoint_dir at submit())"
            )
        self.submit_resume(
            slot.spec,
            checkpoint=ckpt_dir,
            done=int(entry.get("generations") or 0),
        )

    # ------------------------------------------------------------- recovery
    @classmethod
    def recover(
        cls,
        workflow: VectorizedWorkflow,
        journal_dir: str,
        supervisor: Any = None,
        executor: Any = None,
        health_policy: Any = None,
        allow_config_mismatch: bool = False,
        metrics: Any = None,
        attest: Any = None,
    ) -> "RunQueue":
        """Rebuild a journaled sweep after the driver died — at ANY
        point, including mid-background-fsync.

        Reads the journal (hash chain verified; a torn tail is truncated
        with a warning, a tampered middle raises
        :class:`~evox_tpu.workflows.journal.JournalIntegrityError`),
        checks the journaled config fingerprint against ``workflow``
        (mismatch raises :class:`CheckpointConfigError` — the PR-5
        guard, not a new one), restores the fleet from the newest chunk
        barrier whose snapshot is provably intact (torn snapshots are
        skipped, falling back one barrier), and rebuilds
        pending/slots/counters/results exactly as they stood at that
        barrier. Driving the returned queue (``q.run()``) replays the
        lost stretch deterministically: per-tenant results and telemetry
        fingerprints equal the uncrashed run's, each spec admitted
        exactly once.
        """
        from .checkpoint import state_config_fingerprint
        from .journal import RunJournal

        journal = (
            journal_dir
            if isinstance(journal_dir, RunJournal)
            else RunJournal(str(journal_dir))
        )
        recs = journal.records()
        specs: Dict[int, TenantSpec] = {}
        resume_from: Dict[int, Optional[str]] = {}
        resume_done: Dict[int, Optional[int]] = {}
        for r in recs:
            if r["kind"] == "submit":
                seq = int(r["spec_seq"])
                specs[seq] = _spec_from_record(r)
                if r.get("resume_from") is not None:
                    # a continuation submit (preemption / elastic
                    # growth): its tenant resumes from the named
                    # checkpoint, never a fresh init
                    resume_from[seq] = r["resume_from"]
                    resume_done[seq] = (
                        int(r["done"]) if r.get("done") is not None else None
                    )
        start = next((r for r in recs if r["kind"] == "start"), None)
        ckpt_dir = start.get("checkpoint_dir") if start is not None else None
        if (
            health_policy is None
            and start is not None
            and start.get("health_policy")
        ):
            # the journaled policy config rides the start record so the
            # replay keeps isolating poisoned tenants exactly as the
            # uncrashed run would — an explicit health_policy= overrides
            from .fleet_health import FleetHealthPolicy

            health_policy = FleetHealthPolicy(**start["health_policy"])
        q = cls(
            workflow,
            chunk=int(start["chunk"]) if start is not None else 10,
            supervisor=supervisor,
            checkpoint_dir=ckpt_dir,
            keep=int(start.get("keep", 2)) if start is not None else 2,
            executor=executor,
            journal=journal,
            health_policy=health_policy,
            metrics=metrics,
            attest=attest,
        )
        q._spec_seq = max(specs, default=-1) + 1
        q.counters["submitted"] = len(specs)
        def _requeue_all() -> None:
            # continuations born from a preemption/growth close-out IN
            # THIS journal are replay-derived: their original spec is
            # requeued fresh below and the replay re-creates the
            # continuation — requeueing both would run the tenant twice.
            # Cross-journal continuations (elastic growth admits into
            # the TARGET bucket's journal) have no matching close-out
            # here and are kept.
            derived = {
                (r.get("entry") or {}).get("checkpoint")
                for r in recs
                if r["kind"] in ("preempt", "autoscale")
            }
            # a stolen seq is already durable in ANOTHER pod's journal
            # (the steal record is appended only after the target submit
            # fsynced) — requeueing it here would run the tenant twice,
            # once per pod
            stolen = {
                int(r["spec_seq"])
                for r in recs
                if r["kind"] == "steal" and r.get("spec_seq") is not None
            }
            q.pending = [
                specs[s]
                for s in sorted(specs)
                if s not in resume_from and s not in stolen
            ]
            q.continuations = []
            seen_ckpts: set = set()
            for s in sorted(specs):
                if s in stolen:
                    continue
                if s not in resume_from or resume_from[s] in derived:
                    continue
                if resume_from[s] in seen_ckpts:
                    continue  # replay-duplicated submit for one parked
                    # checkpoint (lowest seq wins — the claimed dedup)
                seen_ckpts.add(resume_from[s])
                q.continuations.append(
                    {
                        "spec": specs[s],
                        "seq": s,
                        "checkpoint": resume_from[s],
                        "state": None,
                        "done": resume_done.get(s),
                    }
                )

        if start is None:
            # crashed before (or during) start(): nothing ran to a
            # durable barrier — the whole sweep re-queues and starts
            # fresh, each spec still executed exactly once overall
            _requeue_all()
            journal.append("recover", generation=None, snapshot=None)
            if q.metrics is not None:
                q.metrics.restore_at(generation=None)
            return q
        # --- config guard (PR 5 fingerprint, reused): the supplied
        # workflow must produce the SAME fleet state structure the
        # journal was written under. eval_shape traces init without
        # running it — shapes/dtypes are all the fingerprint reads.
        first_wave = [specs[s] for s in start["slots"]]
        try:
            hp = q._stack_hp([s.hyperparams for s in first_wave])
            keys = jnp.stack([s.key() for s in first_wave])
            expect = jax.eval_shape(
                partial(workflow.init, hyperparams=hp), keys
            )
            if start.get("freeze_mask"):
                import numpy as np

                expect = expect.replace(
                    frozen=jax.ShapeDtypeStruct(
                        (workflow.n_tenants,), np.bool_
                    )
                )
            expected_sha = state_config_fingerprint(expect)
        except Exception as e:
            raise CheckpointConfigError(
                "the supplied workflow cannot even rebuild the journaled "
                f"fleet structure ({type(e).__name__}: {e}) — algorithm, "
                "hyperparameter names, or fleet width changed since the "
                "journal was written"
            ) from e
        recorded = start.get("config_sha")
        if (
            recorded is not None
            and recorded != expected_sha
            and not allow_config_mismatch
        ):
            raise CheckpointConfigError(
                f"journal {journal.path} was written under a different "
                f"fleet config (journal config_sha {recorded[:12]}… != "
                f"supplied workflow's {expected_sha[:12]}…): algorithm, "
                "population size, fleet width, monitors, or hyperparam "
                "names changed. Rebuild the matching workflow or pass "
                "allow_config_mismatch=True."
            )
        q._config_sha = recorded or expected_sha
        # --- newest barrier with an intact snapshot
        barriers = [r for r in recs if r["kind"] == "chunk_complete"]
        meta: Optional[dict] = None
        state = None
        verifier = q.attest
        for b in reversed(barriers):
            state = q._fleet_ckpt.load(int(b["generation"]))
            if state is None:
                continue
            att_rec = b.get("attest")
            if att_rec is not None:
                # the journal pinned a digest of the fleet bits at this
                # barrier — refuse a snapshot whose BITS drifted even if
                # its pickle bytes are internally sha256-consistent
                # (file swapped/rebuilt after the fact), naming the
                # splitting leaves and falling back one barrier
                if verifier is None:
                    from ..core.attest import StateAttestor

                    verifier = StateAttestor()
                try:
                    verifier.verify(
                        state,
                        att_rec,
                        generation=int(b["generation"]),
                        where=f"fleet snapshot {b.get('snapshot')}",
                    )
                except IntegrityError as e:
                    event = {
                        "event": "corrupt_snapshot",
                        "generation": int(b["generation"]),
                        "snapshot": b.get("snapshot"),
                        "leaves": list(e.leaves),
                        "action": "barrier_fallback",
                    }
                    q.integrity_events.append(event)
                    journal.append("integrity", **event, error=str(e)[:300])
                    if q.metrics is not None:
                        q.metrics.count("integrity.recover_refusals")
                        q.metrics.event(
                            "integrity.corrupt_snapshot", **event
                        )
                    state = None
                    continue
            meta = b
            break
        if meta is None:
            # start()ed but no barrier landed (killed in the first chunk
            # or mid-first-fsync): re-queue everything and start fresh
            _requeue_all()
            journal.append("recover", generation=None, snapshot=None)
            if q.metrics is not None:
                q.metrics.restore_at(generation=None)
            return q
        state = workflow.place_restored(state)
        if (
            health_policy is not None
            and health_policy.may_freeze()
            and state.frozen is None
        ):
            state = workflow.with_freeze_mask(state)
        q.state = state
        # a steal record (pre- OR post-barrier) marks work that is
        # already durable in another pod's journal — the WAL order
        # (target submit fsynced before the steal is appended here)
        # makes honoring EVERY steal safe: the barrier may predate the
        # steal, but the moved work must not be restored into this
        # queue or it runs twice, once per pod
        stolen = {
            int(r["spec_seq"])
            for r in recs
            if r["kind"] == "steal" and r.get("spec_seq") is not None
        }
        q.pending = [
            specs[s] for s in meta["pending"] if int(s) not in stolen
        ]
        q.continuations = [
            {
                "spec": specs[int(c["seq"])],
                "seq": int(c["seq"]),
                "checkpoint": c.get("checkpoint"),
                "state": None,
                "done": (
                    int(c["done"]) if c.get("done") is not None else None
                ),
            }
            for c in meta.get("continuations", []) or []
            if int(c["seq"]) not in stolen
        ]
        q.slots = [
            None
            if s is None
            else _Slot(
                spec=specs[s["seq"]],
                active=bool(s["active"]),
                frozen=bool(s.get("frozen", False)),
            )
            for s in meta["slots"]
        ]
        # merge (not replace): barriers written before a counter existed
        # (older journals) must not strip it from the live dict
        q.counters.update({k: int(v) for k, v in meta["counters"].items()})
        # the WAL records every acknowledged submit — len(specs) is the
        # ground truth, not the barrier-time snapshot (a spec submitted
        # AFTER the barrier is requeued below and must stay counted)
        q.counters["submitted"] = len(specs)
        q._slot_restarts = [
            int(v)
            for v in meta.get(
                "slot_restarts", [0] * workflow.n_tenants
            )
        ]
        # close-outs and health events that were durable AT the barrier;
        # later records describe work the crash rolled back — the replay
        # re-executes (and re-journals) them with identical content
        closeouts = {
            int(r["result_seq"]): r["entry"]
            for r in recs
            if r["kind"] in (
                "retire", "evict", "freeze", "preempt", "autoscale",
            )
        }
        q.results = [closeouts[i] for i in range(int(meta["results_len"]))]
        # --- mid-sweep submits (the WAL law: an ACKNOWLEDGED submit
        # survives a crash). SLA work arrives mid-sweep by nature, so a
        # spec journaled after the restored barrier appears in no
        # barrier list — requeue every seq the barrier does not account
        # for: not pending/parked/slotted at the barrier, and not closed
        # out by a record that was durable BEFORE it (close-outs after
        # the barrier describe progress the crash rolled back; their
        # tenants are still in meta["slots"], so they stay accounted)
        barrier_pos = next(
            i for i, r in enumerate(recs) if r is meta
        )
        accounted = (
            {int(s) for s in meta["pending"] if s is not None}
            | {int(c["seq"]) for c in q.continuations}
            | {
                int(s["seq"]) for s in meta["slots"] if s is not None
            }
            | {
                int(r["spec_seq"])
                for r in recs[:barrier_pos]
                if r["kind"]
                in ("retire", "evict", "freeze", "preempt", "autoscale")
                and r.get("spec_seq") is not None
            }
        )
        # ...EXCEPT continuations born from a post-barrier preemption:
        # their victim is still RUNNING in the restored slots, and the
        # deterministic replay re-derives the preemption (and re-journals
        # an identical continuation) — requeueing the crashed-off one
        # would run the tenant twice
        replay_derived = {
            (r.get("entry") or {}).get("checkpoint")
            for r in recs[barrier_pos:]
            if r["kind"] in ("preempt", "autoscale")
        }
        # ...and dedup by the parked CHECKPOINT itself: after a PRIOR
        # crash the replay re-journals a continuation under a NEW seq
        # for the same parked checkpoint — once any seq resuming from
        # that checkpoint is accounted (or requeued first, lowest seq
        # wins), a second seq must not admit the same work twice
        claimed = {
            resume_from[s] for s in accounted if s in resume_from
        }
        for seq in sorted(specs):
            if seq in accounted or seq in stolen:
                continue
            if seq in resume_from:
                ck = resume_from[seq]
                if ck in replay_derived or ck in claimed:
                    continue
                claimed.add(ck)
                q.continuations.append(
                    {
                        "spec": specs[seq],
                        "seq": seq,
                        "checkpoint": ck,
                        "state": None,
                        "done": resume_done.get(seq),
                    }
                )
            else:
                q.pending.append(specs[seq])
        healths = {
            int(r["health_seq"]): {
                k: v
                for k, v in r.items()
                if k in (
                    "health_seq", "chunk", "slot", "tag", "action",
                    "reason", "generation",
                )
            }
            for r in recs
            if r["kind"] == "health"
        }
        q.health_events = [
            healths[i] for i in range(int(meta.get("health_len", 0)))
        ]
        q._used_dirs = {
            Path(e["checkpoint"]).name
            for e in q.results
            if e.get("checkpoint")
        }
        q.finished = False
        journal.append(
            "recover",
            generation=int(meta["generation"]),
            snapshot=meta.get("snapshot"),
        )
        if q.metrics is not None:
            # restore the metrics plane to the SAME barrier the fleet
            # came back to: the replayed stretch re-counts exactly what
            # the crash rolled back, so the post-crash SLO ledger
            # converges to the uncrashed run's (the validator resets its
            # monotonicity baseline at the queue.recover event)
            q.metrics.restore_at(generation=int(meta["generation"]))
        return q

    # -------------------------------------------------------------- report
    def health_report(self) -> Optional[dict]:
        """The ``tenancy.fleet_health`` section: policy config + the
        chunk-boundary action log. None when no policy ever acted."""
        if self.health_policy is None and not self.health_events:
            return None
        return {
            "policy": (
                self.health_policy.report()
                if self.health_policy is not None
                and hasattr(self.health_policy, "report")
                else None
            ),
            "events": list(self.health_events),
        }

    def report(self) -> dict:
        running = sum(1 for s in self.slots if s is not None and s.active)
        out = {
            "capacity": self.workflow.n_tenants,
            "chunk": self.chunk,
            "counters": dict(self.counters),
            "pending": len(self.pending),
            "continuations": len(self.continuations),
            "running": running,
            "results": [
                {k: v for k, v in r.items() if k != "monitors"}
                for r in self.results
            ],
        }
        if self.journal is not None:
            out["journal"] = self.journal.report()
        if self.integrity_events:
            out["integrity_events"] = [dict(e) for e in self.integrity_events]
        return out
