"""One gateway over many pods: a journal-backed multi-pod control plane.

PR 11 made ONE RunQueue durable (the hash-chained ``RunJournal``); PR 12
taught buckets to hand work to each other under a WAL ordering (durable
in the target journal BEFORE the source close-out); PR 14 taught a pod
of processes to shrink-and-resume after member death. This module
composes those disciplines one level up: a :class:`ControlPlane`
(the *gateway*) owns a durable tenant ledger (:class:`ControlLedger`,
the same ``ChainedLog`` machinery as every other durable surface here),
places :class:`~evox_tpu.workflows.elastic.ElasticSpec` requests across
N *pods* — each pod one :class:`~evox_tpu.workflows.elastic.
ElasticServer` with its own journal/checkpoint/metrics directories —
and survives a SIGKILL of anything: the gateway, a pod driver, or a
mid-handoff steal.

The three laws (tests/test_control_plane.py, ``control_chaos`` marker):

- **WAL-before-mutate**: every gateway decision (submit, placement,
  steal, pod open/dead/close, autoscale) is fsynced into the ledger
  before the pod-side mutation it describes. Recovery REPLAYS the
  ledger against the per-pod journals, so a crash between the ledger
  append and the pod mutation re-derives the mutation; a crash between
  the pod mutation and the ledger append is healed by dedup (below).
- **Cross-pod work-stealing, exactly-once**: a pod declared dead (its
  :class:`~evox_tpu.core.pod_supervisor.PodSupervisor` post-mortem, a
  missed heartbeat, or simply "too slow") has its outstanding work
  re-placed on surviving pods from a HOST-ONLY parse of its journals:
  parked continuations move with their durable checkpoints (verified
  intact via the manifest digest — no unpickling), never-finished
  tenants are re-run deterministically, and finished tenants' result
  entries are adopted straight from the close-out records. The steal
  reuses the PR-12 WAL ordering — durable in the target pod's journal
  first, then the ledger ``steal`` record, then (live source only) the
  source queue's ``release_continuation`` — so a kill at any point
  leaves at worst a DUPLICATE placement, which checkpoint/tag dedup
  removes at the next recovery; it can never lose acknowledged work.
- **Kill-anywhere recovery**: :meth:`ControlPlane.recover` rebuilds the
  gateway from the ledger + per-pod journals alone. Per-tenant results
  and telemetry fingerprints equal the uncrashed run's (tenants are
  vmap-isolated and seeded, so results are placement-independent), and
  each spec is admitted exactly once.

Pod autoscaling re-targets the PR-14 shrink-and-resume discipline as a
demand primitive: :class:`PodAutoscaler` reads queue depth and the
flight-recorder SLO ledger between rounds, opens a pod under pressure,
and drains+closes an idle one (queued work steals away; active tenants
finish where they run — the graceful-drain semantics).

Everything the gateway does between dispatches is host-side file I/O —
no callbacks, axon-safe (pinned by tests/test_no_host_callbacks.py).
Fiber (PAPERS.md, arXiv 2003.11164) is the design source: pool
membership is dynamic, member failure is a normal scheduling event, and
the master's job is exactly-once re-placement, not prevention.
"""

from __future__ import annotations

import dataclasses
import re
import warnings
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .elastic import BucketShape, BucketTable, ElasticServer, ElasticSpec
from .journal import ChainedLog, RunJournal

__all__ = [
    "ControlLedger",
    "ControlPlane",
    "PodAutoscaler",
    "PodRuntime",
]

_PAD = "_pad_"
_BUCKET_KEY = re.compile(r"^pop(\d+)_dim(\d+)_w(\d+)$")

#: chaos-test hook (tests/_control_chaos.py): called with a point label
#: at every WAL half-step so a scripted SIGKILL can land exactly between
#: "durable in target" and "ledger append" (the mid-steal kill law)
_CRASH_HOOK: Optional[Callable[[str], None]] = None


def _crash_point(point: str) -> None:
    hook = _CRASH_HOOK
    if hook is not None:
        hook(point)


def _checkpoint_attest(checkpoint_dir: Any) -> Optional[dict]:
    """The parked checkpoint's newest manifest attestation (ISSUE 20):
    ``{"digest", "generation"}`` read straight out of the manifest JSON —
    pure file I/O, no unpickling — so the steal record pins the bits the
    moved tenant is supposed to resume from. ``None`` for pre-v20
    manifests or anything unreadable (the steal itself never fails on a
    missing attestation — verification is the RESUMER's job)."""
    import json

    try:
        manifests = sorted(
            Path(checkpoint_dir).glob("ckpt_????????.pkl.manifest.json")
        )
        if not manifests:
            return None
        with open(manifests[-1]) as f:
            att = json.load(f).get("attest")
        if not isinstance(att, dict) or att.get("digest") is None:
            return None
        return {"digest": att["digest"], "generation": att.get("generation")}
    except Exception:
        return None


def _parse_bucket_key(name: str) -> Optional[BucketShape]:
    m = _BUCKET_KEY.match(name)
    if m is None:
        return None
    return BucketShape(
        pop=int(m.group(1)), dim=int(m.group(2)), width=int(m.group(3))
    )


# ------------------------------------------------------------------ ledger


class ControlLedger(ChainedLog):
    """The gateway's durable decision log: one hash-chained JSON-lines
    file (``ledger.jsonl``) under the control-plane root. Every record
    is fsynced before the transition it describes (WAL-before-mutate);
    recovery replays the FULL history, so — like :class:`RunJournal` —
    retention is refused outright, while size-bounded segment rotation
    (``max_segment_bytes``) is supported."""

    FILENAME = "ledger.jsonl"
    SCHEMA = "evox_tpu.control_ledger/v1"
    KINDS = (
        "submit",      # a tenant acknowledged by the gateway (full payload)
        "place",       # tenant -> pod assignment
        "steal",       # tenant re-placed from one pod onto another
        "pod_open",    # a pod joined the census
        "pod_dead",    # a pod declared dead (post-mortem / slow verdict)
        "pod_close",   # a drained pod left the census
        "autoscale",   # a demand-driven grow/shrink decision
        "recover",     # a gateway recovery replayed this ledger
    )

    def __init__(
        self,
        directory: str,
        max_segment_bytes: Optional[int] = None,
        retain_segments: Optional[int] = None,
    ):
        if retain_segments is not None:
            raise ValueError(
                "ControlLedger does not support retention: recovery "
                "replays the full decision history; use "
                "max_segment_bytes alone"
            )
        super().__init__(directory, max_segment_bytes=max_segment_bytes)


# ------------------------------------------------------------- spec codecs


def _elastic_spec_record(spec: ElasticSpec) -> dict:
    """The ledger ``submit`` payload: everything needed to re-place the
    request after a gateway death (the :func:`~evox_tpu.workflows.
    tenancy.RunQueue._spec_record` discipline, at the elastic layer)."""
    rec: dict = {
        "tag": spec.tag,
        "n_steps": int(spec.n_steps),
        "pop": int(spec.pop),
        "dim": int(spec.dim),
        "deadline": (
            int(spec.deadline) if spec.deadline is not None else None
        ),
        "hyperparams": {
            k: np.asarray(v) for k, v in spec.hyperparams.items()
        },
    }
    seed = spec.seed
    if isinstance(seed, (int, np.integer)):
        rec["seed"] = int(seed)
    else:
        import jax

        arr = np.asarray(
            jax.random.key_data(seed)
            if hasattr(seed, "dtype")
            and jax.dtypes.issubdtype(seed.dtype, jax.dtypes.prng_key)
            else seed
        )
        rec["seed_key"] = arr
        rec["seed_key_dtype"] = str(arr.dtype)
    return rec


def _elastic_spec_from_record(rec: dict) -> ElasticSpec:
    if rec.get("seed") is not None:
        seed: Any = int(rec["seed"])
    else:
        seed = np.asarray(
            rec["seed_key"], dtype=rec.get("seed_key_dtype", "uint32")
        )
    return ElasticSpec(
        seed=seed,
        n_steps=int(rec["n_steps"]),
        pop=int(rec["pop"]),
        dim=int(rec["dim"]),
        hyperparams=dict(rec.get("hyperparams") or {}),
        tag=rec.get("tag"),
        deadline=(
            int(rec["deadline"]) if rec.get("deadline") is not None else None
        ),
    )


# --------------------------------------------------------- steal derivation


def _derive_outstanding(recs: List[dict]) -> tuple:
    """Host-only post-mortem of one bucket journal: partition its
    acknowledged submits into (outstanding submit records, completed
    result entries). A submit is OUTSTANDING unless a terminal close-out
    (retire/evict/freeze), a moved close-out (preempt/autoscale — the
    work continued under a continuation submit), or a steal record
    accounts for its seq. Padding fillers are dropped. Terminal
    close-outs embed the full result entry, so a dead pod's finished
    work surfaces WITHOUT rebuilding its fleet."""
    submits: Dict[int, dict] = {}
    closed: set = set()
    completed: List[dict] = []
    for r in recs:
        kind = r.get("kind")
        if kind == "submit":
            submits[int(r["spec_seq"])] = r
        elif kind in ("retire", "evict", "freeze", "preempt", "autoscale"):
            if r.get("spec_seq") is not None:
                closed.add(int(r["spec_seq"]))
            if kind in ("retire", "evict", "freeze"):
                entry = r.get("entry") or {}
                if not (entry.get("tag") or "").startswith(_PAD):
                    completed.append(entry)
        elif kind == "steal" and r.get("spec_seq") is not None:
            closed.add(int(r["spec_seq"]))
    outstanding = [
        rec
        for seq, rec in sorted(submits.items())
        if seq not in closed
        and not (rec.get("tag") or "").startswith(_PAD)
    ]
    return outstanding, completed


# ---------------------------------------------------------------- runtimes


@dataclasses.dataclass
class PodAutoscaler:
    """Demand-driven pod census policy, evaluated once per gateway
    round. Inputs are deterministic serving state — queued work per
    live pod, per-pod idle streaks, and the flight-recorder SLO
    ledger's deadline-miss counter — so a recovered gateway replays the
    same decisions the crashed one made.

    Args:
        scale_up_depth: open a pod when queued (not yet admitted) work
            per live pod exceeds this.
        miss_pressure: additionally open a pod when the SLO ledger's
            ``deadline_misses`` grew by at least this much since the
            last round (None: queue depth only).
        scale_down_idle_rounds: drain+close a pod that served nothing
            for this many consecutive rounds.
        min_pods / max_pods: census bounds.
    """

    scale_up_depth: int = 4
    miss_pressure: Optional[int] = None
    scale_down_idle_rounds: int = 3
    min_pods: int = 1
    max_pods: int = 4

    def report(self) -> dict:
        return {
            "scale_up_depth": self.scale_up_depth,
            "miss_pressure": self.miss_pressure,
            "scale_down_idle_rounds": self.scale_down_idle_rounds,
            "min_pods": self.min_pods,
            "max_pods": self.max_pods,
        }


class PodRuntime:
    """One pod: an :class:`ElasticServer` over its own directory family
    (``<root>/pods/<pod_id>/{journal,ckpt}``), sharing the gateway's
    executable cache, bucket table, and flight recorder. The pod's
    durable surfaces outlive its process — a dead pod's runtime keeps
    the directories (the steal source) while ``server`` drops to None
    (its in-memory fleets died with it)."""

    def __init__(self, plane: "ControlPlane", pod_id: str):
        self.id = pod_id
        self.root = plane.directory / "pods" / pod_id
        self.dead = False
        self.closed = False
        self.draining = False
        self.idle_rounds = 0
        self.server: Optional[ElasticServer] = ElasticServer(
            factory=plane.factory,
            table=plane.table,
            cache=plane.cache,
            width=plane.width,
            chunk=plane.chunk,
            journal_dir=str(self.root / "journal"),
            checkpoint_dir=str(self.root / "ckpt"),
            autoscaler=plane.autoscaler,
            supervisor=plane.supervisor,
            executor=plane.executor,
            metrics=plane.metrics,
        )

    @property
    def alive(self) -> bool:
        return not self.dead and not self.closed

    def bucket_dirs(self) -> List[Path]:
        root = self.root / "journal"
        if not root.exists():
            return []
        out = []
        for d in sorted(root.iterdir()):
            if not d.is_dir() or _parse_bucket_key(d.name) is None:
                continue
            if (d / RunJournal.FILENAME).exists() or any(
                d.glob(RunJournal.FILENAME + ".[0-9]*")
            ):
                out.append(d)
        return out

    def recover_buckets(self) -> None:
        """Rebuild every journaled bucket of this pod from disk
        (:meth:`ElasticServer.recover_bucket` per bucket directory)."""
        for d in self.bucket_dirs():
            self.server.recover_bucket(_parse_bucket_key(d.name))


# ------------------------------------------------------------------ gateway


class ControlPlane:
    """The gateway: a journal-backed global scheduler over N pods.

    Args:
        factory: the shared bucket factory (``factory(BucketShape) ->
            ElasticWorkflow`` — every pod builds identical fleets, which
            is what makes stolen work placement-independent).
        directory: control-plane root. The ledger lives at the root,
            pods under ``pods/<pod_id>/``, the shared executable cache
            under ``cache/``.
        n_pods: pods opened at construction.
        table / width / chunk: the shared lattice configuration.
        autoscaler: a per-bucket :class:`~evox_tpu.workflows.elastic.
            PopAutoscaler` (pop-rung growth WITHIN a pod).
        pod_autoscaler: a :class:`PodAutoscaler` (census grow/shrink
            ACROSS pods).
        metrics: one :class:`~evox_tpu.workflows.flightrec.
            FlightRecorder` (or a directory to build one) spanning the
            whole plane — its SLO ledger is the autoscaler's pressure
            input and the bench leg's referee.
        supervisor / executor: threaded into every pod's queues.
        max_ledger_segment_bytes: rotate the ledger into size-bounded
            segments (hash chain carried across; see journal.py).
    """

    def __init__(
        self,
        factory: Callable[[BucketShape], Any],
        directory: str,
        n_pods: int = 2,
        table: Optional[BucketTable] = None,
        width: int = 4,
        chunk: int = 5,
        autoscaler: Any = None,
        pod_autoscaler: Optional[PodAutoscaler] = None,
        metrics: Any = None,
        supervisor: Any = None,
        executor: Any = None,
        max_ledger_segment_bytes: Optional[int] = None,
        _adopt: bool = False,
    ):
        from ..core.exec_cache import ExecutableCache

        self.factory = factory
        self.directory = Path(directory)
        self.table = table if table is not None else BucketTable()
        self.width = width
        self.chunk = chunk
        self.autoscaler = autoscaler
        self.pod_autoscaler = pod_autoscaler
        self.supervisor = supervisor
        self.executor = executor
        if isinstance(metrics, (str, Path)):
            from .flightrec import FlightRecorder

            metrics = FlightRecorder(directory=str(metrics))
        self.metrics = metrics
        self.cache = ExecutableCache(directory=str(self.directory / "cache"))
        if metrics is not None:
            self.cache.metrics = metrics
        self.ledger = ControlLedger(
            str(self.directory),
            max_segment_bytes=max_ledger_segment_bytes,
        )
        if not _adopt and self.ledger.records():
            raise RuntimeError(
                f"control-plane directory {self.directory} already holds "
                "a ledger — use ControlPlane.recover() to adopt it "
                "(constructing a fresh gateway over an existing ledger "
                "would fork the decision history)"
            )
        self.pods: Dict[str, PodRuntime] = {}
        self._pod_seq = 0
        self._tenant_seq = 0
        #: tag -> {"record": ledger submit payload, "pod": current pod}
        self._tenants: Dict[str, dict] = {}
        self._adopted_results: List[dict] = []
        self.steal_events: List[dict] = []
        self.autoscale_events: List[dict] = []
        self._round = 0
        self._last_misses = 0
        self.counters: Dict[str, int] = {
            "submitted": 0,
            "placed": 0,
            "stolen": 0,
            "steal_dedup": 0,
            "pods_opened": 0,
            "pods_dead": 0,
            "pods_closed": 0,
            "recoveries": 0,
        }
        if not _adopt:
            for _ in range(int(n_pods)):
                self._open_pod()

    # ------------------------------------------------------------- census
    def _open_pod(self) -> str:
        pod_id = f"pod{self._pod_seq:02d}"
        self._pod_seq += 1
        self.ledger.append("pod_open", pod=pod_id)
        self.pods[pod_id] = PodRuntime(self, pod_id)
        self.counters["pods_opened"] += 1
        if self.metrics is not None:
            self.metrics.count("control.pods_opened")
            self.metrics.set("control.pods_live", len(self.live_pods()))
            self.metrics.event("control.pod_open", pod=pod_id)
        return pod_id

    def live_pods(self) -> List[str]:
        return [pid for pid, pr in self.pods.items() if pr.alive]

    def _placement_candidates(self) -> List[str]:
        return [
            pid
            for pid, pr in self.pods.items()
            if pr.alive and not pr.draining
        ]

    def _pod_load(self, pr: PodRuntime) -> int:
        if pr.server is None:
            return 0
        n = 0
        for b in pr.server._buckets.values():
            q = b.queue
            n += len(q.pending) + len(q.continuations)
            n += sum(
                1
                for s in q.slots
                if s is not None
                and s.active
                and not (s.spec.tag or "").startswith(_PAD)
            )
        return n

    def _queued_depth(self) -> int:
        n = 0
        for pid in self._placement_candidates():
            server = self.pods[pid].server
            for b in server._buckets.values():
                n += len(b.queue.pending) + len(b.queue.continuations)
        return n

    # -------------------------------------------------------------- submit
    def bucket_for(self, spec: ElasticSpec) -> BucketShape:
        return self.table.bucket_for(spec.pop, spec.dim, self.width)

    def _rank_target(self, shape: BucketShape, exclude: tuple = ()) -> str:
        cands = [
            pid for pid in self._placement_candidates() if pid not in exclude
        ]
        if not cands:
            # the autoscaling primitive doubles as the last-resort
            # placement path: work must land SOMEWHERE durable
            self.ledger.append(
                "autoscale", action="grow", reason="no_live_pods"
            )
            pid = self._open_pod()
            self.autoscale_events.append(
                {"action": "grow", "pod": pid, "reason": "no_live_pods"}
            )
            return pid

        def rank(pid: str) -> tuple:
            # least-loaded first; a warm bucket (no compile needed)
            # breaks ties, then pod id for determinism
            pr = self.pods[pid]
            warm = 0 if shape.key in pr.server._buckets else 1
            return (self._pod_load(pr), warm, pid)

        return min(cands, key=rank)

    def submit(self, spec: ElasticSpec) -> str:
        """Acknowledge one tenant and place it: ledger ``submit`` (full
        payload — the gateway's WAL), ledger ``place`` (bucket + least-
        loaded live pod, warm buckets preferred), THEN the pod-journal
        submit. A crash between any two steps is healed by
        :meth:`recover` (re-derive the missing tail; the pod journal is
        the dedup witness). Tags identify tenants across the plane, so
        they must be unique; an untagged spec is assigned one. Returns
        the pod id."""
        if spec.tag is None:
            spec = dataclasses.replace(
                spec, tag=f"t{self._tenant_seq:05d}"
            )
        if (spec.tag or "").startswith(_PAD):
            raise ValueError(
                f"tenant tag {spec.tag!r} collides with the reserved "
                "padding namespace"
            )
        if spec.tag in self._tenants:
            raise ValueError(
                f"duplicate tenant tag {spec.tag!r}: the ledger's "
                "exactly-once admission law needs plane-unique tags"
            )
        rec = _elastic_spec_record(spec)
        self.ledger.append("submit", **rec)
        self._tenant_seq += 1
        self.counters["submitted"] += 1
        self._tenants[spec.tag] = {"record": rec, "pod": None}
        _crash_point(f"pre_place:{spec.tag}")
        shape = self.bucket_for(spec)
        pod_id = self._rank_target(shape)
        self.ledger.append(
            "place", tag=spec.tag, pod=pod_id, bucket=shape.key
        )
        self._tenants[spec.tag]["pod"] = pod_id
        _crash_point(f"pre_pod_submit:{spec.tag}")
        self.pods[pod_id].server.submit(spec)
        self.counters["placed"] += 1
        if self.metrics is not None:
            self.metrics.count("control.placed")
            self.metrics.event(
                "control.place", tag=spec.tag, pod=pod_id, bucket=shape.key
            )
        return pod_id

    # --------------------------------------------------------------- serve
    def has_work(self) -> bool:
        return any(
            pr.server is not None and pr.server.has_work()
            for pr in self.pods.values()
            if pr.alive
        )

    def serve_round(self) -> None:
        """One gateway quantum: every live pod advances one serving
        round (one chunk per bucket), then the pod-autoscale pass runs.
        Chunk boundaries are the only places gateway state changes — the
        same recovery granularity as a single queue."""
        self._round += 1
        for pid, pr in list(self.pods.items()):
            if not pr.alive or pr.server is None:
                continue
            if pr.server.has_work():
                pr.server.serve_round()
                pr.idle_rounds = 0
            else:
                pr.idle_rounds += 1
        self._pod_autoscale_pass()

    def serve(self, max_rounds: Optional[int] = None) -> List[dict]:
        """Drive every pod to completion; returns the merged results."""
        rounds = 0
        while self.has_work():
            self.serve_round()
            rounds += 1
            if max_rounds is not None and rounds >= max_rounds:
                break
        return self.results()

    # --------------------------------------------------------------- steal
    def mark_dead(self, pod_id: str, reason: str = "declared_dead") -> None:
        """Declare a pod dead (post-mortem verdict, missed heartbeats,
        or operator fiat) and steal its outstanding work. The runtime's
        in-memory server is dropped — by definition it died with the
        process; only the pod's DURABLE surfaces (journals, checkpoints)
        are consulted from here on."""
        pr = self.pods[pod_id]
        if pr.dead:
            return
        self.ledger.append("pod_dead", pod=pod_id, reason=reason)
        pr.dead = True
        pr.server = None
        self.counters["pods_dead"] += 1
        if self.metrics is not None:
            self.metrics.count("control.pods_dead")
            self.metrics.set("control.pods_live", len(self.live_pods()))
            self.metrics.event(
                "control.pod_dead", pod=pod_id, reason=reason
            )
        self._steal_from_dead(pod_id)

    def _already_placed(self, tag: str, checkpoint: Optional[str]) -> bool:
        """The dedup witness: is this work already durable in a LIVE
        pod's journal? (Heals the gateway dying between the target
        submit and the ledger ``steal`` append — the re-derived steal
        finds its first half done and skips.)"""
        for pid, pr in self.pods.items():
            if not pr.alive or pr.server is None:
                continue
            for b in pr.server._buckets.values():
                if b.queue.journal is None:
                    continue
                for r in b.queue.journal.records("submit"):
                    if r.get("tag") != tag:
                        continue
                    if checkpoint is None or (
                        r.get("resume_from") == checkpoint
                    ):
                        return True
        return False

    def _steal_from_dead(self, pod_id: str) -> None:
        from .checkpoint import snapshot_dir_intact
        from .tenancy import _spec_from_record

        pr = self.pods[pod_id]
        for bdir in pr.bucket_dirs():
            shape = _parse_bucket_key(bdir.name)
            recs = RunJournal(str(bdir)).records()
            outstanding, completed = _derive_outstanding(recs)
            known = {
                (e.get("tag"), e.get("status"), e.get("generations"))
                for e in self._adopted_results
            }
            for e in completed:
                k = (e.get("tag"), e.get("status"), e.get("generations"))
                if k not in known:
                    self._adopted_results.append(
                        {**e, "bucket": bdir.name, "pod": pod_id}
                    )
            seen_ckpts: set = set()
            for rec in outstanding:
                tag = rec.get("tag")
                ck = rec.get("resume_from")
                if ck is not None:
                    if ck in seen_ckpts:
                        continue  # replay-duplicated continuation
                    seen_ckpts.add(ck)
                if self._already_placed(tag, ck):
                    self.counters["steal_dedup"] += 1
                    continue
                tspec = _spec_from_record(rec)
                target = self._rank_target(shape, exclude=(pod_id,))
                tb = self.pods[target].server._get_bucket(shape)
                resumed = False
                if ck is not None:
                    if snapshot_dir_intact(ck):
                        tb.queue.submit_resume(
                            tspec, checkpoint=ck, done=rec.get("done")
                        )
                        resumed = True
                    else:
                        warnings.warn(
                            f"steal {tag!r} from {pod_id}: parked "
                            f"checkpoint {ck} is torn — re-running the "
                            "tenant fresh (deterministic, but its parked "
                            "progress is lost)"
                        )
                        tb.queue.submit(tspec)
                else:
                    tb.queue.submit(tspec)
                _crash_point(f"steal_target_durable:{tag}")
                self._record_steal(
                    tag, pod_id, target, shape.key,
                    checkpoint=ck if resumed else None,
                    source_seq=int(rec["spec_seq"]),
                )

    def _record_steal(
        self,
        tag: str,
        from_pod: str,
        to_pod: str,
        bucket: str,
        checkpoint: Optional[str],
        source_seq: Optional[int],
    ) -> None:
        # when the tenant moves WITH a parked checkpoint, the steal
        # record also pins that checkpoint's manifest attestation — an
        # auditor (or the resuming pod) can later prove the resumed bits
        # are the parked bits without trusting the file system
        attest = (
            _checkpoint_attest(checkpoint) if checkpoint is not None else None
        )
        self.ledger.append(
            "steal",
            tag=tag,
            from_pod=from_pod,
            to_pod=to_pod,
            bucket=bucket,
            checkpoint=checkpoint,
            attest=attest,
            source_seq=source_seq,
        )
        self.counters["stolen"] += 1
        self._tenants.setdefault(tag, {"record": None, "pod": None})
        self._tenants[tag]["pod"] = to_pod
        ev = {
            "tag": tag,
            "from_pod": from_pod,
            "to_pod": to_pod,
            "bucket": bucket,
            "with_checkpoint": checkpoint is not None,
        }
        self.steal_events.append(ev)
        if self.metrics is not None:
            self.metrics.count("control.stolen")
            self.metrics.event("control.steal", **ev)

    def steal_queued(
        self,
        from_pod: str,
        to_pod: Optional[str] = None,
        limit: Optional[int] = None,
    ) -> List[str]:
        """Re-place a LIVE pod's queued (not yet admitted) work onto
        other pods — the slow-pod rebalance and the shrink drain. Parked
        continuations move with their checkpoints, pending specs move
        whole. WAL order per item: durable in the target journal, then
        the ledger ``steal``, then the source queue's
        ``release_continuation`` (which journals the source-side
        ``steal`` record). Active slots are NOT touched: they finish
        where they run. ``limit`` caps the number of moved items (the
        autoscale rebalance moves half a backlog, not all of it).
        Returns the moved tags."""
        pr = self.pods[from_pod]
        if not pr.alive or pr.server is None:
            raise RuntimeError(
                f"steal_queued needs a live pod; {from_pod} is "
                f"{'dead' if pr.dead else 'closed'} — dead pods are "
                "stolen from their journals (mark_dead)"
            )
        moved: List[str] = []
        for b in list(pr.server._buckets.values()):
            q = b.queue
            shape = b.shape
            items = [
                ("cont", dict(c)) for c in list(q.continuations)
            ] + [("pend", s) for s in list(q.pending)]
            for kind, item in items:
                if limit is not None and len(moved) >= limit:
                    return moved
                if kind == "cont":
                    spec = item["spec"]
                    seq = item.get("seq")
                    ck = item.get("checkpoint")
                    done = item.get("done")
                else:
                    spec = item
                    seq = getattr(spec, "_journal_seq", None)
                    ck, done = None, None
                tag = spec.tag
                if (tag or "").startswith(_PAD) or seq is None:
                    continue
                cands = [
                    p
                    for p in self._placement_candidates()
                    if p != from_pod
                ]
                if to_pod is not None and to_pod in cands:
                    target = to_pod
                elif cands:
                    target = self._rank_target(shape, exclude=(from_pod,))
                else:
                    return moved  # nowhere to move it; keep at source
                spec2 = dataclasses.replace(spec)
                if getattr(spec, "_elastic_grows", 0):
                    spec2._elastic_grows = spec._elastic_grows
                tb = self.pods[target].server._get_bucket(shape)
                if ck is not None:
                    tb.queue.submit_resume(spec2, checkpoint=ck, done=done)
                else:
                    tb.queue.submit(spec2)
                _crash_point(f"steal_target_durable:{tag}")
                self._record_steal(
                    tag, from_pod, target, shape.key,
                    checkpoint=ck, source_seq=int(seq),
                )
                _crash_point(f"pre_source_release:{tag}")
                q.release_continuation(int(seq))
                moved.append(tag)
        return moved

    # ----------------------------------------------------------- autoscale
    def close_pod(self, pod_id: str) -> None:
        """Begin a graceful drain: queued work steals away immediately,
        the pod stops receiving placements, and the census close lands
        (ledger ``pod_close``) once its active tenants finish — the
        PR-14 drain discipline as an autoscaling primitive."""
        pr = self.pods[pod_id]
        if not pr.alive:
            return
        pr.draining = True
        self.steal_queued(pod_id)
        self._maybe_finish_close(pod_id)

    def _maybe_finish_close(self, pod_id: str) -> None:
        pr = self.pods[pod_id]
        if (
            pr.alive
            and pr.draining
            and (pr.server is None or not pr.server.has_work())
        ):
            self.ledger.append("pod_close", pod=pod_id)
            pr.closed = True
            self.counters["pods_closed"] += 1
            if self.metrics is not None:
                self.metrics.event("control.pod_close", pod=pod_id)

    def _pod_autoscale_pass(self) -> None:
        for pid in list(self.pods):
            self._maybe_finish_close(pid)
        a = self.pod_autoscaler
        if a is None:
            return
        cands = self._placement_candidates()
        if not cands:
            return
        depth = self._queued_depth()
        miss_delta = 0
        if self.metrics is not None:
            misses = int(self.metrics.slo_ledger()["deadline_misses"])
            miss_delta = misses - self._last_misses
            self._last_misses = misses
        pressure = depth / len(cands) > a.scale_up_depth or (
            a.miss_pressure is not None and miss_delta >= a.miss_pressure
        )
        if pressure and len(cands) < a.max_pods:
            self.ledger.append(
                "autoscale",
                action="grow",
                depth=depth,
                miss_delta=miss_delta,
            )
            pid = self._open_pod()
            self.autoscale_events.append(
                {
                    "action": "grow",
                    "pod": pid,
                    "depth": depth,
                    "miss_delta": miss_delta,
                }
            )
            # the new pod is useless until work reaches it: rebalance
            # half the deepest backlog onto it (the live-steal WAL)
            deepest = max(
                cands, key=lambda p: self._pod_load(self.pods[p])
            )
            self.steal_queued(deepest, to_pod=pid, limit=max(1, depth // 2))
            return
        if len(cands) > a.min_pods:
            for pid in cands:
                pr = self.pods[pid]
                if pr.idle_rounds >= a.scale_down_idle_rounds:
                    self.ledger.append(
                        "autoscale", action="shrink", pod=pid
                    )
                    self.autoscale_events.append(
                        {"action": "shrink", "pod": pid}
                    )
                    self.close_pod(pid)
                    break

    # -------------------------------------------------------------- recover
    @classmethod
    def recover(
        cls,
        factory: Callable[[BucketShape], Any],
        directory: str,
        table: Optional[BucketTable] = None,
        width: int = 4,
        chunk: int = 5,
        autoscaler: Any = None,
        pod_autoscaler: Optional[PodAutoscaler] = None,
        metrics: Any = None,
        supervisor: Any = None,
        executor: Any = None,
        max_ledger_segment_bytes: Optional[int] = None,
    ) -> "ControlPlane":
        """Rebuild the gateway after a kill ANYWHERE: replay the ledger
        to the pod census and tenant table, recover every live pod's
        buckets from their journals (the PR-11 replay law per bucket),
        then reconcile the half-done: placements whose pod-journal
        submit never landed are re-submitted, ledger steals whose
        source release was lost are re-released, dead pods are re-stolen
        (checkpoint/tag dedup healing double-placements), and closed or
        dead pods' finished results are adopted from their close-out
        records. Driving the returned plane (``serve()``) completes the
        sweep with per-tenant results and telemetry fingerprints equal
        to the uncrashed run's, each spec admitted exactly once."""
        plane = cls(
            factory,
            directory,
            n_pods=0,
            table=table,
            width=width,
            chunk=chunk,
            autoscaler=autoscaler,
            pod_autoscaler=pod_autoscaler,
            metrics=metrics,
            supervisor=supervisor,
            executor=executor,
            max_ledger_segment_bytes=max_ledger_segment_bytes,
            _adopt=True,
        )
        recs = plane.ledger.records()
        opened = [r["pod"] for r in recs if r["kind"] == "pod_open"]
        dead = {r["pod"] for r in recs if r["kind"] == "pod_dead"}
        closed_set = {r["pod"] for r in recs if r["kind"] == "pod_close"}
        submits = {
            r["tag"]: r for r in recs if r["kind"] == "submit"
        }
        places: Dict[str, str] = {}
        for r in recs:
            if r["kind"] == "place":
                places[r["tag"]] = r["pod"]
        steals = [r for r in recs if r["kind"] == "steal"]
        plane._pod_seq = (
            max((int(p[3:]) for p in opened), default=-1) + 1
        )
        plane._tenant_seq = len(submits)
        plane.counters["submitted"] = len(submits)
        plane.counters["pods_opened"] = len(opened)
        plane.counters["pods_dead"] = len(dead)
        plane.counters["pods_closed"] = len(closed_set)
        plane.counters["stolen"] = len(steals)
        # --- census + per-pod journal replay
        for pod_id in opened:
            pr = PodRuntime(plane, pod_id)
            plane.pods[pod_id] = pr
            if pod_id in dead:
                pr.dead = True
                pr.server = None
            elif pod_id in closed_set:
                pr.closed = True
                pr.server = None
            else:
                pr.recover_buckets()
        # --- tenant table from the ledger (steals move ownership)
        for tag, rec in submits.items():
            plane._tenants[tag] = {
                "record": rec, "pod": places.get(tag),
            }
        for s in steals:
            plane._tenants.setdefault(
                s["tag"], {"record": None, "pod": None}
            )
            plane._tenants[s["tag"]]["pod"] = s["to_pod"]
            plane.steal_events.append(
                {
                    "tag": s["tag"],
                    "from_pod": s["from_pod"],
                    "to_pod": s["to_pod"],
                    "bucket": s.get("bucket"),
                    "with_checkpoint": s.get("checkpoint") is not None,
                }
            )
        # --- heal: a ledger steal whose SOURCE release was lost (killed
        # between the ledger append and release_continuation): the
        # recovered source queue may still hold the moved seq
        for s in steals:
            src = plane.pods.get(s["from_pod"])
            if src is None or not src.alive or src.server is None:
                continue
            b = src.server._buckets.get(s.get("bucket"))
            if b is None or s.get("source_seq") is None:
                continue
            try:
                b.queue.release_continuation(int(s["source_seq"]))
            except (KeyError, ValueError):
                pass  # already released (the normal case)
        # --- heal: cross-pod double placement of one parked checkpoint
        # (killed between the target submit and the ledger append, then
        # a prior recovery re-placed it elsewhere): keep the LEDGER's
        # owner when recorded, else the lowest pod id — deterministic
        # either way, so repeated recoveries converge
        claims: Dict[str, List[tuple]] = {}
        for pid in plane.live_pods():
            server = plane.pods[pid].server
            if server is None:
                continue
            for b in server._buckets.values():
                for c in list(b.queue.continuations):
                    ck = c.get("checkpoint")
                    if ck is not None and c.get("seq") is not None:
                        claims.setdefault(ck, []).append(
                            (pid, b, int(c["seq"]))
                        )
        stolen_to = {
            s.get("checkpoint"): s["to_pod"]
            for s in steals
            if s.get("checkpoint") is not None
        }
        for ck, holders in claims.items():
            if len(holders) < 2:
                continue
            owner = stolen_to.get(ck)
            if owner is None or owner not in [h[0] for h in holders]:
                owner = min(h[0] for h in holders)
            for pid, b, seq in holders:
                if pid != owner:
                    try:
                        b.queue.release_continuation(seq)
                    except (KeyError, ValueError):
                        pass
        # --- reconcile acknowledged tenants: place the never-placed,
        # re-submit placements whose pod-journal submit never landed
        for tag, rec in submits.items():
            spec = _elastic_spec_from_record(rec)
            pod = places.get(tag)
            if pod is None:
                shape = plane.bucket_for(spec)
                pod = plane._rank_target(shape)
                plane.ledger.append(
                    "place", tag=tag, pod=pod, bucket=shape.key
                )
                plane._tenants[tag]["pod"] = pod
                plane.pods[pod].server.submit(spec)
                plane.counters["placed"] += 1
                continue
            plane.counters["placed"] += 1
            pr = plane.pods[pod]
            if not pr.alive or pr.server is None:
                continue  # the dead-pod steal below re-derives it
            if not plane._already_placed(tag, None):
                pr.server.submit(spec)
        # --- dead pods: re-derive steals (idempotent via the dedup
        # witness) and adopt their finished results
        for pod_id in opened:
            if pod_id in dead:
                plane._steal_from_dead(pod_id)
            elif pod_id in closed_set:
                plane._adopt_closed_results(pod_id)
        plane.counters["recoveries"] = 1 + sum(
            1 for r in recs if r["kind"] == "recover"
        )
        plane.ledger.append(
            "recover",
            live=sorted(plane.live_pods()),
            dead=sorted(dead),
            tenants=len(submits),
        )
        if plane.metrics is not None:
            plane.metrics.event(
                "control.recover",
                live=len(plane.live_pods()),
                dead=len(dead),
            )
        return plane

    def _adopt_closed_results(self, pod_id: str) -> None:
        pr = self.pods[pod_id]
        known = {
            (e.get("tag"), e.get("status"), e.get("generations"))
            for e in self._adopted_results
        }
        for bdir in pr.bucket_dirs():
            recs = RunJournal(str(bdir)).records()
            _, completed = _derive_outstanding(recs)
            for e in completed:
                k = (e.get("tag"), e.get("status"), e.get("generations"))
                if k not in known:
                    known.add(k)
                    self._adopted_results.append(
                        {**e, "bucket": bdir.name, "pod": pod_id}
                    )

    # -------------------------------------------------------------- results
    def results(self) -> List[dict]:
        """Merged per-tenant results: every live pod's server results
        plus the entries adopted from dead/closed pods' close-out
        records, each annotated with its pod id."""
        out = list(self._adopted_results)
        for pid, pr in self.pods.items():
            if pr.server is None:
                continue
            for r in pr.server.results():
                out.append({**r, "pod": pid})
        return out

    def report(self) -> dict:
        """The ``control_plane`` section of ``run_report()`` (schema
        v12, validated by tools/check_report.py): pod census, ledger
        event counts, tenant accounting, the exactly-once admission
        audit over the live pods' journals, and the steal/autoscale
        event streams."""
        recs = self.ledger.records()
        kinds: Dict[str, int] = {}
        for r in recs:
            kinds[r["kind"]] = kinds.get(r["kind"], 0) + 1
        # exactly-once audit: across LIVE pods, a tenant tag must have
        # at most one fresh (non-continuation) admission — a failed
        # steal dedup would show up here as a duplicate
        fresh: Dict[str, int] = {}
        for pid in self.live_pods():
            server = self.pods[pid].server
            if server is None:
                continue
            for b in server._buckets.values():
                if b.queue.journal is None:
                    continue
                # a live steal leaves the submit in the SOURCE journal
                # with a matching steal release — that admission now
                # lives on the target pod, so it must not count here
                for r in b.queue.journal.records():
                    tag = r.get("tag")
                    if not tag or tag.startswith(_PAD):
                        continue
                    if (
                        r["kind"] == "submit"
                        and r.get("resume_from") is None
                    ):
                        fresh[tag] = fresh.get(tag, 0) + 1
                    elif r["kind"] == "steal":
                        fresh[tag] = fresh.get(tag, 0) - 1
        duplicates = {t: c for t, c in fresh.items() if c > 1}
        fresh = {t: c for t, c in fresh.items() if c > 0}
        out = {
            "pods": {
                "opened": self.counters["pods_opened"],
                "live": sorted(self.live_pods()),
                "dead": sorted(
                    pid for pid, pr in self.pods.items() if pr.dead
                ),
                "closed": sorted(
                    pid for pid, pr in self.pods.items() if pr.closed
                ),
                "draining": sorted(
                    pid
                    for pid, pr in self.pods.items()
                    if pr.alive and pr.draining
                ),
            },
            "tenants": {
                "submitted": self.counters["submitted"],
                "placed": self.counters["placed"],
                "stolen": self.counters["stolen"],
                "steal_dedup": self.counters["steal_dedup"],
                "results": len(self.results()),
            },
            "events": kinds,
            "ledger": {
                "records": len(recs),
                "rotations": self.ledger.rotations,
                "recoveries": self.counters["recoveries"],
            },
            "exactly_once": {
                "audited_tags": len(fresh),
                "duplicate_admissions": duplicates,
            },
            "steals": list(self.steal_events),
            "autoscale": {
                "policy": (
                    self.pod_autoscaler.report()
                    if self.pod_autoscaler is not None
                    else None
                ),
                "events": list(self.autoscale_events),
            },
        }
        if self.metrics is not None:
            out["slo"] = self.metrics.slo_ledger()
            # the gateway's own counter family, straight from the
            # registry (core/metrics.py values()) — the validator's
            # ledger-vs-counter coherence check reads this
            out["metrics"] = self.metrics.registry.values("control.")
        return out

    def close(self) -> None:
        """Release the gateway's process-lifetime resources: the shared
        executable cache's in-memory executables (PERF_NOTES §23 — the
        durable cache state stays) and the executor's background lanes
        when one is threaded through."""
        self.cache.close()
        if self.executor is not None and hasattr(self.executor, "close"):
            self.executor.close()
