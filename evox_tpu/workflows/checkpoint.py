"""Crash-safe, callback-free run checkpointing.

:class:`~evox_tpu.monitors.CheckpointMonitor` auto-saves from INSIDE the
jitted step via ``io_callback`` — which the tunneled axon TPU backend
cannot execute (CLAUDE.md), so on the real target hardware long runs had
no auto-checkpoint path at all. :class:`WorkflowCheckpointer` is the
backend-universal replacement: it runs entirely on the host BETWEEN
dispatches (never inside traced code), so it works identically on CPU,
directly-attached TPU, and the callback-less axon plugin.

Durability contract:

- Snapshots are written atomically (tmp + ``os.replace``), with a
  digest-validated JSON manifest committed AFTER the data file — a crash
  at any byte leaves either a complete (manifest + digest-verified data)
  snapshot or an ignorable partial, never a torn restore.
- :meth:`WorkflowCheckpointer.latest` walks snapshots newest → oldest and
  skips (with a warning) anything whose manifest is missing/garbled or
  whose payload fails the SHA-256 check, restoring the newest snapshot
  that is provably intact.
- The snapshot is the full workflow-state pytree with numpy leaves —
  it drops straight back into ``wf.run`` / ``run_host_pipelined``.

Resume contract (asserted in tests/test_chaos.py): a run of ``n`` total
generations that crashes after generation ``K`` and is resumed from the
gen-``K`` snapshot produces the same final state pytree as the
uninterrupted run — every random draw lives in the state, so the chunked
run re-traverses the identical program. (Host problems that keep
generation-to-generation state on the problem OBJECT — e.g. the rollout
farms' per-generation seed draw — are outside the snapshot; resume
equivalence there requires the problem's evaluate to be deterministic or
externally seeded, see GUIDE.md §6.)
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import warnings
from pathlib import Path
from typing import Any, List, Optional

import jax

_SCHEMA = "evox_tpu.workflow_checkpoint/v1"


class WorkflowCheckpointer:
    """Host-side periodic snapshots of a workflow state, axon-safe.

    Args:
        directory: snapshot directory (created if missing). Snapshots from
            a previous process in the same directory are adopted — that is
            the crash-recovery path.
        every: checkpoint cadence in generations. ``wf.run(...,
            checkpointer=...)`` chunks its fused device loop at this
            cadence and snapshots between dispatches;
            ``run_host_pipelined`` snapshots whenever
            ``state.generation`` crosses a multiple of ``every``.
        keep: newest snapshots retained (older ones pruned after each
            successful save).
    """

    _CONFIG = "checkpointer.json"

    def __init__(self, directory: str, every: int = 10, keep: int = 3):
        if every < 1:
            raise ValueError(f"every must be >= 1, got {every}")
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.every = every
        self.keep = keep

    def _write_config(self) -> None:
        """Persist (every, keep) next to the snapshots, so a resume that
        only names the DIRECTORY (``resume_from="ckpts/run"``) recreates
        the run's configured cadence instead of silently falling back to
        the defaults (and a weaker durability promise)."""
        cpath = self.directory / self._CONFIG
        tmp = cpath.with_suffix(".json.tmp")
        with open(tmp, "w") as f:
            json.dump({"every": self.every, "keep": self.keep}, f)
        os.replace(tmp, cpath)

    # ------------------------------------------------------------------ save
    def save(self, state: Any) -> Path:
        """Atomically snapshot ``state`` (blocking host-side pickle).

        Writes ``ckpt_GGGGGGGG.pkl`` via tmp + rename, then its
        ``.manifest.json`` (schema, generation, byte count, SHA-256) the
        same way — the manifest is the commit record, so a torn data file
        can never masquerade as a valid snapshot."""
        host_state = jax.device_get(state)
        payload = pickle.dumps(host_state, protocol=pickle.HIGHEST_PROTOCOL)
        gen = int(host_state.generation)
        path = self.directory / f"ckpt_{gen:08d}.pkl"
        tmp = path.with_suffix(".pkl.tmp")
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)
        manifest = {
            "schema": _SCHEMA,
            "generation": gen,
            "bytes": len(payload),
            "sha256": hashlib.sha256(payload).hexdigest(),
            "file": path.name,
        }
        mpath = self._manifest_path(path)
        mtmp = mpath.with_suffix(".json.tmp")
        with open(mtmp, "w") as f:
            json.dump(manifest, f)
        os.replace(mtmp, mpath)
        self._write_config()
        self._prune()
        return path

    def maybe_save(self, state: Any) -> Optional[Path]:
        """Snapshot iff ``state.generation`` is a multiple of ``every``.
        Call between dispatches (it blocks on a device->host copy of the
        whole state). Always (re)writes the snapshot — an existing file
        for the same generation might be a torn leftover or belong to a
        previous run of a reused directory, and skipping on its mere
        existence would let it permanently shadow the live state."""
        if int(state.generation) % self.every != 0:
            return None
        return self.save(state)

    # ------------------------------------------------------------------ load
    def snapshots(self) -> List[Path]:
        """Committed snapshot data files, oldest -> newest (manifest
        presence = committed; digest validation happens at restore)."""
        tail = len(".manifest.json")
        return sorted(
            p.parent / p.name[:-tail]
            for p in self.directory.glob("ckpt_????????.pkl.manifest.json")
        )

    def latest(self) -> Optional[Any]:
        """Restore the newest intact snapshot (None when nothing usable).

        Corrupt or torn snapshots — missing/garbled manifest, size or
        SHA-256 mismatch, unpicklable payload — are skipped with a warning
        and the next-older snapshot is tried, so one bad file never takes
        down a resume."""
        for path in reversed(self.snapshots()):
            state = self._load_validated(path)
            if state is not None:
                return state
        return None

    def _manifest_path(self, path: Path) -> Path:
        return path.with_suffix(".pkl.manifest.json")

    def _load_validated(self, path: Path) -> Optional[Any]:
        try:
            with open(self._manifest_path(path)) as f:
                manifest = json.load(f)
            payload = path.read_bytes()
            if len(payload) != manifest["bytes"]:
                raise ValueError(
                    f"size mismatch: {len(payload)} != {manifest['bytes']}"
                )
            digest = hashlib.sha256(payload).hexdigest()
            if digest != manifest["sha256"]:
                raise ValueError("sha256 mismatch")
            return pickle.loads(payload)
        except Exception as e:
            warnings.warn(
                f"skipping corrupt checkpoint {path.name}: {e}", stacklevel=2
            )
            return None

    def _prune(self) -> None:
        snaps = self.snapshots()
        for old in snaps[: max(len(snaps) - self.keep, 0)]:
            for p in (old, self._manifest_path(old)):
                try:
                    p.unlink()
                except FileNotFoundError:
                    pass


def _as_checkpointer(resume_from: Any) -> WorkflowCheckpointer:
    if isinstance(resume_from, WorkflowCheckpointer):
        return resume_from
    # directory string: adopt the crashed run's persisted cadence (see
    # _write_config) rather than silently resuming with the defaults
    kw = {}
    try:
        with open(Path(resume_from) / WorkflowCheckpointer._CONFIG) as f:
            cfg = json.load(f)
        kw = {"every": int(cfg["every"]), "keep": int(cfg["keep"])}
    except Exception:
        pass  # no/garbled config (pre-existing dir): defaults apply
    return WorkflowCheckpointer(str(resume_from), **kw)


def resolve_resume(resume_from: Any, state: Any, n_steps: int):
    """Shared ``resume_from=`` handling for Std and pipelined runs.

    ``resume_from`` (a :class:`WorkflowCheckpointer` or a directory path)
    overrides ``state`` with its newest intact snapshot when one exists;
    ``n_steps`` then counts TOTAL generations from 0, so the remaining
    trip count is ``n_steps - state.generation``. Returns
    ``(state, remaining_steps)``."""
    loaded = _as_checkpointer(resume_from).latest()
    if loaded is not None:
        state = loaded
    return state, max(n_steps - int(state.generation), 0)


def checkpointed_run(wf, state, n_steps: int, checkpointer: WorkflowCheckpointer):
    """``wf.run`` with host-side snapshots between dispatches.

    The fused device loop is chunked at the checkpoint cadence: each chunk
    ends exactly on a multiple of ``checkpointer.every`` (or at
    ``n_steps``), the state is snapshotted, and the next chunk is
    dispatched. Chunking a ``fori_loop`` does not change its math, so the
    final state is identical to a straight ``wf.run(state, n_steps)`` —
    and a crash between chunks resumes from the last snapshot with
    nothing lost but the current chunk. The final state is always
    snapshotted (even off-cadence) so a completed run restores to its
    true end."""
    remaining = n_steps
    while remaining > 0:
        gen = int(state.generation)
        to_boundary = checkpointer.every - gen % checkpointer.every
        chunk = min(remaining, to_boundary)
        state = wf.run(state, chunk)
        remaining -= chunk
        if int(state.generation) % checkpointer.every == 0 or remaining == 0:
            checkpointer.save(state)
    return state
