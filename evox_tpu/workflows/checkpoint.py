"""Crash-safe, callback-free, topology-portable run checkpointing.

:class:`~evox_tpu.monitors.CheckpointMonitor` auto-saves from INSIDE the
jitted step via ``io_callback`` — which the tunneled axon TPU backend
cannot execute (CLAUDE.md), so on the real target hardware long runs had
no auto-checkpoint path at all. :class:`WorkflowCheckpointer` is the
backend-universal replacement: it runs entirely on the host BETWEEN
dispatches (never inside traced code), so it works identically on CPU,
directly-attached TPU, and the callback-less axon plugin.

Durability contract:

- Snapshots are written atomically (tmp + fsync + ``os.replace`` +
  parent-directory fsync), with a digest-validated JSON manifest
  committed AFTER the data file the same way — a crash (or power loss:
  the directory fsync is what makes the rename itself durable, a rename
  without it can tear) at any byte leaves either a complete (manifest +
  digest-verified data) snapshot or an ignorable partial, never a torn
  restore.
- :meth:`WorkflowCheckpointer.latest` walks snapshots newest → oldest and
  skips (with a warning) anything whose manifest is missing/garbled or
  whose payload fails the SHA-256 check, restoring the newest snapshot
  that is provably intact.
- Each manifest carries a **config fingerprint** of the snapshotted
  state (leaf paths + shapes + dtypes + the algorithm state's type) —
  ``latest(expect_like=...)`` / ``resume()`` refuse a snapshot written
  under a different algorithm or population size
  (:class:`CheckpointConfigError`) instead of feeding it to a compiled
  program built for other shapes; ``allow_config_mismatch=True``
  overrides.

Topology portability: snapshot leaves are plain host numpy arrays
(``jax.device_get`` gathers every shard; cross-process-sharded leaves
all-gather through ``core.distributed.host_value`` first), so a snapshot
carries NO mesh — the manifest records the save-time topology (device
AND process counts) and per-leaf sharding specs for provenance only.
Restoring onto a *different* device count OR PROCESS count (checkpoint
on 8 devices in 1 process, restart as 2×4 or 4×2 processes — the pod
recovery path; ``place_state`` reassembles each process's addressable
shards from the host leaves) is therefore data-complete by construction.
Pod saves follow process-0-writes + barrier discipline: the gather is
collective, process 0 writes the one manifest, a coordinator-KV barrier
holds the others until it is durable — one pod save is one manifest,
not N (see :meth:`WorkflowCheckpointer.save`). Restoring on a pod reads
the snapshot on every process (shared or replicated filesystem) and
reassembles; the dryrun_multihost harness asserts the 1-process→
n-process trajectory-reproduction law where the backend can run
cross-process collectives.
:func:`restore_layouts` (or ``StdWorkflow.resume(state_sharding=...)``)
eagerly re-places the host leaves onto the CURRENT mesh according to the
state's own ``field(sharding=...)`` annotations — the same layout law
``constrain_state`` applies inside the step, so the resumed run
reproduces the straight run's remaining trajectory
(tests/test_supervisor.py asserts 8→4→1 equivalence).

Resume contract (asserted in tests/test_chaos.py): a run of ``n`` total
generations that crashes after generation ``K`` and is resumed from the
gen-``K`` snapshot produces the same final state pytree as the
uninterrupted run — every random draw lives in the state, so the chunked
run re-traverses the identical program. (Host problems that keep
generation-to-generation state on the problem OBJECT — e.g. the rollout
farms' per-generation seed draw — are outside the snapshot; resume
equivalence there requires the problem's evaluate to be deterministic or
externally seeded, see GUIDE.md §6.)
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import warnings
from pathlib import Path
from typing import Any, List, Optional

import jax

from ..core.attest import IntegrityError, digest_hex, host_state_digest

_SCHEMA = "evox_tpu.workflow_checkpoint/v1"


def attest_digest_hex(state: Any) -> str:
    """Hex attestation of a (host) state — the NumPy digest mirror, so
    manifest writing costs one host pass, no device dispatch. Bitwise
    equal to the on-device ``state_digest`` of the same bits (the
    core/attest.py host-mirror law)."""
    return digest_hex(host_state_digest(state))

# Crash-injection hook for the process-chaos harness (tests/_proc_chaos.py):
# when set, it is called with a named point inside the durable-write path
# ("pre_rename:<suffix>" before the atomic os.replace, "manifest_pending"
# between a snapshot's committed data file and its manifest) — the chaos
# child SIGKILLs itself there to reproduce a power-loss-shaped tear at an
# exact byte boundary, including on the executor's BACKGROUND checkpoint
# lane (the hook runs on whatever thread performs the write). Always None
# in production; never set it outside tests.
_CRASH_HOOK = None


def _crash_point(point: str) -> None:
    if _CRASH_HOOK is not None:
        _CRASH_HOOK(point)


class CheckpointConfigError(RuntimeError):
    """A snapshot's config fingerprint does not match the run asking to
    restore it — different algorithm, population size, monitors, or
    state structure. Restoring it anyway would hand a compiled program
    arrays of the wrong shape (or silently resurrect a different
    experiment); pass ``allow_config_mismatch=True`` to override."""


def state_config_fingerprint(state: Any) -> str:
    """SHA-256 over the state's structural identity: every leaf's key
    path, shape, and dtype, plus the algorithm state's type name.
    Invariant across devices/meshes/backends AND across the host/device
    boundary (a pickled-numpy snapshot fingerprints identically to the
    live jax state it came from); sensitive to algorithm class,
    population size, dimensionality, and monitor set. Static fields
    (e.g. the ``first_step`` peel flag) are deliberately excluded — they
    legitimately differ between a fresh state and a mid-run snapshot."""
    leaves = jax.tree_util.tree_flatten_with_path(state)[0]
    parts = [type(getattr(state, "algo", state)).__name__]
    for path, leaf in leaves:
        arr = leaf if hasattr(leaf, "shape") else None
        shape = tuple(arr.shape) if arr is not None else ()
        dtype = str(arr.dtype) if arr is not None else type(leaf).__name__
        parts.append(f"{jax.tree_util.keystr(path)}:{shape}:{dtype}")
    return hashlib.sha256("|".join(parts).encode()).hexdigest()


def _fsync_path(path: Path) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_durable(path: Path, payload: bytes, tmp_suffix: str) -> None:
    """tmp + flush + fsync(file) + atomic rename + fsync(directory): the
    full crash/power-loss discipline — an os.replace alone is atomic
    against CRASHES but not durable against power loss until the parent
    directory entry itself is synced."""
    tmp = path.with_suffix(tmp_suffix)
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    _crash_point(f"pre_rename:{path.name}")
    os.replace(tmp, path)
    _fsync_path(path.parent)


def _leaf_shardings(state: Any) -> dict:
    """Per-leaf ``PartitionSpec`` strings of a LIVE (device) state, for
    the manifest's provenance record. Host/numpy leaves record nothing."""
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        sharding = getattr(leaf, "sharding", None)
        spec = getattr(sharding, "spec", None)
        if spec is not None and any(s is not None for s in spec):
            out[jax.tree_util.keystr(path)] = str(spec)
    return out


def restore_layouts(state: Any, mesh: Any = None, state_sharding: Any = None) -> Any:
    """Eagerly place a host-restored snapshot onto the CURRENT mesh.

    ``state_sharding``: an explicit pytree of shardings (e.g. from
    :func:`~evox_tpu.core.distributed.state_sharding`) applied leaf-wise
    with ``jax.device_put``. Without it, the state's own
    ``field(sharding=...)`` annotations drive the placement on ``mesh``
    (:func:`~evox_tpu.core.distributed.place_state`) — the same law
    ``constrain_state`` applies inside every step, now on whatever mesh
    the restoring process built. No-op when both are ``None`` (the first
    dispatch then places leaves with its default device_put, and the
    in-step constraints still land the declared layouts)."""
    if state_sharding is not None:
        return jax.tree.map(jax.device_put, state, state_sharding)
    if mesh is None:
        return state
    from ..core.distributed import place_state

    return place_state(state, mesh)


def chunk_to_boundary(state: Any, checkpointer: Optional["WorkflowCheckpointer"],
                      chunk: Optional[int] = None) -> int:
    """Generations from ``state.generation`` to the next chunk boundary:
    the checkpoint cadence grid when a checkpointer is given, else the
    ``chunk`` grid, else effectively-unbounded (one dispatch for the
    rest). Aligning chunks to a GLOBAL grid (not the entry generation)
    keeps boundary generations identical across crash/resume/replay —
    the same determinism law as ``checkpointed_run`` and ``ipop_run``."""
    every = checkpointer.every if checkpointer is not None else chunk
    if every is None:
        return 1 << 30
    return every - int(state.generation) % every


class WorkflowCheckpointer:
    """Host-side periodic snapshots of a workflow state, axon-safe.

    Args:
        directory: snapshot directory (created if missing). Snapshots from
            a previous process in the same directory are adopted — that is
            the crash-recovery path.
        every: checkpoint cadence in generations. ``wf.run(...,
            checkpointer=...)`` chunks its fused device loop at this
            cadence and snapshots between dispatches;
            ``run_host_pipelined`` snapshots whenever
            ``state.generation`` crosses a multiple of ``every``.
        keep: newest snapshots retained (older ones pruned after each
            successful save).
        barrier_timeout_s: deadline for the pod save's commit barriers
            (multi-process only). A peer SIGKILLed mid-save then raises
            the classified
            :class:`~evox_tpu.core.distributed.BarrierTimeoutError`
            naming the missing processes after this bound instead of
            holding the survivors for the 120 s default (ISSUE 14; the
            pod supervisor further refines it through the census).
    """

    _CONFIG = "checkpointer.json"

    def __init__(
        self,
        directory: str,
        every: int = 10,
        keep: int = 3,
        barrier_timeout_s: Optional[float] = None,
    ):
        if every < 1:
            raise ValueError(f"every must be >= 1, got {every}")
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.every = every
        self.keep = keep
        self.barrier_timeout_s = barrier_timeout_s

    def _commit_barrier(self) -> None:
        from ..core.distributed import process_barrier

        if self.barrier_timeout_s is None:
            process_barrier()
        else:
            process_barrier(timeout_s=self.barrier_timeout_s)

    def _write_config(self) -> None:
        """Persist (every, keep) next to the snapshots, so a resume that
        only names the DIRECTORY (``resume_from="ckpts/run"``) recreates
        the run's configured cadence instead of silently falling back to
        the defaults (and a weaker durability promise)."""
        payload = json.dumps({"every": self.every, "keep": self.keep}).encode()
        _write_durable(self.directory / self._CONFIG, payload, ".json.tmp")

    # ------------------------------------------------------------------ save
    def save(self, state: Any) -> Path:
        """Atomically snapshot ``state`` (blocking host-side pickle).

        Writes ``ckpt_GGGGGGGG.pkl`` via tmp + fsync + rename + directory
        fsync, then its ``.manifest.json`` (schema, generation, byte
        count, SHA-256, config fingerprint, save-time topology) the same
        way — the manifest is the commit record, so a torn data file can
        never masquerade as a valid snapshot.

        Multi-process (pod) discipline: every process participates in the
        device→host gather (cross-process-sharded leaves all-gather
        through :func:`~evox_tpu.core.distributed.host_value` — a
        collective, so ``save`` must be called on EVERY process, the SPMD
        law every dispatch already obeys), but only PROCESS 0 writes —
        one pod save is ONE manifest, not N racing copies — and a KV-
        store barrier holds the others until the manifest is durable, so
        no process can run ahead of a commit it may later restore. The
        snapshot itself stays topology-free host data: a 1-process save
        resumes on any process count and vice versa (``place_state``
        reassembles per-process shards on the restoring pod's mesh)."""
        multiproc = jax.process_count() > 1
        shardings = _leaf_shardings(state)
        if multiproc:
            from ..core.distributed import tree_host_value

            # collective all-gather: every process ends with the FULL
            # host value of every leaf (identical bytes on each process)
            host_state = tree_host_value(state)
        else:
            host_state = jax.device_get(state)
        gen = int(host_state.generation)
        path = self.directory / f"ckpt_{gen:08d}.pkl"
        if multiproc and jax.process_index() != 0:
            # process-0-writes: wait for the writer's manifest commit
            # (save() below hits the same barrier after its writes)
            self._commit_barrier()
            return path
        payload = pickle.dumps(host_state, protocol=pickle.HIGHEST_PROTOCOL)
        _write_durable(path, payload, ".pkl.tmp")
        # a kill here (data durable, manifest not) must leave latest()
        # on the PREVIOUS intact snapshot — the manifest is the commit
        # record; asserted through the background lane by the process-
        # chaos harness
        _crash_point(f"manifest_pending:{path.name}")
        manifest = {
            "schema": _SCHEMA,
            "generation": gen,
            "bytes": len(payload),
            "sha256": hashlib.sha256(payload).hexdigest(),
            "file": path.name,
            # compute-integrity attestation (ISSUE 20, core/attest.py):
            # the layout-invariant digest of the STATE the payload
            # unpickles to, not of the payload bytes — sha256 above
            # guards the file, this guards the bits the run will resume
            # from (_load_validated recomputes and refuses a mismatch)
            "attest": {
                "digest": attest_digest_hex(host_state),
                "generation": gen,
            },
            # structural identity of the run (see state_config_fingerprint)
            "config_sha": state_config_fingerprint(host_state),
            # provenance only: the snapshot itself is topology-free host
            # data; restore_layouts re-places it on whatever mesh the
            # restoring process has
            "save_topology": {
                "device_count": jax.device_count(),
                "process_count": jax.process_count(),
                "leaf_shardings": shardings,
            },
        }
        _write_durable(
            self._manifest_path(path),
            json.dumps(manifest).encode(),
            ".json.tmp",
        )
        self._write_config()
        self._prune()
        if multiproc:
            # release the non-writer processes only after the manifest
            # (the commit record) is durable on disk
            self._commit_barrier()
        return path

    def maybe_save(self, state: Any) -> Optional[Path]:
        """Snapshot iff ``state.generation`` is a multiple of ``every``.
        Call between dispatches (it blocks on a device->host copy of the
        whole state). Always (re)writes the snapshot — an existing file
        for the same generation might be a torn leftover or belong to a
        previous run of a reused directory, and skipping on its mere
        existence would let it permanently shadow the live state."""
        if int(state.generation) % self.every != 0:
            return None
        return self.save(state)

    # ------------------------------------------------------------------ load
    def snapshots(self) -> List[Path]:
        """Committed snapshot data files, oldest -> newest (manifest
        presence = committed; digest validation happens at restore)."""
        tail = len(".manifest.json")
        return sorted(
            p.parent / p.name[:-tail]
            for p in self.directory.glob("ckpt_????????.pkl.manifest.json")
        )

    def latest(
        self,
        expect_like: Any = None,
        allow_config_mismatch: bool = False,
    ) -> Optional[Any]:
        """Restore the newest intact snapshot (None when nothing usable).

        Corrupt or torn snapshots — missing/garbled manifest, size or
        SHA-256 mismatch, unpicklable payload — are skipped with a warning
        and the next-older snapshot is tried, so one bad file never takes
        down a resume.

        ``expect_like``: a state pytree of the RESTORING run (a fresh
        ``wf.init`` result, or the live state being resumed). A snapshot
        whose recorded config fingerprint differs — different algorithm,
        pop size, monitors — raises :class:`CheckpointConfigError`
        instead of being silently restored into a program compiled for
        other shapes (``allow_config_mismatch=True`` overrides; manifests
        predating the fingerprint are never checked)."""
        expected = (
            None if expect_like is None
            else state_config_fingerprint(expect_like)
        )
        for path in reversed(self.snapshots()):
            got = self._load_validated(path)
            if got is None:
                continue
            manifest, state = got
            self._check_config(
                manifest, expected, path, allow_config_mismatch
            )
            return state
        return None

    @staticmethod
    def _check_config(
        manifest: dict,
        expected: Optional[str],
        path: Path,
        allow_config_mismatch: bool,
    ) -> None:
        recorded = manifest.get("config_sha")
        if (
            expected is not None
            and recorded is not None
            and recorded != expected
            and not allow_config_mismatch
        ):
            raise CheckpointConfigError(
                f"checkpoint {path.name} was written under a different "
                f"run config (snapshot config_sha {recorded[:12]}… != "
                f"expected {expected[:12]}…): algorithm, population "
                "size, or monitor set changed. Rebuild the matching "
                "workflow, point at the right directory, or pass "
                "allow_config_mismatch=True to restore anyway."
            )

    def load(
        self,
        generation: int,
        expect_like: Any = None,
        allow_config_mismatch: bool = False,
    ) -> Optional[Any]:
        """Restore the snapshot of ONE specific generation, or None when
        it is absent/uncommitted/torn (same validation + config guard as
        :meth:`latest`). The serving journal's recovery path uses this:
        a ``chunk_complete`` barrier names its snapshot generation, and a
        barrier whose snapshot never landed (driver killed
        mid-background-fsync) must fall back to the previous barrier
        rather than silently restoring a newer-but-unrelated snapshot."""
        path = self.directory / f"ckpt_{int(generation):08d}.pkl"
        if not self._manifest_path(path).exists():
            return None
        got = self._load_validated(path)
        if got is None:
            return None
        manifest, state = got
        expected = (
            None if expect_like is None
            else state_config_fingerprint(expect_like)
        )
        self._check_config(manifest, expected, path, allow_config_mismatch)
        return state

    def _manifest_path(self, path: Path) -> Path:
        return path.with_suffix(".pkl.manifest.json")

    def _load_validated(self, path: Path) -> Optional[tuple]:
        try:
            with open(self._manifest_path(path)) as f:
                manifest = json.load(f)
            payload = path.read_bytes()
            if len(payload) != manifest["bytes"]:
                raise ValueError(
                    f"size mismatch: {len(payload)} != {manifest['bytes']}"
                )
            digest = hashlib.sha256(payload).hexdigest()
            if digest != manifest["sha256"]:
                raise ValueError("sha256 mismatch")
            state = pickle.loads(payload)
            att = manifest.get("attest")  # absent in pre-v20 manifests
            if isinstance(att, dict) and "digest" in att:
                got = attest_digest_hex(state)
                if got != att["digest"]:
                    # file bytes intact but the STATE is not the one
                    # attested at save time — same corrupt-skip law as a
                    # torn payload: warn, fall back one snapshot
                    raise IntegrityError(
                        f"state digest {got} != manifest attestation "
                        f"{att['digest']}",
                        generation=manifest.get("generation"),
                        where=path.name,
                    )
            return manifest, state
        except Exception as e:
            warnings.warn(
                f"skipping corrupt checkpoint {path.name}: {e}", stacklevel=2
            )
            return None

    def _prune(self) -> None:
        snaps = self.snapshots()
        for old in snaps[: max(len(snaps) - self.keep, 0)]:
            for p in (old, self._manifest_path(old)):
                try:
                    p.unlink()
                except FileNotFoundError:
                    pass


def snapshot_dir_intact(directory: Any) -> bool:
    """Host-only intactness probe: does ``directory`` hold at least one
    COMMITTED, UNTORN snapshot — manifest present, payload bytes and
    SHA-256 matching — without unpickling anything? The multi-pod
    control plane uses this before stealing a parked continuation off a
    dead pod: a continuation whose checkpoint is torn cannot be re-
    placed (the target would crash at admission), so it is re-run fresh
    instead. Pure file I/O — safe from the gateway process with no jax
    state, and axon-safe."""
    directory = Path(directory)
    tail = len(".manifest.json")
    manifests = sorted(
        directory.glob("ckpt_????????.pkl.manifest.json"), reverse=True
    )
    for mpath in manifests:
        try:
            with open(mpath) as f:
                manifest = json.load(f)
            payload = (mpath.parent / mpath.name[:-tail]).read_bytes()
            if len(payload) != manifest["bytes"]:
                continue
            if hashlib.sha256(payload).hexdigest() != manifest["sha256"]:
                continue
            return True
        except Exception:
            continue
    return False


def _as_checkpointer(resume_from: Any) -> WorkflowCheckpointer:
    if isinstance(resume_from, WorkflowCheckpointer):
        return resume_from
    # directory string: adopt the crashed run's persisted cadence (see
    # _write_config) rather than silently resuming with the defaults
    kw = {}
    try:
        with open(Path(resume_from) / WorkflowCheckpointer._CONFIG) as f:
            cfg = json.load(f)
        kw = {"every": int(cfg["every"]), "keep": int(cfg["keep"])}
    except Exception:
        pass  # no/garbled config (pre-existing dir): defaults apply
    return WorkflowCheckpointer(str(resume_from), **kw)


def resolve_resume(
    resume_from: Any,
    state: Any,
    n_steps: int,
    expect_like: Any = None,
    allow_config_mismatch: bool = False,
):
    """Shared ``resume_from=`` handling for Std and pipelined runs.

    ``resume_from`` (a :class:`WorkflowCheckpointer` or a directory path)
    overrides ``state`` with its newest intact snapshot when one exists;
    ``n_steps`` then counts TOTAL generations from 0, so the remaining
    trip count is ``n_steps - state.generation``. ``expect_like``
    (normally the caller's live ``state``) arms the config-fingerprint
    guard. Returns ``(state, remaining_steps)``."""
    loaded = _as_checkpointer(resume_from).latest(
        expect_like=expect_like, allow_config_mismatch=allow_config_mismatch
    )
    if loaded is not None:
        state = loaded
    return state, max(n_steps - int(state.generation), 0)


def enter_run(
    state: Any,
    n_steps: int,
    checkpointer: Optional[WorkflowCheckpointer] = None,
    resume_from: Any = None,
    expect_like: Any = None,
    allow_config_mismatch: bool = False,
):
    """The shared run prologue every driver used to hand-roll (std.py,
    islands.py, pipelined.py, tenancy.py, supervisor.py each repeated
    the same three steps): resolve ``resume_from`` into (restored state,
    REMAINING generations), and default the checkpointer to the resumed
    directory — a resumed run must stay crash-safe and record its own
    completion, or a second resume would re-run generations. Returns
    ``(state, remaining_steps, checkpointer)``; a no-op (checkpointer
    passed through) when ``resume_from`` is None."""
    if resume_from is not None:
        state, n_steps = resolve_resume(
            resume_from,
            state,
            n_steps,
            expect_like=expect_like,
            allow_config_mismatch=allow_config_mismatch,
        )
        if checkpointer is None:
            checkpointer = _as_checkpointer(resume_from)
    return state, n_steps, checkpointer


def checkpointed_run(wf, state, n_steps: int, checkpointer: WorkflowCheckpointer):
    """``wf.run`` with host-side snapshots between dispatches.

    The fused device loop is chunked at the checkpoint cadence: each chunk
    ends exactly on a multiple of ``checkpointer.every`` (or at
    ``n_steps``), the state is snapshotted, and the next chunk is
    dispatched. Chunking a ``fori_loop`` does not change its math, so the
    final state is identical to a straight ``wf.run(state, n_steps)`` —
    and a crash between chunks resumes from the last snapshot with
    nothing lost but the current chunk. The final state is always
    snapshotted (even off-cadence) so a completed run restores to its
    true end.

    Since the executor port this is a thin policy over
    :class:`~evox_tpu.core.executor.GenerationExecutor` — the cadence
    chunking lives there once, and the snapshot pickle+fsync runs on the
    executor's background checkpoint lane (bounded in-flight, drained
    before return) instead of stalling the next chunk's dispatch."""
    from ..core.executor import GenerationExecutor

    return GenerationExecutor().run_fused(
        wf, state, n_steps, checkpointer=checkpointer
    )
