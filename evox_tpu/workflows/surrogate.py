"""SurrogateWorkflow — trade cheap on-device FLOPs for expensive TRUE
evaluations (ROADMAP item 5).

For expensive problems — exactly the host-eval/rollout workloads PRs 2/5/8
hardened — wall-clock is dominated by true evaluations, not device
compute. This workflow wraps ANY single-objective algorithm and, each
generation, pre-screens the full ask through an on-device surrogate
(:mod:`evox_tpu.operators.surrogate`): only the top ``screen_frac``
fraction by predicted fitness reaches the real problem; the unscreened
rows are carried as INERT rows — filled with the worst FINITE truly
evaluated value so they lose every comparison cleanly (the elastic
``ACTIVE_ROWS``/worst-finite-fill precedent, workflows/elastic.py) — so
one fixed-shape compiled program serves every generation with NO retrace
as the screened count or the fallback state changes (the evaluated count
is a traced operand under ``lax.cond``; ``screen_frac`` itself is static
config).

Health predicates (the :class:`~evox_tpu.core.guardrail.GuardedAlgorithm`
precedent — jit predicates + ``lax.cond``, never a host branch): a
generation falls back to FULL evaluation when the surrogate's
rank-correlation between predicted and true fitness on the previously
evaluated subset drops below ``rank_floor``, or its mean uncertainty /
ensemble disagreement on the current ask exceeds ``unc_ceiling`` — a
lying surrogate degrades to the bare workflow (plus surrogate overhead),
never to a corrupted search. Every decision is counted on device and
surfaced as the ``surrogate`` section of ``run_report()`` (schema v10,
validated by tools/check_report.py).

Refits: in fused/step runs the refit is a ``lax.cond`` at the
``refit_every`` cadence inside the step. In executor-driven host runs
(:class:`~evox_tpu.core.executor.GenerationExecutor`), the refit is a
SEPARATE jitted program the executor dispatches between tells
(``refit_due``/``dispatch_refit`` hooks): JAX's async dispatch means the
generation loop never blocks on the Cholesky/adam program, and the model
any ask consumes is fitted on an archive at most ``refit_every``
generations stale — the executor's bounded-staleness discipline (PR 8)
applied to the model instead of the tell. Both paths refit at the same
absolute generations on the same archive contents, so checkpoint/resume
reproduces the refit schedule deterministically.

Disabled (``surrogate=None`` or ``screen_frac=1.0``) delegates every
step/half to :class:`~evox_tpu.workflows.std.StdWorkflow` unchanged —
BIT-identical to the bare workflow across step loops, fused runs, and
the pipelined driver (asserted in tests/test_surrogate.py). Composes
with quarantine, ``WorkflowCheckpointer``/resume (archive + model params
are ordinary state leaves), the run supervisor's healing ladder, and
``DtypePolicy`` (archived candidates rest at storage width).
"""

from __future__ import annotations

import math
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..core.algorithm import Algorithm
from ..core.distributed import POP_AXIS as _POP_AXIS_NAME, constrain_state, shard_pop
from ..core.dtype_policy import apply_compute
from ..core.problem import Problem
from ..core.struct import PyTreeNode, field
from ..operators.surrogate import (
    SurrogateArchive,
    spearman_correlation,
)
from ..utils.ring import ring_write
from .common import finish_step, ingest_fitness, quarantine_nonfinite
from .std import StdWorkflow, StdWorkflowState

__all__ = [
    "FALLBACK_RANK",
    "FALLBACK_UNCERTAINTY",
    "SurrogateState",
    "SurrogateWorkflow",
    "SurrogateWorkflowState",
    "masked_worst_finite_fill",
]

# bitmask codes recorded per fallback event (the guardrail trigger-bitmask
# idiom, core/guardrail.py)
FALLBACK_RANK = 1  # predicted/true rank correlation below rank_floor
FALLBACK_UNCERTAINTY = 2  # mean uncertainty/disagreement above unc_ceiling


class SurrogateState(PyTreeNode):
    """The surrogate's slice of the workflow state: the paired archive,
    the fitted model, the health/fallback flags, and the true-eval
    ledger. Everything is a scalar or a small replicated buffer except
    the nested archive/model states, which carry their own
    capacity-leading ``P(POP_AXIS)`` annotations."""

    archive: Any  # ArchiveState — own sharding/storage annotations
    model: Any  # GPModelState / EnsembleModelState — own annotations
    key: jax.Array = field(sharding=P())  # refit PRNG stream
    refits: jax.Array = field(sharding=P())  # () int32
    last_refit_gen: jax.Array = field(sharding=P())  # () int32
    # health: set from the last evaluated subset, consumed next ask
    fallback_next: jax.Array = field(sharding=P())  # () bool
    last_rank_corr: jax.Array = field(sharding=P())  # () f32
    last_uncertainty: jax.Array = field(sharding=P())  # () f32
    # the true-evaluation ledger (int32 — the PR-1 counter bound)
    candidates_seen: jax.Array = field(sharding=P())  # () rows asked
    true_evals: jax.Array = field(sharding=P())  # () rows truly evaluated
    screened_out: jax.Array = field(sharding=P())  # () rows never evaluated
    generations: jax.Array = field(sharding=P())  # () screened-path gens
    screened_gens: jax.Array = field(sharding=P())  # () gens that screened
    fallback_gens: jax.Array = field(sharding=P())  # () triggered full-eval gens
    warmup_gens: jax.Array = field(sharding=P())  # () pre-warm full-eval gens
    # fallback event ring (generation, reason bitmask) — chronological
    fb_gens: jax.Array = field(sharding=P())  # (log,) int32
    fb_reasons: jax.Array = field(sharding=P())  # (log,) int32
    fb_count: jax.Array = field(sharding=P())  # () int32


class SurrogateWorkflowState(StdWorkflowState):
    sur: Any = None


class _ScreenPlan(NamedTuple):
    """One generation's screening decision (all traced)."""

    order: jax.Array  # (n,) evaluation order (identity under full eval)
    n_eval: jax.Array  # () int32 rows to truly evaluate
    full_eval: jax.Array  # () bool — this generation evaluates everything
    warm: jax.Array  # () bool — archive filled and model fitted
    mean_perm: jax.Array  # (n,) predicted fitness in evaluation order
    unc_mean: jax.Array  # () mean uncertainty over the ask
    reason: jax.Array  # () int32 fallback bitmask (0 = none/warmup)


def masked_worst_finite_fill(fitness: jax.Array, eval_mask: jax.Array) -> jax.Array:
    """Fill rows outside ``eval_mask`` with the worst FINITE truly
    evaluated value — the quarantine/elastic inert-row discipline
    (workflows/common.py ``quarantine_nonfinite`` / elastic's
    ``ACTIVE_ROWS`` fill): an unscreened candidate loses every
    comparison cleanly instead of poisoning argmin/ranking. Evaluated
    rows pass through UNTOUCHED (a genuinely non-finite true fitness
    stays visible to telemetry and to the quarantine opt-in exactly as
    in the bare workflow). Single-objective (1-D) fitness."""
    finite = eval_mask & jnp.isfinite(fitness)
    worst = jnp.max(jnp.where(finite, fitness, -jnp.inf))
    worst = jnp.where(jnp.isfinite(worst), worst, jnp.finfo(fitness.dtype).max)
    return jnp.where(eval_mask, fitness, worst)


class SurrogateWorkflow(StdWorkflow):
    """Drive ANY single-objective algorithm with surrogate pre-screened
    evaluation. Full :class:`StdWorkflow` API (``step``/``run``/
    ``resume``/pipelined halves, checkpointer/supervisor/executor
    composition); see the module docstring for the design.

    Args:
        algorithm / problem / **std_kwargs: as :class:`StdWorkflow`
            (``eval_shard_map`` and ``num_objectives > 1`` are rejected
            while screening is enabled — the evaluated subset is a
            dynamic row slice, and the rank predicates are SO).
        surrogate: a model with the ``init_model``/``fit``/``predict``
            interface (:class:`~evox_tpu.operators.surrogate.GPSurrogate`
            or :class:`~evox_tpu.operators.surrogate.EnsembleSurrogate`).
            ``None`` disables screening entirely (bit-identical to the
            bare workflow).
        screen_frac: fraction of each ask that reaches the real problem
            (per batch width: ``k = ceil(screen_frac * width)``, floored
            at 1). ``1.0`` disables screening (bit-identical).
        archive_capacity: paired-archive ring size. Default ``None``
            derives 4x the widest ask width, rounded up to a multiple of
            the mesh's pop-axis size; an explicit capacity must be at
            least the widest ask width (one generation's scatter must
            not collide with itself) and mesh-divisible.
        warmup: archived pairs required before screening starts (until
            then every generation fully evaluates and feeds the
            archive). Default: the widest ask width (one generation).
        refit_every: refit cadence in generations — the model's bounded
            staleness (an ask consumes a model at most ``refit_every``
            generations behind the archive).
        rank_floor: Spearman rank-correlation floor between predicted
            and true fitness on each generation's evaluated subset;
            below it the NEXT generation falls back to full evaluation
            (and keeps falling back until the correlation recovers —
            full-eval generations re-measure it over the whole batch).
        unc_ceiling: mean predictive-uncertainty ceiling over the ask;
            above it THIS generation falls back. Default ``None`` (off:
            the right scale is problem-dependent; the GP's posterior std
            and the ensemble's disagreement are both in fitness units).
        fallback_log: on-device fallback-event ring capacity (the
            telemetry ring discipline — the last ``fallback_log`` events
            are reported with generation + reason bitmask).
    """

    def __init__(
        self,
        algorithm: Algorithm,
        problem: Problem,
        surrogate: Any = None,
        screen_frac: float = 1.0,
        archive_capacity: Optional[int] = None,
        warmup: Optional[int] = None,
        refit_every: int = 1,
        rank_floor: float = 0.5,
        unc_ceiling: Optional[float] = None,
        fallback_log: int = 64,
        **std_kwargs: Any,
    ):
        if not (0.0 < screen_frac <= 1.0):
            raise ValueError(
                f"screen_frac must be in (0, 1], got {screen_frac}"
            )
        if refit_every < 1:
            raise ValueError(f"refit_every must be >= 1, got {refit_every}")
        if fallback_log < 1:
            raise ValueError(f"fallback_log must be >= 1, got {fallback_log}")
        if surrogate is not None:
            for meth in ("init_model", "fit", "predict"):
                if not callable(getattr(surrogate, meth, None)):
                    raise TypeError(
                        f"surrogate must implement {meth}(); got "
                        f"{type(surrogate).__name__}"
                    )
        self.surrogate = surrogate
        self.screen_frac = float(screen_frac)
        self.refit_every = int(refit_every)
        self.rank_floor = float(rank_floor)
        self.unc_ceiling = (
            float(unc_ceiling) if unc_ceiling is not None else None
        )
        self.fallback_log = int(fallback_log)
        # screening active: delegation to the bare StdWorkflow trace is
        # the disabled path's bit-identity mechanism, not an assumption
        self._screening = surrogate is not None and self.screen_frac < 1.0
        if self._screening:
            if std_kwargs.get("num_objectives", 1) != 1:
                raise ValueError(
                    "surrogate screening is single-objective (the rank "
                    "predicates and worst-finite fill are SO); disable "
                    "screening for multi-objective runs"
                )
            if std_kwargs.get("eval_shard_map"):
                raise ValueError(
                    "surrogate screening cannot compose with "
                    "eval_shard_map: the truly evaluated subset is a "
                    "dynamic row slice the explicit-collective island "
                    "cannot tile; use the default GSPMD evaluation path"
                )
        super().__init__(algorithm, problem, **std_kwargs)
        self._sur_kwargs = dict(
            surrogate=surrogate,
            screen_frac=screen_frac,
            archive_capacity=archive_capacity,
            warmup=warmup,
            refit_every=refit_every,
            rank_floor=rank_floor,
            unc_ceiling=unc_ceiling,
            fallback_log=fallback_log,
        )
        if self._screening:
            # screen_frac=1.0 keeps the surrogate config fully inert —
            # no archive/model state is materialized, so the disabled
            # state (and every monitor mirror) is structurally identical
            # to the bare workflow's, not just value-identical
            self._derive_shapes(archive_capacity, warmup)
            self._refit = (
                jax.jit(self._refit_impl) if self.jit_step else self._refit_impl
            )

    # ------------------------------------------------------------ shape prep
    def _derive_shapes(
        self, archive_capacity: Optional[int], warmup: Optional[int]
    ) -> None:
        astate_sds = jax.eval_shape(self.algorithm.init, jax.random.PRNGKey(0))
        widths = []
        ask_sds = jax.eval_shape(
            lambda s: self.algorithm.ask(s)[0], astate_sds
        )
        probes = [ask_sds]
        if self.algorithm.has_init_ask:
            probes.append(
                jax.eval_shape(
                    lambda s: self.algorithm.init_ask(s)[0], astate_sds
                )
            )
        for sds in probes:
            if not hasattr(sds, "shape") or len(sds.shape) != 2:
                raise ValueError(
                    "surrogate screening requires flat 2-D (pop, dim) "
                    f"candidates from ask; got {sds} — flatten the "
                    "genotype before the workflow (pop_transforms map "
                    "candidates AFTER screening) or disable screening"
                )
            widths.append(int(sds.shape[0]))
        steady = widths[0]
        if self._k_for(steady) >= steady:
            # a screen_frac whose ceil rounds back up to the full batch
            # would pay the surrogate cost forever while screening
            # NOTHING — refuse loudly instead of running inert
            raise ValueError(
                f"screen_frac={self.screen_frac} screens nothing at the "
                f"steady ask width {steady} "
                f"(ceil(screen_frac * width) = {self._k_for(steady)} >= "
                "width); lower screen_frac, or pass screen_frac=1.0 to "
                "disable screening explicitly"
            )
        self._dim = int(probes[0].shape[1])
        self._max_width = max(widths)
        n_shards = (
            int(self.mesh.shape[_POP_AXIS_NAME]) if self.mesh is not None else 1
        )
        if archive_capacity is None:
            cap = 4 * self._max_width
            cap += (-cap) % n_shards  # round up to mesh divisibility
        else:
            cap = int(archive_capacity)
            if cap < self._max_width:
                raise ValueError(
                    f"archive_capacity {cap} is smaller than the widest "
                    f"ask batch ({self._max_width}); one generation's "
                    "scatter would collide with itself inside the ring"
                )
            if cap % n_shards != 0:
                raise ValueError(
                    f"archive_capacity {cap} is not divisible by the "
                    f"mesh's '{_POP_AXIS_NAME}' axis ({n_shards} shards)"
                )
        check = getattr(self.surrogate, "check_capacity", None)
        if check is not None:
            check(cap)  # the GP's dense-scale guard, at construction
        self._archive = SurrogateArchive(cap)
        self._warmup = int(warmup) if warmup is not None else self._max_width

    def clone_with_algorithm(self, algorithm: Algorithm) -> "SurrogateWorkflow":
        # the IPOP rebuild point: capacity/warmup re-derive from the
        # grown population when they were defaulted (raw args kept)
        return SurrogateWorkflow(
            algorithm, **dict(self._ctor_args, **self._sur_kwargs)
        )

    # ------------------------------------------------------------------ init
    def init(self, key: jax.Array) -> SurrogateWorkflowState:
        base = super().init(key)
        sur = None
        if self._screening:
            cap = self._archive.capacity
            zero = jnp.zeros((), jnp.int32)
            log = self.fallback_log
            sur = SurrogateState(
                archive=self._archive.init(self._dim),
                model=self.surrogate.init_model(cap, self._dim),
                # the refit stream folds from a key DISJOINT from the
                # algorithm/problem/monitor splits (the guardrail
                # fold_in discipline keeps the delegated trajectory
                # bit-identical to the bare workflow)
                key=jax.random.fold_in(key, 0x5A6E),
                refits=zero,
                last_refit_gen=zero,
                fallback_next=jnp.zeros((), bool),
                last_rank_corr=jnp.ones((), jnp.float32),
                last_uncertainty=jnp.zeros((), jnp.float32),
                candidates_seen=zero,
                true_evals=zero,
                screened_out=zero,
                generations=zero,
                screened_gens=zero,
                fallback_gens=zero,
                warmup_gens=zero,
                fb_gens=jnp.zeros((log,), jnp.int32),
                fb_reasons=jnp.zeros((log,), jnp.int32),
                fb_count=zero,
            )
        state = SurrogateWorkflowState(
            generation=base.generation,
            algo=base.algo,
            prob=base.prob,
            monitors=base.monitors,
            first_step=True,
            sur=sur,
        )
        from ..core.distributed import ensure_global_state

        return ensure_global_state(state, self.mesh)

    # ------------------------------------------------------------- screening
    def _k_for(self, width: int) -> int:
        return max(1, int(math.ceil(self.screen_frac * width)))

    def _screen_plan(self, sur: SurrogateState, pop: jax.Array) -> _ScreenPlan:
        if not (isinstance(pop, jax.Array) or hasattr(pop, "ndim")) or pop.ndim != 2:
            raise ValueError(
                "surrogate screening requires flat 2-D (pop, dim) "
                f"candidates from ask; got shape {getattr(pop, 'shape', None)}"
            )
        n = pop.shape[0]
        k = self._k_for(n)
        mean, unc = self.surrogate.predict(
            sur.model, jnp.asarray(pop, jnp.float32)
        )
        if k >= n:
            # this batch width cannot screen (ceil(screen_frac * n) == n
            # — e.g. a wider init_ask batch; the STEADY width is refused
            # at construction): statically a non-screening generation —
            # full evaluation counted under warmup_gens, never a
            # fallback event (reason stays 0 and is never recorded)
            warm = jnp.zeros((), bool)
        else:
            warm = (self._archive.fill(sur.archive) >= self._warmup) & (
                sur.refits > 0
            )
        unc_mean = jnp.mean(unc)
        ceiling = (
            jnp.float32(self.unc_ceiling)
            if self.unc_ceiling is not None
            else jnp.float32(jnp.inf)
        )
        unc_trip = warm & (unc_mean > ceiling)
        rank_trip = warm & sur.fallback_next
        full_eval = (~warm) | rank_trip | unc_trip
        order = jnp.where(
            full_eval, jnp.arange(n), jnp.argsort(mean)
        )
        return _ScreenPlan(
            order=order,
            n_eval=jnp.where(full_eval, jnp.int32(n), jnp.int32(k)),
            full_eval=full_eval,
            warm=warm,
            mean_perm=mean[order],
            unc_mean=unc_mean,
            reason=rank_trip.astype(jnp.int32) * FALLBACK_RANK
            + unc_trip.astype(jnp.int32) * FALLBACK_UNCERTAINTY,
        )

    def _screened_evaluate(
        self, pstate: Any, cand: Any, full_eval: jax.Array, k: int
    ) -> Tuple[jax.Array, Any]:
        """Evaluate either the leading ``k`` rows (screened; the rest
        padded +inf and masked downstream) or the whole batch (fallback)
        under ONE ``lax.cond`` — both branches live in the same compiled
        program, so fallback flips never retrace."""
        n = jax.tree.leaves(cand)[0].shape[0]
        if k >= n:
            return self._evaluate(pstate, cand)

        def full(ps):
            return self._evaluate(ps, cand)

        def screened(ps):
            sub = jax.tree.map(lambda x: x[:k], cand)
            fit, ps2 = self._evaluate(ps, sub)
            pad = jnp.full((n - k,), jnp.inf, fit.dtype)
            return jnp.concatenate([fit, pad]), ps2

        return jax.lax.cond(full_eval, full, screened, pstate)

    def _refit_model(
        self, sur: SurrogateState, archive: Any, gen_after: jax.Array
    ):
        """Fit on the given archive with the fold_in(key, generation)
        stream — the ONE refit body both the fused step's ``lax.cond``
        and the executor-dispatched ``_refit_impl`` call, so every
        driver reproduces the same model at the same generation."""
        return self.surrogate.fit(
            sur.model,
            jnp.asarray(archive.x, jnp.float32),
            archive.y,
            self._archive.valid_mask(archive),
            jax.random.fold_in(sur.key, gen_after),
        )

    def _update_sur(
        self,
        sur: SurrogateState,
        generation: jax.Array,
        raw_perm: jax.Array,
        flipped_perm: jax.Array,
        eval_mask: jax.Array,
        plan: _ScreenPlan,
        refit_inline: bool,
    ) -> SurrogateState:
        gen_after = jnp.asarray(generation, jnp.int32) + 1
        arch_mask = eval_mask & jnp.isfinite(flipped_perm)
        archive = self._archive.update(
            sur.archive, raw_perm, flipped_perm, arch_mask
        )
        # health: can the model ORDER what we truly measured?
        corr = spearman_correlation(plan.mean_perm, flipped_perm, eval_mask)
        trained = sur.refits > 0
        fallback_next = trained & (corr < jnp.float32(self.rank_floor))
        if refit_inline:
            due = (gen_after % self.refit_every) == 0
            model = jax.lax.cond(
                due,
                lambda: self._refit_model(sur, archive, gen_after),
                lambda: sur.model,
            )
            refits = jnp.where(due, sur.refits + 1, sur.refits)
            last_refit = jnp.where(due, gen_after, sur.last_refit_gen)
        else:
            # executor-driven host runs: the refit is a separate program
            # dispatched between tells (dispatch_refit), same cadence
            model, refits, last_refit = sur.model, sur.refits, sur.last_refit_gen
        n = eval_mask.shape[0]
        ev = plan.full_eval & plan.warm  # a TRIGGERED fallback, not warmup
        # shared ring discipline (utils/ring.py): conditional writes
        fb_gens = ring_write(sur.fb_gens, gen_after, sur.fb_count, cond=ev)
        fb_reasons = ring_write(sur.fb_reasons, plan.reason, sur.fb_count, cond=ev)
        i32 = lambda b: b.astype(jnp.int32)  # noqa: E731
        return SurrogateState(
            archive=archive,
            model=model,
            key=sur.key,
            refits=refits,
            last_refit_gen=last_refit,
            fallback_next=fallback_next,
            last_rank_corr=corr,
            last_uncertainty=plan.unc_mean,
            candidates_seen=sur.candidates_seen + jnp.int32(n),
            true_evals=sur.true_evals + plan.n_eval,
            screened_out=sur.screened_out + (jnp.int32(n) - plan.n_eval),
            generations=sur.generations + 1,
            screened_gens=sur.screened_gens + i32(~plan.full_eval),
            fallback_gens=sur.fallback_gens + i32(ev),
            warmup_gens=sur.warmup_gens + i32(~plan.warm),
            fb_gens=fb_gens,
            fb_reasons=fb_reasons,
            fb_count=sur.fb_count + i32(ev),
        )

    # ------------------------------------------------------- step (screened)
    def _step_impl(self, state: SurrogateWorkflowState) -> SurrogateWorkflowState:
        if not self._screening:
            # disabled: the PARENT trace verbatim (sur rides along
            # untouched) — the bit-identity mechanism, asserted in tests
            return super()._step_impl(state)
        state = apply_compute(state, self.dtype_policy)
        mstates = list(state.monitors)
        self._run_hooks("pre_step", mstates)
        self._run_hooks("pre_ask", mstates)
        use_init, pop, astate = self._dispatch_ask(state)
        self._run_hooks("post_ask", mstates, pop)
        plan = self._screen_plan(state.sur, pop)
        raw_perm = jnp.asarray(pop, jnp.float32)[plan.order]
        cand = pop[plan.order]
        for t in self.pop_transforms:
            cand = t(cand)
        cand = shard_pop(cand, self.mesh)
        self._run_hooks("pre_eval", mstates, cand)
        n = pop.shape[0]
        fitness_perm, pstate = self._screened_evaluate(
            state.prob, cand, plan.full_eval, self._k_for(n)
        )
        fitness_perm = shard_pop(fitness_perm, self.mesh)
        eval_mask = jnp.arange(n) < plan.n_eval
        flipped = self._flip(fitness_perm)
        filled = masked_worst_finite_fill(flipped, eval_mask)
        # monitors observe the evaluation-order batch with inert rows
        # already filled, in the user's direction convention (the flip
        # is linear) — telemetry's eval counter counts batch ROWS; the
        # true-evaluation ledger lives in the surrogate section
        self._run_hooks("post_eval", mstates, cand, filled * self.opt_direction[0])
        fit = filled
        if self.quarantine_nonfinite:
            fit = quarantine_nonfinite(fit)
        fit = fit[jnp.argsort(plan.order)]  # back to ask order for tell
        # shared tell half (workflows/common.py): fit_transforms ->
        # pre_tell -> tell dispatch -> migrate cond -> constrain_state
        astate = ingest_fitness(self, astate, mstates, fit, use_init)
        sur = self._update_sur(
            state.sur, state.generation, raw_perm, flipped, eval_mask, plan,
            refit_inline=True,
        )
        sur = constrain_state(sur, self.mesh, self.dtype_policy)
        self._run_hooks("post_tell", mstates)
        new_state = state.replace(
            generation=state.generation + 1,
            algo=astate,
            prob=pstate,
            monitors=tuple(mstates),
            first_step=False,
            sur=sur,
        )
        return finish_step(self.monitors, self._hook_table, new_state)

    # --------------------------------------------- pipelined halves (screened)
    def _pipeline_ask_impl(self, state: SurrogateWorkflowState):
        if not self._screening:
            return super()._pipeline_ask_impl(state)
        state = apply_compute(state, self.dtype_policy)
        mstates = list(state.monitors)
        self._run_hooks("pre_step", mstates)
        self._run_hooks("pre_ask", mstates)
        _, pop, astate = self._dispatch_ask(state)
        self._run_hooks("post_ask", mstates, pop)
        plan = self._screen_plan(state.sur, pop)
        raw_perm = jnp.asarray(pop, jnp.float32)[plan.order]
        cand = pop[plan.order]
        for t in self.pop_transforms:
            cand = t(cand)
        cand = shard_pop(cand, self.mesh)
        self._run_hooks("pre_eval", mstates, cand)
        extra = (cand, raw_perm, plan)
        # the host driver gets (candidates, rows-to-evaluate): only the
        # leading n_eval rows reach the real problem (host_evaluate)
        return (cand, plan.n_eval), (astate, tuple(mstates), extra)

    def _pipeline_tell_impl(
        self, state: SurrogateWorkflowState, ctx, fitness: jax.Array, pstate: Any
    ) -> SurrogateWorkflowState:
        if not self._screening:
            return super()._pipeline_tell_impl(state, ctx, fitness, pstate)
        astate, mstates_t, extra = ctx
        cand, raw_perm, plan = extra
        mstates = list(mstates_t)
        fitness = shard_pop(jnp.asarray(fitness), self.mesh)
        n = fitness.shape[0]
        eval_mask = jnp.arange(n) < plan.n_eval
        flipped = self._flip(fitness)
        filled = masked_worst_finite_fill(flipped, eval_mask)
        self._run_hooks(
            "post_eval", mstates, cand, filled * self.opt_direction[0]
        )
        fit = filled
        if self.quarantine_nonfinite:
            fit = quarantine_nonfinite(fit)
        fit = fit[jnp.argsort(plan.order)]
        use_init = state.first_step and (
            self.algorithm.has_init_ask or self.algorithm.has_init_tell
        )
        # shared tell half (workflows/common.py): fit_transforms ->
        # pre_tell -> tell dispatch -> migrate cond -> constrain_state
        astate = ingest_fitness(self, astate, mstates, fit, use_init)
        # host-driven runs refit through the executor's dispatch_refit
        # hook (refit_inline=False keeps THIS program refit-free so the
        # cadence is owned in exactly one place per driver)
        sur = self._update_sur(
            state.sur, state.generation, raw_perm, flipped, eval_mask, plan,
            refit_inline=False,
        )
        sur = constrain_state(sur, self.mesh, self.dtype_policy)
        self._run_hooks("post_tell", mstates)
        new_state = state.replace(
            generation=state.generation + 1,
            algo=astate,
            prob=pstate,
            monitors=tuple(mstates),
            first_step=False,
            sur=sur,
        )
        return finish_step(self.monitors, self._hook_table, new_state)

    # ------------------------------------------------- executor host hooks
    def host_evaluate(self, pstate: Any, cand: Any, eval_chunk: Optional[int]):
        """The :class:`~evox_tpu.core.executor.GenerationExecutor`'s
        host-evaluation hook: slice the screened batch to its truly
        evaluated rows (a HOST slice — the jitted halves stay one fixed
        shape), evaluate only those (honoring the supervisor's
        ``eval_chunk`` degradation), and pad the fitness back to the
        declared width with +inf sentinels the tell half masks out. The
        whole point of the workflow: the expensive host problem sees
        ``n_eval`` rows, not ``pop``."""
        from .pipelined import chunked_evaluate

        if not self._screening:
            return chunked_evaluate(self.problem, pstate, cand, eval_chunk)
        cand_arr, n_eval = cand
        n = int(n_eval)  # small scalar fetch, the CLAUDE.md-legal kind
        width = jax.tree.leaves(cand_arr)[0].shape[0]
        part = jax.tree.map(lambda x: x[:n], cand_arr)
        fit, ps = chunked_evaluate(self.problem, pstate, part, eval_chunk)
        if n >= width:
            return fit, ps
        if isinstance(fit, jax.Array):
            pad = jnp.full((width - n,), jnp.inf, fit.dtype)
            return jnp.concatenate([fit, pad]), ps
        fit = np.asarray(fit)
        pad = np.full((width - n,), np.inf, fit.dtype)
        return np.concatenate([fit, pad]), ps

    def refit_due(self, generation: int) -> bool:
        """Host-side cadence predicate the executor polls after each
        tell — pure in the absolute generation, so a resumed run
        reproduces the schedule deterministically."""
        return (
            self._screening
            and generation >= 1
            and generation % self.refit_every == 0
        )

    def dispatch_refit(self, state: Any, generation: int) -> Any:
        """Refit the model on the current archive as ONE separate jitted
        program (async dispatch — the executor's loop never blocks on
        it) and splice the result into the state. Same fit body and
        fold_in stream as the fused step's inline ``lax.cond`` refit, at
        the same absolute generations."""
        return state.replace(
            sur=self._refit(state.sur, jnp.asarray(generation, jnp.int32))
        )

    def _refit_impl(self, sur: SurrogateState, gen: jax.Array) -> SurrogateState:
        # gen is the post-tell generation: match the inline path's
        # _refit_model(sur, archive, gen_after) exactly (archive already
        # updated by the tell that preceded this dispatch)
        return sur.replace(
            model=self._refit_model(sur, sur.archive, gen),
            refits=sur.refits + 1,
            last_refit_gen=gen,
        )

    # ------------------------------------------------------------- reporting
    def surrogate_report(self, state: Any) -> dict:
        """The ``surrogate`` section of ``run_report()`` (schema v10,
        validated by tools/check_report.py): archive fill, refit
        count/staleness, the screened-vs-true eval ledger, health
        readings, and the chronological fallback-event log."""
        from ..core.instrument import sanitize_json

        out: dict = {
            "enabled": bool(self._screening),
            "model": getattr(self.surrogate, "kind", None)
            if self.surrogate is not None
            else None,
            "screen_frac": self.screen_frac,
        }
        sur = getattr(state, "sur", None)
        if sur is None or not self._screening:
            return sanitize_json(out)
        cap = self._archive.capacity
        count = int(sur.fb_count)
        log = self.fallback_log
        n_ev = min(count, log)
        slots = [(i % log) for i in range(count - n_ev, count)]
        gens = np.asarray(sur.fb_gens)
        reasons = np.asarray(sur.fb_reasons)
        out.update(
            archive={
                "capacity": cap,
                "fill": int(self._archive.fill(sur.archive)),
                "writes": int(sur.archive.count),
            },
            refit={
                "count": int(sur.refits),
                "every": self.refit_every,
                "last_generation": int(sur.last_refit_gen),
                # the model any ask consumes is fitted on an archive at
                # most this many generations old — the staleness bound
                "max_staleness_gens": self.refit_every,
            },
            counters={
                "candidates_seen": int(sur.candidates_seen),
                "true_evals": int(sur.true_evals),
                "screened_out": int(sur.screened_out),
                "generations": int(sur.generations),
                "screened_gens": int(sur.screened_gens),
                "fallback_gens": int(sur.fallback_gens),
                "warmup_gens": int(sur.warmup_gens),
            },
            health={
                "rank_floor": self.rank_floor,
                "unc_ceiling": self.unc_ceiling,
                "last_rank_corr": float(sur.last_rank_corr),
                "last_uncertainty": float(sur.last_uncertainty),
                "fallback_armed": bool(sur.fallback_next),
            },
            fallback_events=[
                {"generation": int(gens[s]), "reason": int(reasons[s])}
                for s in slots
            ],
        )
        return sanitize_json(out)
