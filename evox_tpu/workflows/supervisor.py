"""RunSupervisor — deadlines, transient-error retry, and checkpoint replay
for the dispatch layer.

The last unguarded boundary after the PR-2/PR-3 healing layers is the
dispatch itself: on the tunneled axon backend a single ``jit`` dispatch
can hang forever (wedged tunnel), die with a transient RPC error
(``UNAVAILABLE``/connection reset — the 45-100 ms RTT drifts and
occasionally drops, CLAUDE.md), or fail with ``RESOURCE_EXHAUSTED`` /
HTTP 413 when a payload outgrows the tunnel or HBM. Production
ES-on-accelerator systems (OpenAI ES, EvoJAX — PAPERS.md) treat these as
routine; today any of them kills the whole evox_tpu run even though
PR-2 checkpoints sit on disk. This module closes that gap, entirely
host-side (no callbacks, axon-safe):

- **Deadline**: every supervised dispatch chunk runs on a disposable
  worker thread while the supervisor waits with a wall-clock timeout — a
  hung dispatch becomes a raised :class:`DispatchDeadlineError` instead
  of an eternal block. (The wedged thread itself cannot be killed from
  Python; it is daemonized and abandoned — the tunnel either answers
  late into the void or never.)
- **Classifier**: :func:`classify_error` folds the zoo of backend
  failures into ``transient`` / ``oom`` / ``deadline`` / ``fatal`` /
  ``integrity`` (wrong bits — never retried, see core/attest.py).
  Classification is by exception type AND message patterns, so the fake
  faults of tests/_chaos.py::FlakyDispatch classify exactly like the
  real jaxlib ``XlaRuntimeError`` strings they mimic.
- **Escalation ladder**, per dispatch chunk::

      retry (bounded, exponential backoff + deterministic jitter)
        -> restore the latest WorkflowCheckpointer snapshot and replay
        -> degrade (pipelined runs: halve the host eval chunk on OOM/413)
        -> RunAbortedError carrying a structured post-mortem

  (OOM takes the degrade rung first when one exists — retrying the
  identical payload would exhaust the same resource again.) Retrying is
  ALWAYS bit-safe: workflow states are immutable pytrees and the
  dispatch is a pure function of its input state, so a retried (or
  snapshot-replayed) chunk reproduces the exact trajectory of a clean
  run — the chaos acceptance law asserted in tests/test_supervisor.py.

Every supervisor decision (retry, deadline hit, restore, degradation,
abort) is recorded with a host timestamp; :func:`~evox_tpu.core.
instrument.run_report` surfaces them as a ``supervisor`` section and
:func:`~evox_tpu.core.instrument.write_chrome_trace` as instant markers
on a dedicated supervisor track. No reference analog (the reference
assumes every dispatch returns); informed by the fault-domain design of
the PR-2 process farm.

Since the executor port (PR 8) this module is pure POLICY: the chunk
loops live in :class:`~evox_tpu.core.executor.GenerationExecutor`, and
``run``/``run_host_pipelined`` wire the deadline watchdog, the
classified-retry ladder (:meth:`RunSupervisor.call`), the restore
replay, and the eval-chunk degradation in as executor hooks.
"""

from __future__ import annotations

import random
import re
import time
from typing import Any, Callable, Dict, List, Optional

from ..core.attest import IntegrityError
from ..core.distributed import BarrierTimeoutError
from ..core.pod_supervisor import (
    CollectiveDeadlineError,
    PodFailureError,
    _watchdog_call,
)
from .checkpoint import WorkflowCheckpointer

__all__ = [
    "DispatchDeadlineError",
    "RunAbortedError",
    "RunSupervisor",
    "classify_error",
    "TRANSIENT",
    "OOM",
    "DEADLINE",
    "FATAL",
    "INTEGRITY",
]


class DispatchDeadlineError(RuntimeError):
    """A supervised dispatch exceeded its wall-clock deadline — the
    tunneled backend hung instead of answering (or erroring)."""


class RunAbortedError(RuntimeError):
    """The supervisor exhausted its escalation ladder. ``post_mortem``
    holds the structured account of what was tried (see
    :meth:`RunSupervisor.report`); ``__cause__`` chains the final
    underlying failure."""

    def __init__(self, message: str, post_mortem: dict):
        super().__init__(message)
        self.post_mortem = post_mortem


# error classes (strings, so reports stay plain JSON)
TRANSIENT = "transient"
OOM = "oom"
DEADLINE = "deadline"
FATAL = "fatal"
# ISSUE 20: a digest violation is its OWN class, distinct from transient —
# retrying corrupt bits "heals" nothing and risks accepting them; the only
# valid responses are an explicit heal (voted re-dispatch, barrier
# fallback) or an abort, never the retry rung
INTEGRITY = "integrity"

# Message fingerprints of retryable backend failures. gRPC/absl status
# names cover jaxlib's XlaRuntimeError surface (one exception type for
# every status code — the status name in the message is the only
# signal); the socket/tunnel words cover the plugin's HTTP transport.
# Checked case-insensitively.
_TRANSIENT_PATTERNS = (
    "unavailable",
    "deadline_exceeded",
    "deadline exceeded",
    "aborted",
    "cancelled",
    "connection reset",
    "connection refused",
    "connection closed",
    "broken pipe",
    "timed out",
    "timeout",
    "socket",
    "tunnel",
    "temporarily",
    "eof occurred",
)
# OOM / payload-too-large: degradable, not retry-identical — the same
# dispatch at the same size exhausts the same resource again.
_OOM_PATTERNS = (
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
    "payload too large",
    "request entity too large",
    "http 413",
)
# bare "413" only counts next to an HTTP-ish word — it is also a shape
_OOM_413 = re.compile(r"(?:^|[^0-9.])413(?:[^0-9.]|$)")


def classify_error(exc: BaseException) -> str:
    """Fold an exception into ``transient`` / ``oom`` / ``deadline`` /
    ``fatal``. Decisive exception TYPES are checked before any message
    pattern (a ``MemoryError`` is OOM and a ``ConnectionError`` transient
    whatever they say; a bubbled-up :class:`RunAbortedError` is always
    fatal — a supervisor never re-litigates another's verdict), and
    patterns are matched against the MESSAGE only, never the type name
    (``RunAbortedError``'s own name must not read as 'aborted')."""
    if isinstance(exc, IntegrityError):
        # wrong BITS, not a failed dispatch (ISSUE 20): the chunk
        # "succeeded" with corrupt state, so no amount of retrying the
        # same path can be trusted to produce different evidence —
        # healing is the caller's explicit job (vote / barrier fallback)
        return INTEGRITY
    if isinstance(exc, (DispatchDeadlineError, CollectiveDeadlineError, BarrierTimeoutError)):
        # the pod-level deadlines (ISSUE 14) fold into the same class as
        # the dispatch watchdog's: a bounded wait expired
        return DEADLINE
    if isinstance(exc, (RunAbortedError, PodFailureError)):
        # a classified pod fault (worker dead / hung collective /
        # coordinator loss) cannot be healed by retrying IN this process
        # — the escalation continues in the re-formation driver, so the
        # in-process ladder must abort, not spin
        return FATAL
    if isinstance(exc, MemoryError):
        return OOM
    msg = str(exc).lower()
    if any(p in msg for p in _OOM_PATTERNS):
        return OOM
    if _OOM_413.search(msg) and ("http" in msg or "remote" in msg):
        return OOM
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return TRANSIENT
    if any(p in msg for p in _TRANSIENT_PATTERNS):
        return TRANSIENT
    if isinstance(exc, OSError):
        return TRANSIENT
    return FATAL


def _call_with_deadline(
    fn: Callable, deadline_s: Optional[float], label: str
):
    """Run ``fn()`` on a disposable daemon thread and wait at most
    ``deadline_s`` (None = no watchdog, call inline). A fresh thread per
    call is deliberate: a hung call occupies its thread forever, so
    pooling would poison the pool. ~50 µs of thread spawn is noise next
    to the 45-100 ms tunnel round-trip every dispatch already pays.
    One shared body with the pod fault domain's collective watchdog
    (core/pod_supervisor.py — this call supplies the dispatch-flavored
    timeout exception)."""
    return _watchdog_call(
        fn,
        deadline_s,
        label,
        make_timeout=lambda lbl, dl: DispatchDeadlineError(
            f"dispatch '{lbl}' exceeded its {dl:g} s deadline; "
            "the worker thread is abandoned (a wedged tunnel never answers)"
        ),
        thread_prefix="supervised",
    )


# event kind -> cumulative counter it increments
_COUNTER_FOR = {
    "retry": "retries",
    "deadline": "deadline_hits",
    "restore": "restores",
    "degrade": "degradations",
    "abort": "aborts",
}


class RunSupervisor:
    """Drive a workflow's dispatch chunks under deadlines, classified
    retry, checkpoint replay, and degradation.

    Args:
        checkpointer: optional :class:`WorkflowCheckpointer`. When given,
            runs are chunked at its cadence and snapshotted between
            dispatches (exactly the PR-2 ``checkpointed_run`` law, so the
            final state is identical to an unsupervised run), and the
            restore rung of the ladder can replay from the newest intact
            snapshot.
        deadline_s: wall-clock bound per supervised dispatch chunk
            (``None`` disables the watchdog). For pipelined chunks the
            bound covers the whole chunk — size it to
            ``chunk * worst-case generation time``.
        max_retries: transient/deadline retries per chunk before
            escalating to the restore rung.
        max_restores: snapshot-restore-and-replay attempts per chunk.
        backoff_s / backoff_factor / jitter: retry sleep is
            ``backoff_s * factor**(attempt-1) * (1 + jitter*u)`` with
            ``u ~ U[0,1)`` from a seeded PRNG — exponential backoff with
            deterministic jitter (reproducible chaos tests).
        min_eval_chunk: floor for the pipelined host-eval chunk; OOM
            below it escalates instead of degrading further.
        seed: jitter PRNG seed.

    One supervisor instance can drive many runs; counters and events
    accumulate (:meth:`report` is the ``run_report()`` ``supervisor``
    section).
    """

    def __init__(
        self,
        checkpointer: Optional[WorkflowCheckpointer] = None,
        deadline_s: Optional[float] = None,
        max_retries: int = 3,
        max_restores: int = 1,
        backoff_s: float = 0.05,
        backoff_factor: float = 2.0,
        jitter: float = 0.25,
        min_eval_chunk: int = 1,
        seed: int = 0,
        clock: Callable[[], float] = time.perf_counter,
        metrics: Any = None,
        attest: Any = None,
        verify_every: Optional[int] = None,
    ):
        if max_retries < 0 or max_restores < 0:
            raise ValueError("max_retries and max_restores must be >= 0")
        if min_eval_chunk < 1:
            raise ValueError(f"min_eval_chunk must be >= 1, got {min_eval_chunk}")
        self.checkpointer = checkpointer
        self.deadline_s = deadline_s
        self.max_retries = max_retries
        self.max_restores = max_restores
        self.backoff_s = backoff_s
        self.backoff_factor = backoff_factor
        self.jitter = jitter
        self.min_eval_chunk = min_eval_chunk
        # compute-integrity rung (ISSUE 20): when both are set, fused runs
        # re-dispatch every verify_every-th chunk from its immutable entry
        # state and compare digests (2-of-3 vote on mismatch). None/None —
        # the default — is the established no-op discipline: zero extra
        # dispatches, bit-identical to pre-PR.
        self.attest = attest
        self.verify_every = verify_every
        self._rng = random.Random(seed)
        # serving-plane flight recorder (PR 16): when attached, every
        # ladder event mirrors into the live metrics plane and aborts
        # carry the recorder's black-box ring in their post-mortem;
        # None (default) changes nothing
        self.metrics = metrics
        self._clock = clock
        self._created = clock()
        self.events: List[dict] = []
        self.counters: Dict[str, int] = {
            "dispatches": 0,
            "retries": 0,
            "deadline_hits": 0,
            "restores": 0,
            "degradations": 0,
            "aborts": 0,
        }
        self._outcome: Optional[str] = None

    # ------------------------------------------------------------- recording
    def _event(self, kind: str, **fields: Any) -> None:
        ev = {"t": round(self._clock() - self._created, 6), "event": kind}
        ev.update(fields)
        self.events.append(ev)
        counter = _COUNTER_FOR.get(kind)
        if counter is not None:
            self.counters[counter] += 1
        if self.metrics is not None:
            self.metrics.count(f"supervisor.{kind}")

    def report(self) -> dict:
        """The ``supervisor`` section of ``run_report()`` — strict-JSON
        account of every decision this supervisor took. ``outcome``:
        ``clean`` (nothing fired), ``recovered`` (faults healed),
        ``aborted`` (ladder exhausted)."""
        healed = any(
            e["event"] in ("retry", "restore", "degrade") for e in self.events
        )
        outcome = self._outcome
        if outcome is None:
            outcome = "recovered" if healed else "clean"
        return {
            "deadline_s": self.deadline_s,
            "max_retries": self.max_retries,
            "max_restores": self.max_restores,
            "counters": dict(self.counters),
            "outcome": outcome,
            "events": list(self.events),
        }

    def markers(self) -> List[dict]:
        """Events as absolute-timestamped instant markers for the
        Chrome-trace exporter (:func:`~evox_tpu.core.instrument.
        write_chrome_trace` re-bases ``t_abs`` — this supervisor's clock
        is the recorder's clock, ``time.perf_counter``)."""
        return [
            {
                "t_abs": self._created + ev["t"],
                "name": f"supervisor:{ev['event']}",
                "args": {k: v for k, v in ev.items() if k not in ("t", "event")},
            }
            for ev in self.events
        ]

    # -------------------------------------------------------------- plumbing
    def _sleep_backoff(self, attempt: int) -> float:
        dt = self.backoff_s * self.backoff_factor ** max(attempt - 1, 0)
        dt *= 1.0 + self.jitter * self._rng.random()
        time.sleep(dt)
        return dt

    def _abort(self, entry: str, error: BaseException, **ladder: Any) -> None:
        self._event("abort", entry=entry, error=str(error)[:300], **ladder)
        self._outcome = "aborted"
        post_mortem = {
            "entry": entry,
            "error": f"{type(error).__name__}: {error}",
            "classification": classify_error(error),
            "ladder": dict(ladder),
            "counters": dict(self.counters),
            "events_tail": self.events[-20:],
        }
        if self.metrics is not None:
            # the black-box tape: the newest flight-recorder records
            # (queue events, samples, barriers) ride in every abort's
            # forensics, so "what was the service doing" survives the
            # process that was doing it
            self.metrics.event(
                "supervisor.abort", entry=entry, error=str(error)[:120]
            )
            post_mortem["flight_recorder"] = self.metrics.tail(20)
        raise RunAbortedError(
            f"supervised '{entry}' exhausted its escalation ladder "
            f"({ladder}); last failure: {type(error).__name__}: {error}",
            post_mortem=post_mortem,
        ) from error

    def call(
        self,
        fn: Callable[[], Any],
        entry: str = "dispatch",
        restore: Optional[Callable[[], Any]] = None,
        degrade: Optional[Callable[[], bool]] = None,
        restore_budget: Optional[Dict[str, int]] = None,
    ) -> Any:
        """One supervised dispatch of the zero-arg ``fn`` under the full
        ladder (``fn`` is re-invoked on retry, so close over any state a
        degradation should be able to change). ``restore()`` (optional)
        returns a snapshot to replay from; when that rung fires, the
        snapshot is returned as the call's result — the CALLER owns the
        replay (it re-derives remaining work from ``state.generation``).
        ``degrade()`` (optional) applies one degradation (e.g. halving an
        eval chunk) and returns True if it could.

        ``restore_budget``: a ``{"used": n}`` cell shared across every
        chunk of one run. The retry budget is per CHUNK (each chunk is an
        independent dispatch), but restores must be bounded per RUN — a
        permanently failing chunk replayed from the same snapshot would
        otherwise ladder-cycle forever. Defaults to a per-call cell."""
        retries = 0
        if restore_budget is None:
            restore_budget = {"used": 0}
        while True:
            self.counters["dispatches"] += 1
            try:
                return _call_with_deadline(fn, self.deadline_s, entry)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:  # noqa: BLE001 — classified below
                kind = classify_error(e)
                if kind == DEADLINE:
                    self._event(
                        "deadline", entry=entry, deadline_s=self.deadline_s
                    )
                if kind == FATAL:
                    self._abort(entry, e, rung="fatal")
                if kind == INTEGRITY:
                    # never retried into acceptance: the voted re-dispatch
                    # rung (executor) and the barrier fallback (tenancy
                    # recover) heal BEFORE raising; an IntegrityError that
                    # reaches the ladder means healing already failed
                    self._abort(entry, e, rung="integrity")
                if kind == OOM and degrade is not None and degrade():
                    self._event("degrade", entry=entry, error=str(e)[:300])
                    continue
                if retries < self.max_retries and kind != OOM:
                    retries += 1
                    waited = self._sleep_backoff(retries)
                    self._event(
                        "retry",
                        entry=entry,
                        attempt=retries,
                        classification=kind,
                        backoff_s=round(waited, 6),
                        error=str(e)[:300],
                    )
                    continue
                if (
                    restore is not None
                    and restore_budget["used"] < self.max_restores
                ):
                    snapshot = restore()
                    if snapshot is not None:
                        restore_budget["used"] += 1
                        self._event(
                            "restore",
                            entry=entry,
                            attempt=restore_budget["used"],
                            classification=kind,
                        )
                        return snapshot
                self._abort(
                    entry, e, rung="exhausted", retries=retries,
                    restores=restore_budget["used"],
                )

    # ------------------------------------------------------------ fused runs
    def run(
        self,
        wf: Any,
        state: Any,
        n_steps: int,
        chunk: Optional[int] = None,
        resume_from: Any = None,
        executor: Any = None,
        pod_supervisor: Any = None,
    ) -> Any:
        """Supervised ``wf.run``: the fused device loop is chunked (at the
        checkpointer cadence, else ``chunk`` generations, else one
        dispatch for the whole run) and every chunk dispatch runs under
        the deadline + ladder. Chunking a ``fori_loop`` does not change
        its math, so the final state is identical to a straight
        ``wf.run(state, n_steps)`` — and on failure the supervisor
        retries the immutable entry state, or replays from the newest
        snapshot, reproducing the clean run's trajectory bit for bit.

        Works for any workflow exposing ``run(state, n)`` whose state
        carries ``generation`` — :class:`~evox_tpu.workflows.std.
        StdWorkflow` and :class:`~evox_tpu.workflows.islands.
        IslandWorkflow` alike. ``resume_from`` (checkpointer or
        directory) restores the newest intact snapshot first and
        reinterprets ``n_steps`` as the TOTAL generation target.

        The chunk loop itself lives in :class:`~evox_tpu.core.executor.
        GenerationExecutor` (this method is the supervision POLICY:
        deadline, classifier, ladder — wired in as executor hooks);
        snapshots land on the executor's background checkpoint lane,
        drained before any restore replays and before the run returns.
        Pass ``executor=`` to accumulate counters/spans on a shared
        instance, and ``pod_supervisor=`` (a
        :class:`~evox_tpu.core.pod_supervisor.PodSupervisor`) to put
        every SPMD-lockstep collective point under the pod fault domain
        — collective deadlines, chunk-boundary rendezvous, coordinated
        SIGTERM drain (ISSUE 14)."""
        from ..core.executor import GenerationExecutor

        ex = executor if executor is not None else GenerationExecutor()
        return ex.run_fused(
            wf,
            state,
            n_steps,
            checkpointer=self.checkpointer,
            chunk=chunk,
            resume_from=resume_from,
            supervisor=self,
            pod_supervisor=pod_supervisor,
            attest=self.attest,
            verify_every=self.verify_every,
        )

    # --------------------------------------------------------- pipelined runs
    def run_host_pipelined(
        self,
        wf: Any,
        state: Any,
        n_steps: int,
        chunk: Optional[int] = None,
        eval_chunk: Optional[int] = None,
        resume_from: Any = None,
        executor: Any = None,
        restarts: Any = None,
        **pipelined_kw: Any,
    ) -> Any:
        """Supervised ``run_host_pipelined`` for external (host)
        problems: the driver loop is chunked like :meth:`run` and each
        chunk runs under the ladder, with the degrade rung live — on
        OOM / HTTP 413 the host evaluation batch is split
        (``eval_chunk`` halves, floored at ``min_eval_chunk``) and the
        chunk retried from its immutable entry state; see
        ``run_host_pipelined(eval_chunk=...)`` for the bit-equivalence
        contract (row-independent host evaluate). The double-buffered
        loop and the degrade cell live in the
        :class:`~evox_tpu.core.executor.GenerationExecutor`; this method
        supplies the ladder. ``restarts=`` (an ``IPOPRestarts``) keeps
        the host-boundary IPOP recipe supervised: the run is chunked at
        the policy cadence and every pipelined segment dispatches under
        this supervisor's ladder."""
        from ..core.executor import GenerationExecutor

        ex = executor if executor is not None else GenerationExecutor()
        if restarts is not None:
            from .ipop import ipop_run

            return ipop_run(
                wf,
                state,
                n_steps,
                restarts,
                segment=lambda w, s, c, ck: ex.run_host(
                    w, s, c, checkpointer=ck, chunk=chunk,
                    eval_chunk=eval_chunk, supervisor=self, **pipelined_kw,
                ),
                checkpointer=self.checkpointer,
                resume_from=resume_from,
            )
        return ex.run_host(
            wf,
            state,
            n_steps,
            checkpointer=self.checkpointer,
            chunk=chunk,
            eval_chunk=eval_chunk,
            resume_from=resume_from,
            supervisor=self,
            **pipelined_kw,
        )

    def _restorer(self, ckpt, wf, expect_like):
        """Restore thunk for the ladder's replay rung. The host-numpy
        snapshot is re-placed on the workflow's CURRENT mesh by the
        state's own sharding annotations (exactly ``StdWorkflow.resume``'s
        law) — without it, a mesh workflow's warm fused executable would
        see replicated host arrays mid-recovery and pay a full
        re-trace/re-shard right when the run is trying to heal."""
        if ckpt is None:
            return None
        from .checkpoint import restore_layouts

        def restore():
            snapshot = ckpt.latest(expect_like=expect_like)
            if snapshot is None:
                return None
            # tenant-stacked fleet states re-place by their own prefixed
            # layout (VectorizedWorkflow.place_restored) — the plain
            # annotation walk would shard a stacked leaf's TENANT axis
            # over the pop mesh axis
            placer = getattr(wf, "place_restored", None)
            if placer is not None:
                return placer(snapshot)
            return restore_layouts(snapshot, mesh=getattr(wf, "mesh", None))

        return restore
