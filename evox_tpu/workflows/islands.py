"""IslandWorkflow — vmapped multi-population evolution with ring migration.

The classic island model: ``n_islands`` independent populations evolve in
parallel and periodically exchange their best individuals. The reference
approximates this only by replicating whole workflows across Ray workers
(reference workflows/distributed.py:224-225 — identical seeds, no actual
migration); here it is a first-class TPU-native workflow:

- Island states are the algorithm's own pytree state ``vmap``-stacked on a
  leading island axis (the same vmap-over-init pattern as the decomposition
  containers). Works with any algorithm supporting ``migrate`` — the base
  default covers states carrying ``(population, 1-d fitness)``; others
  (distribution-based ES) need an override, since ``lax.cond`` traces the
  migration branch on every step.
- One jitted step runs every island: vmapped ask -> ONE flattened
  evaluation batch (islands x pop candidates scored together, sharded over
  the mesh like any population) -> vmapped tell.
- Every ``migrate_every`` generations each island's top ``migrate_k``
  evaluated candidates are rolled one island around the ring
  (``jnp.roll`` on the island axis — under a mesh with islands sharded
  over devices XLA lowers this to a collective permute over ICI) and
  ingested via ``algorithm.migrate``.
- ``mesh``: the island axis is sharded over the ``"pop"`` mesh axis —
  whole islands per device, migration as the only cross-device traffic;
  the EC analog of data parallelism with periodic weight exchange.

``run()`` fuses generations into one compiled ``fori_loop`` exactly like
:class:`StdWorkflow`.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.algorithm import Algorithm
from ..core.distributed import POP_AXIS as _POP_AXIS_NAME, shard_pop
from ..core.dtype_policy import DtypePolicy, apply_compute, apply_storage
from ..core.monitor import Monitor
from ..core.problem import Problem
from ..core.struct import PyTreeNode, static_field
from ..utils.common import parse_opt_direction
from .common import (
    build_hook_table,
    callback_evaluate,
    finish_step,
    fused_run,
    make_run_loop,
    run_hooks,
)


class IslandWorkflowState(PyTreeNode):
    generation: jax.Array
    algo: Any  # island-stacked algorithm state (leading axis = island)
    prob: Any
    monitors: Tuple[Any, ...] = ()
    first_step: bool = static_field(default=True)


class IslandWorkflow:
    """Evolve ``n_islands`` independent populations with ring migration.

    Args:
        algorithm: the per-island :class:`Algorithm` (every island runs the
            same hyperparameters; diversity comes from independent PRNG
            streams). Must support ``migrate`` (the base default covers
            population+fitness states; PSO ships a pbest-aware override).
        problem: shared :class:`Problem`; candidates of all islands are
            scored as one flattened batch.
        n_islands: number of islands.
        migrate_every: generations between migrations.
        migrate_k: individuals sent per island per migration.
        monitors: 8-hook monitors, as :class:`StdWorkflow`; hooks see the
            flattened ``(islands * pop, ...)`` candidate batch.
        opt_direction / pop_transforms: as :class:`StdWorkflow`; transforms
            see the flattened ``(islands * pop, ...)`` batch.
            ``fit_transforms`` is rejected — population-relative shaping
            cannot coexist with migration's raw stored fitness.
        mesh: optional ``jax.sharding.Mesh``; the island axis is sharded
            over its ``"pop"`` axis (``n_islands`` must divide evenly).
        external_problem: route evaluation through ``jax.pure_callback``
            (host problems), same contract as :class:`StdWorkflow`.
        num_objectives: fitness arity. For ``> 1`` the workflow is
            multi-objective: migration elites are chosen per island by
            non-dominated rank + crowding distance and ingested through
            the algorithm's MO ``migrate`` (GA-skeleton MOEAs merge
            migrants into their (rank, crowding) environmental
            selection — :meth:`~evox_tpu.algorithms.mo.common.
            GAMOAlgorithm.migrate`).
        jit_step: disable to debug eagerly.
    """

    def __init__(
        self,
        algorithm: Algorithm,
        problem: Problem,
        n_islands: int,
        migrate_every: int = 10,
        migrate_k: int = 1,
        monitors: Sequence[Monitor] = (),
        opt_direction: Any = "min",
        pop_transforms: Sequence[Callable] = (),
        fit_transforms: Sequence[Callable] = (),
        mesh: Optional[jax.sharding.Mesh] = None,
        external_problem: Optional[bool] = None,
        num_objectives: int = 1,
        jit_step: bool = True,
        dtype_policy: Optional[DtypePolicy] = None,
        donate_carries: bool = False,
        use_topk_kernel: Optional[bool] = None,
        topk_interpret: bool = False,
    ):
        if n_islands < 2:
            raise ValueError(f"need at least 2 islands, got {n_islands}")
        if migrate_every < 1 or migrate_k < 1:
            raise ValueError("migrate_every and migrate_k must be >= 1")
        if num_objectives < 1:
            raise ValueError(f"num_objectives must be >= 1, got {num_objectives}")
        if fit_transforms:
            # migration writes raw (sign-flipped) fitness into algorithm
            # state; shaped fitness is population-relative and the stored
            # conventions would mix — see Algorithm.migrate
            raise ValueError(
                "fit_transforms cannot be combined with island migration: "
                "migrants carry raw fitness while tell stores shaped values"
            )
        self.algorithm = algorithm
        self.problem = problem
        self.n_islands = n_islands
        self.num_objectives = num_objectives
        self.migrate_every = migrate_every
        self.migrate_k = migrate_k
        self.monitors = tuple(monitors)
        self.opt_direction = parse_opt_direction(opt_direction)
        for m in self.monitors:
            m.set_opt_direction(self.opt_direction)
        self._hook_table = build_hook_table(self.monitors)
        self.pop_transforms = tuple(pop_transforms)
        self.mesh = mesh
        self.external = (not problem.jittable) if external_problem is None else external_problem
        if self.external and mesh is not None:
            from ..core.distributed import mesh_spans_processes

            if mesh_spans_processes(mesh):
                # same refusal (and reason) as StdWorkflow: a
                # pure_callback under a PROCESS-SPANNING mesh would run
                # the host evaluate on every process against
                # unsynchronized host problem state; a process-local
                # mesh in a multi-process run stays legal
                raise ValueError(
                    "external (host) problems are single-process: under "
                    "multi-process SPMD each process would invoke the "
                    "host evaluate on its own shard against "
                    "unsynchronized host state. Use a jittable problem "
                    "for pod-mesh islands, or run islands on a "
                    "process-local mesh."
                )
        if mesh is not None:
            n_shards = mesh.shape[_POP_AXIS_NAME]
            if n_islands % n_shards != 0:
                raise ValueError(
                    f"n_islands {n_islands} is not divisible by the mesh's "
                    f"'pop' axis ({n_shards} shards)"
                )
        self.jit_step = jit_step
        self.dtype_policy = dtype_policy
        self.donate_carries = bool(donate_carries) and jit_step
        # per-island elite selection through the Pallas partial-top-k
        # kernel (kernels/topk.py); None = backend default (currently
        # off), topk_interpret is the CPU-testing escape hatch
        self.use_topk_kernel = use_topk_kernel
        self.topk_interpret = topk_interpret
        self._step = jax.jit(self._step_impl) if jit_step else self._step_impl
        self._run_loop = make_run_loop(self._step_impl, donate=self.donate_carries)

    # ------------------------------------------------------------------ init
    def init(self, key: jax.Array) -> IslandWorkflowState:
        from ..core.distributed import ensure_global_state, mesh_spans_processes

        keys = jax.random.split(key, 2 + len(self.monitors))
        island_keys = jax.random.split(keys[1], self.n_islands)
        algo = jax.vmap(self.algorithm.init)(island_keys)
        if not mesh_spans_processes(self.mesh):
            # an eager sharding constraint cannot target a cross-process
            # layout; the pod path lays out via ensure_global_state below
            algo = self._constrain(algo)
        state = IslandWorkflowState(
            generation=jnp.zeros((), dtype=jnp.int32),
            algo=algo,
            prob=self.problem.init(keys[0]),
            monitors=tuple(m.init(k) for m, k in zip(self.monitors, keys[2:])),
            first_step=True,
        )
        # island-stacked leaves rest at storage width from the start (the
        # field annotations resolve through the extra island axis)
        state = apply_storage(state, self.dtype_policy)
        # pod meshes: assemble per-process shards of the island-stacked
        # leaves (islands shard whole-island over the pop axis, so the
        # leading-axis rule is the island rule here)
        return ensure_global_state(
            state, self.mesh,
            rules=((r"\.algo\.", jax.sharding.PartitionSpec(_POP_AXIS_NAME)),),
        )

    # ------------------------------------------------------------------ step
    def step(self, state: IslandWorkflowState) -> IslandWorkflowState:
        return self._step(state)

    def run(
        self,
        state: IslandWorkflowState,
        n_steps: int,
        checkpointer: Any = None,
        resume_from: Any = None,
    ) -> IslandWorkflowState:
        """Fused multi-generation run (see :meth:`StdWorkflow.run`).

        ``checkpointer=`` / ``resume_from=`` give island runs the same
        crash-safety law as :meth:`StdWorkflow.run` (chunk at the
        cadence, snapshot between dispatches, resume to the TOTAL
        generation target with the config-fingerprint guard armed) — and
        make :class:`~evox_tpu.workflows.supervisor.RunSupervisor`'s
        restore rung work for island runs too. The cadence chunking and
        background snapshot lane live in
        :class:`~evox_tpu.core.executor.GenerationExecutor` (one
        executor, five policies)."""
        from .checkpoint import checkpointed_run, enter_run

        state, n_steps, checkpointer = enter_run(
            state, n_steps, checkpointer, resume_from, expect_like=state
        )
        if checkpointer is not None:
            return checkpointed_run(self, state, n_steps, checkpointer)
        return fused_run(self, state, n_steps)

    def analysis_targets(self, state: IslandWorkflowState) -> dict:
        """AOT cost/memory analysis targets (see
        :meth:`StdWorkflow.analysis_targets`): the steady jitted step and
        the fused run loop (whose dynamic-trip-count body is counted once
        by XLA, i.e. per generation). External problems are skipped —
        their callback step is untraceable on the axon backend and the
        island model has no pipelined halves."""
        if not self.jit_step or self.external:
            return {}
        steady = state.replace(first_step=False) if state.first_step else state
        return {
            "step": (self._step, (steady,)),
            "run": (self._run_loop, (steady, jnp.asarray(1, jnp.int32))),
        }

    def best(self, state: IslandWorkflowState) -> Tuple[jax.Array, jax.Array]:
        """(island-stacked best fitness, global best) in the USER
        convention (same as the monitors report: a maximization run's
        best comes back positive), from states carrying pbest/fitness.

        Multi-objective: per-objective minima — the per-island ideal
        points ``(islands, m)`` and the global ideal point ``(m,)``; for
        the actual front use an :class:`~evox_tpu.monitors.EvalMonitor`
        Pareto archive or ``state.algo.fitness`` directly."""
        astate = state.algo
        for name in ("gbest_fitness", "pbest_fitness", "fitness"):
            arr = getattr(astate, name, None)
            if arr is not None:
                if self.num_objectives > 1:
                    per_island = arr.reshape(
                        self.n_islands, -1, self.num_objectives
                    ).min(axis=1)
                    sign = self.opt_direction
                    return per_island * sign, per_island.min(axis=0) * sign
                per_island = arr.reshape(self.n_islands, -1).min(axis=1)
                sign = self.opt_direction[0]
                return per_island * sign, per_island.min() * sign
        raise NotImplementedError(
            f"{type(astate).__name__} exposes no fitness field"
        )

    # ------------------------------------------------------------- internals
    def _constrain(self, algo_state: Any) -> Any:
        """Shard every island-stacked leaf over the mesh's pop axis."""
        if self.mesh is None:
            return algo_state
        from jax.sharding import NamedSharding, PartitionSpec as P

        def constrain(leaf):
            spec = P(_POP_AXIS_NAME, *([None] * (leaf.ndim - 1)))
            return jax.lax.with_sharding_constraint(
                leaf, NamedSharding(self.mesh, spec)
            )

        return jax.tree.map(constrain, algo_state)

    def _evaluate(self, pstate: Any, cand_flat: Any) -> Tuple[jax.Array, Any]:
        if not self.external:
            return self.problem.evaluate(pstate, cand_flat)
        return callback_evaluate(
            self.problem, pstate, cand_flat, self.num_objectives
        )

    def _migrate(self, astate: Any, cand: Any, fitness: jax.Array) -> Any:
        """Ring migration of each island's current top-k candidates.

        Elites: scalar-fitness ``argsort`` for single-objective; for
        multi-objective, non-dominated rank with crowding-distance
        tie-break per island (the NSGA-II elite criterion)."""
        k = self.migrate_k
        if k > fitness.shape[1]:
            raise ValueError(
                f"migrate_k={k} exceeds the per-island candidate batch "
                f"({fitness.shape[1]})"
            )
        if self.num_objectives > 1:
            from ..operators.selection.non_dominate import (
                crowding_distance,
                non_dominated_sort,
            )

            def island_elites(fit):  # (B, m) -> (k,) indices
                rank = non_dominated_sort(fit)
                crowd = crowding_distance(fit)
                return jnp.lexsort((-crowd, rank))[:k]

            idx = jax.vmap(island_elites)(fitness)  # (islands, k)
            elites = jax.tree.map(
                lambda c: jax.vmap(lambda row, i: row[i])(c, idx), cand
            )
            elite_fit = jax.vmap(lambda f, i: f[i])(fitness, idx)
            recv = jax.tree.map(lambda e: jnp.roll(e, 1, axis=0), elites)
            recv_fit = jnp.roll(elite_fit, 1, axis=0)
            return jax.vmap(self.algorithm.migrate)(astate, recv, recv_fit)
        from ..kernels.topk import default_use_kernel, partial_topk

        use_kernel = (
            default_use_kernel()
            if self.use_topk_kernel is None
            else self.use_topk_kernel
        )
        if use_kernel:
            # best-k per island through the blockwise partial-selection
            # kernel — same indices as the stable argsort (ascending,
            # ties by lowest index), vmapped over the island axis
            idx = jax.vmap(
                lambda f: partial_topk(
                    f, k, use_kernel=True, interpret=self.topk_interpret
                )[1]
            )(fitness)
        else:
            idx = jnp.argsort(fitness, axis=1)[:, :k]  # best-k per island
        elites = jax.tree.map(
            lambda c: jax.vmap(lambda row, i: row[i])(c, idx), cand
        )
        elite_fit = jnp.take_along_axis(fitness, idx, axis=1)
        # island i receives from island i-1; on an island-sharded mesh this
        # roll is a cross-device collective permute over ICI
        recv = jax.tree.map(lambda e: jnp.roll(e, 1, axis=0), elites)
        recv_fit = jnp.roll(elite_fit, 1, axis=0)
        return jax.vmap(self.algorithm.migrate)(astate, recv, recv_fit)

    def _step_impl(self, state: IslandWorkflowState) -> IslandWorkflowState:
        # storage -> compute at step entry (see StdWorkflow._step_impl)
        state = apply_compute(state, self.dtype_policy)
        mstates = list(state.monitors)
        run_hooks(self.monitors, self._hook_table, "pre_step", mstates)
        run_hooks(self.monitors, self._hook_table, "pre_ask", mstates)

        use_init = state.first_step and (
            self.algorithm.has_init_ask or self.algorithm.has_init_tell
        )
        ask = self.algorithm.init_ask if use_init else self.algorithm.ask
        pop, astate = jax.vmap(ask)(state.algo)  # (islands, B, ...)

        batch = jax.tree.leaves(pop)[0].shape[1]
        cand_flat = jax.tree.map(
            lambda x: x.reshape((self.n_islands * batch,) + x.shape[2:]), pop
        )
        run_hooks(self.monitors, self._hook_table, "post_ask", mstates, cand_flat)
        for t in self.pop_transforms:
            cand_flat = t(cand_flat)
        cand_flat = shard_pop(cand_flat, self.mesh)

        run_hooks(self.monitors, self._hook_table, "pre_eval", mstates, cand_flat)
        raw_fitness, pstate = self._evaluate(state.prob, cand_flat)
        # monitors see the flattened (islands * B) batch in the user's
        # fitness convention, exactly like StdWorkflow
        run_hooks(
            self.monitors, self._hook_table, "post_eval", mstates, cand_flat, raw_fitness
        )
        # internal minimization convention, shared by tell and migration
        # (the constructor rejects fit_transforms: shaped fitness is
        # population-relative and would poison the migrants' stored values)
        if self.num_objectives > 1:
            fitness = (raw_fitness * self.opt_direction).reshape(
                self.n_islands, batch, self.num_objectives
            )
        else:
            fitness = (raw_fitness * self.opt_direction[0]).reshape(
                self.n_islands, batch
            )

        run_hooks(
            self.monitors, self._hook_table, "pre_tell", mstates,
            fitness.reshape((self.n_islands * batch,) + fitness.shape[2:]),
        )
        tell = self.algorithm.init_tell if use_init else self.algorithm.tell
        astate = jax.vmap(tell)(astate, fitness)
        run_hooks(self.monitors, self._hook_table, "post_tell", mstates)

        gen = state.generation + 1
        astate = jax.lax.cond(
            gen % self.migrate_every == 0,
            lambda a: self._migrate(a, pop, fitness),
            lambda a: a,
            astate,
        )
        # downcast to storage width BEFORE the shard constraint so the
        # loop carry streams at half width on every device
        astate = self._constrain(apply_storage(astate, self.dtype_policy))
        new_state = state.replace(
            generation=gen,
            algo=astate,
            prob=pstate,
            monitors=tuple(mstates),
            first_step=False,
        )
        return finish_step(self.monitors, self._hook_table, new_state)
