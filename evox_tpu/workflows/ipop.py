"""Host-boundary IPOP: increasing-population restarts between dispatches.

:class:`~evox_tpu.core.guardrail.GuardedAlgorithm` detects degeneracy and
restarts ON DEVICE — but with the SAME population size, because XLA shapes
are static. The other half of the classic IPOP recipe (Auger & Hansen
2005: each restart doubles λ, buying global exploration with the budget
the failed basin wasted) requires new shapes, i.e. a new compiled
program. This module implements that half at the host boundary:
``StdWorkflow.run(restarts=policy)`` and ``run_host_pipelined(...,
restarts=policy)`` chunk the run at ``policy.check_every`` generations,
read the guarded wrapper's on-device counters between dispatches, and on
trigger rebuild the workflow around ``policy.algorithm_factory(pop *
growth)`` — one recompile per doubling, amortized over the whole restart
segment. Best-so-far (point and fitness) and the cumulative restart
counter carry across the boundary; the fresh state re-centers on the
best point (:func:`~evox_tpu.core.guardrail.recenter_state`).

Checkpointing: each segment runs under the PR-2
:class:`~evox_tpu.workflows.checkpoint.WorkflowCheckpointer` as usual,
and the state is snapshotted immediately after every doubling. Resume
correctness across a doubling relies on ``GuardedState.pop_size`` — a
static (pickled) field recording the wrapped algorithm's λ — so
:func:`resolve_ipop_resume` can rebuild the matching compiled program
BEFORE restoring the snapshot; a crash before the post-doubling snapshot
lands simply re-runs the segment from the previous snapshot and
re-triggers the same (deterministic) doubling.

Monitor caveat: monitor states ride across a doubling unchanged.
TelemetryMonitor and EvalMonitor's top-k/Pareto buffers are batch-width
independent and just keep accumulating; ``EvalMonitor(history_capacity=
K)``'s ring is sized by the FIRST generation's batch and raises when the
batch grows — use TelemetryMonitor rings with IPOP runs (GUIDE.md §6).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional, Tuple

import jax

from ..core.guardrail import GuardedState, IPOPRestarts, recenter_state
from .checkpoint import WorkflowCheckpointer, _as_checkpointer

__all__ = ["grow_guarded", "ipop_run", "resolve_ipop_resume"]


def grow_guarded(fresh: GuardedState, old: GuardedState) -> GuardedState:
    """The increasing-population surgery shared by the host-boundary
    doubling (:func:`ipop_run`) and the elastic serving autoscaler
    (``workflows/elastic.py``): take a FRESH guarded state at the grown
    λ, re-center its inner algorithm on the old best-so-far point, and
    carry best/restart bookkeeping across the boundary — the trigger
    that caused the growth is consumed (``checked_restarts`` catches up
    to ``restarts``), so the same signal never double-fires."""
    return fresh.replace(
        inner=recenter_state(fresh.inner, old.best_x),
        best_x=old.best_x,
        best_fitness=old.best_fitness,
        restarts=old.restarts,  # cumulative across the boundary
        checked_restarts=old.restarts,  # this trigger is consumed
    )


def _require_guarded(astate: Any) -> None:
    if not isinstance(astate, GuardedState):
        raise TypeError(
            "restarts=IPOPRestarts(...) needs the on-device detector: wrap "
            "the algorithm in GuardedAlgorithm (core/guardrail.py) — the "
            f"workflow state carries {type(astate).__name__} instead"
        )


def resolve_ipop_resume(
    wf: Any, policy: IPOPRestarts, state: Any, n_steps: int, resume_from: Any
) -> Tuple[Any, Any, int, WorkflowCheckpointer]:
    """Restore the newest intact snapshot and rebuild the workflow at the
    snapshot's (possibly doubled) population size. Returns ``(wf, state,
    remaining_steps, checkpointer)``."""
    ckpt = _as_checkpointer(resume_from)
    loaded = ckpt.latest()
    if loaded is not None:
        _require_guarded(loaded.algo)
        snap_pop = int(loaded.algo.pop_size)
        if snap_pop and snap_pop != int(wf.algorithm.pop_size):
            wf = wf.clone_with_algorithm(policy.make_algorithm(snap_pop))
        state = loaded
    return wf, state, max(n_steps - int(state.generation), 0), ckpt


def _doublings_used(policy: IPOPRestarts, base_pop: int, cur_pop: int) -> int:
    if cur_pop <= base_pop:
        return 0
    return round(math.log(cur_pop / base_pop) / math.log(policy.growth))


def ipop_run(
    wf: Any,
    state: Any,
    n_steps: int,
    policy: IPOPRestarts,
    segment: Callable[[Any, Any, int, Optional[WorkflowCheckpointer]], Any],
    checkpointer: Optional[WorkflowCheckpointer] = None,
    resume_from: Any = None,
) -> Any:
    """Drive ``segment`` (a fused or pipelined chunk runner) under the
    IPOP policy. ``segment(wf, state, chunk, checkpointer) -> state`` runs
    ``chunk`` generations of ``wf`` — everything between host checks stays
    whatever dispatch shape the caller already uses."""
    base_pop = int(wf.algorithm.pop_size)
    # eager schedule validation: every pop size the doubling schedule can
    # reach must be CONSTRUCTIBLE now — a dense-track EighScaleError (or a
    # sharded-track divisibility error) should abort at entry, not hours
    # in at a mid-run host boundary after the compute is already spent
    # (constructors are pure and cheap; the compiled programs are not
    # built here)
    for used in range(1, policy.max_restarts + 1):
        policy.make_algorithm(base_pop * policy.growth**used)
    # escalation events land on the CALLER's workflow object (and every
    # clone), so run_report(workflow=wf, ...) surfaces the doubling/handoff
    # history in its `guardrail.ipop` section even though clones replace
    # the driving workflow at each boundary (instrument.py picks this up
    # duck-typed, like workflow._run_supervisor)
    root_wf = wf
    events = list(getattr(wf, "_ipop_events", []))
    root_wf._ipop_events = events
    if resume_from is not None:
        wf, state, n_steps, resumed_ckpt = resolve_ipop_resume(
            wf, policy, state, n_steps, resume_from
        )
        if checkpointer is None:
            checkpointer = resumed_ckpt
        # pre-crash doublings happened in another process: their event
        # records are gone, but the snapshot's static pop_size re-derives
        # how far the schedule got — seed the history with ONE summary
        # entry so the report still explains the current algorithm/track
        snap_pop = int(getattr(state.algo, "pop_size", 0) or base_pop)
        used = _doublings_used(policy, base_pop, snap_pop)
        if used > 0 and not events:
            events.append(
                {
                    "resumed": True,  # generation stamps not recoverable
                    "generation": int(state.generation),
                    "pop_size": snap_pop,
                    "doublings": used,
                    "handoff": bool(policy.uses_handoff(snap_pop)),
                    "algorithm": type(wf.algorithm.algorithm).__name__,
                }
            )
        wf._ipop_events = events
    _require_guarded(state.algo)

    # Determinism contract (asserted in tests/test_numeric_chaos.py): a
    # crashed/ended run resumed to the same total produces the straight
    # run's state, INCLUDING the doubling schedule. Three pieces make the
    # escalation decision a pure function of the (checkpointed) state:
    # - chunks align to the GLOBAL check_every grid (like
    #   checkpointed_run's cadence), so boundary generations never shift;
    # - the trigger compares `restarts` against the persisted
    #   `checked_restarts` baseline instead of host memory;
    # - a resume landing exactly ON a boundary re-evaluates that
    #   boundary's rule before dispatching (covers both a crash after the
    #   segment's final snapshot and a completed run extended later).
    remaining = n_steps
    while remaining > 0:
        if int(state.generation) % policy.check_every == 0:
            wf, state = _maybe_double(wf, state, policy, base_pop, checkpointer)
        gen = int(state.generation)
        to_boundary = policy.check_every - gen % policy.check_every
        chunk = min(remaining, to_boundary)
        state = segment(wf, state, chunk, checkpointer)
        remaining -= chunk
    return state


def _maybe_double(
    wf: Any,
    state: Any,
    policy: IPOPRestarts,
    base_pop: int,
    checkpointer: Optional[WorkflowCheckpointer],
) -> Tuple[Any, Any]:
    """Evaluate the boundary escalation rule; on trigger rebuild the
    workflow at the grown population, else just commit the baseline."""
    algo_state = state.algo
    used = _doublings_used(policy, base_pop, int(algo_state.pop_size) or base_pop)
    triggered = int(algo_state.restarts) > int(algo_state.checked_restarts)
    if policy.stagnation_limit is not None:
        triggered = triggered or (
            int(algo_state.stagnation) >= policy.stagnation_limit
        )
    if not triggered or used >= policy.max_restarts:
        if int(algo_state.restarts) != int(algo_state.checked_restarts):
            state = state.replace(
                algo=algo_state.replace(checked_restarts=algo_state.restarts)
            )
        return wf, state

    # -------------------------------------------------------- double λ
    used += 1
    new_pop = base_pop * policy.growth**used
    # make_algorithm routes through the low-memory handoff_factory at/past
    # handoff_pop (core/guardrail.py IPOPRestarts) — doubling escapes the
    # dense track's eigh/memory wall instead of marching into it
    events = getattr(wf, "_ipop_events", None)  # shared with the root wf
    algo2 = policy.make_algorithm(new_pop)
    wf = wf.clone_with_algorithm(algo2)
    if events is not None:
        events.append(
            {
                "generation": int(state.generation),
                "pop_size": int(new_pop),
                "doublings": int(used),
                "handoff": bool(policy.uses_handoff(new_pop)),
                "algorithm": type(algo2.algorithm).__name__,
            }
        )
        wf._ipop_events = events
    # fresh state from the wrapper's restart stream (folded per doubling:
    # deterministic, so a resumed run re-derives the identical successor)
    fresh = grow_guarded(
        algo2.init(jax.random.fold_in(algo_state.key, used)), algo_state
    )
    state = state.replace(algo=fresh, first_step=True)
    if checkpointer is not None:
        # land the doubled state durably before running on it: a resume
        # then rebuilds from GuardedState.pop_size directly (the save
        # overwrites the segment's same-generation pre-doubling snapshot)
        checkpointer.save(state)
    return wf, state
