"""Serving-plane flight recorder: durable metrics stream + crash ring.

The registry (``core/metrics.py``) holds the serving stack's live
counters/gauges/histograms; this module makes them OPERABLE:

- :class:`MetricsStream` — an append-only, fsynced, hash-chained
  JSON-lines file (the exact :class:`~evox_tpu.workflows.journal.
  ChainedLog` discipline from PR 11: a torn TAIL — the one artifact a
  crash mid-append can leave — is truncated with a warning on adoption,
  while a tampered MIDDLE record raises
  :class:`~evox_tpu.workflows.journal.JournalIntegrityError` loudly).
  ``tools/evoxtail.py`` tails it live; ``tools/check_report.py``
  validates it (known kinds, monotonic counters, SLO coherence).
- :class:`FlightRecorder` — the producer facade the serving stack
  writes through. It owns one registry, a bounded in-memory ring of the
  most recent events+samples (the *flight recorder* proper: dumped into
  every post-mortem — ``RunSupervisor`` aborts, ``PodSupervisor``
  failures, ``RunQueue`` evict/freeze close-outs), and the optional
  stream. ``directory=None`` keeps everything in memory (zero files);
  passing no recorder at all (``metrics=None`` throughout the stack) is
  an exact no-op — the PR-4 ``analyze=False`` discipline, asserted
  bit-identical by tests/test_serving_chaos.py.
- :func:`merge_pod_streams` — process 0's pod aggregation: per-process
  streams are clock-aligned at their first common ``barrier`` record
  (every process writes one at each pod rendezvous; the barrier IS the
  common instant, so no cross-host clock is compared — the PR-14
  census philosophy applied to time) and merged into one Perfetto/
  Chrome trace with named per-process tracks plus one aggregated
  stream file.

Record kinds (the stream's closed whitelist, :data:`STREAM_KINDS`):

- ``meta`` — once, first: process identity, pid base for the trace
  mapping, wall-clock start.
- ``sample`` — a full registry snapshot + the SLO ledger (+ optionally
  the queue's own counters, the validator's coherence referee), taken
  at dispatch boundaries (chunk barriers), never inside traced code.
- ``event`` — a discrete transition (preemption, eviction, pod
  failure…); mirrored into the ring.
- ``barrier`` — a rendezvous anchor: monotonic-relative + wall time at
  a named barrier, the pod merge's alignment key.

The SLO ledger is the ``slo.*`` counter namespace rendered as a
first-class view (:meth:`FlightRecorder.slo_ledger`): tenant
generations served (and their rate), EDF admissions, preemptions, and
SLA deadline hits/misses — exactly the quantities ROADMAP item 4's
"sustained tenant-gens/sec SLO bench" needs.

Axon rule: everything here is host-side file I/O between dispatches —
no callbacks (pinned by tests/test_no_host_callbacks.py).
"""

from __future__ import annotations

import collections
import json
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

from ..core.metrics import MetricsRegistry
from .journal import ChainedLog, jsonable

__all__ = [
    "FlightRecorder",
    "MetricsStream",
    "STREAM_KINDS",
    "STREAM_SCHEMA",
    "merge_pod_streams",
    "read_stream",
]

STREAM_SCHEMA = "evox_tpu.metrics_stream/v1"

#: closed record-kind whitelist (the journal EVENT_KINDS discipline)
STREAM_KINDS = ("meta", "sample", "event", "barrier")

#: trace pids are ``pid_base + local track``; one stride per process —
#: the deterministic pid←→jax-process-index mapping (PR 16 satellite,
#: shared with core/instrument.py's write_chrome_trace)
PID_STRIDE = 100

_US = 1e6


class MetricsStream(ChainedLog):
    """The metrics stream file: :class:`ChainedLog` under
    ``metrics.jsonl`` with the :data:`STREAM_KINDS` whitelist. All
    durability semantics (per-record fsync, torn-tail repair on
    adoption, loud tamper detection) are inherited — re-asserted for
    this stream by tests/test_serving_chaos.py's SIGKILL-mid-append
    law."""

    FILENAME = "metrics.jsonl"
    SCHEMA = STREAM_SCHEMA
    KINDS = STREAM_KINDS
    # retention may drop old segments but never the newest rendezvous
    # anchor — pod merge alignment needs at least one intact barrier
    PIN_KINDS = ("barrier",)

    def report(self) -> dict:
        """The ``metrics.stream`` subsection of ``run_report()``."""
        return {
            "path": str(self.path),
            "records": len(self._records),
            "events": self.counts(),
            "torn_tail_dropped": self.torn_tail_dropped,
        }


class FlightRecorder:
    """The serving stack's metrics producer facade.

    Args:
        directory: stream directory. ``None`` = in-memory only — the
            registry and ring still work (post-mortem tails, reports),
            but NOTHING is written to disk.
        ring_capacity: bounded in-memory ring of the newest
            events/samples/barriers (``collections.deque(maxlen=...)``)
            — the black-box tape dumped into post-mortems.
        process_id / process_count: pod identity stamped into the
            ``meta`` record and the pid mapping; default auto-detects
            via :func:`~evox_tpu.core.distributed._dist_process_info`
            so a plain single-process recorder needs no arguments.
        max_segment_bytes / retain_segments: forwarded to
            :class:`MetricsStream` — size-bounded segment rotation of
            ``metrics.jsonl`` with the hash chain carried across the
            boundary, and opt-in retention that never drops the newest
            intact ``barrier`` (see :class:`~evox_tpu.workflows.journal.
            ChainedLog`). A long-lived serving process SHOULD set these;
            the defaults keep one unbounded file (the PR-16 behavior).

    Producers call :meth:`count` / :meth:`set` / :meth:`observe`
    (registry mutations — pure host memory, safe at any frequency),
    :meth:`event` (ring + one durable record), :meth:`barrier` (ring +
    one durable alignment record), and :meth:`sample` (ring + one
    durable full-registry snapshot — the per-chunk cadence). Mutators
    never raise into the serving path for I/O reasons: the stream's own
    ``append`` raising (disk full) propagates, matching the journal's
    WAL contract — losing metrics silently would be worse.
    """

    def __init__(
        self,
        directory: Optional[str] = None,
        ring_capacity: int = 256,
        process_id: Optional[int] = None,
        process_count: Optional[int] = None,
        max_segment_bytes: Optional[int] = None,
        retain_segments: Optional[int] = None,
    ):
        if ring_capacity < 1:
            raise ValueError(f"ring_capacity must be >= 1, got {ring_capacity}")
        if process_id is None or process_count is None:
            try:
                from ..core.distributed import _dist_process_info

                pid, pcount = _dist_process_info()
            except Exception:
                pid, pcount = 0, 1
            process_id = pid if process_id is None else process_id
            process_count = pcount if process_count is None else process_count
        self.process_id = int(process_id)
        self.process_count = int(process_count)
        self.registry = MetricsRegistry()
        self._ring: collections.deque = collections.deque(maxlen=ring_capacity)
        self._t0 = time.perf_counter()
        self._started_wall = time.time()
        self.stream: Optional[MetricsStream] = None
        if directory is not None:
            self.stream = MetricsStream(
                str(directory),
                max_segment_bytes=max_segment_bytes,
                retain_segments=retain_segments,
            )
            if not self.stream.records(kind="meta"):
                self.stream.append(
                    "meta",
                    process_id=self.process_id,
                    process_count=self.process_count,
                    pid_base=self.process_id * PID_STRIDE,
                    started_wall=round(self._started_wall, 6),
                )

    # -------------------------------------------------------------- registry
    def count(self, name: str, n: float = 1.0) -> None:
        self.registry.count(name, n)

    def set(self, name: str, v: float) -> None:
        self.registry.set(name, v)

    def observe(self, name: str, v: float, **kw: Any) -> None:
        self.registry.observe(name, v, **kw)

    def _tm(self) -> float:
        return round(time.perf_counter() - self._t0, 6)

    # --------------------------------------------------------------- records
    def _record(self, kind: str, payload: Dict[str, Any]) -> Dict[str, Any]:
        rec = {"kind": kind, "tm": self._tm(), **jsonable(payload)}
        self._ring.append(rec)
        if self.stream is not None:
            self.stream.append(kind, **{k: v for k, v in rec.items() if k != "kind"})
        return rec

    def event(self, name: str, **fields: Any) -> None:
        """One discrete serving transition (preemption, eviction, pod
        failure…): ring + durable ``event`` record. ``name`` is dotted
        like metric names (``queue.preempt``, ``pod.failure``)."""
        self._record("event", {"name": name, **fields})

    def barrier(self, name: str, **fields: Any) -> None:
        """A rendezvous anchor: the merge key for pod clock alignment.
        Every pod member writes the SAME ``name`` at the SAME logical
        barrier, each stamping its own clocks — alignment then needs no
        cross-host clock agreement."""
        self._record(
            "barrier",
            {"name": str(name), "t_wall": round(time.time(), 6), **fields},
        )

    def sample(self, **context: Any) -> Dict[str, Any]:
        """A full registry snapshot + SLO ledger, durably appended —
        the per-dispatch-boundary cadence (RunQueue calls this once per
        chunk). ``context`` rides along verbatim (e.g. ``queue=`` the
        queue's own counters — the validator's coherence referee)."""
        snap = self.registry.snapshot()
        return self._record("sample", {**snap, "slo": self.slo_ledger(), **context})

    # -------------------------------------------------------------- recovery
    def restore(self, sample: Dict[str, Any]) -> None:
        """Re-seed the registry from one stream ``sample`` record — the
        crash-recovery path: ``RunQueue.recover`` restores the fleet to
        a chunk barrier, and this restores the metrics plane to the SAME
        barrier, so the replayed stretch re-counts exactly what the
        crash rolled back and the post-crash ledger converges to the
        uncrashed run's. (Rates restart with the new process's clock —
        wall time is the one thing a crash genuinely spends.)"""
        reg = self.registry
        for name, v in (sample.get("counters") or {}).items():
            reg.counter(name).value = float(v)
        for name, v in (sample.get("gauges") or {}).items():
            reg.set(name, float(v))
        for name, h in (sample.get("histograms") or {}).items():
            hist = reg.histogram(name, h["le"])
            hist.counts = [int(c) for c in h["counts"]]
            hist.count = int(h["count"])
            hist.sum = float(h["sum"])

    def restore_at(self, generation: Optional[int] = None) -> bool:
        """Restore from the stream's newest sample whose ``generation``
        context matches the recovered barrier. Returns False — registry
        left at zero, the correct seed for a from-scratch replay — when
        no such sample exists (including ``generation=None``).
        Appends a ``queue.recover`` event either way: the stream
        validator resets its counter-monotonicity baseline there
        (replayed counts legally rewind past samples the crash rolled
        back)."""
        samples = (
            self.stream.records(kind="sample")
            if self.stream is not None
            else []
        )
        # generation=None (no barrier survived — from-scratch replay)
        # matches nothing: the zeroed registry IS the right seed there
        samples = [r for r in samples if r.get("generation") == generation]
        if samples:
            self.restore(samples[-1])
        self.event(
            "queue.recover",
            generation=generation,
            restored=bool(samples),
        )
        return bool(samples)

    # ------------------------------------------------------------------ views
    def tail(self, n: int = 50) -> List[dict]:
        """The newest ``n`` ring records — the black-box tape every
        post-mortem carries (``RunSupervisor._abort``,
        ``PodSupervisor._fail``, ``RunQueue`` evict/freeze)."""
        ring = list(self._ring)
        return jsonable(ring[-n:])

    def slo_ledger(self) -> dict:
        """The SLO ledger: the ``slo.*`` counter namespace as one view,
        plus the derived tenant-gens/sec rate over the recorder's
        lifetime. Sums are coherent with the RunQueue's own counters by
        construction (incremented at the same call sites); the stream
        validator re-checks that coherence on every sample."""
        elapsed = max(self._tm(), 1e-9)
        reg = self.registry
        gens = reg.value("slo.tenant_gens")
        return {
            "tenant_gens": int(gens),
            "elapsed_s": round(elapsed, 6),
            "tenant_gens_per_s": round(gens / elapsed, 6),
            "admissions": int(reg.value("slo.admissions")),
            "preemptions": int(reg.value("slo.preemptions")),
            "deadline_hits": int(reg.value("slo.deadline_hits")),
            "deadline_misses": int(reg.value("slo.deadline_misses")),
        }

    def record_search(self, search: Dict[str, Any]) -> None:
        """Publish a LineageMonitor search report (monitors/lineage.py,
        the run_report schema-v13 ``search`` section) into the
        ``search.*`` gauge namespace — called at a dispatch boundary
        with ``monitor.search_report(mstate)``, the same host-side
        cadence as :meth:`sample`. Gauges carry absolute values (the
        monitor's on-device counters are the source of truth; re-publish
        after a crash restore and the plane converges like everything
        else): ``search.generations`` / ``search.epoch`` /
        ``search.restarts``, the newest window's ``search.best_fitness``
        / ``search.delta`` (and ``search.front_size`` /
        ``search.churn`` for MO runs), and the per-operator credit table
        as ``search.ledger.<op>.attempts|successes|improvement``."""
        if not isinstance(search, dict) or not search.get("enabled"):
            return
        for key in ("generations", "epoch", "restarts", "width"):
            if isinstance(search.get(key), (int, float)):
                self.set(f"search.{key}", float(search[key]))
        for op, row in (search.get("ledger") or {}).items():
            for key in ("attempts", "successes", "improvement"):
                if isinstance(row.get(key), (int, float)):
                    self.set(f"search.ledger.{op}.{key}", float(row[key]))
        traj = search.get("trajectory") or {}
        for key in ("best_fitness", "delta", "front_size", "churn"):
            col = traj.get(key)
            if isinstance(col, list) and col:
                self.set(f"search.{key}", float(col[-1]))

    def record_integrity(self, integrity: Dict[str, Any]) -> None:
        """Publish a compute-integrity report (core/attest.py, the
        run_report schema-v14 ``integrity`` section) into the
        ``integrity.*`` gauge namespace — same host-side cadence and
        absolute-value discipline as :meth:`record_search`. Gauges:
        ``integrity.attestations`` (ring count) and the newest ring
        entry's ``integrity.last_generation``; the verify rung's
        ``integrity.redispatches`` / ``integrity.verified_chunks``
        / ``integrity.mismatches`` / ``integrity.healed`` /
        ``integrity.aborted``; bisection forensics publish
        ``integrity.first_divergent_generation`` when one was named.
        The verdict rides as an ``integrity.verdict`` event whenever it
        is not ``clean`` (events are the anomaly lane; a clean run adds
        zero event records)."""
        if not isinstance(integrity, dict) or not integrity.get("enabled"):
            return
        if isinstance(integrity.get("attestations"), (int, float)):
            self.set(
                "integrity.attestations", float(integrity["attestations"])
            )
        ring = integrity.get("ring") or []
        if ring and isinstance(ring[-1].get("generation"), (int, float)):
            self.set(
                "integrity.last_generation", float(ring[-1]["generation"])
            )
        verify = integrity.get("verify") or {}
        for key in (
            "redispatches",
            "verified_chunks",
            "mismatches",
            "healed",
            "aborted",
        ):
            if isinstance(verify.get(key), (int, float)):
                self.set(f"integrity.{key}", float(verify[key]))
        bisection = integrity.get("bisection") or {}
        fdg = bisection.get("first_divergent_generation")
        if isinstance(fdg, (int, float)):
            self.set("integrity.first_divergent_generation", float(fdg))
        verdict = integrity.get("verdict")
        if verdict and verdict != "clean":
            self.event("integrity.verdict", verdict=verdict)

    def report(self) -> dict:
        """The ``metrics`` section of ``run_report()`` (schema v11,
        validated by tools/check_report.py)."""
        out: dict = {
            "enabled": True,
            "process_id": self.process_id,
            "process_count": self.process_count,
            "ring_len": len(self._ring),
            "ring_capacity": self._ring.maxlen,
            **self.registry.snapshot(),
        }
        if self.stream is not None:
            out["stream"] = self.stream.report()
        return out

    def to_openmetrics(self) -> str:
        return self.registry.to_openmetrics()


# --------------------------------------------------------------- pod merge


def read_stream(path: Any) -> List[dict]:
    """Read-only stream load: parse ``metrics.jsonl`` records without
    adopting (no truncation — ``evoxtail`` and the merge must never
    write to a stream a live driver owns). A torn tail line is skipped;
    chain verification is the validator's/adoption's job."""
    p = Path(path)
    if p.is_dir():
        p = p / MetricsStream.FILENAME
    records: List[dict] = []
    with open(p) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail — the crash artifact, reader-safe
            if isinstance(rec, dict):
                records.append(rec)
    return records


def _align_offsets(streams: Sequence[List[dict]]) -> List[float]:
    """Per-process time offsets (seconds to SUBTRACT from each stream's
    ``tm``) that put the first barrier name common to ALL processes at
    the same merged instant. With no common barrier (or one process),
    offsets are zero — tracks land on their own clocks, still viewable."""
    barrier_tm: List[Dict[str, float]] = []
    for recs in streams:
        seen: Dict[str, float] = {}
        for r in recs:
            if r.get("kind") == "barrier" and r.get("name") not in seen:
                seen[str(r.get("name"))] = float(r.get("tm", 0.0))
        barrier_tm.append(seen)
    common = set(barrier_tm[0]) if barrier_tm else set()
    for seen in barrier_tm[1:]:
        common &= set(seen)
    if not common or len(streams) < 2:
        return [0.0] * len(streams)
    # earliest common barrier in process 0's clock — deterministic
    anchor = min(common, key=lambda name: barrier_tm[0][name])
    ref = barrier_tm[0][anchor]
    return [seen[anchor] - ref for seen in barrier_tm]


def merge_pod_streams(
    stream_dirs: Sequence[Any],
    trace_path: Optional[str] = None,
    merged_stream_path: Optional[str] = None,
) -> dict:
    """Process 0's pod aggregation: merge per-process metrics streams
    into ONE Perfetto/Chrome trace (named per-process tracks — counter
    tracks from samples, instant markers from events/barriers) and one
    aggregated stream file, clock-aligned at the first common barrier
    record. Returns ``{"trace": <dict>, "records": <aggregated list>,
    "offsets_s": [...], "processes": n}``; writes the files when paths
    are given. Read-only over the inputs."""
    streams = [read_stream(d) for d in stream_dirs]
    if not streams:
        raise ValueError("merge_pod_streams: no streams given")
    offsets = _align_offsets(streams)
    events: List[dict] = []
    merged: List[dict] = []
    for p, (recs, off) in enumerate(zip(streams, offsets)):
        meta = next((r for r in recs if r.get("kind") == "meta"), {})
        proc = int(meta.get("process_id", p))
        pid_base = int(meta.get("pid_base", proc * PID_STRIDE))
        events.append(_meta(pid_base, f"process {proc}: metrics"))
        events.append(_meta(pid_base, "events", tid=1))
        counter_names: List[str] = []
        for r in recs:
            kind = r.get("kind")
            ts = max(float(r.get("tm", 0.0)) - off, 0.0) * _US
            if kind == "event":
                events.append(
                    {
                        "ph": "i",
                        "name": str(r.get("name")),
                        "cat": "metrics",
                        "pid": pid_base,
                        "tid": 1,
                        "ts": round(ts, 3),
                        "s": "t",
                    }
                )
            elif kind == "barrier":
                events.append(
                    {
                        "ph": "i",
                        "name": f"barrier:{r.get('name')}",
                        "cat": "metrics",
                        "pid": pid_base,
                        "tid": 1,
                        "ts": round(ts, 3),
                        "s": "p",
                    }
                )
            elif kind == "sample":
                flat = dict(r.get("counters") or {})
                flat.update(r.get("gauges") or {})
                flat["slo.tenant_gens_per_s"] = (r.get("slo") or {}).get(
                    "tenant_gens_per_s", 0
                )
                for name, v in flat.items():
                    if not isinstance(v, (int, float)) or isinstance(v, bool):
                        continue
                    if name not in counter_names:
                        counter_names.append(name)
                    events.append(
                        {
                            "ph": "C",
                            "name": name,
                            "pid": pid_base,
                            "ts": round(ts, 3),
                            "args": {name.rsplit(".", 1)[-1]: v},
                        }
                    )
            merged.append({**r, "process_id": proc, "tm_aligned": round(ts / _US, 6)})
    merged.sort(key=lambda r: (r.get("tm_aligned", 0.0), r.get("process_id", 0)))
    trace = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "exporter": "evox_tpu.workflows.flightrec.merge_pod_streams",
            "time_origin": "first common pod barrier",
            "processes": len(streams),
            "offsets_s": [round(o, 6) for o in offsets],
        },
    }
    if trace_path is not None:
        with open(trace_path, "w") as f:
            json.dump(trace, f, allow_nan=False)
    if merged_stream_path is not None:
        with open(merged_stream_path, "w") as f:
            for rec in merged:
                f.write(json.dumps(jsonable(rec), allow_nan=False) + "\n")
    return {
        "trace": trace,
        "records": merged,
        "offsets_s": [round(o, 6) for o in offsets],
        "processes": len(streams),
    }


def _meta(pid: int, name: str, tid: Optional[int] = None) -> dict:
    e: dict = {
        "ph": "M",
        "pid": pid,
        "name": "process_name" if tid is None else "thread_name",
        "args": {"name": name},
    }
    if tid is not None:
        e["tid"] = tid
    return e
