"""evox_tpu — a TPU-native evolutionary-computation framework.

Same capability surface as EvoX (ask–evaluate–tell algorithms, benchmark
problems, neuroevolution, distributed workflows, monitors and metrics),
re-architected for TPU: one jitted step over a ``jax.sharding.Mesh``,
population sharded across chips, collectives over ICI, Pallas kernels for
hot operators.
"""

__version__ = "0.1.0"

from .core import (
    Algorithm,
    BF16_STORAGE,
    DtypePolicy,
    GuardedAlgorithm,
    IPOPRestarts,
    Problem,
    Monitor,
    PyTreeNode,
    field,
    static_field,
    pytree_dataclass,
    create_mesh,
    POP_AXIS,
    DispatchRecorder,
    RetraceError,
    CostAnalyzer,
    instrument,
    run_report,
    write_chrome_trace,
    write_report_jsonl,
)
from . import algorithms, core, metrics, monitors, operators, problems, utils, vis_tools, workflows
from .workflows import (
    CheckpointConfigError,
    DispatchDeadlineError,
    IslandWorkflow,
    RunAbortedError,
    RunSupervisor,
    StdWorkflow,
    WorkflowCheckpointer,
    run_host_pipelined,
)

__all__ = [
    "Algorithm",
    "BF16_STORAGE",
    "DtypePolicy",
    "GuardedAlgorithm",
    "IPOPRestarts",
    "Problem",
    "Monitor",
    "PyTreeNode",
    "field",
    "static_field",
    "pytree_dataclass",
    "create_mesh",
    "POP_AXIS",
    "DispatchRecorder",
    "RetraceError",
    "CostAnalyzer",
    "instrument",
    "run_report",
    "write_chrome_trace",
    "write_report_jsonl",
    "StdWorkflow",
    "IslandWorkflow",
    "WorkflowCheckpointer",
    "CheckpointConfigError",
    "RunSupervisor",
    "RunAbortedError",
    "DispatchDeadlineError",
    "run_host_pipelined",
    "algorithms",
    "core",
    "monitors",
    "operators",
    "problems",
    "utils",
    "vis_tools",
    "metrics",
    "workflows",
]
