"""EvoXVisMonitor — stream generations to Apache Arrow IPC files for the
EvoXVis GUI (reference src/evox/monitors/evoxvis_monitor.py:60-224).

Same wire format as the reference so the external EvoXVis tool can read
either: one record batch per ``batch_size`` generations, columns
``generation`` (uint64), ``fitness`` (fixed-width binary of the raw array
bytes), optional ``population``, optional ``duration`` (seconds since the
run began) and one float64 column per metric; array dtype/population-size
recorded as schema metadata. Schema is inferred at the first write so the
binary widths are exact.

Device side, this is a ``post_eval`` hook shipping (cand, fitness) out via
ordered ``io_callback`` — the jitted step never blocks on the file.
"""

from __future__ import annotations

import time
import warnings
from pathlib import Path
from typing import Any, Optional

import jax
import numpy as np
from jax.experimental import io_callback

from ..core.monitor import Monitor
from .common import host0_sharding


class EvoXVisMonitor(Monitor):
    """Args:
        base_filename: output files are ``<base>_<i>.arrow`` in ``out_dir``
            (``i`` = first unused index).
        out_dir: defaults to ``./evox_vis``.
        batch_size: generations per Arrow record batch.
        record_population: also store decision-space arrays.
        record_time: store per-generation wall-clock offsets.
        compression: ``None`` | ``"lz4"`` | ``"zstd"``.
    """
    # convention flag: this monitor streams through host callbacks
    # (io_callback/pure_callback) inside the traced step — consumed by
    # surfaces that cannot host callbacks at all (VectorizedWorkflow
    # fleets: a callback cannot run under vmap on ANY backend)
    uses_host_callbacks = True


    def __init__(
        self,
        base_filename: str = "evox",
        out_dir: Optional[str] = None,
        batch_size: int = 64,
        record_population: bool = False,
        record_time: bool = True,
        compression: Optional[str] = None,
    ):
        import pyarrow as pa

        self.pa = pa
        base = Path(out_dir) if out_dir is not None else Path("evox_vis")
        base.mkdir(parents=True, exist_ok=True)
        i = 0
        while (base / f"{base_filename}_{i}.arrow").exists():
            i += 1
        self.path = base / f"{base_filename}_{i}.arrow"
        self.sink = pa.OSFile(str(self.path), "wb")
        self.batch_size = batch_size
        self.record_population = record_population
        self.record_time_enabled = record_time
        self.compression = compression

        self.schema = None
        self.writer = None
        self.is_closed = False
        self.generation_counter = 0
        self.generations: list = []
        self.fitness: list = []
        self.population: list = []
        self.duration: list = []
        self.fitness_meta = None  # (dtype str, pop_size)
        self.population_dtype = None
        self.start_time = None
        self.ref_time = None

    def hooks(self):
        return ("post_eval",)

    def post_eval(self, mstate: Any, cand: Any, fitness: jax.Array) -> Any:
        if self.record_population:
            pop_arr = jax.tree.leaves(cand)[0]
            io_callback(
                self._record,
                None,
                pop_arr,
                fitness,
                sharding=host0_sharding(),
                ordered=True,
            )
        else:
            io_callback(
                self._record_fit_only,
                None,
                fitness,
                sharding=host0_sharding(),
                ordered=True,
            )
        return mstate

    # ---------------------------------------------------------------- host side
    def _record_fit_only(self, fitness):
        self._append(None, np.asarray(fitness))

    def _record(self, population, fitness):
        self._append(np.asarray(population), np.asarray(fitness))

    def _append(self, population, fitness):
        if self.is_closed:
            return  # the workflow may keep stepping after close(); drop quietly
        if self.record_time_enabled:
            if self.start_time is None:
                self.start_time = time.time()
                self.ref_time = time.monotonic()
            self.duration.append(time.monotonic() - self.ref_time)
        self.generations.append(self.generation_counter)
        self.generation_counter += 1
        self.fitness.append(fitness.tobytes())
        self.fitness_meta = (str(fitness.dtype), fitness.shape[0])
        if population is not None:
            self.population.append(population.tobytes())
            self.population_dtype = str(population.dtype)
        if len(self.fitness) >= self.batch_size:
            self._write_batch()

    def _build_schema(self):
        # variable-length binary, not pa.binary(n): algorithms with
        # init_ask/init_tell (e.g. CSO) evaluate a different candidate count
        # on the first generation, so row byte-lengths legitimately vary
        pa = self.pa
        fields = [
            ("generation", pa.uint64()),
            ("fitness", pa.binary()),
        ]
        metadata = {
            "population_size": str(self.fitness_meta[1]),
            "fitness_dtype": self.fitness_meta[0],
        }
        if self.population:
            fields.append(("population", pa.binary()))
            metadata["population_dtype"] = self.population_dtype
        if self.duration:
            fields.append(("duration", pa.float64()))
            metadata["begin_time"] = str(self.start_time)
        self.schema = pa.schema(fields, metadata=metadata)
        self.writer = pa.ipc.new_file(
            self.sink,
            self.schema,
            options=pa.ipc.IpcWriteOptions(compression=self.compression),
        )

    def _write_batch(self):
        if not self.fitness:
            return
        if self.schema is None:
            self._build_schema()
        n = len(self.fitness)
        cols = [self.generations[:n], self.fitness[:n]]
        if self.population:
            cols.append(self.population[:n])
            self.population = self.population[n:]
        if self.duration:
            cols.append(self.duration[:n])
            self.duration = self.duration[n:]
        self.writer.write_batch(self.pa.record_batch(cols, schema=self.schema))
        self.generations = self.generations[n:]
        self.fitness = self.fitness[n:]

    def flush(self):
        jax.effects_barrier()
        self._write_batch()

    def close(self, flush: bool = True):
        if self.is_closed:
            return
        try:
            if flush:
                self.flush()
        finally:
            # even if the flush raises, finalize the Arrow footer so the
            # file stays readable, and only then mark closed
            self.is_closed = True
            if self.writer is not None:
                self.writer.close()
            self.sink.close()

    def __del__(self):
        try:  # interpreter teardown may have cleared module globals
            if not self.is_closed:
                warnings.warn(
                    "EvoXVisMonitor was garbage-collected without close(); "
                    "trailing generations were not flushed"
                )
                self.close(flush=False)
        except Exception:
            pass
