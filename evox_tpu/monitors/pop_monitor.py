"""PopMonitor — record the algorithm's full population/fitness every
generation (reference src/evox/monitors/pop_monitor.py:54-71).

The recording is an ``io_callback`` out of the jitted step (host-side
history is unbounded, so it cannot live in the on-device monitor state),
pinned to one device like the reference. Use ``fitness_only=True`` to skip
the decision-space arrays when only objective-space trajectories matter.
"""

from __future__ import annotations

import warnings
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.experimental import io_callback

from ..core.monitor import Monitor
from .common import host0_sharding


class PopMonitor(Monitor):
    # convention flag: this monitor streams through host callbacks
    # (io_callback/pure_callback) inside the traced step — consumed by
    # surfaces that cannot host callbacks at all (VectorizedWorkflow
    # fleets: a callback cannot run under vmap on ANY backend)
    uses_host_callbacks = True

    def __init__(
        self,
        population_name: str = "population",
        fitness_name: str = "fitness",
        fitness_only: bool = False,
    ):
        self.population_name = population_name
        self.fitness_name = fitness_name
        self.fitness_only = fitness_only
        self.population_history: list = []
        self.fitness_history: list = []

    def hooks(self):
        return ("post_step",)

    def post_step(self, mstate: Any, wf_state: Any) -> Any:
        fitness = getattr(wf_state.algo, self.fitness_name)
        if self.fitness_only:
            io_callback(
                self._record_fit,
                None,
                fitness,
                sharding=host0_sharding(),
                ordered=True,
            )
        else:
            population = getattr(wf_state.algo, self.population_name)
            io_callback(
                self._record,
                None,
                population,
                fitness,
                sharding=host0_sharding(),
                ordered=True,
            )
        return mstate

    def _record(self, population, fitness):
        self.population_history.append(population)
        self.fitness_history.append(fitness)

    def _record_fit(self, fitness):
        self.fitness_history.append(fitness)

    # --------------------------------------------------------------- getters
    def get_latest_fitness(self):
        self.flush()
        return self.fitness_history[-1]

    def get_latest_population(self):
        self.flush()
        return self.population_history[-1]

    def get_population_history(self):
        self.flush()
        return self.population_history

    def get_fitness_history(self):
        self.flush()
        return self.fitness_history

    def plot(self, problem_pf: Optional[Any] = None, **kwargs):
        """Objective-space animation over generations (vis_tools)."""
        self.flush()
        if not self.fitness_history:
            warnings.warn("no fitness history recorded, returning None")
            return None
        from ..vis_tools import plot

        n_objs = (
            1
            if self.fitness_history[0].ndim == 1
            else self.fitness_history[0].shape[1]
        )
        if n_objs == 1:
            return plot.plot_obj_space_1d(self.fitness_history, **kwargs)
        if n_objs == 2:
            return plot.plot_obj_space_2d(self.fitness_history, problem_pf, **kwargs)
        if n_objs == 3:
            return plot.plot_obj_space_3d(self.fitness_history, problem_pf, **kwargs)
        warnings.warn(f"plotting {n_objs}-objective space is not supported")
        return None
