"""LineageMonitor — on-device search-dynamics rings: per-slot lineage,
operator attribution, and convergence forensics (ISSUE 19).

Answers the questions the other observability planes cannot: *which slot
did the current best descend from, which operator earned each
improvement, and why did this run stall?* Everything is the
EvalMonitor/TelemetryMonitor ring discipline — fixed-shape ``(K, ...)``
buffers written at ``count % K`` (utils/ring.py), zero host callbacks
(pinned by tests/test_no_host_callbacks.py), so it runs unchanged in step
loops, the fused ``run()`` fori_loop, ``run_host_pipelined``, the
8-device mesh, sharded populations, and vmapped VectorizedWorkflow fleets
(per-tenant rings and ancestry).

Per generation it records:

- the **parent-index map** ``(K, width)``: which slot each survivor
  descended from. Algorithms publishing the ``core/attribution.py``
  contract (the DE family) supply it exactly; everything else is tagged
  at the selection boundary (slot identity — see below).
- a per-candidate **operator tag** ``(K, width)`` from the shared
  vocabulary (``OP_NAMES``), plus a cumulative per-operator credit
  ledger: attempts, successes, improvement mass.
- per-slot **age** (generations since last improvement) and
  **improvement counters**.
- the per-generation **best-so-far delta** and best slot/fitness.
- a restart/exploit **epoch counter**: a GuardedAlgorithm's on-device
  ``restarts`` counter is mirrored (the TelemetryMonitor discipline) and
  external drivers (PBT exploit surgery) can call :meth:`bump_epoch`;
  every ring row records its epoch so ``best_ancestry()`` never walks an
  edge across a restart — cross-epoch "descent" would be fiction.
- multi-objective runs (``num_objectives > 1``) additionally get
  **front-size** and **non-dominated-churn** rings: the rank-0 front of
  each generation's batch (operators/selection/non_dominate.py) and the
  masked IGD (metrics/igd.py) between consecutive fronts — churn near 0
  with a full front means the front has genuinely settled.

Attribution sources, in order:

1. ``wf_state.algo`` (unwrapping guardrail ``.inner``) exposing an
   ``attrib`` field — the exact bookkeeping the algorithm's own
   adaptation used (bit-identical contract, core/attribution.py).
2. Selection-boundary fallback for everything else: parent = slot
   identity, operator = ``default_op`` (constructor; e.g. ``"sample"``
   for ES/CMA, ``"velocity"`` for PSO, ``"crossover"`` for MO GAs),
   success = per-slot fitness improvement over the previous generation.
   Honest but coarser: replacement-based algorithms (ES) re-sample every
   slot, so "age" there reads as positional stagnation, not individual
   survival.

``lineage=None`` (simply not attaching the monitor) is an exact no-op on
every other state in the workflow — the PR-16 digest law, asserted by
tests/test_lineage.py.

No reference analog (PARITY row 63); design sources are the per-member
exploit/explore provenance planes of PBT-style systems (PAPERS.md: "Fast
Population-Based RL on a Single Machine", Fiber).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..core.attribution import (
    N_OPS,
    OP_INIT,
    OP_NAMES,
    Attribution,
    find_attribution,
    improvement_mass,
    op_credit,
    success_mask,
)
from ..core.instrument import sanitize_json
from ..core.monitor import Monitor
from ..core.struct import PyTreeNode, field
from ..metrics.igd import masked_igd
from .common import ring_slots, ring_write


class LineageState(PyTreeNode):
    # cumulative scalars (always materialized; int32 counters)
    count: jax.Array = field(sharding=P())  # () generations recorded
    epoch_extra: jax.Array = field(sharding=P())  # () manual bump_epoch count
    restarts_seen: jax.Array = field(sharding=P())  # () guardrail mirror
    best_key: jax.Array = field(sharding=P())  # () best-so-far, internal key
    # per-operator credit ledger, cumulative
    ledger_attempts: jax.Array = field(sharding=P())  # (N_OPS,) i32
    ledger_success: jax.Array = field(sharding=P())  # (N_OPS,) i32
    ledger_improvement: jax.Array = field(sharding=P())  # (N_OPS,) f32
    # width-dependent buffers, materialized by the first post_eval (the
    # EvalMonitor lazy-ring pattern; width = first batch's slot count)
    cur_fit: Optional[jax.Array] = field(sharding=P(), default=None)  # (w,) stash
    prev_fit: Optional[jax.Array] = field(sharding=P(), default=None)  # (w,)
    age: Optional[jax.Array] = field(sharding=P(), default=None)  # (w,) i32
    improvements: Optional[jax.Array] = field(sharding=P(), default=None)  # (w,) i32
    ring_parent: Optional[jax.Array] = field(sharding=P(), default=None)  # (K, w) i32
    ring_op: Optional[jax.Array] = field(sharding=P(), default=None)  # (K, w) i32
    ring_best_slot: Optional[jax.Array] = field(sharding=P(), default=None)  # (K,) i32
    ring_best_fit: Optional[jax.Array] = field(sharding=P(), default=None)  # (K,) f32
    ring_delta: Optional[jax.Array] = field(sharding=P(), default=None)  # (K,) f32
    ring_epoch: Optional[jax.Array] = field(sharding=P(), default=None)  # (K,) i32
    # multi-objective extras (None when num_objectives == 1)
    cur_front: Optional[jax.Array] = field(sharding=P(), default=None)  # (w, m) stash
    cur_front_mask: Optional[jax.Array] = field(sharding=P(), default=None)  # (w,)
    prev_front: Optional[jax.Array] = field(sharding=P(), default=None)  # (w, m)
    prev_front_mask: Optional[jax.Array] = field(sharding=P(), default=None)  # (w,)
    ring_front_size: Optional[jax.Array] = field(sharding=P(), default=None)  # (K,) i32
    ring_churn: Optional[jax.Array] = field(sharding=P(), default=None)  # (K,) f32


class LineageMonitor(Monitor):
    """On-device lineage rings + operator-attribution ledger.

    Args:
        history_capacity: ring size K — the last K generations' parent
            maps, operator tags, best slot/fitness/delta and epoch are
            kept on device (older slots overwritten, ring semantics).
        num_objectives: fitness arity. ``m > 1`` adds the front-size and
            non-dominated-churn rings (an O(batch²) dominance pass per
            generation — size the batch accordingly).
        default_op: vocabulary name (``core.attribution.OP_NAMES``) used
            to tag candidates of algorithms that do not publish the
            attribution contract — ``"sample"`` (ES/CMA default),
            ``"velocity"`` (PSO), ``"crossover"``/``"mutation"`` (GAs).

    Fitness quantities are stored in the algorithm-internal minimize key;
    ``report()``/``search_report()`` convert back to the user convention
    for single-objective runs.
    """

    def __init__(
        self,
        history_capacity: int = 64,
        num_objectives: int = 1,
        default_op: str = "sample",
    ):
        if history_capacity < 1:
            raise ValueError(
                f"history_capacity must be >= 1, got {history_capacity}"
            )
        if num_objectives < 1:
            raise ValueError(
                f"num_objectives must be >= 1, got {num_objectives}"
            )
        if default_op not in OP_NAMES:
            raise ValueError(
                f"default_op {default_op!r} is not in the attribution "
                f"vocabulary {OP_NAMES}"
            )
        self.capacity = history_capacity
        self.num_objectives = num_objectives
        self.default_op = OP_NAMES.index(default_op)
        self.opt_direction = jnp.ones((1,), dtype=jnp.float32)

    def hooks(self):
        return ("post_eval", "post_step")

    def init(self, key: Optional[jax.Array] = None) -> LineageState:
        i32 = lambda: jnp.zeros((), dtype=jnp.int32)  # noqa: E731
        return LineageState(
            count=i32(),
            epoch_extra=i32(),
            restarts_seen=i32(),
            best_key=jnp.asarray(jnp.inf, jnp.float32),
            ledger_attempts=jnp.zeros((N_OPS,), jnp.int32),
            ledger_success=jnp.zeros((N_OPS,), jnp.int32),
            ledger_improvement=jnp.zeros((N_OPS,), jnp.float32),
        )

    # ----------------------------------------------------------- internals
    def _scalar_key(self, fitness: jax.Array) -> jax.Array:
        """Per-candidate scalar minimize key. SO: fitness flipped to the
        internal direction. MO: the mean internal objective — used ONLY
        to pick a representative best slot / delta for the lineage rings
        (front quality lives in the churn/front-size rings)."""
        if self.num_objectives == 1:
            return (fitness * self.opt_direction[0]).astype(jnp.float32)
        key = fitness * self.opt_direction
        return jnp.mean(key, axis=-1).astype(jnp.float32)

    def _fold_width(self, key_fit: jax.Array, width: int) -> jax.Array:
        """Fold a wider-than-width batch onto the slot axis. CoDE
        evaluates ``3 * pop`` trials laid out ``reshape(3, pop)`` (its
        own tell layout, code.py:102); the per-slot best trial is the one
        that competes at that slot. Narrower batches inf-pad."""
        w = key_fit.shape[0]
        if w == width:
            return key_fit
        if w % width == 0:
            return key_fit.reshape(-1, width).min(axis=0)
        if w < width:
            return jnp.pad(key_fit, (0, width - w), constant_values=jnp.inf)
        raise ValueError(
            f"lineage ring was sized by the first generation (width "
            f"{width}); cannot fold a batch of {w} (not a multiple). "
            "Evaluate the widest batch first or use a fresh monitor."
        )

    # ---------------------------------------------------------------- hooks
    def post_eval(
        self, mstate: LineageState, cand: Any, fitness: jax.Array
    ) -> LineageState:
        m = self.num_objectives
        if m == 1 and fitness.ndim != 1:
            raise ValueError(
                f"LineageMonitor(num_objectives=1) got fitness of shape "
                f"{fitness.shape}; pass num_objectives={fitness.shape[-1]} "
                "for multi-objective runs"
            )
        if m > 1 and (fitness.ndim != 2 or fitness.shape[-1] != m):
            raise ValueError(
                f"LineageMonitor(num_objectives={m}) got fitness of shape "
                f"{fitness.shape}"
            )
        key_fit = self._scalar_key(fitness)
        K = self.capacity

        if mstate.cur_fit is None:
            # first batch sizes the slot axis (EvalMonitor discipline)
            width = key_fit.shape[0]
            mstate = mstate.replace(
                cur_fit=key_fit,
                prev_fit=jnp.full((width,), jnp.inf, jnp.float32),
                age=jnp.zeros((width,), jnp.int32),
                improvements=jnp.zeros((width,), jnp.int32),
                ring_parent=jnp.zeros((K, width), jnp.int32),
                ring_op=jnp.zeros((K, width), jnp.int32),
                ring_best_slot=jnp.zeros((K,), jnp.int32),
                ring_best_fit=jnp.full((K,), jnp.inf, jnp.float32),
                ring_delta=jnp.zeros((K,), jnp.float32),
                ring_epoch=jnp.zeros((K,), jnp.int32),
            )
            if m > 1:
                mstate = mstate.replace(
                    cur_front=jnp.zeros((width, m), jnp.float32),
                    cur_front_mask=jnp.zeros((width,), bool),
                    prev_front=jnp.zeros((width, m), jnp.float32),
                    prev_front_mask=jnp.zeros((width,), bool),
                    ring_front_size=jnp.zeros((K,), jnp.int32),
                    ring_churn=jnp.zeros((K,), jnp.float32),
                )
        else:
            width = mstate.cur_fit.shape[0]
            mstate = mstate.replace(cur_fit=self._fold_width(key_fit, width))

        if m > 1:
            if fitness.shape[0] != mstate.cur_front.shape[0]:
                raise ValueError(
                    "LineageMonitor MO rings need a constant batch width "
                    f"(sized {mstate.cur_front.shape[0]} by the first "
                    f"generation, got {fitness.shape[0]})"
                )
            # rank-0 front of this batch, internal minimize convention.
            # Deferred import: operators -> core only; monitors sit above.
            from ..operators.selection.non_dominate import non_dominated_sort

            key_obj = (fitness * self.opt_direction).astype(jnp.float32)
            finite = jnp.all(jnp.isfinite(key_obj), axis=-1)
            rank = non_dominated_sort(
                jnp.where(finite[:, None], key_obj, jnp.inf), until=1
            )
            front_mask = (rank == 0) & finite
            mstate = mstate.replace(
                cur_front=jnp.where(front_mask[:, None], key_obj, 0.0),
                cur_front_mask=front_mask,
            )
        return mstate

    def post_step(self, mstate: LineageState, wf_state: Any) -> LineageState:
        if mstate.cur_fit is None:  # post_eval never ran: nothing to record
            return mstate
        width = mstate.cur_fit.shape[0]
        cur, prev = mstate.cur_fit, mstate.prev_fit

        astate = getattr(wf_state, "algo", None)
        attrib = find_attribution(astate)
        if attrib is not None and attrib.parent_idx.shape[0] != width:
            attrib = None  # container/fleet reshaping: fall back honestly
        if attrib is None:
            # selection-boundary tagging: parent = slot identity, success
            # = this slot's fitness improved over the previous generation,
            # replacement semantics (the whole batch becomes the new
            # per-slot fitness — ES/PSO/MO discipline)
            succ = success_mask(cur, prev)
            tag = jnp.where(
                mstate.count == 0, jnp.int32(OP_INIT), jnp.int32(self.default_op)
            )
            attrib = Attribution(
                parent_idx=jnp.arange(width, dtype=jnp.int32),
                op_tag=jnp.broadcast_to(tag, (width,)),
                success=succ,
                improvement=improvement_mass(cur, prev, succ),
            )
            new_fit = cur
        else:
            # contract attribution: greedy slot descent — the slot keeps
            # its incumbent unless the candidate succeeded
            new_fit = jnp.where(attrib.success, cur, prev)

        # epoch: guardrail restarts mirrored structurally (TelemetryMonitor
        # discipline) + manual bump_epoch() increments
        restarts = mstate.restarts_seen
        if hasattr(astate, "restarts"):
            restarts = jnp.asarray(astate.restarts, jnp.int32)
        epoch = restarts + mstate.epoch_extra

        # per-slot counters
        age = jnp.where(attrib.success, 0, mstate.age + 1)
        improvements = mstate.improvements + attrib.success.astype(jnp.int32)

        # credit ledger
        attempts, successes, improvement = op_credit(attrib, N_OPS)
        ledger_attempts = mstate.ledger_attempts + attempts
        ledger_success = mstate.ledger_success + successes
        ledger_improvement = mstate.ledger_improvement + improvement

        # best-so-far delta (internal key; monotone, so delta >= 0)
        gen_best = jnp.min(new_fit)
        best_slot = jnp.argmin(new_fit).astype(jnp.int32)
        new_best = jnp.minimum(mstate.best_key, gen_best)
        delta = jnp.where(
            jnp.isfinite(mstate.best_key),
            jnp.maximum(mstate.best_key - new_best, 0.0),
            0.0,
        )

        count = mstate.count
        mstate = mstate.replace(
            count=count + 1,
            restarts_seen=restarts,
            best_key=new_best,
            ledger_attempts=ledger_attempts,
            ledger_success=ledger_success,
            ledger_improvement=ledger_improvement,
            prev_fit=new_fit,
            age=age,
            improvements=improvements,
            ring_parent=ring_write(mstate.ring_parent, attrib.parent_idx, count),
            ring_op=ring_write(mstate.ring_op, attrib.op_tag, count),
            ring_best_slot=ring_write(mstate.ring_best_slot, best_slot, count),
            ring_best_fit=ring_write(mstate.ring_best_fit, gen_best, count),
            ring_delta=ring_write(mstate.ring_delta, delta, count),
            ring_epoch=ring_write(mstate.ring_epoch, epoch, count),
        )
        if self.num_objectives > 1:
            churn = masked_igd(
                mstate.cur_front,
                mstate.cur_front_mask,
                mstate.prev_front,
                mstate.prev_front_mask,
            )
            front_size = jnp.sum(mstate.cur_front_mask).astype(jnp.int32)
            mstate = mstate.replace(
                prev_front=mstate.cur_front,
                prev_front_mask=mstate.cur_front_mask,
                ring_front_size=ring_write(
                    mstate.ring_front_size, front_size, count
                ),
                ring_churn=ring_write(mstate.ring_churn, churn, count),
            )
        return mstate

    # ------------------------------------------------------------- epoching
    def bump_epoch(self, mstate: LineageState) -> LineageState:
        """Advance the exploit epoch (jit-safe). External drivers that
        perform population surgery between steps — PBT exploit/explore,
        island migrations, manual recenters — call this so subsequent
        ring rows are never read as descent from pre-surgery slots."""
        return mstate.replace(epoch_extra=mstate.epoch_extra + 1)

    # --------------------------------------------------------------- getters
    def _chronology(self, mstate: LineageState):
        """Host-side (generation, slot) pairs, oldest first."""
        slots = ring_slots(mstate.count, self.capacity)
        count = int(mstate.count)
        gens = list(range(count - len(slots) + 1, count + 1))
        return gens, slots

    def best_ancestry(self, mstate: LineageState) -> list:
        """Trace the current best individual back through the recorded
        window: newest entry first, each ``{generation, slot, parent,
        op, epoch}``. The walk stops at a ring-window edge or an epoch
        boundary (restart/exploit) — an edge across epochs is not
        descent. Host-side, eager."""
        if mstate.ring_best_slot is None or int(mstate.count) == 0:
            return []
        gens, slots = self._chronology(mstate)
        ring_parent = np.asarray(jax.device_get(mstate.ring_parent))
        ring_op = np.asarray(jax.device_get(mstate.ring_op))
        ring_best = np.asarray(jax.device_get(mstate.ring_best_slot))
        ring_epoch = np.asarray(jax.device_get(mstate.ring_epoch))
        chain = []
        slot = int(ring_best[slots[-1]])
        epoch = int(ring_epoch[slots[-1]])
        for gen, s in zip(reversed(gens), reversed(slots)):
            if int(ring_epoch[s]) != epoch:
                break  # restart/exploit boundary: lineage ends here
            parent = int(ring_parent[s][slot])
            chain.append(
                {
                    "generation": gen,
                    "slot": slot,
                    "parent": parent,
                    "op": OP_NAMES[int(ring_op[s][slot])],
                    "epoch": int(ring_epoch[s]),
                }
            )
            slot = parent
        return chain

    def ledger(self, mstate: LineageState) -> dict:
        """The per-operator credit table (host-side): only operators with
        at least one attempt appear."""
        attempts = np.asarray(jax.device_get(mstate.ledger_attempts))
        success = np.asarray(jax.device_get(mstate.ledger_success))
        improvement = np.asarray(jax.device_get(mstate.ledger_improvement))
        out = {}
        for i, name in enumerate(OP_NAMES):
            if int(attempts[i]) > 0:
                out[name] = {
                    "attempts": int(attempts[i]),
                    "successes": int(success[i]),
                    "improvement": float(improvement[i]),
                }
        return out

    def get_trajectory(self, mstate: LineageState) -> dict:
        """Chronological per-generation window: best slot, best fitness
        (user convention for SO), best-so-far delta, epoch — plus front
        size and churn for MO."""
        if mstate.ring_best_slot is None:
            return {
                "generation": [],
                "best_slot": [],
                "best_fitness": [],
                "delta": [],
                "epoch": [],
            }
        gens, slots = self._chronology(mstate)
        direction = (
            float(self.opt_direction[0]) if self.num_objectives == 1 else 1.0
        )
        best_fit = np.asarray(jax.device_get(mstate.ring_best_fit))
        out = {
            "generation": gens,
            "best_slot": [int(np.asarray(mstate.ring_best_slot)[s]) for s in slots],
            "best_fitness": [float(best_fit[s] * direction) for s in slots],
            "delta": [float(np.asarray(mstate.ring_delta)[s]) for s in slots],
            "epoch": [int(np.asarray(mstate.ring_epoch)[s]) for s in slots],
        }
        if self.num_objectives > 1:
            out["front_size"] = [
                int(np.asarray(mstate.ring_front_size)[s]) for s in slots
            ]
            out["churn"] = [
                float(np.asarray(mstate.ring_churn)[s]) for s in slots
            ]
        return out

    def counter_tracks(self, mstate: LineageState) -> dict:
        """Generation-indexed counter samples for the Chrome-trace
        exporter (core/instrument.py ``write_chrome_trace``):
        ``{track_name: [(generation, value), ...]}``."""
        traj = self.get_trajectory(mstate)
        gens = traj["generation"]
        tracks = {
            "search/best_fitness": list(zip(gens, traj["best_fitness"])),
            "search/delta": list(zip(gens, traj["delta"])),
            "search/epoch": list(zip(gens, traj["epoch"])),
        }
        if self.num_objectives > 1:
            tracks["search/front_size"] = list(zip(gens, traj["front_size"]))
            tracks["search/churn"] = list(zip(gens, traj["churn"]))
        return tracks

    def fingerprint(self, mstate: LineageState) -> str:
        """SHA-256 over the exact bytes of every lineage field — the
        bit-identity witness used by the fused/pipelined equivalence
        laws (same discipline as TelemetryMonitor.fingerprint)."""
        import hashlib

        h = hashlib.sha256()
        for path, leaf in jax.tree_util.tree_flatten_with_path(mstate)[0]:
            h.update(jax.tree_util.keystr(path).encode())
            h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
        return h.hexdigest()

    def search_report(self, mstate: LineageState) -> dict:
        """The ``search`` section of ``run_report()`` (schema v13):
        strictly JSON-serializable, validated by tools/check_report.py."""
        width = (
            int(mstate.cur_fit.shape[0]) if mstate.cur_fit is not None else 0
        )
        age = (
            np.asarray(jax.device_get(mstate.age))
            if mstate.age is not None
            else np.zeros((0,), np.int32)
        )
        report = {
            "enabled": True,
            "generations": int(mstate.count),
            "capacity": self.capacity,
            "width": width,
            "num_objectives": self.num_objectives,
            "epoch": int(mstate.restarts_seen) + int(mstate.epoch_extra),
            "restarts": int(mstate.restarts_seen),
            "ledger": self.ledger(mstate),
            "ancestry": self.best_ancestry(mstate),
            "age": {
                "max": int(age.max()) if age.size else 0,
                "mean": float(age.mean()) if age.size else 0.0,
            },
            "trajectory": self.get_trajectory(mstate),
        }
        return sanitize_json(report)

    def report(self, mstate: LineageState) -> dict:
        """Monitor-report protocol (run_report telemetry list, per-tenant
        fleet reports): the search report under the standard keys."""
        return self.search_report(mstate)
