"""Shared monitor plumbing: host-callback probes and the fixed-capacity
device-ring discipline.

The ring helpers are the one implementation behind every on-device
history buffer in the stack — EvalMonitor's device history,
TelemetryMonitor's trajectory rings, LineageMonitor's lineage rings, the
SurrogateArchive, and the surrogate fallback-event log. All share the
same law: a ``(K, ...)`` buffer plus a monotone ``count``; the write slot
is ``count % K``; host readback is chronological over the last
``min(count, K)`` writes. Keeping them on one helper keeps the discipline
identical (fixed shapes, no retrace as counts grow, axon-safe — zero host
callbacks in the write path).
"""

from __future__ import annotations

import jax
from jax.sharding import SingleDeviceSharding

from ..utils.ring import ring_scatter_indices, ring_slots, ring_write  # noqa: F401


def host0_sharding() -> SingleDeviceSharding:
    """Sharding that pins a host callback to GLOBAL device 0 — on a
    multi-host mesh the callback then fires on process 0 only (the process
    that owns device 0), the same discipline as the reference
    (eval_monitor.py:69 ``SingleDeviceSharding(jax.devices()[0])``)."""
    return SingleDeviceSharding(jax.devices()[0])


# Backends whose runtimes cannot execute host callbacks (io_callback /
# pure_callback): the tunneled axon TPU plugin. Anything built on host
# callbacks must fail loudly at init/trace time there instead of hanging
# inside the runtime (measured: the callback never completes). The plugin
# reports platform "tpu"; its identity only shows in the PJRT client's
# platform_version string ("axon x.y.z; ...").
CALLBACK_LESS_MARKERS = ("axon",)


def backend_supports_callbacks() -> bool:
    """True when the default backend can run io_callback/pure_callback."""
    try:
        version = getattr(jax.devices()[0].client, "platform_version", "")
    except Exception:  # pragma: no cover - backend probing must never fail
        return True
    return not any(m in version for m in CALLBACK_LESS_MARKERS)


# ---------------------------------------------------------- device rings
# The implementation lives in utils/ring.py (the bottom layer, so
# operators — e.g. the SurrogateArchive — can share it without importing
# monitors); monitor code imports the discipline from here.
