"""Shared monitor plumbing."""

from __future__ import annotations

import jax
from jax.sharding import SingleDeviceSharding


def host0_sharding() -> SingleDeviceSharding:
    """Sharding that pins a host callback to GLOBAL device 0 — on a
    multi-host mesh the callback then fires on process 0 only (the process
    that owns device 0), the same discipline as the reference
    (eval_monitor.py:69 ``SingleDeviceSharding(jax.devices()[0])``)."""
    return SingleDeviceSharding(jax.devices()[0])
