"""EvalMonitor — elite / Pareto-front tracking (reference:
src/evox/monitors/eval_monitor.py).

TPU-first redesign: instead of shipping every batch to the host through
``io_callback`` and keeping Python-side state (reference eval_monitor.py:
69-96), the elite top-k buffer and the fixed-capacity Pareto archive are
device arrays inside the monitor's pytree state, updated with pure jittable
math — zero host sync in the hot loop. Unbounded full history (opt-in) still
streams host-side via ``io_callback``, pinned to one device like the
reference.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import io_callback

from ..core.monitor import Monitor
from jax.sharding import PartitionSpec as P
from .common import backend_supports_callbacks, host0_sharding, ring_slots, ring_write
from ..core.struct import PyTreeNode, field
from ..operators.selection.non_dominate import (
    crowding_distance,
    non_dominated_sort,
)


class EvalMonitorState(PyTreeNode):
    # layout annotations are all P(): every buffer here is capacity- or
    # k-leading (elite/archive/ring), never population-leading
    topk_fitness: Optional[jax.Array] = field(sharding=P())  # (k,) or (cap, m) raw user-direction
    topk_solution: Optional[Any] = field(sharding=P())
    pf_count: Optional[jax.Array] = field(sharding=P())
    # device-side generation-history ring buffer (history_capacity > 0):
    hist_fit: Optional[jax.Array] = field(sharding=P(), default=None)  # (K, width[, m]) inf-padded
    hist_sol: Optional[Any] = field(sharding=P(), default=None)  # (K, width, ...) when history_solutions
    hist_len: Optional[jax.Array] = field(sharding=P(), default=None)  # (K,) int32 valid rows per slot
    hist_count: Optional[jax.Array] = field(sharding=P(), default=None)  # () int32 total generations seen


# Backward-compat alias: the probe now lives in monitors/common.py so every
# callback-dependent monitor (StepTimerMonitor included) shares one marker
# list. full_*_history relies on io_callback, so it must fail loudly at
# trace time on callback-less backends instead of hanging inside the
# runtime (measured: the callback never completes).
_default_backend_supports_callbacks = backend_supports_callbacks


class EvalMonitor(Monitor):
    """Tracks the best-so-far individuals seen at evaluation time.

    Single-objective: a ``topk`` elite buffer. Multi-objective: a running
    Pareto archive of capacity ``pf_capacity`` (set ``multi_obj=True``).

    Generation history comes in two forms:

    - ``full_fit_history`` / ``full_sol_history``: unbounded, streamed to
      HOST memory via ``io_callback`` (the reference's design,
      eval_monitor.py:98-162). Requires a backend with host-callback
      support — NOT the tunneled axon TPU plugin (raises at trace time
      there).
    - ``history_capacity=K``: a fixed-capacity on-DEVICE ring buffer of
      the last ``K`` generations' fitness (and solutions with
      ``history_solutions=True``) inside the monitor's pytree state —
      zero host sync, works on every backend including callback-less
      ones. When more than ``K`` generations run, the oldest slots are
      overwritten (ring semantics); per-slot batch widths are tracked so
      variable evaluation sizes (e.g. CSO's full-then-half pattern) read
      back exactly. Rows wider than the first generation's batch raise
      at trace time (the buffer is sized by the first generation).
    """

    def __init__(
        self,
        topk: int = 1,
        multi_obj: bool = False,
        pf_capacity: int = 1024,
        full_fit_history: bool = False,
        full_sol_history: bool = False,
        history_capacity: int = 0,
        history_solutions: bool = False,
    ):
        self.topk = topk
        self.multi_obj = multi_obj
        self.pf_capacity = pf_capacity
        self.full_fit_history = full_fit_history
        self.full_sol_history = full_sol_history
        self.history_capacity = history_capacity
        self.history_solutions = history_solutions
        if history_solutions and not history_capacity:
            raise ValueError("history_solutions requires history_capacity > 0")
        self.fitness_history: list = []
        self.solution_history: list = []
        self.opt_direction = jnp.ones((1,), dtype=jnp.float32)
        # full histories stream through a host callback inside the step
        # (the convention flag VectorizedWorkflow fleets reject — a
        # callback cannot run under vmap); the on-device ring
        # (history_capacity=K) stays fleet-safe
        self.uses_host_callbacks = bool(full_fit_history or full_sol_history)

    def hooks(self):
        return ("post_eval",)

    def init(self, key: Optional[jax.Array] = None) -> EvalMonitorState:
        # lazy: buffers materialize on the first post_eval (shapes unknown here);
        # the workflow's first-generation retrace absorbs the structure change.
        return EvalMonitorState(topk_fitness=None, topk_solution=None, pf_count=None)

    # ------------------------------------------------------------------ hook
    def post_eval(self, mstate: EvalMonitorState, cand: Any, fitness: jax.Array) -> EvalMonitorState:
        if self.full_fit_history or self.full_sol_history:
            self._record_history(cand, fitness)
        hist = {}
        if self.history_capacity:
            hist = self._update_device_history(mstate, cand, fitness)
        if fitness.ndim == 1 and not self.multi_obj:
            return self._update_so(mstate, cand, fitness).replace(**hist)
        return self._update_mo(mstate, cand, fitness).replace(**hist)

    # ------------------------------------------- device-side history ring
    def _update_device_history(self, mstate, cand, fitness) -> dict:
        K = self.history_capacity
        if mstate.hist_fit is None:
            width = fitness.shape[0]
            hist_fit = jnp.full((K, width) + fitness.shape[1:], jnp.inf, fitness.dtype)
            hist_sol = (
                jax.tree.map(
                    lambda x: jnp.zeros((K, width) + x.shape[1:], x.dtype), cand
                )
                if self.history_solutions
                else None
            )
            hist_len = jnp.zeros((K,), dtype=jnp.int32)
            count = jnp.zeros((), dtype=jnp.int32)
        else:
            hist_fit, hist_sol = mstate.hist_fit, mstate.hist_sol
            hist_len, count = mstate.hist_len, mstate.hist_count
            width = hist_fit.shape[1]
        n = fitness.shape[0]
        if n > width:
            raise ValueError(
                f"history ring buffer was sized by the first generation "
                f"(batch {width}); cannot record a larger batch ({n}). "
                "Evaluate the widest batch first or disable history_capacity."
            )
        row = jnp.pad(
            fitness,
            ((0, width - n),) + ((0, 0),) * (fitness.ndim - 1),
            constant_values=jnp.inf,
        )
        # shared ring discipline (monitors/common.py): slot = count % K
        hist_fit = ring_write(hist_fit, row, count)
        if hist_sol is not None:
            hist_sol = jax.tree.map(
                lambda buf, c: ring_write(
                    buf,
                    jnp.pad(c, ((0, width - n),) + ((0, 0),) * (c.ndim - 1)),
                    count,
                ),
                hist_sol,
                cand,
            )
        hist_len = ring_write(hist_len, n, count)
        return dict(
            hist_fit=hist_fit,
            hist_sol=hist_sol,
            hist_len=hist_len,
            hist_count=count + 1,
        )

    def _record_history(self, cand: Any, fitness: jax.Array) -> None:
        if not _default_backend_supports_callbacks():
            raise RuntimeError(
                "full_fit_history/full_sol_history need host callbacks, "
                "which this backend (axon-tunneled TPU) does not support; "
                "use EvalMonitor(history_capacity=K) for an on-device "
                "generation-history ring buffer instead"
            )

        def append(fit, sol):
            if self.full_fit_history:
                self.fitness_history.append(fit)
            if self.full_sol_history:
                self.solution_history.append(sol)
            return jnp.zeros((), dtype=jnp.int32)

        # ordered=True threads a token whose replicated sharding the SPMD
        # partitioner rejects on multi-process meshes ("side-effect HLO
        # cannot have a replicated sharding"); drop the ordering token
        # there — the callback still fires exactly once per generation on
        # process 0 (asserted in tests/test_multiprocess_distributed.py),
        # but cross-generation append order follows dispatch order rather
        # than a token chain.
        io_callback(
            append,
            jax.ShapeDtypeStruct((), jnp.int32),
            fitness,
            cand,
            sharding=host0_sharding(),
            ordered=jax.process_count() == 1,
        )

    def _update_so(self, mstate, cand, fitness):
        key_fit = fitness * self.opt_direction[0]  # minimize internally
        if mstate.topk_fitness is None:
            merged_key, merged_fit, merged_sol = key_fit, fitness, cand
        else:
            prev_key = mstate.topk_fitness * self.opt_direction[0]
            merged_key = jnp.concatenate([prev_key, key_fit])
            merged_fit = jnp.concatenate([mstate.topk_fitness, fitness])
            merged_sol = jax.tree.map(
                lambda a, b: jnp.concatenate([a, b]), mstate.topk_solution, cand
            )
        _, idx = jax.lax.top_k(-merged_key, self.topk)
        return EvalMonitorState(
            topk_fitness=merged_fit[idx],
            topk_solution=jax.tree.map(lambda x: x[idx], merged_sol),
            pf_count=None,
        )

    def _update_mo(self, mstate, cand, fitness):
        key_fit = fitness * self.opt_direction
        if mstate.topk_fitness is None:
            prev_fit = jnp.full((self.pf_capacity,) + fitness.shape[1:], jnp.inf, fitness.dtype)
            prev_sol = jax.tree.map(
                lambda x: jnp.zeros((self.pf_capacity,) + x.shape[1:], x.dtype), cand
            )
        else:
            prev_fit = mstate.topk_fitness * self.opt_direction
            prev_sol = mstate.topk_solution
        merged_fit = jnp.concatenate([prev_fit, key_fit])
        merged_sol = jax.tree.map(lambda a, b: jnp.concatenate([a, b]), prev_sol, cand)
        # fixed-capacity archive refresh: rank once on the merged set, keep
        # the best (rank, -crowding) rows, then inf-pad everything that is
        # not a FINITE rank-0 member — environmental selection tops up with
        # dominated rows whenever the true front is smaller than the
        # capacity, and those must not masquerade as front members. One
        # liveness criterion (finite & rank 0) drives the padding, the
        # count, and get_pf_mask alike.
        rank = non_dominated_sort(merged_fit, until=self.pf_capacity)
        worst = jnp.sort(rank)[self.pf_capacity - 1]
        crowd = crowding_distance(merged_fit, mask=rank == worst)
        order = jnp.lexsort((-crowd, rank))[: self.pf_capacity]
        sel_fit = merged_fit[order]
        live = (rank[order] == 0) & jnp.all(jnp.isfinite(sel_fit), axis=-1)
        # stable re-sort so live rows occupy the leading slots (a finite
        # rank-0 block can be interrupted by an inf-coordinate row)
        reorder = jnp.argsort(~live, stable=True)
        sel_fit = jnp.where(live[reorder][:, None], sel_fit[reorder], jnp.inf)
        new_sol = jax.tree.map(
            lambda x: x[order][reorder], merged_sol
        )
        return EvalMonitorState(
            topk_fitness=sel_fit * self.opt_direction,  # store user direction
            topk_solution=new_sol,
            pf_count=jnp.sum(live.astype(jnp.int32)),
        )

    # --------------------------------------------------------------- getters
    def get_best_fitness(self, mstate: EvalMonitorState) -> jax.Array:
        return mstate.topk_fitness[0]

    def get_topk_fitness(self, mstate: EvalMonitorState) -> jax.Array:
        return mstate.topk_fitness

    def get_best_solution(self, mstate: EvalMonitorState):
        return jax.tree.map(lambda x: x[0], mstate.topk_solution)

    def get_topk_solutions(self, mstate: EvalMonitorState):
        return mstate.topk_solution

    def get_pf_mask(self, mstate: EvalMonitorState) -> jax.Array:
        """(pf_capacity,) bool — which archive rows hold real PF members.
        Jit-safe companion to the padded getters below."""
        return jnp.all(jnp.isfinite(mstate.topk_fitness), axis=-1)

    def get_pf_fitness(self, mstate: EvalMonitorState) -> jax.Array:
        """Pareto-archive fitness. Eagerly: sliced to the live rows. Under
        jit (``mstate`` is traced): the full fixed-capacity buffer, with
        dead rows inf-padded — combine with :meth:`get_pf_mask`."""
        if isinstance(mstate.pf_count, jax.core.Tracer):
            return mstate.topk_fitness
        n = int(mstate.pf_count)
        return mstate.topk_fitness[:n]

    def get_pf_solutions(self, mstate: EvalMonitorState):
        """Pareto-archive solutions; same eager-slice / traced-padded
        contract as :meth:`get_pf_fitness`."""
        if isinstance(mstate.pf_count, jax.core.Tracer):
            return mstate.topk_solution
        n = int(mstate.pf_count)
        return jax.tree.map(lambda x: x[:n], mstate.topk_solution)

    def get_fitness_history(self) -> list:
        jax.effects_barrier()
        return self.fitness_history

    def get_solution_history(self) -> list:
        jax.effects_barrier()
        return self.solution_history

    # ----------------------------------------- device-history ring getters
    def _ring_slots(self, mstate: EvalMonitorState):
        return ring_slots(mstate.hist_count, self.history_capacity)

    def get_device_fitness_history(self, mstate: EvalMonitorState) -> list:
        """The last ``min(count, history_capacity)`` generations' fitness,
        chronological, each sliced to its true batch width. Eager (host)
        utility; for jit-side access read ``mstate.hist_fit`` /
        ``hist_len`` / ``hist_count`` directly (ring layout, inf-padded)."""
        if mstate.hist_fit is None:
            return []
        return [
            mstate.hist_fit[s][: int(mstate.hist_len[s])]
            for s in self._ring_slots(mstate)
        ]

    def get_device_solution_history(self, mstate: EvalMonitorState) -> list:
        if mstate.hist_sol is None:
            return []
        return [
            jax.tree.map(lambda x: x[s][: int(mstate.hist_len[s])], mstate.hist_sol)
            for s in self._ring_slots(mstate)
        ]
