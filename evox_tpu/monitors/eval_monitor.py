"""EvalMonitor — elite / Pareto-front tracking (reference:
src/evox/monitors/eval_monitor.py).

TPU-first redesign: instead of shipping every batch to the host through
``io_callback`` and keeping Python-side state (reference eval_monitor.py:
69-96), the elite top-k buffer and the fixed-capacity Pareto archive are
device arrays inside the monitor's pytree state, updated with pure jittable
math — zero host sync in the hot loop. Unbounded full history (opt-in) still
streams host-side via ``io_callback``, pinned to one device like the
reference.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import io_callback

from ..core.monitor import Monitor
from .common import host0_sharding
from ..core.struct import PyTreeNode
from ..operators.selection.non_dominate import (
    crowding_distance,
    non_dominated_sort,
)


class EvalMonitorState(PyTreeNode):
    topk_fitness: Optional[jax.Array]  # (k,) or (cap, m) raw user-direction
    topk_solution: Optional[Any]
    pf_count: Optional[jax.Array]


class EvalMonitor(Monitor):
    """Tracks the best-so-far individuals seen at evaluation time.

    Single-objective: a ``topk`` elite buffer. Multi-objective: a running
    Pareto archive of capacity ``pf_capacity`` (set ``multi_obj=True``).
    ``full_fit_history`` / ``full_sol_history`` stream every generation to
    host memory (outside jit) for offline analysis / plotting.
    """

    def __init__(
        self,
        topk: int = 1,
        multi_obj: bool = False,
        pf_capacity: int = 1024,
        full_fit_history: bool = False,
        full_sol_history: bool = False,
    ):
        self.topk = topk
        self.multi_obj = multi_obj
        self.pf_capacity = pf_capacity
        self.full_fit_history = full_fit_history
        self.full_sol_history = full_sol_history
        self.fitness_history: list = []
        self.solution_history: list = []
        self.opt_direction = jnp.ones((1,), dtype=jnp.float32)

    def hooks(self):
        return ("post_eval",)

    def init(self, key: Optional[jax.Array] = None) -> EvalMonitorState:
        # lazy: buffers materialize on the first post_eval (shapes unknown here);
        # the workflow's first-generation retrace absorbs the structure change.
        return EvalMonitorState(topk_fitness=None, topk_solution=None, pf_count=None)

    # ------------------------------------------------------------------ hook
    def post_eval(self, mstate: EvalMonitorState, cand: Any, fitness: jax.Array) -> EvalMonitorState:
        if self.full_fit_history or self.full_sol_history:
            self._record_history(cand, fitness)
        if fitness.ndim == 1 and not self.multi_obj:
            return self._update_so(mstate, cand, fitness)
        return self._update_mo(mstate, cand, fitness)

    def _record_history(self, cand: Any, fitness: jax.Array) -> None:
        def append(fit, sol):
            if self.full_fit_history:
                self.fitness_history.append(fit)
            if self.full_sol_history:
                self.solution_history.append(sol)
            return jnp.zeros((), dtype=jnp.int32)

        io_callback(
            append,
            jax.ShapeDtypeStruct((), jnp.int32),
            fitness,
            cand,
            sharding=host0_sharding(),
            ordered=True,
        )

    def _update_so(self, mstate, cand, fitness):
        key_fit = fitness * self.opt_direction[0]  # minimize internally
        if mstate.topk_fitness is None:
            merged_key, merged_fit, merged_sol = key_fit, fitness, cand
        else:
            prev_key = mstate.topk_fitness * self.opt_direction[0]
            merged_key = jnp.concatenate([prev_key, key_fit])
            merged_fit = jnp.concatenate([mstate.topk_fitness, fitness])
            merged_sol = jax.tree.map(
                lambda a, b: jnp.concatenate([a, b]), mstate.topk_solution, cand
            )
        _, idx = jax.lax.top_k(-merged_key, self.topk)
        return EvalMonitorState(
            topk_fitness=merged_fit[idx],
            topk_solution=jax.tree.map(lambda x: x[idx], merged_sol),
            pf_count=None,
        )

    def _update_mo(self, mstate, cand, fitness):
        key_fit = fitness * self.opt_direction
        if mstate.topk_fitness is None:
            prev_fit = jnp.full((self.pf_capacity,) + fitness.shape[1:], jnp.inf, fitness.dtype)
            prev_sol = jax.tree.map(
                lambda x: jnp.zeros((self.pf_capacity,) + x.shape[1:], x.dtype), cand
            )
        else:
            prev_fit = mstate.topk_fitness * self.opt_direction
            prev_sol = mstate.topk_solution
        merged_fit = jnp.concatenate([prev_fit, key_fit])
        merged_sol = jax.tree.map(lambda a, b: jnp.concatenate([a, b]), prev_sol, cand)
        # fixed-capacity archive refresh: rank once on the merged set, keep
        # the best (rank, -crowding) rows, then inf-pad everything that is
        # not a FINITE rank-0 member — environmental selection tops up with
        # dominated rows whenever the true front is smaller than the
        # capacity, and those must not masquerade as front members. One
        # liveness criterion (finite & rank 0) drives the padding, the
        # count, and get_pf_mask alike.
        rank = non_dominated_sort(merged_fit, until=self.pf_capacity)
        worst = jnp.sort(rank)[self.pf_capacity - 1]
        crowd = crowding_distance(merged_fit, mask=rank == worst)
        order = jnp.lexsort((-crowd, rank))[: self.pf_capacity]
        sel_fit = merged_fit[order]
        live = (rank[order] == 0) & jnp.all(jnp.isfinite(sel_fit), axis=-1)
        # stable re-sort so live rows occupy the leading slots (a finite
        # rank-0 block can be interrupted by an inf-coordinate row)
        reorder = jnp.argsort(~live, stable=True)
        sel_fit = jnp.where(live[reorder][:, None], sel_fit[reorder], jnp.inf)
        new_sol = jax.tree.map(
            lambda x: x[order][reorder], merged_sol
        )
        return EvalMonitorState(
            topk_fitness=sel_fit * self.opt_direction,  # store user direction
            topk_solution=new_sol,
            pf_count=jnp.sum(live.astype(jnp.int32)),
        )

    # --------------------------------------------------------------- getters
    def get_best_fitness(self, mstate: EvalMonitorState) -> jax.Array:
        return mstate.topk_fitness[0]

    def get_topk_fitness(self, mstate: EvalMonitorState) -> jax.Array:
        return mstate.topk_fitness

    def get_best_solution(self, mstate: EvalMonitorState):
        return jax.tree.map(lambda x: x[0], mstate.topk_solution)

    def get_topk_solutions(self, mstate: EvalMonitorState):
        return mstate.topk_solution

    def get_pf_mask(self, mstate: EvalMonitorState) -> jax.Array:
        """(pf_capacity,) bool — which archive rows hold real PF members.
        Jit-safe companion to the padded getters below."""
        return jnp.all(jnp.isfinite(mstate.topk_fitness), axis=-1)

    def get_pf_fitness(self, mstate: EvalMonitorState) -> jax.Array:
        """Pareto-archive fitness. Eagerly: sliced to the live rows. Under
        jit (``mstate`` is traced): the full fixed-capacity buffer, with
        dead rows inf-padded — combine with :meth:`get_pf_mask`."""
        if isinstance(mstate.pf_count, jax.core.Tracer):
            return mstate.topk_fitness
        n = int(mstate.pf_count)
        return mstate.topk_fitness[:n]

    def get_pf_solutions(self, mstate: EvalMonitorState):
        """Pareto-archive solutions; same eager-slice / traced-padded
        contract as :meth:`get_pf_fitness`."""
        if isinstance(mstate.pf_count, jax.core.Tracer):
            return mstate.topk_solution
        n = int(mstate.pf_count)
        return jax.tree.map(lambda x: x[:n], mstate.topk_solution)

    def get_fitness_history(self) -> list:
        jax.effects_barrier()
        return self.fitness_history

    def get_solution_history(self) -> list:
        jax.effects_barrier()
        return self.solution_history
