"""TelemetryMonitor — callback-free, on-device run telemetry.

SURVEY.md §5.1: the reference has no built-in observability; evox_tpu's
StepTimerMonitor covers wall-clock but rides ``io_callback``, which the
tunneled axon TPU backend cannot execute (CLAUDE.md). This monitor is the
backend-universal alternative: every accumulator is a device array inside
the monitor's frozen pytree state, updated with pure jittable math in the
``post_eval`` hook — zero host traffic on the hot path, so it works
identically in a ``wf.step`` loop, inside ``wf.run``'s fused
``lax.fori_loop`` (where host callbacks are impossible on every backend),
and under ``run_host_pipelined``. Host-side wall-clock/compile timing is
the job of :mod:`evox_tpu.core.instrument`, which wraps the workflow's
entry points *outside* traced code; :func:`evox_tpu.core.instrument.
run_report` merges both sides into one structured report.

Tracked per generation (fixed-capacity ring, same pattern as
``EvalMonitor(history_capacity=K)``): best and mean fitness (finite-masked
mean, so a few poison rows don't blank the trajectory) and population
diversity (mean per-dimension std of the candidate batch). Tracked
cumulatively: NaN/Inf element counts for candidates and fitness,
generations-since-improvement (stagnation), the generation of the last
improvement, and generation/evaluation counters.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.instrument import sanitize_json
from ..core.monitor import Monitor
from jax.sharding import PartitionSpec as P
from ..core.struct import PyTreeNode, field
from .common import ring_slots, ring_write


class TelemetryState(PyTreeNode):
    # cumulative counters (int32: documented bound, ~2.1e9 events)
    generations: jax.Array = field(sharding=P())  # () generations observed
    evals: jax.Array = field(sharding=P())  # () candidate evaluations observed
    nan_candidates: jax.Array = field(sharding=P())  # () NaN elements across candidate leaves
    inf_candidates: jax.Array = field(sharding=P())  # () Inf elements across candidate leaves
    nan_fitness: jax.Array = field(sharding=P())  # () NaN fitness elements
    inf_fitness: jax.Array = field(sharding=P())  # () Inf fitness elements
    # best-so-far tracking, internal minimization convention
    best_key: jax.Array = field(sharding=P())  # () or (m,): per-objective ideal point for MO
    best_generation: jax.Array = field(sharding=P())  # () 1-based generation of last improvement
    stagnation: jax.Array = field(sharding=P())  # () generations since best improved
    # per-generation rings, slot = (generation - 1) % capacity
    ring_best: jax.Array = field(sharding=P())  # (K,) or (K, m), USER fitness convention
    ring_mean: jax.Array = field(sharding=P())  # (K,) or (K, m), finite-masked mean
    ring_diversity: jax.Array = field(sharding=P())  # (K,) mean per-dim std of the candidates
    # guardrail mirror (core/guardrail.py): cumulative on-device restarts
    # and the latest trigger bitmask of a GuardedAlgorithm driving this
    # run; stays 0 for unguarded algorithms (picked up in post_step)
    restarts: jax.Array = field(sharding=P())
    last_trigger: jax.Array = field(sharding=P())
    # surrogate mirror (workflows/surrogate.py, ISSUE 15): the TRUE
    # evaluation count and triggered-fallback count of a screening
    # SurrogateWorkflow driving this run — the headline `evals` counter
    # above counts batch ROWS, which under screening includes the inert
    # filled rows; these two make the real spend visible in report().
    # Always materialized (zeros for every other workflow) so the state
    # structure — and with it the checkpoint config fingerprint — never
    # changes mid-run.
    sur_true_evals: jax.Array = field(sharding=P(), default=None)
    sur_fallback_gens: jax.Array = field(sharding=P(), default=None)


class TelemetryMonitor(Monitor):
    """On-device run telemetry with no host callbacks anywhere.

    Args:
        capacity: ring size — the last ``capacity`` generations' best/mean
            fitness and diversity are kept on device; older slots are
            overwritten (ring semantics, exactly like
            ``EvalMonitor(history_capacity=K)``).
        num_objectives: fitness arity. ``1`` tracks scalar best/mean;
            ``m > 1`` tracks the per-objective ideal point and
            per-objective means (rings become ``(capacity, m)``).
            Declared up front so the state structure is static from
            ``init`` — no lazy buffers, no retrace beyond the workflow's
            own first-step peel.

    All fitness values are reported in the USER's direction convention
    (the workflow un-flips ``opt_direction`` before ``post_eval``, so a
    maximization run's best comes back positive); improvement/stagnation
    honor the direction internally. Counters are int32.
    """

    def __init__(self, capacity: int = 128, num_objectives: int = 1):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if num_objectives < 1:
            raise ValueError(
                f"num_objectives must be >= 1, got {num_objectives}"
            )
        self.capacity = capacity
        self.num_objectives = num_objectives
        self.opt_direction = jnp.ones((1,), dtype=jnp.float32)

    def hooks(self):
        return ("post_eval", "post_step")

    def init(self, key: Optional[jax.Array] = None) -> TelemetryState:
        K, m = self.capacity, self.num_objectives
        stat_shape = () if m == 1 else (m,)
        ring_shape = (K,) if m == 1 else (K, m)
        i32 = lambda: jnp.zeros((), dtype=jnp.int32)  # noqa: E731
        return TelemetryState(
            generations=i32(),
            evals=i32(),
            nan_candidates=i32(),
            inf_candidates=i32(),
            nan_fitness=i32(),
            inf_fitness=i32(),
            best_key=jnp.full(stat_shape, jnp.inf, dtype=jnp.float32),
            best_generation=i32(),
            stagnation=i32(),
            ring_best=jnp.full(ring_shape, jnp.inf, dtype=jnp.float32),
            ring_mean=jnp.full(ring_shape, jnp.inf, dtype=jnp.float32),
            ring_diversity=jnp.full((K,), jnp.inf, dtype=jnp.float32),
            restarts=i32(),
            last_trigger=i32(),
            sur_true_evals=i32(),
            sur_fallback_gens=i32(),
        )

    # ------------------------------------------------------------------ hook
    def post_eval(
        self, mstate: TelemetryState, cand: Any, fitness: jax.Array
    ) -> TelemetryState:
        m = self.num_objectives
        if m == 1 and fitness.ndim != 1:
            raise ValueError(
                f"TelemetryMonitor(num_objectives=1) got fitness of shape "
                f"{fitness.shape}; pass num_objectives={fitness.shape[-1]} "
                "for multi-objective runs"
            )
        if m > 1 and (fitness.ndim != 2 or fitness.shape[-1] != m):
            raise ValueError(
                f"TelemetryMonitor(num_objectives={m}) got fitness of "
                f"shape {fitness.shape}"
            )
        fitness = fitness.astype(jnp.float32)

        # -- NaN/Inf element counts -----------------------------------------
        def _count(pred, tree):
            leaves = [
                x for x in jax.tree.leaves(tree)
                if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
            ]
            total = jnp.zeros((), dtype=jnp.int32)
            for x in leaves:
                total = total + jnp.sum(pred(x)).astype(jnp.int32)
            return total

        nan_cand = mstate.nan_candidates + _count(jnp.isnan, cand)
        inf_cand = mstate.inf_candidates + _count(jnp.isinf, cand)
        nan_fit = mstate.nan_fitness + _count(jnp.isnan, fitness)
        inf_fit = mstate.inf_fitness + _count(jnp.isinf, fitness)

        # -- population diversity: mean per-dim std over the batch axis.
        # Finite-masked like the fitness stats (matches jnp.std when every
        # element is finite): one poison candidate must not NaN the whole
        # diversity trajectory — the counters record the poison instead.
        float_leaves = [
            jnp.asarray(x, jnp.float32)
            for x in jax.tree.leaves(cand)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
        ]
        std_sum = jnp.zeros((), dtype=jnp.float32)
        n_dims = 0
        for x in float_leaves:
            flat = x.reshape(x.shape[0], -1)
            ok = jnp.isfinite(flat)
            n = jnp.maximum(jnp.sum(ok.astype(jnp.float32), axis=0), 1.0)
            mean = jnp.sum(jnp.where(ok, flat, 0.0), axis=0) / n
            var = jnp.sum(jnp.where(ok, (flat - mean) ** 2, 0.0), axis=0) / n
            std_sum = std_sum + jnp.sum(jnp.sqrt(var))
            n_dims += flat.shape[1]
        diversity = std_sum / max(n_dims, 1)

        # -- per-generation fitness stats, internal minimization key --------
        direction = self.opt_direction[0] if m == 1 else self.opt_direction
        key_fit = fitness * direction
        finite = jnp.isfinite(key_fit)
        masked_key = jnp.where(finite, key_fit, jnp.inf)
        gen_best_key = jnp.min(masked_key, axis=0)  # () or (m,)
        n_finite = jnp.sum(finite.astype(jnp.float32), axis=0)
        gen_mean = jnp.sum(
            jnp.where(finite, fitness, 0.0), axis=0
        ) / jnp.maximum(n_finite, 1.0)

        # -- stagnation / best-so-far ---------------------------------------
        improved = jnp.any(gen_best_key < mstate.best_key)
        best_key = jnp.minimum(mstate.best_key, gen_best_key)
        generations = mstate.generations + 1
        best_generation = jnp.where(
            improved, generations, mstate.best_generation
        )
        stagnation = jnp.where(improved, 0, mstate.stagnation + 1)

        # -- ring update (shared discipline: monitors/common.py) ------------
        upd = lambda buf, row: ring_write(buf, row, mstate.generations)  # noqa: E731
        return TelemetryState(
            generations=generations,
            evals=mstate.evals + jnp.int32(fitness.shape[0]),
            nan_candidates=nan_cand,
            inf_candidates=inf_cand,
            nan_fitness=nan_fit,
            inf_fitness=inf_fit,
            best_key=best_key,
            best_generation=best_generation,
            stagnation=stagnation,
            ring_best=upd(mstate.ring_best, gen_best_key * direction),
            ring_mean=upd(mstate.ring_mean, gen_mean),
            ring_diversity=upd(mstate.ring_diversity, diversity),
            restarts=mstate.restarts,  # owned by post_step (guardrail mirror)
            last_trigger=mstate.last_trigger,
            sur_true_evals=mstate.sur_true_evals,  # owned by post_step
            sur_fallback_gens=mstate.sur_fallback_gens,
        )

    def post_step(self, mstate: TelemetryState, wf_state: Any) -> TelemetryState:
        """Mirror a GuardedAlgorithm's health counters (restart count and
        latest trigger bitmask) into the telemetry state, so they reach
        ``report()``/``run_report()`` without the caller touching the
        algorithm state. Structural (trace-time) detection: unguarded
        workflows compile this hook to a no-op."""
        astate = getattr(wf_state, "algo", None)
        if hasattr(astate, "restarts") and hasattr(astate, "last_trigger"):
            mstate = mstate.replace(
                restarts=jnp.asarray(astate.restarts, jnp.int32),
                last_trigger=jnp.asarray(astate.last_trigger, jnp.int32),
            )
        # surrogate mirror (workflows/surrogate.py): structural
        # detection, compiles to a no-op for every other workflow
        sur = getattr(wf_state, "sur", None)
        if hasattr(sur, "true_evals") and hasattr(sur, "fallback_gens"):
            mstate = mstate.replace(
                sur_true_evals=jnp.asarray(sur.true_evals, jnp.int32),
                sur_fallback_gens=jnp.asarray(sur.fallback_gens, jnp.int32),
            )
        return mstate

    # --------------------------------------------------------------- getters
    def get_best_fitness(self, mstate: TelemetryState) -> jax.Array:
        """Best-so-far (SO) / per-objective ideal point (MO), in the
        user's direction convention. Jit-safe."""
        direction = (
            self.opt_direction[0]
            if self.num_objectives == 1
            else self.opt_direction
        )
        return mstate.best_key * direction

    def _ring_slots(self, mstate: TelemetryState):
        return ring_slots(mstate.generations, self.capacity)

    def get_trajectory(self, mstate: TelemetryState) -> dict:
        """Chronological per-generation history of the last
        ``min(generations, capacity)`` generations. Eager (host) utility;
        under jit read the ring fields directly (ring layout,
        slot = (generation - 1) % capacity, inf-padded)."""
        slots = self._ring_slots(mstate)
        count = int(mstate.generations)
        best = np.asarray(mstate.ring_best)
        mean = np.asarray(mstate.ring_mean)
        div = np.asarray(mstate.ring_diversity)
        return {
            "generation": list(range(count - len(slots) + 1, count + 1)),
            "best": [best[s].tolist() for s in slots],
            "mean": [mean[s].tolist() for s in slots],
            "diversity": [float(div[s]) for s in slots],
        }

    def counter_tracks(self, mstate: TelemetryState) -> dict:
        """Generation-indexed counter samples for the Chrome-trace
        exporter (:func:`evox_tpu.core.instrument.write_chrome_trace`):
        ``{track_name: [(generation, value), ...]}``. Per-generation
        tracks come from the on-device rings (best/mean fitness,
        diversity — the last ``min(generations, capacity)`` generations);
        cumulative counters without a ring (stagnation, restarts, NaN
        fitness elements) contribute their final value as a single sample
        at the last generation. Non-finite samples are the exporter's
        problem (it skips them) — this stays a faithful read-back."""
        traj = self.get_trajectory(mstate)
        gens = traj["generation"]
        tracks: dict = {}
        if self.num_objectives == 1:
            tracks["telemetry/best_fitness"] = list(zip(gens, traj["best"]))
            tracks["telemetry/mean_fitness"] = list(zip(gens, traj["mean"]))
        else:
            for j in range(self.num_objectives):
                tracks[f"telemetry/best_obj{j}"] = [
                    (g, row[j]) for g, row in zip(gens, traj["best"])
                ]
        tracks["telemetry/diversity"] = list(zip(gens, traj["diversity"]))
        last = int(mstate.generations)
        for name, v in (
            ("stagnation", mstate.stagnation),
            ("restarts", mstate.restarts),
            ("nan_fitness", mstate.nan_fitness),
        ):
            tracks[f"telemetry/{name}"] = [(last, int(v))]
        return tracks

    # The bitwise-stable counter surface: integer accumulators whose bits
    # are identical across device-mesh layouts (each is a count of exact
    # events — no float reduction whose summation order a resharding could
    # permute). The float rings and best_key are deliberately OUT: a mean
    # over a differently-laid-out population batch may legally differ in
    # the last ulp, and a fingerprint that flickers across layouts is
    # worse than none.
    STABLE_SURFACE = (
        "generations",
        "evals",
        "nan_candidates",
        "inf_candidates",
        "nan_fitness",
        "inf_fitness",
        "best_generation",
        "stagnation",
        "restarts",
        "last_trigger",
        "sur_true_evals",
        "sur_fallback_gens",
    )

    def fingerprint(self, mstate: TelemetryState, stable: bool = False) -> str:
        """Host-side bit-identity witness over the telemetry state.

        Default (``stable=False``): SHA-256 over the EXACT bytes of every
        telemetry field (rings included). Two runs whose fingerprints
        match produced byte-identical trajectories and counters; the
        supervisor chaos law (tests/test_supervisor.py) asserts a
        faulted-and-healed run fingerprints identically to the clean run,
        and a post-mortem can cite the fingerprint as evidence of how far
        a run got before aborting. This form is layout-DEPENDENT: the
        float rings hold reduction results (mean fitness, diversity)
        whose bits can shift across device-mesh layouts.

        ``stable=True``: the attestor reduction (:func:`evox_tpu.core.
        attest.host_state_digest`) over only the integer counter surface
        (``STABLE_SURFACE``) — bitwise-identical across 1/4/8-device
        layouts because every field is an exact event count. Use this
        form for cross-layout equality laws; use the default when both
        runs share one layout and you want the rings covered too. The two
        forms are different widths (48 vs 64 hex chars) so they can never
        be confused for one another.
        """
        if stable:
            from ..core.attest import digest_hex, host_state_digest

            surface = {
                name: getattr(mstate, name) for name in self.STABLE_SURFACE
            }
            return digest_hex(host_state_digest(surface))
        import hashlib

        h = hashlib.sha256()
        for path, leaf in jax.tree_util.tree_flatten_with_path(mstate)[0]:
            h.update(jax.tree_util.keystr(path).encode())
            h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
        return h.hexdigest()

    def report(self, mstate: TelemetryState) -> dict:
        """One strictly JSON-serializable dict of every device counter
        plus the ring trajectory (non-finite values → ``None``) — the
        device half of :func:`evox_tpu.core.instrument.run_report`."""
        best = np.asarray(self.get_best_fitness(mstate))
        return sanitize_json({
            "generations": int(mstate.generations),
            "evals": int(mstate.evals),
            "best_fitness": best.tolist(),
            "best_generation": int(mstate.best_generation),
            "stagnation": int(mstate.stagnation),
            "nan_candidates": int(mstate.nan_candidates),
            "inf_candidates": int(mstate.inf_candidates),
            "nan_fitness": int(mstate.nan_fitness),
            "inf_fitness": int(mstate.inf_fitness),
            "restarts": int(mstate.restarts),
            "last_trigger": int(mstate.last_trigger),
            "sur_true_evals": int(mstate.sur_true_evals),
            "sur_fallback_gens": int(mstate.sur_fallback_gens),
            "capacity": self.capacity,
            "num_objectives": self.num_objectives,
            "trajectory": self.get_trajectory(mstate),
        })
