from .eval_monitor import EvalMonitor, EvalMonitorState
from .pop_monitor import PopMonitor
from .evoxvis_monitor import EvoXVisMonitor
from .checkpoint_monitor import CheckpointMonitor
from .profiler import StepTimerMonitor, trace as profiler_trace
from .telemetry import TelemetryMonitor, TelemetryState
from .lineage import LineageMonitor, LineageState
from .common import backend_supports_callbacks
from . import profiler

__all__ = [
    "EvalMonitor",
    "EvalMonitorState",
    "PopMonitor",
    "EvoXVisMonitor",
    "CheckpointMonitor",
    "StepTimerMonitor",
    "TelemetryMonitor",
    "TelemetryState",
    "LineageMonitor",
    "LineageState",
    "backend_supports_callbacks",
    "profiler_trace",
    "profiler",
]
