from .eval_monitor import EvalMonitor, EvalMonitorState

__all__ = ["EvalMonitor", "EvalMonitorState"]
