from .eval_monitor import EvalMonitor, EvalMonitorState
from .pop_monitor import PopMonitor
from .evoxvis_monitor import EvoXVisMonitor
from .profiler import StepTimerMonitor, trace as profiler_trace
from . import profiler

__all__ = [
    "EvalMonitor",
    "EvalMonitorState",
    "PopMonitor",
    "EvoXVisMonitor",
    "StepTimerMonitor",
    "profiler_trace",
    "profiler",
]
