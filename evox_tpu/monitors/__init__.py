from .eval_monitor import EvalMonitor, EvalMonitorState
from .pop_monitor import PopMonitor
from .evoxvis_monitor import EvoXVisMonitor
from .checkpoint_monitor import CheckpointMonitor
from .profiler import StepTimerMonitor, trace as profiler_trace
from . import profiler

__all__ = [
    "EvalMonitor",
    "EvalMonitorState",
    "PopMonitor",
    "EvoXVisMonitor",
    "CheckpointMonitor",
    "StepTimerMonitor",
    "profiler_trace",
    "profiler",
]
