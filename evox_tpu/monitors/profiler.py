"""Profiling hooks (SURVEY.md §5.1: the reference has nothing built in —
its closest analog is EvoXVisMonitor.record_time; users fall back on
``jax.profiler``). evox_tpu ships both as first-class monitors:

- :class:`StepTimerMonitor` — per-generation wall-clock durations via
  ordered host callbacks around the step (works inside ``run()``'s fused
  fori_loop too, since the callbacks are ordered effects inside the loop
  body).
- :func:`trace` — a context manager around ``jax.profiler.trace`` that
  captures a TPU/XLA profile (TensorBoard format) for any code region,
  e.g. ``with profiler.trace("/tmp/tb"): state = wf.run(state, 100)``.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Iterator, Optional

import jax
import numpy as np
from jax.experimental import io_callback

from ..core.monitor import Monitor
from .common import backend_supports_callbacks, host0_sharding


class StepTimerMonitor(Monitor):
    """Records wall-clock duration of every generation.

    Requires a backend that can execute host callbacks — NOT the tunneled
    axon TPU plugin. ``init()`` probes the backend (the same
    platform_version probe EvalMonitor's full history uses) and fails with
    a pointer to the callback-free alternatives instead of the opaque
    trace-time error the raw ``io_callback`` would produce.
    """
    # convention flag: this monitor streams through host callbacks
    # (io_callback/pure_callback) inside the traced step — consumed by
    # surfaces that cannot host callbacks at all (VectorizedWorkflow
    # fleets: a callback cannot run under vmap on ANY backend)
    uses_host_callbacks = True


    def __init__(self):
        self.start_times: list = []
        self.end_times: list = []

    def hooks(self):
        return ("pre_step", "post_step")

    def init(self, key=None):
        if not backend_supports_callbacks():
            raise RuntimeError(
                "StepTimerMonitor times generations with ordered host "
                "callbacks, which this backend (axon-tunneled TPU) cannot "
                "execute. Use TelemetryMonitor (monitors/telemetry.py) for "
                "on-device per-generation statistics and core.instrument."
                "DispatchRecorder for host-side compile/dispatch wall-clock "
                "— both are callback-free and axon-safe."
            )
        return None

    def pre_step(self, mstate: Any) -> Any:
        io_callback(
            lambda: self.start_times.append(time.monotonic()),
            None,
            sharding=host0_sharding(),
            ordered=True,
        )
        return mstate

    def post_step(self, mstate: Any, wf_state: Any) -> Any:
        io_callback(
            lambda: self.end_times.append(time.monotonic()),
            None,
            sharding=host0_sharding(),
            ordered=True,
        )
        return mstate

    def get_step_times(self) -> np.ndarray:
        """(n_generations,) seconds per generation."""
        self.flush()
        n = min(len(self.start_times), len(self.end_times))
        return np.asarray(self.end_times[:n]) - np.asarray(self.start_times[:n])

    def summary(self) -> dict:
        t = self.get_step_times()
        if t.size == 0:
            return {"steps": 0}
        return {
            "steps": int(t.size),
            "mean_s": float(t.mean()),
            "p50_s": float(np.percentile(t, 50)),
            "p99_s": float(np.percentile(t, 99)),
            "total_s": float(t.sum()),
        }


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_link: bool = False) -> Iterator[None]:
    """Capture a ``jax.profiler`` trace (XLA/TPU timeline) of the region.

    View with TensorBoard's profile plugin, or Perfetto when
    ``create_perfetto_link`` is set.
    """
    jax.profiler.start_trace(log_dir, create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
