"""CheckpointMonitor — periodic auto-checkpointing of the whole workflow
state (SURVEY.md §5.4: the reference has save/load but no auto-checkpoint
cadence; this closes that gap).

A ``post_step`` hook checks the save predicate ON DEVICE and routes through
``lax.cond`` so the device-to-host copy of the state happens only on save
generations — off-generations execute an operand-free no-op callback, so
large populations pay no transfer. Saves are atomic (tmp + rename) and the
newest ``keep`` snapshots are retained. Restore with :meth:`latest` (which
also finds checkpoints left by a previous process) or
``evox_tpu.core.state_io.load(path, backend="pickle")`` — the saved object
is the full ``StdWorkflowState`` pytree with numpy leaves, which drops
straight back into ``wf.run``.

Requires a callback-capable backend (NOT the tunneled axon TPU plugin):
``init()`` probes and fails loudly there, pointing at the callback-free
:class:`~evox_tpu.workflows.checkpoint.WorkflowCheckpointer`, which
snapshots host-side between dispatches instead.
"""

from __future__ import annotations

import os
import pickle
import warnings
from pathlib import Path
from typing import Any, List, Optional

import jax
from jax.experimental import io_callback

from ..core.monitor import Monitor
from .common import backend_supports_callbacks, host0_sharding


class CheckpointMonitor(Monitor):
    # convention flag: this monitor streams through host callbacks
    # (io_callback/pure_callback) inside the traced step — consumed by
    # surfaces that cannot host callbacks at all (VectorizedWorkflow
    # fleets: a callback cannot run under vmap on ANY backend)
    uses_host_callbacks = True

    def __init__(self, directory: str, every: int = 10, keep: int = 3):
        if every < 1:
            raise ValueError(f"every must be >= 1, got {every}")
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.every = every
        self.keep = keep
        # adopt checkpoints from a previous process so crash-recovery and
        # keep-pruning see them
        self.saved: List[Path] = sorted(self.directory.glob("ckpt_????????"))

    def init(self, key: Optional[jax.Array] = None):
        # same loud-at-init contract as StepTimerMonitor: the in-step save
        # rides io_callback, which the tunneled axon TPU backend cannot
        # execute — fail here with a pointer at the callback-free path
        # instead of hanging inside the runtime at the first save
        if not backend_supports_callbacks():
            raise RuntimeError(
                "CheckpointMonitor saves from inside the jitted step via "
                "io_callback, which this backend (axon-tunneled TPU) cannot "
                "execute. Use workflows.checkpoint.WorkflowCheckpointer — "
                "it snapshots host-side between dispatches (wf.run(..., "
                "checkpointer=...) / run_host_pipelined(..., "
                "checkpointer=...)) and is callback-free on every backend."
            )
        return None

    def hooks(self):
        return ("post_step",)

    def post_step(self, mstate: Any, wf_state: Any) -> Any:
        def save():
            io_callback(
                self._save,
                None,
                wf_state.generation,
                wf_state,
                sharding=host0_sharding(),
            )

        def skip():
            io_callback(self._noop, None, sharding=host0_sharding())

        jax.lax.cond(wf_state.generation % self.every == 0, save, skip)
        return mstate

    def _noop(self):
        pass

    def _save(self, generation, wf_state):
        gen = int(generation)
        path = self.directory / f"ckpt_{gen:08d}"
        tmp = path.with_suffix(".tmp")
        with open(tmp, "wb") as f:
            pickle.dump(wf_state, f)
        os.replace(tmp, path)  # atomic: never leave a torn checkpoint
        if path in self.saved:  # re-saving a generation after a restore
            self.saved.remove(path)
        self.saved.append(path)
        while len(self.saved) > self.keep:
            old = self.saved.pop(0)
            try:
                old.unlink()
            except FileNotFoundError:
                pass

    def latest(self) -> Any:
        """Load the newest INTACT checkpoint (None if nothing usable).

        A corrupt/torn snapshot (killed mid-write by a crash that predates
        the atomic rename, disk trouble, partial copy) is skipped with a
        warning and the next-older one is tried — restore never raises
        mid-recovery because of one bad file."""
        self.flush()
        for path in reversed(self.saved):
            try:
                with open(path, "rb") as f:
                    return pickle.load(f)
            except Exception as e:  # unpicklable/truncated/unreadable
                warnings.warn(
                    f"skipping corrupt checkpoint {path.name}: {e}",
                    stacklevel=2,
                )
        return None
