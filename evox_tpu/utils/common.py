"""Common utilities (reference: src/evox/utils/common.py).

- ``TreeAndVector``: flatten neural-net param pytrees to flat genomes and
  back (batched), the neuroevolution adapter (reference common.py:157-219).
- fitness shaping: ``rank_based_fitness`` centered ranks (common.py:135-139).
- ``parse_opt_direction``: min/max → ±1 per objective (common.py:222-245).
- pairwise distances + ``dominate_relation`` (common.py:35-107).
- ``min_by``, ``compose`` (common.py:15-24, 110-121).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence, Union

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree


class TreeAndVector:
    """Bidirectional adapter between a parameter pytree and a flat genome.

    ``to_vector``/``to_tree`` convert a single pytree; ``batched_to_tree``/
    ``batched_to_vector`` convert arrays with a leading population axis,
    suitable as workflow candidate transforms.
    """

    def __init__(self, dummy_input: Any):
        flat, self._unravel = ravel_pytree(dummy_input)
        self.dim = flat.shape[0]
        self.dtype = flat.dtype

    def to_vector(self, tree: Any) -> jax.Array:
        flat, _ = ravel_pytree(tree)
        return flat

    def to_tree(self, vector: jax.Array) -> Any:
        return self._unravel(vector)

    def batched_to_vector(self, trees: Any) -> jax.Array:
        return jax.vmap(self.to_vector)(trees)

    def batched_to_tree(self, vectors: jax.Array) -> Any:
        return jax.vmap(self.to_tree)(vectors)

    # pickling: the unravel closure is rebuilt from a dummy tree
    def __getstate__(self):
        zeros = self._unravel(jnp.zeros((self.dim,), dtype=self.dtype))
        return {"dummy": jax.device_get(zeros)}

    def __setstate__(self, state):
        self.__init__(state["dummy"])


def parse_opt_direction(opt_direction: Union[str, Sequence[str]]) -> jax.Array:
    """Map ``"min"``/``"max"`` (or a per-objective list) to a ±1 vector.

    Workflows multiply fitness by this so algorithms always minimize.
    """
    if isinstance(opt_direction, str):
        opt_direction = [opt_direction]
    signs = []
    for d in opt_direction:
        if d == "min":
            signs.append(1.0)
        elif d == "max":
            signs.append(-1.0)
        else:
            raise ValueError(f"opt_direction must be 'min' or 'max', got {d!r}")
    return jnp.asarray(signs, dtype=jnp.float32)


def rank_based_fitness(fitness: jax.Array) -> jax.Array:
    """Centered-rank fitness shaping in [-0.5, 0.5] (OpenAI-ES style)."""
    n = fitness.shape[0]
    ranks = jnp.empty_like(fitness).at[jnp.argsort(fitness)].set(jnp.arange(n, dtype=fitness.dtype))
    return ranks / (n - 1) - 0.5


def min_by(values: Sequence[jax.Array], keys: Sequence[jax.Array]):
    """Select the value whose key is minimal across several batches."""
    values = jnp.concatenate([jnp.atleast_1d(v) if v.ndim <= 1 else v for v in values])
    keys = jnp.concatenate([jnp.atleast_1d(k) for k in keys])
    i = jnp.argmin(keys)
    return values[i], keys[i]


def compose(*functions: Callable) -> Callable:
    """Left-to-right function composition: ``compose(f, g)(x) == g(f(x))``."""

    def composed(x):
        for f in functions:
            x = f(x)
        return x

    return composed


# -- pairwise distances ------------------------------------------------------

def pairwise_euclidean_dist(x: jax.Array, y: jax.Array) -> jax.Array:
    """(n, d), (m, d) → (n, m) Euclidean distances, MXU-friendly formulation."""
    x2 = jnp.sum(x * x, axis=1, keepdims=True)
    y2 = jnp.sum(y * y, axis=1, keepdims=True)
    sq = x2 - 2.0 * (x @ y.T) + y2.T
    return jnp.sqrt(jnp.maximum(sq, 0.0))


def pairwise_manhattan_dist(x: jax.Array, y: jax.Array) -> jax.Array:
    return jnp.sum(jnp.abs(x[:, None, :] - y[None, :, :]), axis=-1)


def pairwise_chebyshev_dist(x: jax.Array, y: jax.Array) -> jax.Array:
    return jnp.max(jnp.abs(x[:, None, :] - y[None, :, :]), axis=-1)


def cos_dist(x: jax.Array, y: jax.Array) -> jax.Array:
    """(n, d), (m, d) → (n, m) cosine similarity (matmul on the MXU)."""
    xn = x / jnp.linalg.norm(x, axis=1, keepdims=True)
    yn = y / jnp.linalg.norm(y, axis=1, keepdims=True)
    return xn @ yn.T


def dominate_relation(x: jax.Array, y: jax.Array) -> jax.Array:
    """Boolean (n, m) matrix: ``out[i, j]`` iff ``x[i]`` Pareto-dominates ``y[j]``.

    Minimization convention (reference: utils/common.py:94-97). Formulated
    as a static loop over the (small) objective axis so every compare is an
    (n, n) pass with the population in the TPU lane dimension — the
    broadcast-compare form puts m in the lanes and measures ~2x slower at
    n=20000 on v5e.
    """
    le = jnp.ones((x.shape[0], y.shape[0]), dtype=jnp.bool_)
    lt = jnp.zeros((x.shape[0], y.shape[0]), dtype=jnp.bool_)
    for k in range(x.shape[1]):
        xk = x[:, k][:, None]
        yk = y[:, k][None, :]
        le &= xk <= yk
        lt |= xk < yk
    return le & lt


def new_key(key: jax.Array):
    """Split a key, returning (carry_key, use_key)."""
    k1, k2 = jax.random.split(key)
    return k1, k2


def frames2gif(frames, save_path: str, duration: float = 0.1) -> None:
    """Write a list of (H, W, 3) uint8 frames to an animated GIF (reference
    utils/common.py:248-261). Uses imageio when present, else PIL."""
    import numpy as _np

    arrs = [_np.asarray(f, dtype=_np.uint8) for f in frames]
    try:
        import imageio

        with imageio.get_writer(save_path, mode="I", duration=duration) as w:
            for a in arrs:
                w.append_data(a)
        return
    except ImportError:
        pass
    from PIL import Image

    imgs = [Image.fromarray(a) for a in arrs]
    imgs[0].save(
        save_path,
        save_all=True,
        append_images=imgs[1:],
        duration=int(duration * 1000),
        loop=0,
    )
