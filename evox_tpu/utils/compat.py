"""JAX version-compatibility shims.

The repo targets whatever jax ships in the TPU container, but the public
API surface has moved between releases. Everything version-dependent is
resolved HERE, once, at import time — call sites stay on the modern
spelling and older jax versions are adapted underneath:

- ``shard_map``: promoted from ``jax.experimental.shard_map.shard_map``
  to top-level ``jax.shard_map`` in modern jax; PRE-promotion versions
  (the 0.4.x line, e.g. 0.4.37, where the bare ``jax.shard_map``
  attribute raises ``AttributeError``) have only the experimental path.
  The replication-check kwarg was also renamed ``check_rep`` ->
  ``check_vma`` along the way. The shim accepts the modern ``check_vma``
  name and translates to whatever the resolved implementation
  understands (dropping it only if neither spelling exists).
"""

from __future__ import annotations

import inspect
from typing import Any

import jax

if hasattr(jax, "shard_map"):
    _SHARD_MAP = jax.shard_map
else:  # pre-promotion jax: the experimental path is the only one
    from jax.experimental.shard_map import shard_map as _SHARD_MAP

_SM_PARAMS = frozenset(inspect.signature(_SHARD_MAP).parameters)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True, **kw: Any):
    """``jax.shard_map`` across jax versions (modern keyword spelling).

    ``check_vma`` follows the current jax name for the static
    replication/varying-mesh-axes check; on jax versions whose
    ``shard_map`` still calls it ``check_rep`` the value is passed
    through under that name."""
    if "check_vma" in _SM_PARAMS:
        kw["check_vma"] = check_vma
    elif "check_rep" in _SM_PARAMS:
        kw["check_rep"] = check_vma
    # else: a version without either spelling — nothing to forward
    return _SHARD_MAP(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
    )
