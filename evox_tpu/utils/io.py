"""x32 coercion helpers (reference src/evox/utils/io.py:6-26).

JAX defaults to 32-bit; host libraries (numpy loaders, gym envs, EnvPool)
hand back 64-bit arrays whose dtypes must match declared io_callback /
pure_callback signatures exactly."""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import numpy as np

_X64_MAP = {np.dtype(np.float64): np.float32, np.dtype(np.int64): np.int32}


def to_x32_if_needed(values: Any) -> Any:
    """Coerce 64-bit leaves of a pytree to their 32-bit counterparts.

    Matches the reference's semantics: a no-op when ``jax_enable_x64`` is
    on (64-bit data is then representable on device), and leaves without a
    64-bit dtype — including device ``jax.Array``s and Python scalars —
    pass through untouched (no host transfer, no conversion)."""
    if jax.config.jax_enable_x64:
        return values

    def fix(x):
        dt = getattr(x, "dtype", None)
        if dt is not None and np.dtype(dt) in _X64_MAP:
            return np.asarray(x).astype(_X64_MAP[np.dtype(dt)])
        return x

    return jax.tree.map(fix, values)


def x32_func_call(func: Callable) -> Callable:
    """Wrap a host function so its outputs are x32-coerced (decorator form,
    for callbacks handed to io_callback/pure_callback)."""

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        return to_x32_if_needed(func(*args, **kwargs))

    return wrapper
