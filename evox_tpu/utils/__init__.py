from .common import (
    TreeAndVector,
    parse_opt_direction,
    rank_based_fitness,
    min_by,
    compose,
    pairwise_euclidean_dist,
    pairwise_manhattan_dist,
    pairwise_chebyshev_dist,
    cos_dist,
    dominate_relation,
    new_key,
    frames2gif,
)
from .aggregation import AggregationFunction
from .optimizers import clipup, make_optimizer

__all__ = [
    "TreeAndVector",
    "parse_opt_direction",
    "rank_based_fitness",
    "min_by",
    "compose",
    "pairwise_euclidean_dist",
    "pairwise_manhattan_dist",
    "pairwise_chebyshev_dist",
    "cos_dist",
    "dominate_relation",
    "frames2gif",
    "new_key",
    "AggregationFunction",
    "clipup",
    "make_optimizer",
]
