from .common import (
    TreeAndVector,
    parse_opt_direction,
    rank_based_fitness,
    min_by,
    compose,
    pairwise_euclidean_dist,
    pairwise_manhattan_dist,
    pairwise_chebyshev_dist,
    cos_dist,
    dominate_relation,
    new_key,
    frames2gif,
)
from .aggregation import AggregationFunction
from .io import to_x32_if_needed, x32_func_call
from .optimizers import clipup, make_optimizer
from . import compat

__all__ = [
    "TreeAndVector",
    "parse_opt_direction",
    "rank_based_fitness",
    "min_by",
    "compose",
    "pairwise_euclidean_dist",
    "pairwise_manhattan_dist",
    "pairwise_chebyshev_dist",
    "cos_dist",
    "dominate_relation",
    "frames2gif",
    "to_x32_if_needed",
    "x32_func_call",
    "new_key",
    "AggregationFunction",
    "clipup",
    "make_optimizer",
    "compat",
]
