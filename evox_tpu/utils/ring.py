"""The fixed-capacity device-ring discipline, as three primitives.

One implementation behind every on-device history buffer in the stack —
EvalMonitor's device history, TelemetryMonitor's trajectory rings,
LineageMonitor's lineage rings, the SurrogateArchive, and the surrogate
fallback-event log. All share the same law: a ``(K, ...)`` buffer plus a
monotone ``count``; the write slot is ``count % K``; host readback is
chronological over the last ``min(count, K)`` writes. Fixed shapes, no
retrace as counts grow, zero host callbacks in the write path (axon-safe).

Lives in utils (the bottom layer) so both operators and monitors can use
it; monitor code imports the same names via ``monitors/common.py``.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = ["ring_write", "ring_scatter_indices", "ring_slots"]


def ring_write(buf: jax.Array, row, count, cond=None) -> jax.Array:
    """Write ``row`` at slot ``count % buf.shape[0]`` along axis 0.

    One fixed-shape ``dynamic_update_index_in_dim`` — the write cost does
    not grow with history length, and the traced program is identical for
    every generation (no retrace as ``count`` advances). ``row`` may be
    one rank lower than ``buf`` (a single slot's payload) and is cast to
    the buffer dtype. With ``cond`` (a traced bool) the write is
    conditional: the buffer passes through unchanged when false, still
    one fixed-shape program (the surrogate fallback-log pattern)."""
    slot = count % buf.shape[0]
    out = jax.lax.dynamic_update_index_in_dim(
        buf, jnp.asarray(row).astype(buf.dtype), slot, 0
    )
    if cond is not None:
        out = jnp.where(cond, out, buf)
    return out


def ring_scatter_indices(
    count, mask: jax.Array, capacity: int
) -> Tuple[jax.Array, jax.Array]:
    """Indices for a masked multi-row ring append (the SurrogateArchive
    pattern): the ``mask``-selected rows land consecutively at the ring
    head, masked-out rows get index ``capacity`` so an ``.at[idx].set(...,
    mode="drop")`` scatter drops them — one fixed-shape write regardless
    of how many rows are truly accepted. Returns ``(idx, new_count)``; the
    caller applies ``idx`` to each payload buffer."""
    mask = mask.astype(jnp.int32)
    offsets = jnp.cumsum(mask) - 1  # position among accepted rows
    idx = jnp.where(mask > 0, (count + offsets) % capacity, capacity)
    return idx, count + jnp.sum(mask)


def ring_slots(count, capacity: int) -> list:
    """Host-side chronological slot order: the last ``min(count,
    capacity)`` writes, oldest first. Eager (pulls ``count`` to host)."""
    count = int(count)
    n = min(count, capacity)
    return [(i % capacity) for i in range(count - n, count)]
