"""Gradient-step optimizers for ES-style algorithms.

The reference wraps optax behind a ``Stateful`` (``OptaxWrapper``,
reference: src/evox/utils/common.py:142-153) and hand-rolls ClipUp
(reference: src/evox/algorithms/so/es_variants/pgpe.py:34-64). Here both are
plain ``optax.GradientTransformation``s — the idiomatic JAX form — so every
ES algorithm just keeps an ``opt_state`` leaf in its own pytree state.
"""

from __future__ import annotations

from typing import NamedTuple, Union

import jax
import jax.numpy as jnp
import optax


class ClipUpState(NamedTuple):
    velocity: jax.Array


def clipup(
    learning_rate: float = 0.15,
    momentum: float = 0.9,
    max_speed: float = 0.3,
    fix_gradient_size: bool = True,
) -> optax.GradientTransformation:
    """ClipUp (Toklu et al. 2020): normalized gradient + clipped velocity."""

    def init_fn(params):
        return ClipUpState(velocity=jax.tree.map(jnp.zeros_like, params))

    def update_fn(grads, state, params=None):
        del params

        def upd(g, v):
            if fix_gradient_size:
                g = g / jnp.maximum(jnp.linalg.norm(g), 1e-12)
            v = momentum * v + learning_rate * g
            speed = jnp.linalg.norm(v)
            v = jnp.where(speed > max_speed, v * (max_speed / speed), v)
            return v

        velocity = jax.tree.map(upd, grads, state.velocity)
        # optax convention: updates are *added* to params
        return jax.tree.map(jnp.negative, velocity), ClipUpState(velocity)

    return optax.GradientTransformation(init_fn, update_fn)


def make_optimizer(
    optimizer: Union[str, optax.GradientTransformation, None],
    learning_rate: float = 0.01,
    **kwargs,
) -> optax.GradientTransformation:
    """Resolve a name ('adam', 'sgd', 'clipup', …) or pass through an optax
    transformation. Note: ES algorithms *minimize*, and gradients passed in
    are descent directions, so plain optax semantics apply."""
    if optimizer is None:
        return optax.sgd(learning_rate)
    if isinstance(optimizer, optax.GradientTransformation):
        return optimizer
    if optimizer == "clipup":
        return clipup(learning_rate=learning_rate, **kwargs)
    factory = getattr(optax, optimizer, None)
    if factory is None:
        raise ValueError(f"unknown optimizer {optimizer!r}")
    return factory(learning_rate, **kwargs)
