"""Scalarization / aggregation functions for decomposition-based MOEAs
(reference: src/evox/utils/common.py:264-310). Each maps
``(fitness (n, m), weights (n, m), ideal (m,) [, nadir (m,)])`` → ``(n,)``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

EPS = 1e-6


def weighted_sum(f: jax.Array, w: jax.Array, ideal=None, nadir=None) -> jax.Array:
    return jnp.sum(f * w, axis=-1)


def tchebycheff(f: jax.Array, w: jax.Array, ideal: jax.Array, nadir=None) -> jax.Array:
    return jnp.max(jnp.abs(f - ideal) * w, axis=-1)


def tchebycheff_norm(f: jax.Array, w: jax.Array, ideal: jax.Array, nadir: jax.Array) -> jax.Array:
    return jnp.max(jnp.abs(f - ideal) / jnp.maximum(nadir - ideal, EPS) * w, axis=-1)


def modified_tchebycheff(f: jax.Array, w: jax.Array, ideal: jax.Array, nadir=None) -> jax.Array:
    return jnp.max(jnp.abs(f - ideal) / jnp.maximum(w, EPS), axis=-1)


def pbi(f: jax.Array, w: jax.Array, ideal: jax.Array, nadir=None, theta: float = 5.0) -> jax.Array:
    norm_w = jnp.linalg.norm(w, axis=-1)
    diff = f - ideal
    d1 = jnp.sum(diff * w, axis=-1) / jnp.maximum(norm_w, EPS)
    d2 = jnp.linalg.norm(diff - d1[..., None] * w / jnp.maximum(norm_w, EPS)[..., None], axis=-1)
    return d1 + theta * d2


_FUNCS = {
    "weighted_sum": weighted_sum,
    "tchebycheff": tchebycheff,
    "tchebycheff_norm": tchebycheff_norm,
    "modified_tchebycheff": modified_tchebycheff,
    "pbi": pbi,
}


class AggregationFunction:
    """Callable wrapper selecting an aggregation function by name."""

    def __init__(self, name: str):
        if name not in _FUNCS:
            raise ValueError(f"unknown aggregation function {name!r}; options: {sorted(_FUNCS)}")
        self.name = name
        self.func = _FUNCS[name]

    def __call__(self, f, w, ideal=None, nadir=None):
        return self.func(f, w, ideal, nadir)
