"""Plotly-compatible figure JSON, with zero plotly dependency.

The reference's vis_tools (reference src/evox/vis_tools/plot.py) emit
plotly animations (frames + generation slider + play/pause buttons).
plotly is not part of this build, but a plotly figure is just JSON — so
these functions build the same figure *structure* as plain dicts:

- load them anywhere plotly exists: ``plotly.io.from_json(json.dumps(d))``
- or render standalone: :func:`save_html` writes a self-contained page
  that pulls plotly.js from the CDN — no Python plotly needed ever.

Entry points mirror the reference's four: ``plot_dec_space``,
``plot_obj_space_1d`` (min/max/median/mean curves), ``plot_obj_space_2d``
and ``plot_obj_space_3d`` (scatter per generation). Each takes the same
per-generation history lists the matplotlib helpers (plot.py) take and
returns ``{"data": ..., "layout": ..., "frames": ...}``.
"""

from __future__ import annotations

import json
from typing import Any, List, Optional, Sequence

import numpy as np


def _hist(history: Sequence[Any]) -> List[np.ndarray]:
    return [np.asarray(h) for h in history]


def _padded_range(lo: float, hi: float, pad: float = 0.1):
    r = hi - lo
    return [float(lo - pad * r), float(hi + pad * r)]


def _slider_steps(n: int):
    return [
        {
            "label": i,
            "method": "animate",
            "args": [
                [str(i)],
                {
                    "frame": {"duration": 200, "redraw": True},
                    "mode": "immediate",
                    "transition": {"duration": 200},
                },
            ],
        }
        for i in range(n)
    ]


def _animation_layout(n_frames: int) -> dict:
    """Generation slider + play/pause buttons (the reference's controls)."""
    return {
        "sliders": [
            {
                "currentvalue": {"prefix": "Generation: "},
                "pad": {"b": 1, "t": 10},
                "len": 0.8,
                "x": 0.2,
                "y": 0,
                "yanchor": "top",
                "xanchor": "left",
                "steps": _slider_steps(n_frames),
            }
        ],
        "updatemenus": [
            {
                "type": "buttons",
                "x": 0.2,
                "xanchor": "right",
                "y": 0,
                "yanchor": "top",
                "direction": "left",
                "pad": {"r": 10, "t": 30},
                "buttons": [
                    {
                        "label": "Play",
                        "method": "animate",
                        "args": [
                            None,
                            {
                                "frame": {"duration": 200, "redraw": True},
                                "fromcurrent": True,
                                "mode": "immediate",
                                "transition": {"duration": 200, "easing": "linear"},
                            },
                        ],
                    },
                    {
                        "label": "Pause",
                        "method": "animate",
                        "args": [
                            [None],
                            {
                                "frame": {"duration": 0, "redraw": True},
                                "mode": "immediate",
                                "transition": {"duration": 0},
                            },
                        ],
                    },
                ],
            }
        ],
        "legend": {"x": 1, "y": 1, "xanchor": "auto"},
        "margin": {"l": 0, "r": 0, "t": 0, "b": 0},
    }


def _scatter(x, y, z=None, mode="markers", name=None, **extra) -> dict:
    d = {
        "type": "scatter3d" if z is not None else "scatter",
        "mode": mode,
        "x": np.asarray(x).tolist(),
        "y": np.asarray(y).tolist(),
    }
    if z is not None:
        d["z"] = np.asarray(z).tolist()
    if name is not None:
        d["name"] = name
    d.update(extra)
    return d


def plot_dec_space(population_history: Sequence[Any], **layout_kw) -> dict:
    """Animated 2-D decision-space scatter (reference plot.py:6-139)."""
    hist = _hist(population_history)
    allp = np.concatenate(hist, axis=0)
    frames = [
        {
            "name": str(i),
            "data": [_scatter(pop[:, 0], pop[:, 1], marker={"color": "#636EFA"})],
        }
        for i, pop in enumerate(hist)
    ]
    layout = _animation_layout(len(hist))
    layout["xaxis"] = {"range": _padded_range(allp[:, 0].min(), allp[:, 0].max())}
    layout["yaxis"] = {"range": _padded_range(allp[:, 1].min(), allp[:, 1].max())}
    layout.update(layout_kw)
    return {"data": frames[0]["data"], "layout": layout, "frames": frames}


def plot_obj_space_1d(
    fitness_history: Sequence[Any], animation: bool = True, **layout_kw
) -> dict:
    """Min/max/median/mean fitness curves over generations (reference
    plot.py:141-318); ``animation=True`` reveals them generation by
    generation with the slider."""
    hist = _hist(fitness_history)
    gen = list(range(len(hist)))
    series = {
        "Min": [float(np.min(f)) for f in hist],
        "Max": [float(np.max(f)) for f in hist],
        "Median": [float(np.median(f)) for f in hist],
        "Average": [float(np.mean(f)) for f in hist],
    }
    full = [
        _scatter(gen, v, mode="lines", name=k) for k, v in series.items()
    ]
    base_layout = {
        "legend": {"x": 1, "y": 1, "xanchor": "auto"},
        "margin": {"l": 0, "r": 0, "t": 0, "b": 0},
    }
    if not animation:
        base_layout.update(layout_kw)
        return {"data": full, "layout": base_layout}
    frames = [
        {
            "name": str(i),
            "data": [
                _scatter(gen[: i + 1], v[: i + 1], mode="lines", name=k)
                for k, v in series.items()
            ],
        }
        for i in gen
    ]
    layout = _animation_layout(len(hist))
    layout["xaxis"] = {"range": [0, max(len(hist) - 1, 1)]}
    lo = min(min(v) for v in series.values())
    hi = max(max(v) for v in series.values())
    layout["yaxis"] = {"range": _padded_range(lo, hi)}
    layout.update(layout_kw)
    return {"data": frames[0]["data"], "layout": layout, "frames": frames}


def _obj_scatter_nd(
    fitness_history: Sequence[Any],
    problem_pf: Optional[Any],
    sort_points: bool,
    dims: int,
    **layout_kw,
) -> dict:
    hist = _hist(fitness_history)
    if sort_points:
        hist = [f[np.lexsort(f.T[::-1])] for f in hist]
    pf_traces = []
    if problem_pf is not None:
        pf = np.asarray(problem_pf)
        pf_traces.append(
            _scatter(
                *(pf[:, k] for k in range(dims)),
                name="Pareto Front",
                marker={"color": "#FFA15A", "size": 2 if dims == 3 else 4},
            )
        )
    frames = [
        {
            "name": str(i),
            "data": pf_traces
            + [
                _scatter(
                    *(f[:, k] for k in range(dims)),
                    name="Population",
                    marker={"color": "#636EFA", "size": 2 if dims == 3 else 4},
                )
            ],
        }
        for i, f in enumerate(hist)
    ]
    layout = _animation_layout(len(hist))
    allf = np.concatenate(hist, axis=0)
    axes = ["xaxis", "yaxis", "zaxis"][:dims]
    ranges = {
        ax: {"range": _padded_range(allf[:, k].min(), allf[:, k].max())}
        for k, ax in enumerate(axes)
    }
    if dims == 3:
        layout["scene"] = ranges
    else:
        layout.update(ranges)
    layout.update(layout_kw)
    return {"data": frames[0]["data"], "layout": layout, "frames": frames}


def plot_obj_space_2d(
    fitness_history: Sequence[Any],
    problem_pf: Optional[Any] = None,
    sort_points: bool = False,
    **layout_kw,
) -> dict:
    """Animated 2-objective scatter + optional true front (ref :320-451)."""
    return _obj_scatter_nd(fitness_history, problem_pf, sort_points, 2, **layout_kw)


def plot_obj_space_3d(
    fitness_history: Sequence[Any],
    problem_pf: Optional[Any] = None,
    sort_points: bool = False,
    **layout_kw,
) -> dict:
    """Animated 3-objective scatter + optional true front (ref :453+)."""
    return _obj_scatter_nd(fitness_history, problem_pf, sort_points, 3, **layout_kw)


def to_json(fig: dict) -> str:
    """Serialize a figure dict; ``plotly.io.from_json``-compatible."""
    return json.dumps(fig)


def _script_safe(obj: Any) -> str:
    """JSON for embedding inside a <script> element: '</' must not appear
    literally or a '</script>' inside any user string would terminate the
    element early (same guard plotly.io.to_html applies)."""
    return json.dumps(obj).replace("</", "<\\/")


def save_html(fig: dict, path: str, title: str = "evox_tpu") -> None:
    """Standalone HTML page rendering the figure with plotly.js from the
    CDN — viewable in any browser, no Python plotly required."""
    import html as _html

    html = f"""<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>{_html.escape(title)}</title>
<script src="https://cdn.plot.ly/plotly-2.32.0.min.js"></script></head>
<body><div id="fig" style="width:100%;height:95vh;"></div>
<script>
Plotly.newPlot("fig", {_script_safe(fig["data"])}, {_script_safe(fig["layout"])})
  .then(function(gd) {{ Plotly.addFrames(gd, {_script_safe(fig.get("frames", []))}); }});
</script></body></html>
"""
    with open(path, "w") as f:
        f.write(html)
