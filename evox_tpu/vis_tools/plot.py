"""Plot helpers for decision/objective-space trajectories (capability
parity with reference src/evox/vis_tools/plot.py, 577 LoC of plotly
animations). This build has matplotlib, not plotly, so the same four
entry points produce matplotlib figures; pass ``animated=True`` to get a
``FuncAnimation`` stepping through generations instead of a static
last-generation figure (save with ``anim.save(..., writer="pillow")``).

All functions accept a list of per-generation arrays (what
:class:`~evox_tpu.monitors.PopMonitor` / ``EvalMonitor`` histories hold).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import numpy as np


def _mpl():
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    return plt


def _as_list(history: Sequence[Any]) -> List[np.ndarray]:
    return [np.asarray(h) for h in history]


def _animate(fig, update, n_frames: int, interval: int):
    from matplotlib.animation import FuncAnimation

    return FuncAnimation(fig, update, frames=n_frames, interval=interval, blit=False)


def plot_dec_space(
    population_history: Sequence[Any],
    lb: Optional[Any] = None,
    ub: Optional[Any] = None,
    animated: bool = False,
    interval: int = 100,
    **_: Any,
):
    """2-D decision-space scatter over generations (reference
    plot.plot_dec_space)."""
    hist = _as_list(population_history)
    if hist[0].shape[1] != 2:
        raise ValueError("plot_dec_space draws 2-D decision spaces only")
    plt = _mpl()
    fig, ax = plt.subplots()
    sc = ax.scatter(hist[-1][:, 0], hist[-1][:, 1], s=12)
    if lb is not None and ub is not None:
        lb, ub = np.asarray(lb), np.asarray(ub)
        ax.set_xlim(lb[0], ub[0])
        ax.set_ylim(lb[1], ub[1])
    ax.set_xlabel("x1")
    ax.set_ylabel("x2")
    if not animated:
        return fig

    def update(i):
        sc.set_offsets(hist[i])
        ax.set_title(f"generation {i}")
        return (sc,)

    return _animate(fig, update, len(hist), interval)


def plot_obj_space_1d(
    fitness_history: Sequence[Any], animated: bool = False, interval: int = 100, **_: Any
):
    """Single-objective progress: min/mean/max fitness per generation.
    ``animated=True`` grows the curves generation by generation."""
    hist = _as_list(fitness_history)
    plt = _mpl()
    gens = np.arange(len(hist))
    mins = np.array([h.min() for h in hist])
    means = np.array([h.mean() for h in hist])
    maxs = np.array([h.max() for h in hist])
    fig, ax = plt.subplots()
    lines = [
        ax.plot(gens, mins, label="min")[0],
        ax.plot(gens, means, label="mean")[0],
        ax.plot(gens, maxs, label="max")[0],
    ]
    ax.set_xlabel("generation")
    ax.set_ylabel("fitness")
    ax.legend()
    if not animated:
        return fig

    series = (mins, means, maxs)

    def update(i):
        for line, ys in zip(lines, series):
            line.set_data(gens[: i + 1], ys[: i + 1])
        ax.set_title(f"generation {i}")
        return lines

    return _animate(fig, update, len(hist), interval)


def plot_obj_space_2d(
    fitness_history: Sequence[Any],
    problem_pf: Optional[Any] = None,
    animated: bool = False,
    interval: int = 100,
    **_: Any,
):
    """2-objective scatter (optionally against the true Pareto front)."""
    hist = _as_list(fitness_history)
    plt = _mpl()
    fig, ax = plt.subplots()
    if problem_pf is not None:
        pf = np.asarray(problem_pf)
        ax.scatter(pf[:, 0], pf[:, 1], s=4, c="lightgray", label="true PF")
    sc = ax.scatter(hist[-1][:, 0], hist[-1][:, 1], s=12, label="population")
    ax.set_xlabel("f1")
    ax.set_ylabel("f2")
    ax.legend()
    if not animated:
        return fig

    def update(i):
        sc.set_offsets(hist[i])
        ax.set_title(f"generation {i}")
        return (sc,)

    return _animate(fig, update, len(hist), interval)


def plot_obj_space_3d(
    fitness_history: Sequence[Any],
    problem_pf: Optional[Any] = None,
    animated: bool = False,
    interval: int = 100,
    **_: Any,
):
    """3-objective scatter (optionally against the true Pareto front)."""
    hist = _as_list(fitness_history)
    plt = _mpl()
    fig = plt.figure()
    ax = fig.add_subplot(projection="3d")
    if problem_pf is not None:
        pf = np.asarray(problem_pf)
        ax.scatter(pf[:, 0], pf[:, 1], pf[:, 2], s=4, c="lightgray", label="true PF")
    last = hist[-1]
    sc = ax.scatter(last[:, 0], last[:, 1], last[:, 2], s=12, label="population")
    ax.set_xlabel("f1")
    ax.set_ylabel("f2")
    ax.set_zlabel("f3")
    ax.legend()
    if not animated:
        return fig

    def update(i):
        sc._offsets3d = (hist[i][:, 0], hist[i][:, 1], hist[i][:, 2])
        ax.set_title(f"generation {i}")
        return (sc,)

    return _animate(fig, update, len(hist), interval)
