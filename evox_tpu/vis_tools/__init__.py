from . import plot, plotly_json
from .plot import (
    plot_dec_space,
    plot_obj_space_1d,
    plot_obj_space_2d,
    plot_obj_space_3d,
)

__all__ = [
    "plot",
    "plotly_json",
    "plot_dec_space",
    "plot_obj_space_1d",
    "plot_obj_space_2d",
    "plot_obj_space_3d",
]
