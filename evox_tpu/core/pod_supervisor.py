"""PodSupervisor — the fault domain of a ``jax.distributed`` pod.

PR 13 made multi-process execution first-class, but SPMD collectives are
LOCKSTEP: a SIGKILLed, wedged, or preempted worker leaves every survivor
blocked inside a collective (or a :func:`~evox_tpu.core.distributed.
process_barrier`) with no deadline, no diagnosis, and no recovery. Every
other layer already heals itself — the evaluation farm (PR 2), the
numerics (PR 3), the dispatch layer (PR 5), the serving queue (PR 11);
Fiber (arXiv 2003.11164) and "Distributed ES with Multi-Level Learning"
(arXiv 2310.05377) treat elastic membership and failure re-formation as
the defining property of a production ES fleet. This module closes the
pod-level gap, entirely host-side (no callbacks, axon-safe):

- **Heartbeats**: every member runs a daemon thread bumping a sequence
  counter in the coordinator's KV store (the ``process_barrier``
  plumbing — no XLA collective, so it works on backends that cannot run
  one). A :meth:`PodSupervisor.census` is a DOUBLE read separated by a
  probe interval: a member whose counter did not advance is not alive,
  with no cross-host clock comparison involved.
- **Collective deadlines**: :meth:`PodSupervisor.supervised` runs a
  dispatch (an SPMD-lockstep collective point — a pod ``wf.run`` chunk,
  a pod checkpoint gather) on a disposable watchdog thread with a
  wall-clock deadline — the PR-5 ``RunSupervisor`` pattern extended
  cross-process. A hung collective becomes a raised, classified error
  instead of an eternal block (the wedged thread is daemonized and
  abandoned, exactly like the PR-5 dispatch watchdog).
- **Failure classification**: deadline hits and coordination-channel
  errors are refined through the census into ``worker_dead`` (a peer's
  heartbeat stopped), ``hung_collective`` (every peer alive, the
  collective itself is wedged), or ``coordinator_loss`` (the KV channel
  is gone — the coordinator process died). Anything that is NOT a
  pod-domain fault propagates unchanged, and
  :func:`~evox_tpu.workflows.supervisor.classify_error` folds the pod
  errors into the PR-5 taxonomy (barrier/collective deadlines →
  ``deadline``, a classified :class:`PodFailureError` → ``fatal``: a
  single process cannot heal a pod fault in-process — the escalation
  continues OUTSIDE, in the re-formation driver).
- **Escalation ladder** (the cross-process continuation of PR 5's):
  deadline-abort → survivor census → post-mortem
  (:class:`PodFailureError` carries classification, census, detection
  latency, event tail; every process exits loudly instead of blocking)
  → **pod re-formation** by the respawn driver
  (``tools/_multihost_worker.PodManager``: fresh coordinator
  rendezvous, ``create_pod_mesh`` over the survivor device set) →
  :meth:`PodSupervisor.resume_from_barrier` restores the newest intact
  pod-barrier snapshot, which the PR-5/13 topology-portable manifests
  make process-count-portable — an ``n``-process run killed mid-flight
  replays on the survivor set reproducing the uninjured trajectory.
  ``ShardedES(n_shards=...)`` keeps the sampling law fixed across the
  shrink (bit-identity up to psum order) whenever the survivor DEVICE
  total divides the pinned ``n_shards``; survivor counts that don't
  divide resume on the REPLICATED twin of the same law (``mesh=None``,
  same ``n_shards`` — the documented sharded≡replicated contract), so
  no survivor count is unrecoverable.
- **Preemption-graceful drain**: :meth:`install_sigterm_drain` turns a
  cloud preemption notice (SIGTERM) into a COORDINATED drain — the
  in-flight chunk finishes, every member agrees on the decision at the
  next :meth:`chunk_boundary` (process 0 arbitrates through the KV
  store, so no member drains while another continues into a collective
  nobody will join), a final barrier checkpoint is fsynced, background
  lanes drain, and the process exits 0. The resumed run equals the
  uninterrupted run (the drain law, tests/test_pod_chaos.py).

Membership transitions (join / census / failure / reform / resume /
drain) are journaled through the PR-11 WAL discipline
(:class:`~evox_tpu.workflows.journal.RunJournal` ``pod_*`` kinds,
process-0-writes — the checkpoint commit discipline), surface as the
``pod_supervisor`` section of ``run_report()`` (schema v9, validated by
tools/check_report.py) and as ``supervisor:pod:*`` instant markers in
``write_chrome_trace``. The whole layer is opt-in: with no pod
supervisor configured, single-process and dryrun paths are bit-identical
to the pre-ISSUE-14 tree.

No reference analog: the reference's fault story is Ray actor restart
(PARITY row 59); re-forming a ``jax.distributed`` pod on the survivor
set is the documented deviation this module implements.
"""

from __future__ import annotations

import signal
import threading
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence

from .distributed import (
    BarrierTimeoutError,
    _dist_client,
    _INTROSPECT_FAILED,
    process_barrier,
)

__all__ = [
    "WORKER_DEAD",
    "HUNG_COLLECTIVE",
    "COORDINATOR_LOSS",
    "INTEGRITY_DISSENT",
    "POD_FAILURE_CLASSES",
    "POD_EVENT_KINDS",
    "JAXLIB_COORD_ABORT_S",
    "CollectiveDeadlineError",
    "PodFailureError",
    "PodSupervisor",
]

#: jaxlib's coordination client aborts the PROCESS (C++ LOG(FATAL) →
#: SIGABRT) roughly this many seconds after it stops reaching the
#: coordinator (PERF_NOTES §25) — the hard ceiling a supervisor deadline
#: must undercut in a real multi-process pod to classify the failure
#: before the runtime kills the classifier
JAXLIB_COORD_ABORT_S = 10.0
#: safety margin under the abort ceiling (the ~10 s figure is empirical,
#: not an API contract)
_COORD_ABORT_MARGIN_S = 0.5

# pod-domain failure classes (strings so post-mortems stay plain JSON) —
# the cross-process refinement of the PR-5 transient/oom/deadline/fatal
# taxonomy (classify_error folds these back into it)
WORKER_DEAD = "worker_dead"
HUNG_COLLECTIVE = "hung_collective"
COORDINATOR_LOSS = "coordinator_loss"
# a pod whose chunk result lost a 2-of-3 integrity vote (ISSUE 20): the
# hardware answered in time with WRONG bits — quarantined through the
# same census/re-formation machinery as a dead worker, but the class is
# distinct because the remedy differs (drop the dissenting result, keep
# the pod out of voted dispatches until re-formed)
INTEGRITY_DISSENT = "integrity_dissent"
POD_FAILURE_CLASSES = (
    WORKER_DEAD,
    HUNG_COLLECTIVE,
    COORDINATOR_LOSS,
    INTEGRITY_DISSENT,
)

#: every event kind a PodSupervisor records (run_report section +
#: ``supervisor:pod:*`` trace markers; tools/check_report.py pins the set)
POD_EVENT_KINDS = (
    "join",
    "census",
    "barrier_timeout",
    "failure",
    "drain_requested",
    "drain",
    "reform",
    "resume",
)

# event kind -> cumulative counter it increments (the RunSupervisor shape)
_COUNTER_FOR = {
    "census": "censuses",
    "barrier_timeout": "barrier_timeouts",
    "failure": "failures",
    "drain": "drains",
    "reform": "reforms",
    "resume": "resumes",
}

# message fingerprints of a dead/dying coordination channel — the errors
# the KV client raises once the coordinator process is gone (gRPC status
# names + the coordination agent's own state strings)
_CHANNEL_PATTERNS = (
    "coordination service",
    "coordination agent",
    "coordinator",
    "unavailable",
    "connection reset",
    "connection refused",
    "connection closed",
    "broken pipe",
    "socket closed",
    "failed to connect",
    "shutting down",
)


class CollectiveDeadlineError(RuntimeError):
    """A supervised pod collective exceeded its wall-clock deadline —
    some peer never entered (or never left) the lockstep dispatch. The
    cross-process twin of :class:`~evox_tpu.workflows.supervisor.
    DispatchDeadlineError`; ``classify_error`` folds it into the
    ``deadline`` class, and the pod supervisor refines it via the
    heartbeat census."""


class PodFailureError(RuntimeError):
    """The pod supervisor diagnosed a pod-domain fault. ``classification``
    is one of :data:`POD_FAILURE_CLASSES`; ``post_mortem`` is the
    structured account (entry point, census, detection latency, event
    tail) every process writes out before aborting — the input to the
    re-formation driver's survivor decision. ``classify_error`` reads it
    as ``fatal``: no single process can heal a pod fault in-process."""

    def __init__(self, message: str, classification: str, post_mortem: dict):
        super().__init__(message)
        self.classification = classification
        self.post_mortem = post_mortem


def _watchdog_call(
    fn: Callable,
    deadline_s: Optional[float],
    label: str,
    make_timeout: Optional[Callable[[str, float], BaseException]] = None,
    thread_prefix: str = "pod",
):
    """Run ``fn()`` on a disposable daemon thread with a wall-clock bound
    (None = call inline). THE disposable-watchdog implementation — the
    PR-5 dispatch watchdog (workflows/supervisor.py) delegates here with
    its own timeout exception via ``make_timeout``, so the two fault
    domains share one body. A hung call occupies its thread forever, so
    the thread is abandoned, never pooled; spawn cost is noise next to
    any cross-host collective or tunneled dispatch."""
    if deadline_s is None:
        return fn()
    box: dict = {}
    done = threading.Event()

    def target():
        try:
            box["value"] = fn()
        except BaseException as e:  # noqa: BLE001 — re-raised on the caller
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(
        target=target, daemon=True, name=f"{thread_prefix}:{label}"
    )
    t.start()
    if not done.wait(deadline_s):
        if make_timeout is not None:
            raise make_timeout(label, deadline_s)
        raise CollectiveDeadlineError(
            f"pod collective '{label}' exceeded its {deadline_s:g} s "
            "deadline; the worker thread is abandoned (a lockstep "
            "collective with a missing peer never completes)"
        )
    if "error" in box:
        raise box["error"]
    return box["value"]


def _is_channel_error(exc: BaseException) -> bool:
    if isinstance(exc, ConnectionError):
        return True
    msg = str(exc).lower()
    return any(p in msg for p in _CHANNEL_PATTERNS)


class PodSupervisor:
    """Per-process liveness, collective deadlines, coordinated drain, and
    shrink-and-resume for one ``jax.distributed`` pod member.

    Args:
        deadline_s: wall-clock bound for supervised collective points
            (chunk dispatches, pod checkpoint gathers) and the default
            barrier timeout. ``None`` disables the watchdog (barriers
            keep the ``process_barrier`` default).
        heartbeat_interval_s: KV heartbeat period. The census probe
            waits ``2 × interval + 0.2 s`` between its two reads, so
            detection latency after a deadline hit is roughly
            ``deadline_s + 2 × interval`` (PERF_NOTES §25 budgets it).
        journal: a :class:`~evox_tpu.workflows.journal.RunJournal`, a
            directory path for one, or ``None``. Membership transitions
            are appended as ``pod_*`` records by PROCESS 0 only (the
            single-writer WAL discipline; a re-formed pod's new process
            0 ADOPTS the chain and continues it).
        epoch: pod formation counter — 0 for the original pod, bumped by
            the re-formation driver for each survivor pod. Namespaces
            the heartbeat/intent keys so a re-formed pod (new
            coordinator, fresh KV store — or a reused one) never reads
            a dead epoch's records.
        namespace: KV prefix for heartbeat / drain-intent / decision
            keys.
        clock: monotonic seconds source (``time.perf_counter`` — the
            recorder/supervisor clock, so trace tracks align).

    Single-process (or ``jax.distributed`` not initialized) every method
    degrades to its local meaning: census is ``{0: True}``, barriers and
    drain arbitration are local, ``supervised`` keeps only the watchdog.
    That is what the in-process 8→4 shrink-resume analog in
    tests/test_pod_supervisor.py drives on the virtual mesh.
    """

    def __init__(
        self,
        deadline_s: Optional[float] = None,
        checkpoint_deadline_s: Optional[float] = None,
        heartbeat_interval_s: float = 0.5,
        journal: Any = None,
        epoch: int = 0,
        namespace: str = "evox_tpu/pod",
        clock: Callable[[], float] = time.perf_counter,
        metrics: Any = None,
    ):
        if heartbeat_interval_s <= 0:
            raise ValueError(
                f"heartbeat_interval_s must be > 0, got {heartbeat_interval_s}"
            )
        self.deadline_s = deadline_s
        # a pod checkpoint save legitimately outlasts a chunk dispatch
        # (full host gather + pickle + fsync — ~6.6 s per 256 MB on the
        # tunneled env — vs a single compiled chunk), so watchdogging it
        # with the chunk deadline would abort a HEALTHY pod at every
        # cadence. Default: 6× the chunk deadline; a dead peer mid-save
        # is usually caught earlier anyway by the save's own commit
        # barrier (WorkflowCheckpointer.barrier_timeout_s, classified)
        self.checkpoint_deadline_s = (
            checkpoint_deadline_s
            if checkpoint_deadline_s is not None
            else (6.0 * deadline_s if deadline_s is not None else None)
        )
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.epoch = int(epoch)
        self.namespace = f"{namespace}/e{self.epoch}"
        # serving-plane flight recorder (PR 16): when attached, every
        # pod transition mirrors into the metrics plane (`pod.*`
        # counters, heartbeat-publish latency histogram), pod barriers
        # stamp stream `barrier` records (the merged-trace alignment
        # anchors), and failures carry the black-box ring in their
        # post-mortem. None (default) changes nothing.
        self.metrics = metrics
        self._clock = clock
        self._created = clock()
        try:
            # runtime-state read, not a backend touch (a supervisor may
            # be built before any device work — see _dist_process_info)
            from .distributed import _dist_process_info

            self.process_id, self.process_count = _dist_process_info()
        except Exception:  # pragma: no cover - backend not initializable
            self.process_id, self.process_count = 0, 1
        # PERF_NOTES §25: in a REAL multi-process pod, jaxlib's own
        # coordination client LOG(FATAL)s the process ~10 s after it
        # stops reaching the coordinator — a supervisor deadline whose
        # worst-case detection latency (deadline + census probe slack)
        # cannot beat that race yields SIGABRT post-mortems instead of
        # classified failures. Validate at construction: clamp + warn
        # rather than silently losing the race. Single-process
        # supervisors (tests, local watchdogs) have no coordination
        # client, so any deadline is fine there.
        if (
            self.deadline_s is not None
            and self.process_count > 1
        ):
            slack = 2.0 * self.heartbeat_interval_s + 0.2  # census probe
            budget = JAXLIB_COORD_ABORT_S - _COORD_ABORT_MARGIN_S - slack
            if self.deadline_s > budget:
                clamped = max(budget, self.heartbeat_interval_s)
                warnings.warn(
                    f"PodSupervisor deadline_s={self.deadline_s} cannot "
                    f"win the race against jaxlib's ~{JAXLIB_COORD_ABORT_S:g} s "
                    "coordination heartbeat abort (PERF_NOTES §25): "
                    f"detection needs deadline + {slack:.1f} s census "
                    f"slack; clamping to {clamped:.2f} s so pod faults "
                    "are classified instead of dying by SIGABRT",
                    stacklevel=2,
                )
                self.deadline_s = clamped
                if checkpoint_deadline_s is None:
                    self.checkpoint_deadline_s = 6.0 * clamped
        self._journal = self._resolve_journal(journal)
        self._hb_seq = 0
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._drain_flag = threading.Event()
        self._drain_reason: Optional[str] = None
        self._drain_event_recorded = False
        self._prev_boundary_gen: Optional[int] = None
        self._prev_sigterm: Any = None
        self._lock = threading.Lock()
        self._outcome: Optional[str] = None
        self.events: List[dict] = []
        self.counters: Dict[str, int] = {
            "heartbeats": 0,
            "censuses": 0,
            "barriers": 0,
            "barrier_timeouts": 0,
            "supervised_calls": 0,
            "failures": 0,
            "drains": 0,
            "reforms": 0,
            "resumes": 0,
        }

    # ------------------------------------------------------------- plumbing
    def _resolve_journal(self, journal: Any):
        if journal is None:
            return None
        if isinstance(journal, (str, bytes)) or hasattr(journal, "__fspath__"):
            from ..workflows.journal import RunJournal  # deferred (layering)

            return RunJournal(str(journal))
        return journal

    def _client(self):
        client = _dist_client()
        if client is _INTROSPECT_FAILED or self.process_count <= 1:
            return None
        return client

    def _event(self, kind: str, **fields: Any) -> None:
        assert kind in POD_EVENT_KINDS, kind
        ev = {"t": round(self._clock() - self._created, 6), "event": kind}
        ev.update(fields)
        with self._lock:
            self.events.append(ev)
            counter = _COUNTER_FOR.get(kind)
            if counter is not None:
                self.counters[counter] += 1
        if self.metrics is not None:
            self.metrics.count(f"pod.{kind}")

    def _journal_event(self, kind: str, **payload: Any) -> None:
        """WAL the transition (process-0-writes). A journal append
        failing must never mask the event being journaled — the run's
        own failure path is usually already unwinding."""
        if self._journal is None or self.process_id != 0:
            return
        try:
            self._journal.append(
                kind, epoch=self.epoch, process_id=self.process_id, **payload
            )
        except Exception:  # pragma: no cover - disk-full etc.
            pass

    # ----------------------------------------------------------- heartbeats
    def start(self) -> "PodSupervisor":
        """Join the pod: record membership, start the heartbeat thread.
        Idempotent — a second call neither duplicates the join
        event/WAL record nor spawns a second beater; returns self so
        ``PodSupervisor(...).start()`` chains."""
        if self._hb_thread is None or not self._hb_thread.is_alive():
            self._event(
                "join",
                process_id=self.process_id,
                process_count=self.process_count,
                epoch=self.epoch,
            )
            self._journal_event(
                "pod_join", process_count=self.process_count
            )
            self._hb_stop.clear()
            self.beat()  # first beat lands before any peer can census us
            self._hb_thread = threading.Thread(
                target=self._beat_loop, daemon=True, name="pod:heartbeat"
            )
            self._hb_thread.start()
        return self

    def stop(self) -> None:
        """Stop the heartbeat thread (idempotent; the key simply stops
        advancing, which is exactly what a census reads as death — a
        clean exit should barrier first, not rely on this)."""
        self._hb_stop.set()
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except (ValueError, OSError):  # pragma: no cover - non-main thread
                pass
            self._prev_sigterm = None

    def beat(self) -> int:
        """Advance and publish this process's heartbeat counter once."""
        self._hb_seq += 1
        self.counters["heartbeats"] += 1
        client = self._client()
        if client is not None:
            # overwrite-in-place: one key per member per epoch, no growth
            t0 = self._clock()
            client.key_value_set(
                f"{self.namespace}/hb/{self.process_id}",
                str(self._hb_seq),
                allow_overwrite=True,
            )
            if self.metrics is not None:
                # heartbeat PUBLISH latency: the KV round-trip each beat
                # pays — the earliest coordination-plane distress signal
                # (it climbs before collectives start timing out)
                self.metrics.observe(
                    "pod.heartbeat_ms", (self._clock() - t0) * 1e3
                )
        return self._hb_seq

    #: consecutive failed beats before the heartbeat thread gives up —
    #: ONE transient KV blip must not freeze a healthy member's counter
    #: (a frozen counter reads as worker_dead in every peer's census)
    _HB_MAX_CONSECUTIVE_FAILURES = 5

    def _beat_loop(self) -> None:
        failures = 0
        while not self._hb_stop.wait(self.heartbeat_interval_s):
            try:
                self.beat()
                failures = 0
            except Exception:
                # transient blip: keep beating (the same tolerance
                # classify_failure applies to channel errors). Only a
                # PERSISTENT failure — the coordinator is really gone —
                # ends the loop; even then the MAIN thread classifies
                # at its next collective point with a census — a
                # heartbeat thread must never decide the process's fate
                failures += 1
                if failures >= self._HB_MAX_CONSECUTIVE_FAILURES:
                    return

    def _read_heartbeats(self) -> Dict[int, int]:
        client = self._client()
        if client is None:
            return {self.process_id: self._hb_seq}
        return {
            int(k.rsplit("/", 1)[-1]): int(v)
            for k, v in client.key_value_dir_get(f"{self.namespace}/hb/")
        }

    def census(self, probe_s: Optional[float] = None) -> Dict[int, bool]:
        """Who is alive? Two KV reads separated by ``probe_s`` (default
        ``2 × heartbeat_interval + 0.2 s``): a member whose sequence
        counter advanced between them is alive; one whose counter is
        frozen (SIGKILL, SIGSTOP, machine gone) or absent is not. No
        cross-host clock is compared — the counter IS the liveness
        signal. Raises whatever the KV channel raises when the
        coordinator itself is gone (callers classify that as
        :data:`COORDINATOR_LOSS`)."""
        if self._client() is None:
            alive = {self.process_id: True}
        else:
            probe = (
                2.0 * self.heartbeat_interval_s + 0.2
                if probe_s is None
                else probe_s
            )
            first = self._read_heartbeats()
            if probe > 0:
                time.sleep(probe)
            second = self._read_heartbeats()
            alive = {}
            for p in range(self.process_count):
                if p == self.process_id:
                    alive[p] = True
                    continue
                s0, s1 = first.get(p), second.get(p)
                alive[p] = s0 is not None and s1 is not None and s1 > s0
        self._event(
            "census",
            alive=sorted(p for p, a in alive.items() if a),
            dead=sorted(p for p, a in alive.items() if not a),
        )
        return alive

    # --------------------------------------------------------- classification
    def classify_failure(self, exc: BaseException) -> Optional[str]:
        """Refine ``exc`` into a pod-domain class, or ``None`` when it is
        not a pod fault (a numerics error, an OOM — the caller's own
        ladder owns those). Deadlines (collective or barrier) consult
        the census: a frozen peer ⇒ :data:`WORKER_DEAD`, everyone alive
        ⇒ :data:`HUNG_COLLECTIVE`; a dead KV channel anywhere ⇒
        :data:`COORDINATOR_LOSS`."""
        if isinstance(exc, PodFailureError):
            return exc.classification
        deadline = isinstance(
            exc, (CollectiveDeadlineError, BarrierTimeoutError)
        )
        if not deadline and not _is_channel_error(exc):
            return None
        try:
            alive = self.census()
        except Exception:
            return COORDINATOR_LOSS
        dead = [p for p, a in alive.items() if not a]
        if dead:
            return WORKER_DEAD
        if deadline:
            return HUNG_COLLECTIVE
        # channel error but the census works and everyone is alive: a
        # transient RPC blip, not a pod fault — let the caller retry
        return None

    def _fail(
        self, entry: str, exc: BaseException, t0: float
    ) -> PodFailureError:
        classification = self.classify_failure(exc)
        if classification is None:
            raise exc
        detect_s = round(self._clock() - t0, 6)
        census_ev = next(
            (e for e in reversed(self.events) if e["event"] == "census"), None
        )
        self._event(
            "failure",
            entry=entry,
            classification=classification,
            detect_s=detect_s,
            error=str(exc)[:300],
        )
        self._outcome = "failed"
        post_mortem = {
            "entry": entry,
            "classification": classification,
            "detect_s": detect_s,
            "error": f"{type(exc).__name__}: {exc}",
            "census": (
                {k: v for k, v in census_ev.items() if k in ("alive", "dead")}
                if census_ev
                else None
            ),
            "epoch": self.epoch,
            "process_id": self.process_id,
            "process_count": self.process_count,
            "events_tail": self.events[-20:],
        }
        if self.metrics is not None:
            # every pod post-mortem carries the flight-recorder tail:
            # the last queue/executor/pod records before the fault,
            # recoverable from the surviving stream even if this
            # process dies before the error is printed
            self.metrics.event(
                "pod.failure", entry=entry, classification=classification
            )
            post_mortem["flight_recorder"] = self.metrics.tail(20)
        self._journal_event(
            "pod_failure",
            entry=entry,
            classification=classification,
            detect_s=detect_s,
        )
        return PodFailureError(
            f"pod fault at '{entry}': {classification} "
            f"(detected in {detect_s:g} s): {type(exc).__name__}: {exc}",
            classification=classification,
            post_mortem=post_mortem,
        )

    # ------------------------------------------------------ collective points
    def supervised(
        self,
        fn: Callable[[], Any],
        entry: str = "collective",
        deadline_s: Optional[float] = None,
    ) -> Any:
        """Run one SPMD-lockstep collective point (a pod chunk dispatch,
        a checkpoint gather) under the disposable-watchdog deadline.
        A deadline hit or a dead coordination channel is classified
        through the census and raised as :class:`PodFailureError` with a
        full post-mortem; any other failure propagates untouched (the
        PR-5 ladder, numerics guards, etc. own those)."""
        dl = self.deadline_s if deadline_s is None else deadline_s
        self.counters["supervised_calls"] += 1
        t0 = self._clock()
        try:
            return _watchdog_call(fn, dl, entry)
        except (KeyboardInterrupt, SystemExit):
            raise
        except PodFailureError:
            raise
        except BaseException as e:  # noqa: BLE001 — classified below
            raise self._fail(entry, e, t0) from e

    def barrier(self, name: str, timeout_s: Optional[float] = None) -> None:
        """A classified :func:`~evox_tpu.core.distributed.process_barrier`:
        the timeout (default ``deadline_s``) raises through the census as
        a :class:`PodFailureError` naming the missing processes."""
        tmo = timeout_s if timeout_s is not None else self.deadline_s
        self.counters["barriers"] += 1
        t0 = self._clock()
        try:
            if tmo is None:
                process_barrier(name)
            else:
                process_barrier(name, timeout_s=tmo)
            if self.metrics is not None:
                # a REAL pod rendezvous just released: every member
                # stamps the same barrier name into its own stream at
                # (approximately) the same instant — the clock-alignment
                # anchor merge_pod_streams aligns the per-process trace
                # tracks on
                self.metrics.barrier(
                    f"pod:{name}",
                    wait_ms=round((self._clock() - t0) * 1e3, 3),
                )
        except (KeyboardInterrupt, SystemExit):
            raise
        except BarrierTimeoutError as e:
            self._event(
                "barrier_timeout",
                name=name,
                missing=list(e.missing),
                arrived=list(e.arrived),
            )
            raise self._fail(f"barrier:{name}", e, t0) from e
        except Exception as e:  # channel death inside the barrier RPC
            raise self._fail(f"barrier:{name}", e, t0) from e

    # ------------------------------------------------------------------ drain
    def install_sigterm_drain(self) -> None:
        """Route SIGTERM (the cloud preemption notice) into the
        coordinated drain: the handler only sets a flag — the in-flight
        chunk finishes, the next :meth:`chunk_boundary` arbitrates the
        pod-wide decision, the driver writes a final barrier checkpoint
        and exits 0. Must be called from the main thread (CPython signal
        rule); the previous handler is restored by :meth:`stop`."""
        self._prev_sigterm = signal.signal(
            signal.SIGTERM, lambda signum, frame: self.request_drain("SIGTERM")
        )

    def request_drain(self, reason: str = "api") -> None:
        """Ask the pod to drain at the next chunk boundary (signal-safe:
        only sets a flag; the KV publication happens on the main thread
        inside :meth:`chunk_boundary`)."""
        self._drain_flag.set()
        self._drain_reason = reason

    def drain_requested(self) -> bool:
        return self._drain_flag.is_set()

    def chunk_boundary(
        self, generation: int, timeout_s: Optional[float] = None
    ) -> str:
        """The per-chunk rendezvous: every member publishes its drain
        intent, passes the classified barrier, and PROCESS 0 arbitrates
        one pod-wide decision through the KV store — ``"continue"`` or
        ``"drain"``. Arbitration is what keeps the decision SPMD-
        consistent: a SIGTERM landing between two members' flag reads
        must not let one drain while the other walks into a collective
        nobody will join. Single-process the decision is the local flag."""
        gen = int(generation)
        client = self._client()
        if client is None:
            decision = "drain" if self._drain_flag.is_set() else "continue"
        else:
            ns = self.namespace
            t0 = self._clock()
            try:
                client.key_value_set(
                    f"{ns}/intent/{gen}/{self.process_id}",
                    "drain" if self._drain_flag.is_set() else "ok",
                    allow_overwrite=True,
                )
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                raise self._fail(f"boundary:{gen}", e, t0) from e
            self.barrier(f"{ns}/gen{gen}", timeout_s)
            tmo = timeout_s if timeout_s is not None else self.deadline_s
            tmo_ms = int((tmo if tmo is not None else 120.0) * 1000)
            try:
                if self.process_id == 0:
                    intents = client.key_value_dir_get(f"{ns}/intent/{gen}/")
                    decision = (
                        "drain"
                        if any(v == "drain" for _, v in intents)
                        else "continue"
                    )
                    client.key_value_set(
                        f"{ns}/decision/{gen}", decision, allow_overwrite=True
                    )
                    # KV hygiene (the process_barrier arrival-record
                    # discipline): this gen's intents are consumed, and
                    # by reaching THIS barrier every member has read the
                    # PREVIOUS boundary's decision — long pod runs must
                    # not accrete nprocs+1 keys per chunk forever.
                    # Best-effort: cleanup failure must never fail a
                    # healthy boundary
                    try:
                        for k, _ in intents:
                            client.key_value_delete(k)
                        if self._prev_boundary_gen is not None:
                            client.key_value_delete(
                                f"{ns}/decision/{self._prev_boundary_gen}"
                            )
                    except Exception:
                        pass
                    self._prev_boundary_gen = gen
                else:
                    decision = client.blocking_key_value_get(
                        f"{ns}/decision/{gen}", tmo_ms
                    )
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                raise self._fail(f"decision:{gen}", e, t0) from e
        if decision == "drain" and not self._drain_event_recorded:
            self._drain_event_recorded = True
            self._event(
                "drain_requested",
                generation=gen,
                reason=self._drain_reason or "peer",
            )
        return decision

    def note_integrity_dissent(
        self, generation: int, entry: str = "verify", dissent: str = "first"
    ) -> None:
        """Record that a 2-of-3 integrity vote outvoted THIS pod's chunk
        result (``dissent`` names which dispatch lost: ``"first"`` — the
        original chunk, ``"redo"`` — the re-dispatch). The result was
        already discarded by the voter, so nothing is raised: the pod
        stays schedulable but carries the ``integrity_dissent`` failure
        event for the re-formation driver / fleet health policy to act
        on (the same census-driven quarantine lane as a dead worker)."""
        self._event(
            "failure",
            entry=entry,
            classification=INTEGRITY_DISSENT,
            generation=int(generation),
            dissent=dissent,
        )
        if self.metrics is not None:
            self.metrics.event(
                "pod.failure", entry=entry, classification=INTEGRITY_DISSENT
            )
        self._journal_event(
            "pod_failure",
            entry=entry,
            classification=INTEGRITY_DISSENT,
            generation=int(generation),
            dissent=dissent,
        )

    def note_drained(self, generation: int, checkpointed: bool = True) -> None:
        """Record the completed drain: the driver exits 0 after this —
        preemption became a clean stop. ``checkpointed=False`` records
        honestly that NO final barrier snapshot exists (the run had no
        checkpointer) — the resumed-equals-uninterrupted law then has
        nothing to resume from, and the event/journal record says so
        instead of implying a durable snapshot that was never written."""
        self._event(
            "drain", generation=int(generation), checkpointed=bool(checkpointed)
        )
        self._journal_event(
            "pod_drain",
            generation=int(generation),
            checkpointed=bool(checkpointed),
        )
        self._outcome = "drained"

    # ------------------------------------------------------------ re-formation
    def note_reform(self, survivors: Sequence[int], from_epoch: int) -> None:
        """Record that THIS pod is the re-formation of ``from_epoch`` on
        the ``survivors`` process set (called by the re-formed member,
        normally with the driver-provided survivor list)."""
        self._event(
            "reform",
            survivors=sorted(int(p) for p in survivors),
            from_epoch=int(from_epoch),
            epoch=self.epoch,
        )
        self._journal_event(
            "pod_reform",
            survivors=sorted(int(p) for p in survivors),
            from_epoch=int(from_epoch),
        )

    def resume_from_barrier(
        self,
        wf: Any,
        checkpointer: Any,
        expect_like: Any = None,
        allow_config_mismatch: bool = False,
    ) -> Any:
        """Restore the newest intact pod-barrier snapshot onto the
        CURRENT (re-formed, possibly shrunken) topology and record the
        resume. ``checkpointer`` is a
        :class:`~evox_tpu.workflows.checkpoint.WorkflowCheckpointer` or
        its directory; placement follows the state's own sharding
        annotations on ``wf.mesh`` (``wf.place_restored`` when the
        workflow defines it — tenant fleets), exactly the PR-5
        topology-portable resume law, now driven by the pod ladder.
        Raises ``RuntimeError`` when no intact snapshot exists (the
        re-formation driver treats that as unrecoverable)."""
        from ..workflows.checkpoint import _as_checkpointer, restore_layouts

        ckpt = _as_checkpointer(checkpointer)
        snapshot = ckpt.latest(
            expect_like=expect_like,
            allow_config_mismatch=allow_config_mismatch,
        )
        if snapshot is None:
            raise RuntimeError(
                f"resume_from_barrier: no intact pod-barrier snapshot in "
                f"{ckpt.directory} — nothing to re-form from"
            )
        placer = getattr(wf, "place_restored", None)
        if placer is not None:
            state = placer(snapshot)
        else:
            state = restore_layouts(snapshot, mesh=getattr(wf, "mesh", None))
        gen = int(snapshot.generation)
        self._event("resume", generation=gen)
        self._journal_event("pod_resume", generation=gen)
        self._outcome = "resumed"
        return state

    # ------------------------------------------------------------------ report
    def report(self) -> dict:
        """The ``pod_supervisor`` section of ``run_report()`` (schema v9,
        strict JSON). ``outcome``: ``clean`` (nothing fired),
        ``drained`` (graceful preemption stop), ``failed`` (pod fault
        diagnosed, post-mortem written), ``resumed`` (this pod re-formed
        and restored a barrier snapshot)."""
        return {
            "process_id": self.process_id,
            "process_count": self.process_count,
            "epoch": self.epoch,
            "deadline_s": self.deadline_s,
            "checkpoint_deadline_s": self.checkpoint_deadline_s,
            "heartbeat_interval_s": self.heartbeat_interval_s,
            "outcome": self._outcome or "clean",
            "counters": dict(self.counters),
            "events": list(self.events),
        }

    def markers(self) -> List[dict]:
        """Events as ``supervisor:pod:*`` instant markers for
        :func:`~evox_tpu.core.instrument.write_chrome_trace` (same
        ``perf_counter`` clock as the recorder)."""
        return [
            {
                "t_abs": self._created + ev["t"],
                "name": f"supervisor:pod:{ev['event']}",
                "args": {
                    k: v for k, v in ev.items() if k not in ("t", "event")
                },
            }
            for ev in self.events
        ]
