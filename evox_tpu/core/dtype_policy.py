"""Mixed-precision storage policy — bf16 at rest, f32 in flight.

PR 4's roofline analytics showed the streaming legs (CSO at 331 GB/s,
55% of the measured 607 GB/s HBM ceiling) are memory-bound: every
generation round-trips the whole population/velocity/fitness state
through HBM. evosax (PAPERS.md) made the same observation for batched
JAX strategies — memory traffic per generation is the budget. The
cheapest lever is to halve the bytes: store the per-individual state in
``bfloat16`` and compute in ``float32``.

Design (mirrors the ``field(sharding=...)`` layout convention):

- Fields declare eligibility with ``field(storage=True)`` —
  population-leading float arrays (population, fitness, velocity,
  offspring, per-individual noise) — or explicitly opt out with
  ``storage=False`` (must-stay-f32). Replicated strategy parameters
  (CMA mean/covariance/paths, step sizes) are simply never annotated,
  so CMA's eigh and rank-µ update paths stay f32 by construction.
- The workflow applies the policy at the *state boundary*: annotated
  leaves are cast to ``policy.storage`` when the step's new state is
  formed (fused into the same tree walk as ``constrain_state``) and
  cast back to ``policy.compute`` at step entry. All algorithm math —
  sorting, reductions, means, covariance — therefore runs in the
  compute dtype; only the loop-carried bytes shrink.
- The default workflow policy is ``None``: every ``apply_*`` helper
  returns the state object *unchanged* (same python object, no tree
  traversal), so the f32 path is bit-identical to the pre-policy
  behavior (golden-pinned in tests/test_dtype_policy.py).

Accuracy contract: bf16 storage quantizes the carried per-individual
state once per generation (~3 decimal digits). Convergence-threshold
tests (CLAUDE.md convention) gate the mode per algorithm — see
tests/test_dtype_policy.py for CMAES / CSO / NSGA-II. Integer, bool and
PRNG-key leaves are never cast.

Axon-safe by construction: pure ``convert_element_type`` inside traced
code, no host callbacks (pinned by tests/test_no_host_callbacks.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

__all__ = [
    "DtypePolicy",
    "BF16_STORAGE",
    "apply_storage",
    "apply_compute",
    "storage_eligible_fields",
    "policy_report",
]


@dataclasses.dataclass(frozen=True)
class DtypePolicy:
    """``(storage, compute)`` dtype pair threaded through a workflow.

    ``storage``: dtype of storage-annotated leaves *at rest* (between
    generations, in the fused-run carry, in checkpoints).
    ``compute``: dtype those leaves are upcast to at the step boundary,
    and the dtype every reduction/accumulation therefore runs in.

    Hashable and static: policies ride workflow objects (not states), so
    switching policy means a new compiled program — by design, exactly
    like resizing a population. The no-op policy (storage == compute)
    and ``None`` compile identical programs.
    """

    storage: Any = jnp.float32
    compute: Any = jnp.float32

    def __post_init__(self):
        object.__setattr__(self, "storage", jnp.dtype(self.storage))
        object.__setattr__(self, "compute", jnp.dtype(self.compute))
        for name in ("storage", "compute"):
            dt = getattr(self, name)
            if not jnp.issubdtype(dt, jnp.floating):
                raise ValueError(
                    f"DtypePolicy.{name} must be a floating dtype, got {dt}"
                )

    @property
    def is_noop(self) -> bool:
        return self.storage == self.compute

    def report(self) -> dict:
        """JSON-serializable description (lands in run_report/bench)."""
        return {
            "storage": str(self.storage.name),
            "compute": str(self.compute.name),
            "active": not self.is_noop,
        }


# the one policy the bench / docs talk about: bf16 at rest, f32 in flight
BF16_STORAGE = DtypePolicy(storage=jnp.bfloat16, compute=jnp.float32)


def _storage_flag_for_path(state: Any, path: tuple) -> bool:
    """Resolve the deepest ``field(storage=...)`` annotation along a
    pytree key path (same walk as distributed._spec_for_path — inner
    annotations override outer ones; absent means ineligible)."""
    obj, flag = state, False
    for key in path:
        if isinstance(key, jax.tree_util.GetAttrKey) and dataclasses.is_dataclass(obj):
            f = obj.__dataclass_fields__.get(key.name)
            if f is not None and "storage" in f.metadata:
                flag = bool(f.metadata["storage"])
            obj = getattr(obj, key.name)
        elif isinstance(key, jax.tree_util.SequenceKey):
            obj = obj[key.idx]
        elif isinstance(key, jax.tree_util.DictKey):
            obj = obj[key.key]
        else:
            break
    return flag


def _castable(leaf: Any) -> bool:
    dt = getattr(leaf, "dtype", None)
    return dt is not None and jnp.issubdtype(dt, jnp.floating)


def _apply(state: Any, policy: Optional[DtypePolicy], target_attr: str) -> Any:
    if policy is None or policy.is_noop:
        return state  # same object: the default path traces identically
    target = getattr(policy, target_attr)

    def cast(path, leaf):
        if _castable(leaf) and _storage_flag_for_path(state, path):
            return jax.lax.convert_element_type(leaf, target)
        return leaf

    return jax.tree_util.tree_map_with_path(cast, state)


def apply_storage(state: Any, policy: Optional[DtypePolicy]) -> Any:
    """Cast storage-annotated float leaves to the storage dtype — the
    at-rest form carried between generations / in checkpoints. Exact
    no-op (same object) when ``policy`` is ``None`` or storage == compute.
    """
    return _apply(state, policy, "storage")


def apply_compute(state: Any, policy: Optional[DtypePolicy]) -> Any:
    """Cast storage-annotated float leaves to the compute dtype — the
    step-entry upcast, so all algorithm math runs full-precision."""
    return _apply(state, policy, "compute")


def storage_eligible_fields(state: Any) -> dict:
    """``{field_path: bool}`` of every *annotated* dataclass field in
    ``state`` (recursing into nested dataclasses) — the introspection
    surface tests/test_state_contracts.py enforces the convention with.
    Unannotated fields are absent (ineligible by default)."""
    out: dict = {}

    def walk(obj: Any, prefix: str) -> None:
        if not dataclasses.is_dataclass(obj):
            return
        for f in dataclasses.fields(obj):
            path = f"{prefix}{f.name}"
            if "storage" in f.metadata:
                out[path] = bool(f.metadata["storage"])
            walk(getattr(obj, f.name), f"{path}.")

    walk(state, "")
    return out


def policy_report(workflow: Any) -> dict:
    """The ``dtype_policy`` section for run_report / bench JSON, duck-
    typed off ``workflow.dtype_policy`` (absent → explicit f32 default,
    so reports always state the precision they ran at)."""
    policy = getattr(workflow, "dtype_policy", None)
    if policy is None:
        return {"storage": "float32", "compute": "float32", "active": False}
    return policy.report()
