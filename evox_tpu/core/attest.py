"""Compute-integrity layer: on-device state attestation and forensics.

No reference analog (PARITY row 64): the reference stack (SURVEY §2.9)
trusts every bit the accelerator returns. This module gives the stack an
answer to "is the state still the state we computed?":

- ``state_digest`` — a jitted, callback-free, fixed-shape digest of a
  state pytree. Built from **bitwise-stable reductions only** (wrapping
  u32 sum / XOR / min / max over position-mixed bit-cast uint32 views,
  plus exact nan/inf counts). Float sums are reassociation-dependent
  across GSPMD layouts (PERF_NOTES §15), so a digest built on them would
  false-alarm on every mesh change; modular-integer reductions are
  associative *and* commutative exactly, so the digest is a function of
  the logical value alone — layout-invariant by construction (law tested
  across 1/4/8-device meshes and ShardedES).
- ``host_state_digest`` — an exact NumPy mirror: digesting a fetched host
  copy gives bitwise the same 6 words as the device digest. This is what
  lets checkpoint manifests and journal barriers attest state cheaply.
- ``StateAttestor`` — a Monitor that records the digest ring at a cadence
  inside the fused loop (traced ``lax.cond``, the TelemetryMonitor ring
  discipline via ``utils/ring.py``; zero host callbacks, axon-safe), and
  the digest engine handed to ``GenerationExecutor.run_fused``'s
  ``verify_every=K`` voted re-dispatch rung.
- ``IntegrityError`` — corruption is its own ``classify_error`` class
  (``"integrity"``): never retried into acceptance, always an abort or an
  explicit heal (vote / barrier fallback).
- ``bisect_divergence`` — host-side forensic: replay from the last
  attested barrier at halving chunk sizes to name the first divergent
  generation and the leaf paths whose digests split.

Digest layout (``DIGEST_WORDS = 6`` uint32 words)::

    [ wrapping-sum(mix(w ^ i·φ ^ salt)),        # order-sensitive, exact
      wrapping-sum(mix(w ^ i·φ ^ salt ^ c2)),   # independent mixed channel
      min(w), max(w),                           # raw word envelope
      nan_count, inf_count ]                    # exact counts, float leaves

(the second per-leaf channel is a second independently-mixed modular sum
rather than an elementwise XOR-reduce: GSPMD's partitioned reduce only
supports the standard monoids, and modular add is exactly as
layout-invariant; across *leaves* word 1 combines by true bit-sliced XOR)

where ``w`` is the leaf's canonical uint32 word stream (4-byte dtypes are
bit-cast; 2-byte bit-cast to u16 then zero-extended; 1-byte via u8;
8-byte split into u32 pairs), ``i`` the global flat logical index, ``φ``
the golden-ratio constant, and ``salt`` a static hash of the leaf's
keystr path (so swapping two identically-shaped leaves changes the
digest). Per-leaf digests combine across leaves by the same exact
reductions. Hex form is the 48-char concatenation of the 6 words.
"""

from __future__ import annotations

import hashlib
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .monitor import Monitor
from .struct import PyTreeNode, field
from ..utils.ring import ring_slots, ring_write

__all__ = [
    "DIGEST_WORDS",
    "AttestState",
    "IntegrityError",
    "StateAttestor",
    "bisect_divergence",
    "digest_hex",
    "host_leaf_digests",
    "host_state_digest",
    "leaf_digests",
    "state_digest",
    "verify_state_digest",
]

DIGEST_WORDS = 6

_PHI = 0x9E3779B1  # 2**32 / golden ratio — index decorrelation
_MIX1 = 0x85EBCA6B  # murmur3 finalizer constants
_MIX2 = 0xC2B2AE35
_CH2 = 0x5BD1E995  # second-channel tweak (murmur2 constant)
_MIN_IDENTITY = 0xFFFFFFFF  # empty-leaf min/max identities


class IntegrityError(RuntimeError):
    """State bits do not match their attestation.

    Distinct from transient dispatch failures: corruption must never be
    "retried" into acceptance, so ``classify_error`` maps this to the
    ``"integrity"`` class which the supervisor ladder aborts (or the
    caller heals explicitly — voted re-dispatch, barrier fallback)."""

    def __init__(
        self,
        message: str,
        *,
        generation: Optional[int] = None,
        leaves: Sequence[str] = (),
        where: Optional[str] = None,
    ):
        super().__init__(message)
        self.generation = generation
        self.leaves = tuple(leaves)
        self.where = where


# -- word canonicalization ---------------------------------------------------


def _mix32(h):
    """Murmur3 finalizer over uint32 — bijective, elementwise, exact."""
    h = h ^ (h >> 16)
    h = h * jnp.uint32(_MIX1)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(_MIX2)
    h = h ^ (h >> 16)
    return h


def _mix32_np(h: np.ndarray) -> np.ndarray:
    h = h.astype(np.uint32, copy=True)
    h ^= h >> np.uint32(16)
    h *= np.uint32(_MIX1)
    h ^= h >> np.uint32(13)
    h *= np.uint32(_MIX2)
    h ^= h >> np.uint32(16)
    return h


def _xor_reduce(h):
    """Exact XOR-reduce expressed through add-monoid reductions (bit-sliced
    parity), because GSPMD's partitioned ``lax.reduce`` rejects custom
    reduction computations. Only used over small stacks (one row per leaf),
    never over full leaf word streams."""
    h = h.reshape(-1)
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = (h[:, None] >> shifts[None, :]) & jnp.uint32(1)
    parity = jnp.sum(bits, axis=0, dtype=jnp.uint32) & jnp.uint32(1)
    return jnp.sum(parity << shifts, dtype=jnp.uint32)


def _salt(name: str) -> int:
    return int.from_bytes(hashlib.sha256(name.encode()).digest()[:4], "little")


def _leaf_words(x) -> jax.Array:
    """Canonical uint32 word stream for one leaf (traced).

    Canonicalization mirrors ``_leaf_words_np`` bit-for-bit: weak Python
    scalars take jnp's x32 defaults; 1-byte dtypes route through uint8 on
    BOTH sides (a direct int8→uint32 astype would sign-extend on device
    but zero-extend through a host u8 view)."""
    x = jnp.asarray(x)
    if jnp.issubdtype(x.dtype, jax.dtypes.prng_key):
        x = jax.random.key_data(x)  # typed keys digest as their uint32 words
    dt = x.dtype
    if dt == jnp.bool_:
        w = x.astype(jnp.uint32)
    elif dt.itemsize == 1:
        w = jax.lax.bitcast_convert_type(x, jnp.uint8).astype(jnp.uint32)
    elif dt.itemsize == 2:
        w = jax.lax.bitcast_convert_type(x, jnp.uint16).astype(jnp.uint32)
    elif dt.itemsize == 4:
        w = jax.lax.bitcast_convert_type(x, jnp.uint32)
    elif dt.itemsize == 8:
        w = jax.lax.bitcast_convert_type(x, jnp.uint32)  # trailing dim 2
    else:
        raise TypeError(f"state_digest: unsupported leaf dtype {dt}")
    return w.reshape(-1)


def _canon_np(x) -> np.ndarray:
    # Mirror jnp.asarray's x32 weak-type defaults for bare Python scalars
    # so host and device word streams agree.
    if isinstance(x, (bool, np.bool_)):
        return np.asarray(x, np.bool_)
    if isinstance(x, int) and not isinstance(x, np.generic):
        return np.asarray(x, np.int32)
    if isinstance(x, float) and not isinstance(x, np.generic):
        return np.asarray(x, np.float32)
    if isinstance(x, jax.Array) and jnp.issubdtype(x.dtype, jax.dtypes.prng_key):
        # Typed PRNG keys refuse np.asarray; digest their uint32 key words
        # (bit-identical to the device path's jax.random.key_data).
        return np.asarray(jax.device_get(jax.random.key_data(x)))
    return np.asarray(x)


def _leaf_words_np(x) -> np.ndarray:
    x = np.ascontiguousarray(_canon_np(x))
    dt = x.dtype
    if dt == np.bool_:
        w = x.astype(np.uint32)
    elif dt.itemsize == 1:
        w = x.view(np.uint8).astype(np.uint32)
    elif dt.itemsize == 2:
        w = x.view(np.uint16).astype(np.uint32)
    elif dt.itemsize in (4, 8):
        w = x.view(np.uint32)
    else:
        raise TypeError(f"host_state_digest: unsupported leaf dtype {dt}")
    return w.reshape(-1)


def _float_counts(x):
    x = jnp.asarray(x)
    if jnp.issubdtype(x.dtype, jnp.floating) and x.size:
        return (
            jnp.sum(jnp.isnan(x), dtype=jnp.uint32),
            jnp.sum(jnp.isinf(x), dtype=jnp.uint32),
        )
    return jnp.uint32(0), jnp.uint32(0)


def _float_counts_np(x: np.ndarray):
    if np.issubdtype(x.dtype, np.floating) and x.size:
        return (
            np.sum(np.isnan(x), dtype=np.uint32),
            np.sum(np.isinf(x), dtype=np.uint32),
        )
    return np.uint32(0), np.uint32(0)


def _empty_leaf_digest_np(salt: int) -> np.ndarray:
    h = _mix32_np(np.asarray([salt ^ _PHI, salt ^ _PHI ^ _CH2], np.uint32))
    return np.asarray([h[0], h[1], _MIN_IDENTITY, 0, 0, 0], np.uint32)


def _leaf_digest(x, salt: int) -> jax.Array:
    w = _leaf_words(x)
    if w.shape[0] == 0:  # static — no retrace risk
        return jnp.asarray(_empty_leaf_digest_np(salt))
    nan, inf = _float_counts(x)
    idx = jnp.arange(w.shape[0], dtype=jnp.uint32)
    base = w ^ (idx * jnp.uint32(_PHI)) ^ jnp.uint32(salt)
    return jnp.stack(
        [
            jnp.sum(_mix32(base), dtype=jnp.uint32),
            jnp.sum(_mix32(base ^ jnp.uint32(_CH2)), dtype=jnp.uint32),
            jnp.min(w),
            jnp.max(w),
            nan,
            inf,
        ]
    )


def _leaf_digest_np(x, salt: int) -> np.ndarray:
    x = _canon_np(x)
    w = _leaf_words_np(x)
    if w.shape[0] == 0:
        return _empty_leaf_digest_np(salt)
    nan, inf = _float_counts_np(np.asarray(x))
    idx = np.arange(w.shape[0], dtype=np.uint32)
    base = w ^ (idx * np.uint32(_PHI)) ^ np.uint32(salt)
    return np.asarray(
        [
            np.sum(_mix32_np(base), dtype=np.uint32),
            np.sum(_mix32_np(base ^ np.uint32(_CH2)), dtype=np.uint32),
            np.min(w),
            np.max(w),
            nan,
            inf,
        ],
        np.uint32,
    )


_EMPTY_TREE = np.asarray([0, 0, _MIN_IDENTITY, 0, 0, 0], np.uint32)


def _named_leaves(tree):
    return [
        (jax.tree_util.keystr(path), leaf)
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]
        if leaf is not None
    ]


def _combine(digests: List) -> Any:
    d = jnp.stack(digests)  # (L, 6) uint32
    return jnp.stack(
        [
            jnp.sum(d[:, 0], dtype=jnp.uint32),
            _xor_reduce(d[:, 1]),
            jnp.min(d[:, 2]),
            jnp.max(d[:, 3]),
            jnp.sum(d[:, 4], dtype=jnp.uint32),
            jnp.sum(d[:, 5], dtype=jnp.uint32),
        ]
    )


def _combine_np(digests: List[np.ndarray]) -> np.ndarray:
    d = np.stack(digests).astype(np.uint32)
    return np.asarray(
        [
            np.sum(d[:, 0], dtype=np.uint32),
            np.bitwise_xor.reduce(d[:, 1]),
            np.min(d[:, 2]),
            np.max(d[:, 3]),
            np.sum(d[:, 4], dtype=np.uint32),
            np.sum(d[:, 5], dtype=np.uint32),
        ],
        np.uint32,
    )


# -- public digest API ---------------------------------------------------------


def state_digest(tree) -> jax.Array:
    """Layout-invariant ``uint32[6]`` digest of a pytree (traced/jittable)."""
    named = _named_leaves(tree)
    if not named:
        return jnp.asarray(_EMPTY_TREE)
    return _combine([_leaf_digest(leaf, _salt(name)) for name, leaf in named])


def host_state_digest(tree) -> np.ndarray:
    """Exact NumPy mirror of ``state_digest`` (fetches device leaves)."""
    named = _named_leaves(tree)
    if not named:
        return _EMPTY_TREE.copy()
    return _combine_np(
        [_leaf_digest_np(jax.device_get(leaf), _salt(name)) for name, leaf in named]
    )


def leaf_digests(tree) -> Dict[str, jax.Array]:
    """Per-leaf ``uint32[6]`` digests keyed by keystr path (traced/jittable)."""
    return {
        name: _leaf_digest(leaf, _salt(name)) for name, leaf in _named_leaves(tree)
    }


def host_leaf_digests(tree) -> Dict[str, str]:
    """Per-leaf hex digests computed on host (exact device mirror)."""
    return {
        name: digest_hex(_leaf_digest_np(jax.device_get(leaf), _salt(name)))
        for name, leaf in _named_leaves(tree)
    }


def digest_hex(words) -> str:
    """48-char hex form of a 6-word digest."""
    w = np.asarray(jax.device_get(words)).astype(np.uint32).reshape(-1)
    if w.shape[0] != DIGEST_WORDS:
        raise ValueError(f"digest must have {DIGEST_WORDS} words, got {w.shape}")
    return "".join(f"{int(v):08x}" for v in w)


def verify_state_digest(
    state,
    expected: Union[str, Any],
    *,
    generation: Optional[int] = None,
    where: str = "state",
    expected_leaves: Optional[Dict[str, str]] = None,
) -> str:
    """Verify ``state``'s bits against an attestation; raise on mismatch.

    ``expected`` is a hex digest (or 6-word array). When a per-leaf
    attestation map is supplied the error names the exact leaf paths whose
    digests split. Returns the verified hex digest on success."""
    got = digest_hex(host_state_digest(state))
    want = expected if isinstance(expected, str) else digest_hex(expected)
    if got == want:
        return got
    split: List[str] = []
    if expected_leaves:
        actual = host_leaf_digests(state)
        split = [
            name
            for name in sorted(set(actual) | set(expected_leaves))
            if actual.get(name) != expected_leaves.get(name)
        ]
    at = f" at generation {generation}" if generation is not None else ""
    leaf_note = f" (splitting leaves: {', '.join(split)})" if split else ""
    raise IntegrityError(
        f"integrity violation in {where}{at}: digest {got} != attested "
        f"{want}{leaf_note}",
        generation=generation,
        leaves=split,
        where=where,
    )


# -- the attestor monitor ------------------------------------------------------


class AttestState(PyTreeNode):
    """On-device attestation ring (all replicated — tiny)."""

    count: jax.Array = field(sharding=P())
    ring_digest: jax.Array = field(sharding=P())
    ring_generation: jax.Array = field(sharding=P())


class StateAttestor(Monitor):
    """Digest the workflow state at a cadence, on device, inside the loop.

    Attach as a monitor: every ``every`` generations the post_step hook
    records ``(generation, digest)`` in a fixed-capacity ring (one traced
    ``lax.cond`` around a ``ring_write`` — no retrace, no host callbacks,
    axon-safe). The same object is the digest engine for the executor's
    ``verify_every`` voted re-dispatch rung and for journal/checkpoint
    attestation.

    ``select`` narrows the digested subtree (e.g. ``lambda s: s.algo``).
    The default digests the workflow state *minus its ``monitors`` field*:
    monitor states are observability artifacts (and include this ring
    itself, which updates after the digest is taken — including it would
    make a recorded digest unreproducible from the state it describes).
    """

    def __init__(
        self,
        every: int = 10,
        capacity: int = 64,
        select: Optional[Callable[[Any], Any]] = None,
    ):
        if every < 1:
            raise ValueError(f"every must be >= 1, got {every}")
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.every = int(every)
        self.capacity = int(capacity)
        self.select = select
        self._jit_digest = jax.jit(self._digest_traced)
        self._jit_attest = jax.jit(
            lambda s: (
                state_digest(self._selected(s)),
                leaf_digests(self._selected(s)),
            )
        )

    # -- digest engine --------------------------------------------------------

    def _selected(self, state):
        if self.select is not None:
            return self.select(state)
        try:
            return state.replace(monitors=())
        except (AttributeError, TypeError):
            return state

    def _digest_traced(self, state):
        return state_digest(self._selected(state))

    def digest(self, state) -> jax.Array:
        """Device digest of (the selected subtree of) ``state`` — jitted."""
        return self._jit_digest(state)

    def digest_hex(self, state) -> str:
        return digest_hex(self.digest(state))

    def host_digest_hex(self, state) -> str:
        """NumPy-mirror digest (no device dispatch; fetches leaves)."""
        return digest_hex(host_state_digest(self._selected(state)))

    def leaf_digest_hex(self, state) -> Dict[str, str]:
        return host_leaf_digests(self._selected(state))

    def attestation(self, state) -> Dict[str, Any]:
        """One jitted call -> ``{"digest": hex, "leaves": {path: hex}}``.

        Both digests come back from a single dispatch, and only the
        L x 6 uint32 digest words are fetched — never the state itself
        (a full-state fetch on a synchronous barrier path is ruinously
        expensive over the tunneled-TPU transport).
        """
        combined, leaves = jax.device_get(self._jit_attest(state))
        return {
            "digest": digest_hex(np.asarray(combined)),
            "leaves": {k: digest_hex(np.asarray(v)) for k, v in leaves.items()},
        }

    def verify(self, state, attestation, *, generation=None, where="state") -> str:
        """Check ``state`` against a journaled attestation record.

        ``attestation`` is a hex digest or an :meth:`attestation` dict.
        Returns the (matching) hex digest, or raises :class:`IntegrityError`
        naming the first splitting leaves. Host-side — safe on restored
        (unplaced) pytrees.
        """
        want = attestation["digest"] if isinstance(attestation, dict) else attestation
        expected_leaves = (
            attestation.get("leaves") if isinstance(attestation, dict) else None
        )
        return verify_state_digest(
            self._selected(state),
            want,
            generation=generation,
            where=where,
            expected_leaves=expected_leaves,
        )

    # -- monitor surface -------------------------------------------------------

    def hooks(self) -> Sequence[str]:
        return ("post_step",)

    def init(self, key=None) -> AttestState:
        return AttestState(
            count=jnp.zeros((), jnp.int32),
            ring_digest=jnp.zeros((self.capacity, DIGEST_WORDS), jnp.uint32),
            ring_generation=jnp.full((self.capacity,), -1, jnp.int32),
        )

    def post_step(self, mstate: AttestState, wf_state) -> AttestState:
        gen = jnp.asarray(wf_state.generation, jnp.int32)
        due = (gen % self.every) == 0

        def _attest(ms):
            words = state_digest(self._selected(wf_state))
            return ms.replace(
                count=ms.count + 1,
                ring_digest=ring_write(ms.ring_digest, words, ms.count),
                ring_generation=ring_write(ms.ring_generation, gen, ms.count),
            )

        return jax.lax.cond(due, _attest, lambda ms: ms, mstate)

    # -- host readback ---------------------------------------------------------

    def ledger(self, mstate: AttestState) -> List[Dict[str, Any]]:
        """Chronological ``[{generation, digest}]`` over the ring."""
        count = int(jax.device_get(mstate.count))
        gens = np.asarray(jax.device_get(mstate.ring_generation))
        digs = np.asarray(jax.device_get(mstate.ring_digest))
        return [
            {"generation": int(gens[s]), "digest": digest_hex(digs[s])}
            for s in ring_slots(count, self.capacity)
        ]

    def integrity_report(self, mstate: AttestState) -> Dict[str, Any]:
        """run_report ``integrity`` section contribution (host-side)."""
        ring = self.ledger(mstate)
        return {
            "enabled": True,
            "every": self.every,
            "capacity": self.capacity,
            "attestations": int(jax.device_get(mstate.count)),
            "ring": ring,
        }

    def journal_ring(self, mstate: AttestState, journal) -> int:
        """Append one ``attest`` record per ring entry to a RunJournal."""
        ring = self.ledger(mstate)
        for rec in ring:
            journal.append(
                "attest", generation=rec["generation"], digest=rec["digest"]
            )
        return len(ring)


# -- divergence forensics ------------------------------------------------------


def _journal_records(journal_dir) -> List[Dict[str, Any]]:
    if isinstance(journal_dir, (list, tuple)):
        return list(journal_dir)
    journal = journal_dir
    if not hasattr(journal, "records"):
        from ..workflows.journal import RunJournal  # deferred: layering

        journal = RunJournal(os.fspath(journal_dir))
    return journal.records()


def _pod_context(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Epoch and pod census from the journal's pod lifecycle records."""
    epoch, census = 0, None
    for rec in records:
        if not isinstance(rec, dict):
            continue
        if "epoch" in rec:
            epoch = max(epoch, int(rec["epoch"]))
        if rec.get("kind") == "census":
            census = rec.get("alive", rec.get("census"))
        elif rec.get("kind") == "pod_join":
            census = rec.get("world", census)
    return {"epoch": epoch, "pod_census": census}


def _load_attestations(records: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Distill ``[{generation, digest}]`` sorted by generation from journal
    records or an explicit ledger list (deduped, last record wins —
    re-attestation after a heal supersedes)."""
    by_gen: Dict[int, str] = {}
    for rec in records:
        kind = rec.get("kind") if isinstance(rec, dict) else None
        if kind == "attest" or (kind is None and "digest" in rec):
            by_gen[int(rec["generation"])] = str(rec["digest"])
        elif kind == "chunk_complete" and isinstance(rec.get("attest"), dict):
            att = rec["attest"]
            if "digest" in att:
                by_gen[int(rec["generation"])] = str(att["digest"])
    return [
        {"generation": g, "digest": by_gen[g]} for g in sorted(by_gen)
    ]


def bisect_divergence(
    journal_dir,
    *,
    wf,
    start_state,
    suspect: Optional[Callable[[Any, int], Any]] = None,
    attestor: Optional[StateAttestor] = None,
    report_to=None,
) -> Dict[str, Any]:
    """Name the first generation where a run's bits went wrong.

    ``journal_dir`` holds the suspect run's attestations (``attest``
    records or ``chunk_complete`` barriers with an ``attest`` field; an
    explicit ``[{generation, digest}]`` ledger is also accepted).
    ``start_state`` is the trusted state at the last attested barrier
    (digest-verified against the journal when attested there); ``wf.run``
    replays the honest trajectory from it.

    Phase 1 (windowing) replays through the journaled attestations to find
    the first cadence window whose digest splits. Phase 2 (bisection)
    needs a reproducible suspect leg — ``suspect(state, n_steps)`` re-runs
    the faulty path (a sticky-fault pod, or a fault-injected drive in
    tests) — and advances both legs at halving chunk sizes until the first
    divergent generation is pinned exactly. Without ``suspect`` (transient
    SDC) the report carries the window only.

    Returns the structured report consumed by run_report schema v14
    ``integrity.bisection`` and the ``integrity.*`` FlightRecorder gauges;
    ``report_to`` (a workflow) additionally stashes it on
    ``._integrity_forensics`` for run_report pickup.
    """
    att = attestor if attestor is not None else StateAttestor()
    records = _journal_records(journal_dir)
    ledger = _load_attestations(records)

    cur = int(jax.device_get(start_state.generation))
    start_gen = cur
    report: Dict[str, Any] = {
        "enabled": True,
        "barrier_generation": start_gen,
        **_pod_context(records),
        "attestations_checked": 0,
        "chunks_replayed": 0,
        "generations_replayed": 0,
        "first_divergent_generation": None,
        "window": None,
        "leaves": [],
        "reproducible": None,
        "verdict": "clean",
    }
    if report_to is not None:
        report_to._integrity_forensics = report

    # Trust check: the start state must match its own journaled attestation.
    at_start = [r for r in ledger if r["generation"] == start_gen]
    if at_start and att.digest_hex(start_state) != at_start[-1]["digest"]:
        raise IntegrityError(
            f"bisect_divergence: start state at generation {start_gen} does "
            f"not match its journaled attestation — no trusted barrier to "
            f"replay from",
            generation=start_gen,
            where="bisect_divergence",
        )

    # Phase 1: replay the honest leg through the journaled attestations.
    ref_state = start_state
    g_lo, g_hi = start_gen, None
    for rec in ledger:
        gen = rec["generation"]
        if gen <= cur:
            continue
        ref_state = wf.run(ref_state, gen - cur)
        report["chunks_replayed"] += 1
        report["generations_replayed"] += gen - cur
        cur = gen
        report["attestations_checked"] += 1
        if att.digest_hex(ref_state) == rec["digest"]:
            g_lo = gen
        else:
            g_hi = gen
            break
    if g_hi is None:
        return report  # every attestation matches the honest replay

    report["window"] = [g_lo + 1, g_hi]
    report["verdict"] = "detected"
    if suspect is None:
        return report

    # Phase 2: synchronized two-leg halving replay inside (g_lo, g_hi].
    ref_state = start_state
    if g_lo > start_gen:
        ref_state = wf.run(ref_state, g_lo - start_gen)
        report["generations_replayed"] += g_lo - start_gen
        report["chunks_replayed"] += 1
    sus_state = ref_state
    g, hi = g_lo, g_hi
    first_divergent = None
    while g < hi:
        step = max(1, (hi - g) // 2)
        ref_next = wf.run(ref_state, step)
        sus_next = suspect(sus_state, step)
        report["chunks_replayed"] += 2
        report["generations_replayed"] += 2 * step
        if att.digest_hex(ref_next) == att.digest_hex(sus_next):
            g += step
            ref_state, sus_state = ref_next, sus_next
            if g == hi:
                # The suspect leg did not reproduce the journaled fault.
                report["reproducible"] = False
                return report
        else:
            hi = g + step
            if step == 1:
                first_divergent = hi
                ref_leaves = host_leaf_digests(att._selected(ref_next))
                sus_leaves = host_leaf_digests(att._selected(sus_next))
                report["leaves"] = [
                    name
                    for name in sorted(set(ref_leaves) | set(sus_leaves))
                    if ref_leaves.get(name) != sus_leaves.get(name)
                ]
                break
    report["reproducible"] = True
    report["first_divergent_generation"] = first_divergent
    return report
