"""Pytree dataclasses — the foundation of every state object in evox_tpu.

Design note (TPU-first): unlike the reference's hierarchical ``State`` dict
tree with node-ids and ``use_state`` re-scoping (reference:
src/evox/core/state.py, src/evox/core/module.py), evox_tpu states are plain
typed, frozen dataclasses registered as JAX pytrees. Composition is by
*fields* (a workflow state holds the algorithm state as a field), stacking is
by ``jax.vmap`` over ``init``, and sharding is by ``jax.NamedSharding`` over
leaves. This keeps every state a first-class pytree that `jit`, `vmap`,
`shard_map`, `pjit` and orbax all understand natively.
"""

from __future__ import annotations

import dataclasses
from typing import Any, TypeVar

import jax

_T = TypeVar("_T")

__all__ = [
    "field",
    "static_field",
    "pytree_dataclass",
    "PyTreeNode",
    "replace",
]


def field(
    *,
    static: bool = False,
    sharding: Any = None,
    storage: Any = None,
    **kwargs: Any,
) -> dataclasses.Field:
    """A dataclass field; ``static=True`` marks it as pytree metadata
    (hashable aux data, not traced).

    ``sharding``: an optional ``jax.sharding.PartitionSpec`` declaring how
    this field's arrays lay out over the workflow mesh (e.g.
    ``P("pop")`` for population-leading arrays). Unannotated fields default
    to replicated. Consumed by
    :func:`evox_tpu.core.distributed.state_sharding` and applied by the
    workflow each step — this makes the annotation the single source of
    truth for state layout (the reference declared the same idea but never
    consumed it; reference core/pytree_dataclass.py:12-19, SURVEY §2.3).

    ``storage``: the mixed-precision storage annotation, consumed by
    :mod:`evox_tpu.core.dtype_policy`. ``storage=True`` marks the field's
    floating-point leaves as *storage-eligible*: under a workflow
    ``DtypePolicy(storage=bf16, compute=f32)`` they are held in the
    storage dtype between generations (halving the loop-carry HBM bytes
    of every memory-bound leg) and upcast to the compute dtype at the
    step boundary, so all algorithm math — reductions, means, covariance
    updates — runs in the compute dtype. ``storage=False`` explicitly
    opts a field out (must-stay-f32); ``None`` (default) is treated as
    ineligible. Integer/bool/key leaves are never cast regardless of the
    annotation. Convention (enforced by tests/test_state_contracts.py):
    population-leading float fields carry an explicit ``storage``
    annotation alongside their ``sharding=P(POP_AXIS)``.
    """
    metadata = dict(kwargs.pop("metadata", {}) or {})
    metadata["static"] = static
    if sharding is not None:
        metadata["sharding"] = sharding
    if storage is not None:
        metadata["storage"] = bool(storage)
    return dataclasses.field(metadata=metadata, **kwargs)


def static_field(**kwargs: Any) -> dataclasses.Field:
    """Shorthand for ``field(static=True)``."""
    return field(static=True, **kwargs)


def _replace(self: _T, **changes: Any) -> _T:
    """Return a copy of this pytree dataclass with the given fields replaced."""
    return dataclasses.replace(self, **changes)


def pytree_dataclass(cls: type[_T]) -> type[_T]:
    """Turn ``cls`` into a frozen dataclass registered as a JAX pytree.

    Fields declared with ``static_field()`` become aux (metadata) fields; all
    other fields are pytree children. Adds a ``.replace(**changes)`` method.
    """
    cls = dataclasses.dataclass(frozen=True)(cls)
    data_fields = []
    meta_fields = []
    for f in dataclasses.fields(cls):
        if f.metadata.get("static", False):
            meta_fields.append(f.name)
        else:
            data_fields.append(f.name)
    jax.tree_util.register_dataclass(cls, data_fields, meta_fields)
    cls.replace = _replace
    return cls


class PyTreeNode:
    """Base class: subclasses are automatically pytree dataclasses.

    Example::

        class PSOState(PyTreeNode):
            population: jax.Array
            velocity: jax.Array
            key: jax.Array
    """

    def __init_subclass__(cls, **kwargs: Any) -> None:
        super().__init_subclass__(**kwargs)
        pytree_dataclass(cls)

    # These stubs are overwritten by pytree_dataclass; they exist so type
    # checkers know every PyTreeNode has them.
    def replace(self: _T, **changes: Any) -> _T:  # pragma: no cover
        raise NotImplementedError

    def __init__(self, *args: Any, **kwargs: Any) -> None:  # pragma: no cover
        raise NotImplementedError


def replace(obj: _T, **changes: Any) -> _T:
    """Functional ``dataclasses.replace`` for any pytree dataclass."""
    return dataclasses.replace(obj, **changes)
