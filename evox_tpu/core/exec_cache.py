"""AOT executable cache: compile once, serve from memory, warm-start from disk.

XLA's world is static-shape: every distinct (program, abstract signature)
pays a full trace+compile — seconds to minutes on real fleets — and the
PR-4 retrace detector can only *report* that cost after it landed on the
critical path. This module makes the compile an *artifact* instead of an
event: entry points are AOT-compiled once per cache key
(``jax.jit(fn).lower(*args).compile()``), held in memory, and persisted
through :func:`jax.experimental.serialize_executable.serialize` to a
content-addressed on-disk store, so a COLD PROCESS warm-starts its fleet
by deserializing executables in milliseconds instead of recompiling
(ROADMAP item 4; measured in bench.py's ``serving_elastic`` leg).

Cache key anatomy (what must match for an entry to be reusable):

- the **entry label** (``step`` / ``run_loop`` / ``solo_peel`` …),
- the caller's **config fingerprint** — algorithm class + any static
  workflow config the traced program depends on (the elastic layer
  passes ``workflows/elastic.py``'s bucket workflow fingerprint),
- the **abstract argument signature** (leaf shapes/dtypes + static
  pytree metadata — :func:`~evox_tpu.core.xla_cost.abstract_signature`,
  the same signature the retrace detector watches),
- the **bucket** (the elastic serving shape, when one applies) and the
  **mesh axes/shape**.

Deliberately NOT in the key: platform, device count, process count, and
jax version. Those are recorded in the entry's manifest as **topology
provenance** instead, and a lookup that finds an entry written under a
different topology *refuses loudly* (:class:`ExecCacheError`, the
``CheckpointConfigError`` discipline from PR 5) rather than silently
recompiling — a silently-cold store on the serving path is exactly the
failure this cache exists to make visible. A torn/corrupt entry (size or
SHA-256 mismatch, unpicklable payload — the crash artifact) is skipped
with a warning and recompiled, the ``WorkflowCheckpointer.latest()``
corrupt-skip discipline.

Durability: payload and manifest are written tmp + fsync + atomic rename
+ parent-directory fsync (the PR-5 power-loss discipline), manifest
last — the manifest is the commit record, so a torn payload can never
masquerade as a valid entry.

Strictness: ``strict=True`` (or :meth:`ExecutableCache.freeze` after
warming) promotes any UNPLANNED miss to :class:`ExecCacheMissError` — a
subclass of :class:`~evox_tpu.core.instrument.RetraceError`, so the PR-4
``strict_retrace`` machinery and the cache-miss alarm are one alarm
family: shape instability raises at dispatch, cold programs raise at
lookup. Planned warms (``planned=True``) never raise.

Everything here is host-side file I/O + AOT compilation outside traced
code — no callbacks, axon-safe (pinned by tests/test_no_host_callbacks.py).

Portability caveats (jax 0.4.x, non-TPU backends):

- Programs embedding HOST custom calls (LAPACK eigh — the CMA family's
  decomposition) serialize raw function pointers that do not survive a
  process boundary under ASLR: a cold process would SEGFAULT, not
  recompile. ``_save_disk`` therefore refuses to persist such entries
  off-TPU (warned; the entry amortizes in memory only). Custom-call-free
  algorithms (PSO, OpenES, SepCMAES) persist and cold-start fine.
- A DESERIALIZED executable still referenced at interpreter exit can
  segfault jax's atexit ``clear_backends`` — every cache therefore
  registers itself with a module-level atexit guard that calls
  :meth:`ExecutableCache.close` (drop the in-memory executable refs)
  before jax's teardown runs, so a process exiting with cache hits no
  longer needs ``os._exit`` or manual reference surgery (PR 18; the
  guard is armed at first construction, AFTER jax registered its own
  handler, so atexit's LIFO order runs ours first). ``close()`` is
  also callable directly for deterministic teardown. Executables
  compiled in-process are unaffected either way.
"""

from __future__ import annotations

import atexit
import hashlib
import json
import os
import pickle
import time
import warnings
import weakref
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

from .instrument import RetraceError
from .xla_cost import abstract_signature

__all__ = [
    "ExecCacheError",
    "ExecCacheMissError",
    "ExecutableCache",
    "topology_fingerprint",
]

_SCHEMA = "evox_tpu.exec_cache/v1"

# every live cache, so the atexit guard can drop deserialized-executable
# references before jax's clear_backends runs (PERF_NOTES §23: such a
# reference surviving to interpreter teardown can segfault). WeakSet: the
# guard must never be what keeps a cache alive.
_LIVE_CACHES: "weakref.WeakSet" = weakref.WeakSet()
_GUARD_ARMED = False


def _close_live_caches() -> None:
    for cache in list(_LIVE_CACHES):
        try:
            cache.close()
        except Exception:
            pass  # teardown must never raise over other atexit handlers


def _arm_teardown_guard() -> None:
    global _GUARD_ARMED
    if not _GUARD_ARMED:
        # registered lazily at FIRST cache construction — necessarily
        # after `import jax` registered clear_backends, so LIFO atexit
        # ordering runs this guard before jax tears the backend down
        atexit.register(_close_live_caches)
        _GUARD_ARMED = True


class ExecCacheError(RuntimeError):
    """An on-disk executable entry exists for the requested key but was
    written under a different topology (platform, device count, process
    count) or fails its own manifest (key mismatch) — loading it would
    hand the runtime an executable compiled for other hardware. Like
    :class:`~evox_tpu.workflows.checkpoint.CheckpointConfigError`, the
    refusal is loud: rebuild the store on this topology (delete the
    entry) instead of silently eating a recompile."""


class ExecCacheMissError(RetraceError):
    """A frozen/strict cache was asked for an executable it does not
    hold — the serving-path analog of a retrace (and a subclass of
    :class:`~evox_tpu.core.instrument.RetraceError`, so the PR-4
    strict-retrace alarm family catches both): compile cost is about to
    land on the critical path. Raised instead of compiling; warm the
    entry explicitly (``planned=True``) or drop ``strict``."""


def topology_fingerprint(mesh: Any = None) -> Dict[str, Any]:
    """The hardware/runtime identity an executable is only valid on:
    platform, device/process counts, jax version, and (when the program
    was lowered under one) the mesh's axis names and shape. Recorded in
    every entry manifest; checked — not keyed — so a foreign store
    refuses loudly instead of silently missing."""
    fp: Dict[str, Any] = {
        "platform": jax.devices()[0].platform,
        "device_count": jax.device_count(),
        "process_count": jax.process_count(),
        "jax": jax.__version__,
    }
    if mesh is not None:
        fp["mesh_axes"] = list(mesh.axis_names)
        fp["mesh_shape"] = [int(mesh.shape[a]) for a in mesh.axis_names]
    return fp


def _fsync_path(path: Path) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_durable(path: Path, payload: bytes) -> None:
    """tmp + fsync + atomic rename + directory fsync — the PR-5
    checkpoint durability discipline (workflows/checkpoint.py), spelled
    locally because core must not import workflows."""
    tmp = path.with_name(path.name + ".tmp")
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_path(path.parent)


class _CachedDispatch:
    """Callable shim over a cached ``jax.stages.Compiled``: dispatches
    the executable directly, while keeping the ORIGINAL jittable
    reachable through ``.lower`` so the roofline analyzer
    (core/xla_cost.py ``analyze_callable`` — ``fn if hasattr(fn,
    "lower")``) still AOT-analyzes the same program instead of failing
    to trace through a Compiled."""

    def __init__(self, compiled: Any, original: Any):
        self._compiled = compiled
        self._original = original
        if hasattr(original, "lower"):
            self.lower = original.lower

    def __call__(self, *args: Any, **kwargs: Any):
        return self._compiled(*args, **kwargs)


class ExecutableCache:
    """Keyed store of AOT-compiled XLA executables, memory + disk.

    Args:
        directory: on-disk store (created if missing). ``None`` keeps
            the cache memory-only — hits still amortize compiles within
            the process, but a cold process starts cold.
        strict: promote any unplanned miss to
            :class:`ExecCacheMissError` (see module docstring). Usually
            set via :meth:`freeze` after the serving layer warmed its
            buckets.
        max_entries: in-memory executables retained (LRU eviction
            preferring DISK-BACKED victims, whose re-request is a disk
            hit; a memory-only entry — one the backend refused to
            persist, see the module's portability caveats — is evicted
            only when every resident entry is memory-only, and its
            re-request pays a full recompile). ``None`` = unbounded.

    Counters (``report()["counters"]``): ``hits`` (memory),
    ``disk_hits`` (deserialized from the store), ``misses`` (compiled —
    every miss is a compile event, the coherence rule
    tools/check_report.py v7 enforces), ``saves``, ``evictions``.
    """

    def __init__(
        self,
        directory: Optional[str] = None,
        strict: bool = False,
        max_entries: Optional[int] = None,
    ):
        self.directory = Path(directory) if directory is not None else None
        if self.directory is not None:
            self.directory.mkdir(parents=True, exist_ok=True)
        self.strict = strict
        self.max_entries = max_entries
        self._mem: Dict[str, Any] = {}  # key -> Compiled (insertion = LRU)
        self._on_disk: set = set()  # keys with a committed disk entry
        self.counters = {
            "hits": 0,
            "disk_hits": 0,
            "misses": 0,
            "saves": 0,
            "evictions": 0,
        }
        self.compile_s_paid = 0.0  # misses: measured lower+compile time
        self.compile_s_saved = 0.0  # disk hits: manifest-recorded compile_s
        self.load_s = 0.0  # disk hits: measured deserialize time
        self.bytes_written = 0
        self.bytes_read = 0
        self.entries: List[dict] = []  # per-key provenance, report() order
        # serving-plane flight recorder (PR 16): attach with
        # `cache.metrics = rec` (RunQueue auto-threads its recorder) to
        # mirror hit/miss/compile-ms into the live metrics plane; None
        # (default) changes nothing
        self.metrics: Any = None
        _LIVE_CACHES.add(self)
        _arm_teardown_guard()

    def close(self) -> None:
        """Drop every in-memory executable reference (PERF_NOTES §23:
        a DESERIALIZED executable alive at interpreter exit can
        segfault jax's atexit teardown). Durable state — the on-disk
        store, counters, provenance — is untouched, and the cache stays
        usable: a later request simply pays a disk hit (or a recompile)
        again. Idempotent; also run by the module's atexit guard."""
        self._mem.clear()

    # -------------------------------------------------------------- keying
    @staticmethod
    def cache_key(
        label: str,
        config_fingerprint: str,
        args: tuple,
        kwargs: Optional[dict] = None,
        bucket: Optional[Tuple[int, ...]] = None,
        mesh: Any = None,
    ) -> str:
        """Content address of one executable: sha256 over (label, the
        caller's static-config fingerprint, the abstract argument
        signature, the serving bucket, the mesh axes/shape). Topology is
        deliberately excluded — see the module docstring."""
        aval, static = abstract_signature(args, kwargs or {})
        parts = [label, config_fingerprint, aval, static]
        if bucket is not None:
            parts.append("bucket:" + ",".join(str(int(b)) for b in bucket))
        if mesh is not None:
            parts.append(
                "mesh:"
                + ",".join(
                    f"{a}={int(mesh.shape[a])}" for a in mesh.axis_names
                )
            )
        return hashlib.sha256("|".join(parts).encode()).hexdigest()

    # ------------------------------------------------------------- lookup
    def _paths(self, key: str) -> Tuple[Path, Path]:
        return (
            self.directory / f"{key}.exec",
            self.directory / f"{key}.manifest.json",
        )

    def _mem_put(self, key: str, compiled: Any) -> None:
        self._mem[key] = compiled
        if self.max_entries is not None:
            while len(self._mem) > self.max_entries:
                # prefer the oldest DISK-BACKED victim: its re-request
                # deserializes; evicting a memory-only entry (one the
                # backend refused to persist) forfeits its compile
                victim = next(
                    (k for k in self._mem if k in self._on_disk),
                    next(iter(self._mem)),
                )
                del self._mem[victim]
                self.counters["evictions"] += 1

    def _load_disk(self, key: str, mesh: Any) -> Optional[Tuple[Any, dict]]:
        """Deserialize the on-disk entry for ``key``. Returns
        ``(compiled, manifest)``; ``None`` when no committed entry
        exists OR the entry is torn/corrupt (warned, recompile path);
        raises :class:`ExecCacheError` when the entry is intact but
        written under a different topology or an inconsistent key —
        stale entries refuse loudly, broken ones self-heal."""
        exec_path, man_path = self._paths(key)
        if not man_path.exists():
            return None
        try:
            with open(man_path) as f:
                manifest = json.load(f)
            payload = exec_path.read_bytes()
            if len(payload) != manifest["bytes"]:
                raise ValueError(
                    f"size mismatch: {len(payload)} != {manifest['bytes']}"
                )
            if hashlib.sha256(payload).hexdigest() != manifest["sha256"]:
                raise ValueError("sha256 mismatch")
        except ExecCacheError:
            raise
        except Exception as e:
            warnings.warn(
                f"skipping corrupt executable-cache entry {key[:12]}…: {e}",
                stacklevel=3,
            )
            return None
        # the entry is INTACT: now the provenance guards, loud by design
        if manifest.get("key") != key:
            raise ExecCacheError(
                f"executable-cache entry {key[:12]}… carries manifest key "
                f"{str(manifest.get('key'))[:12]}… — the store was "
                "rewritten or copied inconsistently; delete the entry and "
                "re-warm"
            )
        recorded = manifest.get("topology") or {}
        current = topology_fingerprint(mesh)
        mismatched = {
            k: (recorded.get(k), current[k])
            for k in current
            if recorded.get(k) != current[k]
        }
        if mismatched:
            raise ExecCacheError(
                f"executable-cache entry {key[:12]}… was compiled under a "
                f"different topology ({mismatched}) — an executable is only "
                "valid on the hardware it was compiled for. Re-warm the "
                "store on this topology (delete the stale entry) instead "
                "of serving a foreign binary."
            )
        from jax.experimental import serialize_executable as _se

        t0 = time.perf_counter()
        serialized, in_tree, out_tree = pickle.loads(payload)
        compiled = _se.deserialize_and_load(serialized, in_tree, out_tree)
        self.load_s += time.perf_counter() - t0
        self.bytes_read += len(payload)
        self._on_disk.add(key)
        return compiled, manifest

    @staticmethod
    def _host_custom_calls(compiled: Any) -> List[str]:
        """Custom-call targets embedded in the compiled program. On
        non-TPU backends these lower to RAW HOST FUNCTION POINTERS
        (LAPACK eigh is the canonical case — CMA-ES fleets), which do
        not survive a process boundary: a cold process executing the
        deserialized binary segfaults under ASLR instead of erroring.
        Verified empirically on jax 0.4.x CPU; TPU executables are
        device binaries and unaffected."""
        try:
            txt = compiled.as_text()
        except Exception:
            return []
        return sorted(
            {
                line.split('custom_call_target="', 1)[1].split('"', 1)[0]
                for line in txt.splitlines()
                if 'custom_call_target="' in line
            }
        )

    def _save_disk(
        self,
        key: str,
        compiled: Any,
        label: str,
        bucket: Optional[Tuple[int, ...]],
        mesh: Any,
        compile_s: float,
    ) -> Optional[int]:
        from jax.experimental import serialize_executable as _se

        if jax.devices()[0].platform != "tpu":
            calls = self._host_custom_calls(compiled)
            if calls:
                # refuse to write an artifact that would SEGFAULT (not
                # recompile) a cold process — memory-only is the honest
                # degradation, and the warning names the culprit ops
                warnings.warn(
                    f"executable for {label!r} embeds host custom calls "
                    f"{calls} — raw function pointers that do not survive "
                    "a process boundary on the "
                    f"{jax.devices()[0].platform} backend; entry stays "
                    "memory-only (a cold process will recompile, not "
                    "crash). Algorithms without LAPACK decompositions "
                    "(PSO/OpenES/SepCMAES) persist fine.",
                    stacklevel=4,
                )
                return None
        try:
            serialized, in_tree, out_tree = _se.serialize(compiled)
            payload = pickle.dumps(
                (serialized, in_tree, out_tree),
                protocol=pickle.HIGHEST_PROTOCOL,
            )
        except Exception as e:
            # some backends cannot serialize (ValueError "Compilation
            # does not support serialization") — the cache degrades to
            # memory-only for that entry, recorded so report() explains
            # the missing bytes instead of faking persistence
            warnings.warn(
                f"executable for {label!r} is not serializable on this "
                f"backend ({type(e).__name__}: {e}); entry stays "
                "memory-only",
                stacklevel=3,
            )
            return None
        exec_path, man_path = self._paths(key)
        _write_durable(exec_path, payload)
        manifest = {
            "schema": _SCHEMA,
            "key": key,
            "label": label,
            "bucket": list(bucket) if bucket is not None else None,
            "bytes": len(payload),
            "sha256": hashlib.sha256(payload).hexdigest(),
            "topology": topology_fingerprint(mesh),
            "compile_s": round(compile_s, 6),
            "created": round(time.time(), 3),
        }
        # manifest LAST: it is the commit record (a torn payload without
        # a manifest is invisible; a manifest always points at a payload
        # that was durable first)
        _write_durable(man_path, json.dumps(manifest).encode())
        self.counters["saves"] += 1
        self._on_disk.add(key)
        self.bytes_written += len(payload)
        return len(payload)

    # ---------------------------------------------------------------- get
    def get_or_compile(
        self,
        label: str,
        config_fingerprint: str,
        fn: Callable,
        args: tuple,
        kwargs: Optional[dict] = None,
        bucket: Optional[Tuple[int, ...]] = None,
        mesh: Any = None,
        planned: bool = False,
    ) -> Any:
        """The one lookup: memory hit → disk hit → compile (the miss).

        ``fn`` may be a ``jax.jit`` wrapper (lowered directly — the same
        program the workflow dispatches) or any traceable callable.
        ``args``/``kwargs`` may be concrete arrays or
        ``jax.ShapeDtypeStruct`` pytrees — lowering never executes.
        ``planned=True`` marks a warm-path compile that must not trip
        the strict-miss alarm. Returns a ``jax.stages.Compiled``."""
        kwargs = kwargs or {}
        key = self.cache_key(
            label, config_fingerprint, args, kwargs, bucket, mesh
        )
        compiled = self._mem.get(key)
        if compiled is not None:
            # refresh LRU position
            del self._mem[key]
            self._mem[key] = compiled
            self.counters["hits"] += 1
            if self.metrics is not None:
                self.metrics.count("exec_cache.hits")
            return compiled
        if self.directory is not None:
            got = self._load_disk(key, mesh)
            if got is not None:
                compiled, manifest = got
                self._mem_put(key, compiled)
                self.counters["disk_hits"] += 1
                self.compile_s_saved += float(manifest.get("compile_s") or 0.0)
                if self.metrics is not None:
                    self.metrics.count("exec_cache.disk_hits")
                self._note_entry(
                    {
                        "key": key[:16],
                        "label": label,
                        "bucket": list(bucket) if bucket is not None else None,
                        "source": "disk",
                        "bytes": int(manifest["bytes"]),
                        "compile_s_saved": float(
                            manifest.get("compile_s") or 0.0
                        ),
                    }
                )
                return compiled
        if self.strict and not planned:
            raise ExecCacheMissError(
                f"executable cache miss for entry {label!r} (key "
                f"{key[:12]}…) on a frozen cache — an unplanned compile "
                "was about to land on the serving path. Warm the bucket "
                "explicitly (planned=True) or drop strict."
            )
        self.counters["misses"] += 1
        t0 = time.perf_counter()
        lowerable = fn if hasattr(fn, "lower") else jax.jit(fn)
        compiled = lowerable.lower(*args, **kwargs).compile()
        compile_s = time.perf_counter() - t0
        self.compile_s_paid += compile_s
        if self.metrics is not None:
            self.metrics.count("exec_cache.misses")
            self.metrics.observe("exec_cache.compile_ms", compile_s * 1e3)
        nbytes = None
        if self.directory is not None:
            nbytes = self._save_disk(
                key, compiled, label, bucket, mesh, compile_s
            )
        self._mem_put(key, compiled)
        self._note_entry(
            {
                "key": key[:16],
                "label": label,
                "bucket": list(bucket) if bucket is not None else None,
                "source": "compiled",
                "bytes": int(nbytes) if nbytes is not None else None,
                "compile_s": round(compile_s, 6),
            }
        )
        return compiled

    def _note_entry(self, entry: dict) -> None:
        """Record per-key provenance WITHOUT growing without bound: a
        long-lived server whose ``max_entries`` is smaller than its
        working set reloads evicted keys from disk continuously, and a
        fresh dict per reload would leak memory (and bloat ``report()``)
        linearly with traffic. Repeat events for the same (key, source)
        aggregate into the existing record's ``repeats`` count."""
        for e in self.entries:
            if e["key"] == entry["key"] and e["source"] == entry["source"]:
                e["repeats"] = int(e.get("repeats", 1)) + 1
                return
        self.entries.append(entry)

    def freeze(self) -> "ExecutableCache":
        """Arm the miss alarm: after the serving layer has warmed every
        planned bucket, any further miss is an unplanned compile and
        raises :class:`ExecCacheMissError`."""
        self.strict = True
        return self

    # ------------------------------------------------------------- report
    def report(self) -> dict:
        """The ``serving.cache`` section of ``run_report()`` (schema v7,
        validated by tools/check_report.py): counters whose coherence
        rule is *misses == compile events* (every miss pays exactly one
        compile; every disk hit saves the manifest-recorded one), byte
        traffic, and per-entry provenance."""
        return {
            "directory": str(self.directory) if self.directory else None,
            "strict": bool(self.strict),
            "counters": dict(self.counters),
            "compile_s_paid": round(self.compile_s_paid, 6),
            "compile_s_saved": round(self.compile_s_saved, 6),
            "load_s": round(self.load_s, 6),
            "bytes_written": int(self.bytes_written),
            "bytes_read": int(self.bytes_read),
            "entries": list(self.entries),
        }
