"""Abstract Problem (reference: src/evox/core/problem.py:12-31).

Functional form: ``init(key) -> state`` (``None`` for stateless problems) and
``evaluate(state, pop) -> (fitness, state)``. Fitness is ``(pop,)`` for
single-objective, ``(pop, m)`` for multi-objective. Problems that cannot run
under jit (host simulators, external services) set ``jittable = False`` and
declare ``fit_shape``/``fit_dtype`` so workflows can route them through
``jax.pure_callback`` with a known output signature.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax

ProblemState = Any


class Problem:
    """Base class for every optimization problem."""

    #: False for host-side problems that must run via callback.
    jittable: bool = True

    def init(self, key: Optional[jax.Array] = None) -> ProblemState:
        return None

    def evaluate(self, state: ProblemState, pop: Any) -> Tuple[jax.Array, ProblemState]:
        raise NotImplementedError

    def fit_shape(self, pop_size: int) -> Tuple[int, ...]:
        """Fitness shape for a given pop size (used for callback problems)."""
        return (pop_size,)

    #: dtype of the fitness array (used for callback problems).
    fit_dtype = "float32"

    def pf(self) -> jax.Array:
        """True Pareto front, for problems that know it (MO benchmarks)."""
        raise NotImplementedError(f"{type(self).__name__} has no known Pareto front")
