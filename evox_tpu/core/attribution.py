"""Operator attribution: the uniform tag vocabulary + success/credit
contract behind search-dynamics observability (ISSUE 19).

An :class:`Attribution` is what an algorithm's ``tell`` already knows the
moment it selects survivors: which slot each candidate targets
(``parent_idx``), which variation operator produced it (``op_tag``),
whether it replaced its parent (``success``), and how much fitness it
gained (``improvement``, internal minimize direction, clipped to 0 for
non-improving candidates). Adaptive DE variants (SaDE/JaDE/CoDE/SHADE)
compute exactly this bookkeeping internally for self-adaptation; the
helpers here are those expressions factored out *verbatim* so attribution
reads what the algorithm already knows — the adaptive-DE regression tests
(tests/test_lineage.py) pin the refactor bit-identical to the pre-PR
adaptation goldens.

Algorithms that carry an ``attrib`` field in their state publish it for
``monitors/lineage.py``'s :class:`LineageMonitor`, which folds it into
on-device rings and a per-operator credit ledger (attempts, successes,
improvement mass). Algorithms without the field (ES/PSO/MO families) are
tagged by the monitor at the selection boundary instead — same ledger,
coarser tags. Everything here is pure jittable math: zero host callbacks
(pinned by tests/test_no_host_callbacks.py), so the contract holds on the
axon-tunneled TPU backend.

No reference analog (PARITY row 63); design sources are the PBT/Fiber
per-member provenance arcs (PAPERS.md).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .distributed import POP_AXIS
from .struct import PyTreeNode, field

__all__ = [
    "OP_NONE",
    "OP_INIT",
    "OP_SAMPLE",
    "OP_VELOCITY",
    "OP_DE_RAND_1",
    "OP_DE_RAND_2",
    "OP_DE_RAND_TO_BEST_2",
    "OP_DE_CUR_TO_RAND_1",
    "OP_DE_CUR_TO_PBEST_1",
    "OP_DE_BEST",
    "OP_CROSSOVER",
    "OP_MUTATION",
    "N_OPS",
    "OP_NAMES",
    "SADE_STRATEGY_TAGS",
    "CODE_STRATEGY_TAGS",
    "Attribution",
    "de_variant_tag",
    "success_mask",
    "improvement_mass",
    "slot_attribution",
    "strategy_success_counts",
    "lehmer_mean_of_successful",
    "arithmetic_mean_of_successful",
    "op_credit",
    "argsort_inverse",
    "find_attribution",
]

# ---------------------------------------------------------------- vocabulary
# A single flat namespace so ledgers from different algorithms are
# comparable. Growing it is append-only: tags are persisted in lineage
# rings and run_report ledgers, so renumbering would corrupt forensics
# across checkpoint resumes.
OP_NONE = 0  # no attribution recorded (padding / pre-first-tell)
OP_INIT = 1  # initial population sampling (generation 0)
OP_SAMPLE = 2  # distribution sampling (ES/CMA-family ask)
OP_VELOCITY = 3  # PSO velocity update
OP_DE_RAND_1 = 4  # DE/rand/1/bin
OP_DE_RAND_2 = 5  # DE/rand/2/bin
OP_DE_RAND_TO_BEST_2 = 6  # DE/rand-to-best/2/bin
OP_DE_CUR_TO_RAND_1 = 7  # DE/current-to-rand/1
OP_DE_CUR_TO_PBEST_1 = 8  # DE/current-to-pbest/1 (JaDE/SHADE)
OP_DE_BEST = 9  # DE/best/n/bin
OP_CROSSOVER = 10  # GA crossover (MO selection boundary)
OP_MUTATION = 11  # GA mutation / unclassified variation
N_OPS = 12

OP_NAMES = (
    "none",
    "init",
    "sample",
    "velocity",
    "de_rand_1",
    "de_rand_2",
    "de_rand_to_best_2",
    "de_cur_to_rand_1",
    "de_cur_to_pbest_1",
    "de_best",
    "crossover",
    "mutation",
)
assert len(OP_NAMES) == N_OPS

# SaDE's strategy axis (sade.py ask: v0..v3) in vocabulary terms
SADE_STRATEGY_TAGS = (
    OP_DE_RAND_1,
    OP_DE_RAND_TO_BEST_2,
    OP_DE_RAND_2,
    OP_DE_CUR_TO_RAND_1,
)
# CoDE's trial axis (code.py ask: t1..t3)
CODE_STRATEGY_TAGS = (OP_DE_RAND_1, OP_DE_RAND_2, OP_DE_CUR_TO_RAND_1)


def de_variant_tag(base_vector: str, n_diff: int) -> int:
    """The vocabulary tag for a plain-DE configuration (static)."""
    if base_vector == "best":
        return OP_DE_BEST
    if n_diff == 1:
        return OP_DE_RAND_1
    if n_diff == 2:
        return OP_DE_RAND_2
    return OP_MUTATION


# ----------------------------------------------------------------- contract


class Attribution(PyTreeNode):
    """Per-slot attribution for one generation's selection.

    All fields are population-leading, one row per *surviving slot* (the
    algorithm's ``pop_size``, even when the evaluated batch was wider —
    CoDE folds its 3-trials-per-parent axis before attributing). Fitness
    quantities are in the algorithm-internal minimize direction.
    """

    parent_idx: jax.Array = field(sharding=P(POP_AXIS))  # (pop,) i32
    op_tag: jax.Array = field(sharding=P(POP_AXIS))  # (pop,) i32
    success: jax.Array = field(sharding=P(POP_AXIS))  # (pop,) bool
    # credit mass must stay f32 between steps — bf16 storage would shear
    # the ledger sums the v13 validator cross-checks (explicit opt-out)
    improvement: jax.Array = field(sharding=P(POP_AXIS), storage=False)  # (pop,) f32

    @staticmethod
    def empty(pop_size: int) -> "Attribution":
        return Attribution(
            parent_idx=jnp.arange(pop_size, dtype=jnp.int32),
            op_tag=jnp.full((pop_size,), OP_INIT, jnp.int32),
            success=jnp.zeros((pop_size,), bool),
            improvement=jnp.zeros((pop_size,), jnp.float32),
        )


def success_mask(new_fitness: jax.Array, prev_fitness: jax.Array) -> jax.Array:
    """The greedy-selection success mask, exactly as the DE family writes
    it: strict improvement over the incumbent (de.py:112, sade.py:123,
    jade.py:116, shade.py:106)."""
    return new_fitness < prev_fitness

def improvement_mass(
    new_fitness: jax.Array, prev_fitness: jax.Array, success: jax.Array
) -> jax.Array:
    """Clipped per-slot fitness gain. The first greedy tell improves on an
    ``inf`` incumbent — that is initialization credit, not operator
    credit, so non-finite incumbents contribute zero mass."""
    gain = prev_fitness - new_fitness
    return jnp.where(
        success & jnp.isfinite(prev_fitness), gain, jnp.float32(0.0)
    ).astype(jnp.float32)


def slot_attribution(
    new_fitness: jax.Array,
    prev_fitness: jax.Array,
    op_tag,
    parent_idx: jax.Array | None = None,
) -> Attribution:
    """Attribution for 1:1 slot-descent selection (every DE variant: slot
    ``i``'s trial competes only with parent ``i``). ``op_tag`` may be a
    scalar (one operator for the whole generation) or a (pop,) array."""
    n = new_fitness.shape[0]
    succ = success_mask(new_fitness, prev_fitness)
    tags = jnp.broadcast_to(jnp.asarray(op_tag, jnp.int32), (n,))
    if parent_idx is None:
        parent_idx = jnp.arange(n, dtype=jnp.int32)
    return Attribution(
        parent_idx=parent_idx.astype(jnp.int32),
        op_tag=tags,
        success=succ,
        improvement=improvement_mass(new_fitness, prev_fitness, succ),
    )


def strategy_success_counts(
    success: jax.Array, strategy: jax.Array, n_strategy: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """SaDE's per-strategy success/failure bookkeeping, verbatim
    (sade.py:124-126 pre-refactor): one-hot the chosen strategies and sum
    the (pop,) success mask through them. Returns ``(succ, fail, onehot)``
    with the one-hot reused for the CR-memory update."""
    onehot = jax.nn.one_hot(strategy, n_strategy)
    succ = (success[:, None] * onehot).sum(axis=0)
    fail = ((~success)[:, None] * onehot).sum(axis=0)
    return succ, fail, onehot


def lehmer_mean_of_successful(values: jax.Array, success: jax.Array) -> jax.Array:
    """JaDE/SHADE F adaptation: Lehmer mean over successful parameters,
    verbatim (jade.py:120-122 pre-refactor)."""
    s = jnp.where(success, values, 0.0)
    return jnp.sum(s**2) / jnp.maximum(jnp.sum(s), 1e-12)


def arithmetic_mean_of_successful(
    values: jax.Array, success: jax.Array, n_success: jax.Array
) -> jax.Array:
    """JaDE CR adaptation: arithmetic mean over successful parameters,
    verbatim (jade.py:121-123 pre-refactor). ``n_success`` is passed in so
    the caller's existing count is reused (bit-identity)."""
    s = jnp.where(success, values, 0.0)
    return jnp.sum(s) / jnp.maximum(n_success, 1)


def op_credit(
    attrib: Attribution, n_ops: int = N_OPS
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fold one generation's attribution into ledger increments:
    ``(attempts, successes, improvement)`` per operator tag — attempts
    count every candidate that carried the tag, successes those that
    replaced their parent, improvement the clipped fitness mass. The
    ledger identity ``successes[tag] == strategy_success_counts(...)[0]``
    for DE strategies is asserted by tests/test_lineage.py."""
    onehot = jax.nn.one_hot(attrib.op_tag, n_ops, dtype=jnp.int32)
    attempts = onehot.sum(axis=0)
    successes = (attrib.success[:, None].astype(jnp.int32) * onehot).sum(axis=0)
    improvement = (
        attrib.improvement[:, None] * onehot.astype(jnp.float32)
    ).sum(axis=0)
    return attempts, successes, improvement


def argsort_inverse(order: jax.Array) -> jax.Array:
    """Parent map for sort-based survivor selection: when a tell places
    the candidate at pre-selection position ``order[i]`` into slot ``i``
    (the usual truncation/sort pattern), the slot->origin map IS
    ``order`` itself — and when a tell instead says "candidate ``i`` went
    to slot ``order[i]``", this inverse turns that scatter into the
    gather the lineage ring wants. One pop-sized scatter, O(n)."""
    n = order.shape[0]
    return (
        jnp.zeros((n,), jnp.int32)
        .at[order]
        .set(jnp.arange(n, dtype=jnp.int32))
    )


def find_attribution(algo_state):
    """Structurally locate an ``attrib`` field on an algorithm state,
    unwrapping guardrail/recenter wrappers (``.inner``). Trace-time
    (hasattr on the state object), so it is free inside jit — the same
    discipline as TelemetryMonitor's restart mirror. Returns ``None`` if
    the algorithm does not publish attribution."""
    seen = 0
    while algo_state is not None and seen < 8:
        attrib = getattr(algo_state, "attrib", None)
        if attrib is not None:
            return attrib
        algo_state = getattr(algo_state, "inner", None)
        seen += 1
    return None
