"""Numerical self-defense: on-device degenerate-state detection + restarts.

PR 2 made the EVALUATION side self-healing (farm fault tolerance, crash-safe
checkpoints, NaN fitness quarantine) — but a poisoned ALGORITHM state (a
non-finite ``eigh`` on CMA-ES's covariance, a collapsed ``sigma``, a
stagnated search) persisted forever with no detection and no recovery.
:class:`GuardedAlgorithm` closes that hole: a generic wrapper with the same
:class:`~evox_tpu.core.algorithm.Algorithm` interface that, after every
``tell``, evaluates a set of jit-compatible health predicates over the
wrapped state and — on trigger — performs an ON-DEVICE restart under
``lax.cond``: a fresh ``init()`` from a split key, re-centered on the
best-so-far point, with best-so-far and a restart counter carried in the
wrapper's own state. Everything is pure jittable math (axon-safe, no host
callbacks), so it works identically in ``wf.step`` loops, the fused
``wf.run`` ``fori_loop``, and ``run_host_pipelined``.

The restart-strategy literature this follows: IPOP/BIPOP increasing-
population restarts (Auger & Hansen 2005; Hansen 2009; arXiv 2409.11765)
and evosax's restart wrappers (arXiv 2212.04180). The wrapper implements
the *detect + same-shape restart* half on device; population GROWTH needs
new static shapes and therefore lives at the host boundary —
:class:`IPOPRestarts` (consumed by ``StdWorkflow.run(restarts=...)`` and
``run_host_pipelined(restarts=...)``, workflows/ipop.py) doubles the
population between dispatches, one recompile per doubling.

No-trigger law (asserted in tests/test_numeric_chaos.py): with guards
enabled but never triggered, ``GuardedAlgorithm(alg)`` produces a
BIT-identical trajectory to bare ``alg`` — ``init`` hands the wrapped
algorithm the caller's key unchanged (the wrapper's restart key is derived
via ``fold_in``), ``ask``/``tell`` delegate exactly, and the untriggered
``lax.cond`` branch returns the delegated result untouched.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .algorithm import Algorithm
from .distributed import POP_AXIS
from .struct import PyTreeNode, field, static_field

__all__ = [
    "GuardedAlgorithm",
    "GuardedState",
    "IPOPRestarts",
    "recenter_state",
    "TRIGGER_NONFINITE",
    "TRIGGER_SIGMA",
    "TRIGGER_DIVERSITY",
    "TRIGGER_STAGNATION",
]

# bitmask codes recorded in GuardedState.last_trigger
TRIGGER_NONFINITE = 1  # NaN (optionally Inf) leaves in the wrapped state
TRIGGER_SIGMA = 2  # step size below floor / above ceiling
TRIGGER_DIVERSITY = 4  # candidate diversity collapsed below the floor
TRIGGER_STAGNATION = 8  # generations without best-so-far improvement


class GuardedState(PyTreeNode):
    inner: Any  # wrapped algorithm state (sharding: the inner annotations)
    pop: Any = field(sharding=P(POP_AXIS), storage=True)  # last asked candidate batch
    best_x: Any = field(sharding=P())  # best-so-far candidate
    best_fitness: jax.Array = field(sharding=P())  # internal (minimize) key
    stagnation: jax.Array = field(sharding=P())  # gens since best improved
    restarts: jax.Array = field(sharding=P())  # on-device restarts so far
    # host-boundary baseline: the value of `restarts` when the IPOP driver
    # (workflows/ipop.py) last evaluated its escalation rule. Written ONLY
    # by the host between dispatches; device code never touches it. Living
    # in the state (and therefore in every checkpoint), it makes the
    # escalation decision stateless — a crashed-and-resumed run re-derives
    # the identical doubling schedule.
    checked_restarts: jax.Array = field(sharding=P())
    last_trigger: jax.Array = field(sharding=P())  # bitmask, 0 = healthy
    key: jax.Array = field(sharding=P())  # restart PRNG stream
    # static metadata: the wrapped algorithm's population size, pickled
    # with checkpoints so an IPOP resume (workflows/ipop.py) can rebuild
    # the matching compiled program before restoring the snapshot
    pop_size: int = static_field(default=0)


def _has_field(state: Any, name: str) -> bool:
    return dataclasses.is_dataclass(state) and name in getattr(
        state, "__dataclass_fields__", {}
    )


def recenter_state(astate: Any, best_x: Any) -> Any:
    """Re-center a fresh algorithm state on the best-so-far point.

    Duck-typed, shape-preserving: a distribution-based state (``mean`` or
    ``center`` field matching ``best_x``'s shape) moves its distribution
    center onto ``best_x``; a population-based state (2-D ``population``)
    gets ``best_x`` written into row 0 (elitist seeding — the rest of the
    fresh population keeps exploring). States with neither field are
    returned unchanged (the fresh ``init()`` alone is the restart).
    """
    # accept numpy leaves too: a checkpoint-restored state carries numpy
    # arrays until the next dispatch re-devices them
    if not isinstance(best_x, (jax.Array, np.ndarray)) or best_x.ndim != 1:
        return astate  # pytree candidates (neuroevolution): no re-centering
    best_x = jnp.asarray(best_x)
    for name in ("mean", "center"):
        if _has_field(astate, name):
            cur = getattr(astate, name)
            if isinstance(cur, jax.Array) and cur.shape == best_x.shape:
                return astate.replace(**{name: best_x.astype(cur.dtype)})
    if _has_field(astate, "population"):
        pop = astate.population
        if (
            isinstance(pop, jax.Array)
            and pop.ndim == 2
            and pop.shape[1:] == best_x.shape
        ):
            return astate.replace(
                population=pop.at[0].set(best_x.astype(pop.dtype))
            )
    return astate


class GuardedAlgorithm(Algorithm):
    """Wrap any single-objective :class:`Algorithm` with on-device health
    checks and automatic restart.

    After each ``tell`` the wrapper evaluates the enabled predicates
    against the freshly updated inner state:

    - **non-finite leaves** (``check_nonfinite``): any NaN in a floating
      leaf of the inner state. ``check_inf=True`` also triggers on ±Inf —
      off by default because +Inf fitness sentinels are idiomatic in this
      codebase (DE's unevaluated rows, PSO's initial pbest).
    - **step-size collapse/explosion** (``sigma_floor``/``sigma_ceiling``):
      checked only when the inner state carries a ``sigma`` field (ES
      family); skipped statically otherwise.
    - **diversity collapse** (``diversity_floor``): finite-masked mean
      per-dimension std of the last asked candidate batch (same statistic
      as TelemetryMonitor's diversity ring) below the floor. Off by
      default — the right floor is problem-scale dependent.
    - **stagnation** (``stagnation_limit``): generations since the
      best-so-far fitness improved (the direction-aware counter from
      monitors/telemetry.py, re-derived here on the wrapper's own
      best-so-far key — fitness arrives already sign-flipped by the
      workflow, so "improved" is always "strictly smaller"). Off by
      default.

    On trigger, a ``lax.cond`` swaps in ``inner.init(fresh_key)``
    re-centered on the best-so-far point (:func:`recenter_state`), resets
    the stagnation counter and increments ``restarts``; the best-so-far
    pair survives the restart. With no trigger the trajectory is
    bit-identical to the bare algorithm (see module docstring).

    The wrapper forwards unknown attributes (``pop_size``, ``dim``,
    ``lb``...) to the wrapped algorithm, so it composes with workflows and
    containers that duck-type those.
    """

    def __init__(
        self,
        algorithm: Algorithm,
        check_nonfinite: bool = True,
        check_inf: bool = False,
        sigma_floor: Optional[float] = 1e-20,
        sigma_ceiling: Optional[float] = 1e20,
        diversity_floor: Optional[float] = None,
        stagnation_limit: Optional[int] = None,
    ):
        self.algorithm = algorithm
        self.check_nonfinite = check_nonfinite
        self.check_inf = check_inf
        self.sigma_floor = sigma_floor
        self.sigma_ceiling = sigma_ceiling
        self.diversity_floor = diversity_floor
        self.stagnation_limit = stagnation_limit

    def __getattr__(self, name: str) -> Any:
        # only reached when normal lookup fails: forward hyperparameter
        # reads (pop_size, dim, lb, ub, ...) to the wrapped algorithm
        if name.startswith("__") or name == "algorithm":
            raise AttributeError(name)
        return getattr(object.__getattribute__(self, "algorithm"), name)

    # first-generation dispatch mirrors the wrapped algorithm exactly
    @property
    def has_init_ask(self) -> bool:
        return self.algorithm.has_init_ask

    @property
    def has_init_tell(self) -> bool:
        return self.algorithm.has_init_tell

    # ------------------------------------------------------------------ api
    def init(self, key: jax.Array) -> GuardedState:
        # the INNER algorithm gets the caller's key unchanged — this is
        # what makes the no-trigger trajectory bit-identical to the bare
        # algorithm; the wrapper's restart stream is folded off it
        inner = self.algorithm.init(key)
        restart_key = jax.random.fold_in(key, 0x6A72)  # "gr"
        # the candidate buffer must keep ONE static shape across the whole
        # run or the fused run()'s fori_loop carry changes type: size it to
        # the widest batch the algorithm ever evaluates (init_ask and ask
        # may differ — CSO scores the full population first, halves after),
        # and let tell slice down to the live batch width
        first_sds = jax.eval_shape(self._first_ask, inner)[0]
        steady_sds = jax.eval_shape(self.algorithm.ask, inner)[0]
        pop = jax.tree.map(
            lambda f, s: jnp.zeros(
                (max(f.shape[0], s.shape[0]),) + f.shape[1:], f.dtype
            ),
            first_sds,
            steady_sds,
        )
        best_x = jax.tree.map(
            lambda s: jnp.zeros(s.shape[1:], s.dtype), first_sds
        )
        return GuardedState(
            inner=inner,
            pop=pop,
            best_x=best_x,
            best_fitness=jnp.asarray(jnp.inf, dtype=jnp.float32),
            stagnation=jnp.zeros((), dtype=jnp.int32),
            restarts=jnp.zeros((), dtype=jnp.int32),
            checked_restarts=jnp.zeros((), dtype=jnp.int32),
            last_trigger=jnp.zeros((), dtype=jnp.int32),
            key=restart_key,
            pop_size=int(getattr(self.algorithm, "pop_size", 0)),
        )

    def _first_ask(self, inner: Any):
        # the batch the workflow will evaluate FIRST (init_ask when the
        # algorithm has one) — sizes the `pop`/`best_x` buffers
        if self.algorithm.has_init_ask or self.algorithm.has_init_tell:
            return self.algorithm.init_ask(inner)
        return self.algorithm.ask(inner)

    @staticmethod
    def _store_pop(buf: Any, pop: Any) -> Any:
        """Write ``pop`` into the leading rows of the fixed-width buffer
        (static shapes: the leftover rows keep their previous content and
        are never read — tell slices to the live batch width)."""
        return jax.tree.map(
            lambda b, p: p if p.shape[0] == b.shape[0]
            else jnp.concatenate([p.astype(b.dtype), b[p.shape[0]:]]),
            buf,
            pop,
        )

    def ask(self, state: GuardedState) -> Tuple[Any, GuardedState]:
        pop, inner = self.algorithm.ask(state.inner)
        return pop, state.replace(
            inner=inner, pop=self._store_pop(state.pop, pop)
        )

    def init_ask(self, state: GuardedState) -> Tuple[Any, GuardedState]:
        pop, inner = self.algorithm.init_ask(state.inner)
        return pop, state.replace(
            inner=inner, pop=self._store_pop(state.pop, pop)
        )

    def tell(self, state: GuardedState, fitness: jax.Array) -> GuardedState:
        inner = self.algorithm.tell(state.inner, fitness)
        return self._postcheck(state, inner, fitness)

    def init_tell(self, state: GuardedState, fitness: jax.Array) -> GuardedState:
        inner = self.algorithm.init_tell(state.inner, fitness)
        return self._postcheck(state, inner, fitness)

    def migrate(self, state: GuardedState, pop: Any, fitness: jax.Array) -> GuardedState:
        # migrants count as progress: fold them into best-so-far/stagnation
        # (fitness arrives in the internal minimization convention, like
        # tell's) — otherwise an island's best genome is invisible to the
        # stagnation guard, which would fire a spurious restart and
        # re-center on a stale pre-migration best
        fitness = fitness.astype(jnp.float32)
        masked = jnp.where(jnp.isfinite(fitness), fitness, jnp.inf)
        mig_best = jnp.min(masked)
        mig_best_i = jnp.argmin(masked)
        improved = mig_best < state.best_fitness
        best_x = jax.tree.map(
            lambda b, p: jnp.where(improved, p[mig_best_i].astype(b.dtype), b),
            state.best_x,
            pop,
        )
        return state.replace(
            inner=self.algorithm.migrate(state.inner, pop, fitness),
            best_x=best_x,
            best_fitness=jnp.minimum(state.best_fitness, mig_best),
            stagnation=jnp.where(improved, 0, state.stagnation),
        )

    # ------------------------------------------------------- health checks
    def _postcheck(
        self, state: GuardedState, inner: Any, fitness: jax.Array
    ) -> GuardedState:
        if fitness.ndim != 1:
            raise ValueError(
                "GuardedAlgorithm restarts re-center on a scalar best-so-far "
                f"point and are single-objective; got fitness of shape "
                f"{fitness.shape}"
            )
        fitness = fitness.astype(jnp.float32)
        # the rows of the fixed-width pop buffer this fitness scored
        # (static slice: fitness length is a trace-time constant)
        batch = jax.tree.map(lambda p: p[: fitness.shape[0]], state.pop)

        # -- best-so-far / stagnation (internal minimization convention;
        #    finite-masked so a poison generation cannot claim the best)
        masked = jnp.where(jnp.isfinite(fitness), fitness, jnp.inf)
        gen_best = jnp.min(masked)
        gen_best_i = jnp.argmin(masked)
        improved = gen_best < state.best_fitness
        best_fitness = jnp.minimum(state.best_fitness, gen_best)
        best_x = jax.tree.map(
            lambda b, p: jnp.where(improved, p[gen_best_i].astype(b.dtype), b),
            state.best_x,
            batch,
        )
        stagnation = jnp.where(improved, 0, state.stagnation + 1)

        trigger = jnp.zeros((), dtype=jnp.int32)
        if self.check_nonfinite:
            bad = self._nonfinite_in(inner)
            trigger = trigger | jnp.where(bad, TRIGGER_NONFINITE, 0)
        if _has_field(inner, "sigma") and (
            self.sigma_floor is not None or self.sigma_ceiling is not None
        ):
            sigma = jnp.abs(jnp.asarray(inner.sigma, jnp.float32))
            bad = jnp.zeros((), dtype=bool)
            # inclusive comparisons so the algorithm-local rails compose:
            # clamp_step_size (es/common.py) pins a collapsed sigma at
            # EXACTLY its floor/ceiling, which must still read as collapsed.
            # Per-axis sigma (SNES family): ANY collapsed/exploded axis is
            # degenerate — min against the floor, max against the ceiling
            if self.sigma_floor is not None:
                bad = bad | (jnp.min(sigma) <= self.sigma_floor)
            if self.sigma_ceiling is not None:
                bad = bad | (jnp.max(sigma) >= self.sigma_ceiling)
            trigger = trigger | jnp.where(bad, TRIGGER_SIGMA, 0)
        if self.diversity_floor is not None:
            div = self._diversity(batch)
            trigger = trigger | jnp.where(
                div < self.diversity_floor, TRIGGER_DIVERSITY, 0
            )
        if self.stagnation_limit is not None:
            trigger = trigger | jnp.where(
                stagnation >= self.stagnation_limit, TRIGGER_STAGNATION, 0
            )

        checked = state.replace(
            inner=inner,
            best_x=best_x,
            best_fitness=best_fitness,
            stagnation=stagnation,
            last_trigger=trigger,
        )
        return jax.lax.cond(trigger > 0, self._restart, lambda s: s, checked)

    def _restart(self, state: GuardedState) -> GuardedState:
        key, k_init = jax.random.split(state.key)
        fresh = self.algorithm.init(k_init)
        fresh = recenter_state(fresh, state.best_x)
        return state.replace(
            inner=fresh,
            stagnation=jnp.zeros((), dtype=jnp.int32),
            restarts=state.restarts + 1,
            key=key,
        )

    def _nonfinite_in(self, tree: Any) -> jax.Array:
        bad = jnp.zeros((), dtype=bool)
        for leaf in jax.tree.leaves(tree):
            x = jnp.asarray(leaf)
            if not jnp.issubdtype(x.dtype, jnp.floating):
                continue
            bad = bad | jnp.any(jnp.isnan(x))
            if self.check_inf:
                bad = bad | jnp.any(jnp.isinf(x))
        return bad

    @staticmethod
    def _diversity(pop: Any) -> jax.Array:
        """Finite-masked mean per-dimension std over the batch axis —
        the same statistic TelemetryMonitor rings (telemetry.py)."""
        std_sum = jnp.zeros((), dtype=jnp.float32)
        n_dims = 0
        for x in jax.tree.leaves(pop):
            x = jnp.asarray(x)
            if not jnp.issubdtype(x.dtype, jnp.floating):
                continue
            flat = jnp.asarray(x, jnp.float32).reshape(x.shape[0], -1)
            ok = jnp.isfinite(flat)
            n = jnp.maximum(jnp.sum(ok.astype(jnp.float32), axis=0), 1.0)
            mean = jnp.sum(jnp.where(ok, flat, 0.0), axis=0) / n
            var = jnp.sum(jnp.where(ok, (flat - mean) ** 2, 0.0), axis=0) / n
            std_sum = std_sum + jnp.sum(jnp.sqrt(var))
            n_dims += flat.shape[1]
        return std_sum / max(n_dims, 1)

    # -------------------------------------------------------------- report
    def health_report(self, state: GuardedState) -> dict:
        """Eager JSON-friendly snapshot of the wrapper's health counters."""
        trig = int(state.last_trigger)
        return {
            "restarts": int(state.restarts),
            "stagnation": int(state.stagnation),
            "best_fitness": float(state.best_fitness),
            "pop_size": int(state.pop_size),
            "algorithm": type(self.algorithm).__name__,
            "last_trigger": trig,
            "last_trigger_names": [
                name
                for bit, name in (
                    (TRIGGER_NONFINITE, "nonfinite_state"),
                    (TRIGGER_SIGMA, "sigma_collapse"),
                    (TRIGGER_DIVERSITY, "diversity_collapse"),
                    (TRIGGER_STAGNATION, "stagnation"),
                )
                if trig & bit
            ],
        }


class IPOPRestarts:
    """Host-boundary IPOP policy: double the population on restart.

    Population growth needs new static shapes — on TPU that means a new
    compiled program, so growth lives BETWEEN dispatches (one recompile
    per doubling, amortized over the whole restart segment). Consumed by
    ``StdWorkflow.run(restarts=...)`` and ``run_host_pipelined(...,
    restarts=...)`` (workflows/ipop.py), which chunk the run at
    ``check_every`` generations and consult the GuardedAlgorithm counters
    between chunks.

    Args:
        algorithm_factory: ``pop_size -> Algorithm``; must return a
            :class:`GuardedAlgorithm` (the device-side detector the host
            boundary reads). Must be deterministic in ``pop_size`` so a
            resumed run rebuilds the identical program.
        max_restarts: population doublings allowed (IPOP budget).
        growth: population multiplier per restart (2 = classic IPOP).
        check_every: generations per dispatch segment between host checks.
        stagnation_limit: additionally escalate when the guarded state's
            stagnation counter reaches this limit, even if no on-device
            restart fired (lets the device wrapper keep only cheap NaN /
            sigma guards while the host owns stagnation escalation).
        handoff_pop: population threshold for the low-memory handoff —
            once a doubling reaches/crosses it, the restart builds from
            ``handoff_factory`` instead of ``algorithm_factory``, so IPOP
            growth escapes the dense track's single-device memory/eigh
            wall (``es.common.EighScaleError``) onto the sharded
            low-memory track (e.g. ``GuardedAlgorithm(ShardedES(
            SepCMAES(...), mesh))``). Must be paired with
            ``handoff_factory``. The handoff is deterministic in
            ``pop_size``, so checkpointed resumes rebuild the identical
            (possibly handed-off) program; each handoff is surfaced in
            ``run_report()["guardrail"]["ipop"]``.
        handoff_factory: ``pop_size -> GuardedAlgorithm`` over the
            low-memory track, used at/past ``handoff_pop``.
    """

    def __init__(
        self,
        algorithm_factory,
        max_restarts: int = 4,
        growth: int = 2,
        check_every: int = 50,
        stagnation_limit: Optional[int] = None,
        handoff_pop: Optional[int] = None,
        handoff_factory=None,
    ):
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
        if growth < 2:
            raise ValueError(f"growth must be >= 2, got {growth}")
        if check_every < 1:
            raise ValueError(f"check_every must be >= 1, got {check_every}")
        if (handoff_pop is None) != (handoff_factory is None):
            raise ValueError(
                "handoff_pop and handoff_factory must be given together"
            )
        self.algorithm_factory = algorithm_factory
        self.max_restarts = max_restarts
        self.growth = growth
        self.check_every = check_every
        self.stagnation_limit = stagnation_limit
        self.handoff_pop = handoff_pop
        self.handoff_factory = handoff_factory

    def uses_handoff(self, pop_size: int) -> bool:
        """Whether a (re)build at ``pop_size`` lands on the low-memory
        handoff track — pure in ``pop_size`` so resumes re-derive it."""
        return self.handoff_pop is not None and pop_size >= self.handoff_pop

    def make_algorithm(self, pop_size: int) -> "GuardedAlgorithm":
        factory = (
            self.handoff_factory
            if self.uses_handoff(pop_size)
            else self.algorithm_factory
        )
        algo = factory(pop_size)
        if not isinstance(algo, GuardedAlgorithm):
            raise TypeError(
                "IPOPRestarts factories must return a GuardedAlgorithm "
                "(the on-device detector the host boundary reads); got "
                f"{type(algo).__name__}"
            )
        return algo
