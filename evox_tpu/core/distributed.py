"""Mesh-native distributed primitives.

Replaces the reference's pmap + Ray stack (reference: src/evox/core/
distributed.py, src/evox/workflows/distributed.py) with the modern JAX
sharding model: one global ``jax.sharding.Mesh`` whose default axis is
``"pop"``; population arrays are sharded along ``"pop"``; algorithm state is
replicated; collectives (all_gather / psum over fitness) ride ICI within a
TPU slice and DCN across slices, inserted either automatically by GSPMD from
sharding constraints or explicitly inside ``shard_map`` islands.

Multi-host: call :func:`init_distributed` (a thin wrapper over
``jax.distributed.initialize``) on every host, then build the mesh over
``jax.devices()`` — the same single-program step then runs SPMD across the
whole pod, which is the TPU-native equivalent of the reference's
``jax.distributed`` + NCCL path and entirely replaces its Ray RPC path for
jittable problems.
"""

from __future__ import annotations

import re
from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

POP_AXIS = "pop"
# Second mesh axis for multi-tenant fleets (workflows/tenancy.py): N
# independent runs vmap-stacked on a leading tenant axis lay out on a
# (TENANT, POP) 2-D mesh — tenant-leading leaves sharded over "tenant",
# per-individual leaves over ("tenant", "pop").
TENANT_AXIS = "tenant"

__all__ = [
    "POP_AXIS",
    "TENANT_AXIS",
    "create_mesh",
    "pop_sharding",
    "replicated_sharding",
    "shard_pop",
    "place_pop",
    "replicate",
    "match_partition_rules",
    "state_sharding",
    "constrain_state",
    "place_state",
    "all_gather",
    "tree_all_gather",
    "init_distributed",
    "process_id",
    "process_count",
    "is_dist_initialized",
]


def create_mesh(
    axis_names: Sequence[str] = (POP_AXIS,),
    devices: Optional[Sequence[jax.Device]] = None,
    shape: Optional[Sequence[int]] = None,
) -> Mesh:
    """Build a device mesh. Default: 1-D mesh named ``"pop"`` over all devices."""
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices)
    if shape is None:
        shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    return Mesh(devices.reshape(shape), axis_names)


def pop_sharding(mesh: Mesh, axis_name: str = POP_AXIS) -> NamedSharding:
    """Sharding that splits the leading (population) axis across the mesh."""
    return NamedSharding(mesh, P(axis_name))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated sharding over the mesh."""
    return NamedSharding(mesh, P())


def _constrain(tree: Any, sharding: NamedSharding) -> Any:
    return jax.tree.map(lambda x: jax.lax.with_sharding_constraint(x, sharding), tree)


def shard_pop(tree: Any, mesh: Optional[Mesh], axis_name: str = POP_AXIS) -> Any:
    """Constrain every leaf's leading axis to be sharded over ``axis_name``.

    No-op when ``mesh`` is None (single-device path compiles identically).
    """
    if mesh is None:
        return tree
    return _constrain(tree, pop_sharding(mesh, axis_name))


def replicate(tree: Any, mesh: Optional[Mesh]) -> Any:
    """Constrain every leaf to be replicated over the mesh (no-op sans mesh)."""
    if mesh is None:
        return tree
    return _constrain(tree, replicated_sharding(mesh))


def _spec_for_path(state: Any, path: tuple, default: "P") -> "P":
    """Resolve the deepest ``field(sharding=...)`` annotation along a pytree
    key path (inner annotations override outer ones)."""
    import dataclasses

    obj, spec = state, default
    for key in path:
        if isinstance(key, jax.tree_util.GetAttrKey) and dataclasses.is_dataclass(obj):
            f = obj.__dataclass_fields__.get(key.name)
            if f is not None and f.metadata.get("sharding") is not None:
                spec = f.metadata["sharding"]
            obj = getattr(obj, key.name)
        elif isinstance(key, jax.tree_util.SequenceKey):
            obj = obj[key.idx]
        elif isinstance(key, jax.tree_util.DictKey):
            obj = obj[key.key]
        else:
            break
    return spec


def match_partition_rules(
    rules: Sequence[Tuple[str, "P"]],
    tree: Any,
    default: Optional["P"] = None,
    strict: bool = False,
) -> Any:
    """A pytree of ``PartitionSpec`` assigned by REGEX RULES over leaf key
    paths — the rule-driven alternative to per-field annotations (the
    ``match_partition_rules`` pattern of LLM sharding stacks, SNIPPETS.md
    [2]), for states whose layout the annotations don't (or shouldn't)
    describe: tenant-stacked fleets, externally defined pytrees, one-off
    layout experiments.

    ``rules``: ``[(pattern, spec), ...]`` tried in order against each
    leaf's ``jax.tree_util.keystr`` path (``re.search`` semantics, so
    ``r"\\.population$"`` anchors a suffix and ``r"algo"`` matches
    anywhere); the FIRST match wins. Scalar (0-d) leaves always resolve
    to ``P()`` — there is nothing to partition. Unmatched leaves get
    ``default`` (``None`` keeps them unconstrained / GSPMD-propagated);
    ``strict=True`` raises on an unmatched leaf instead, the
    exhaustiveness check of the exemplar.

    Returns a pytree of ``PartitionSpec``/``None`` matching ``tree`` —
    feed it to :func:`constrain_state` (``rules=`` takes the raw rule
    list directly), ``jax.device_put`` via ``NamedSharding``, or jit's
    ``in_shardings``."""
    resolve = _rule_resolver(rules)

    def assign(path, leaf):
        if getattr(leaf, "ndim", None) == 0:
            return P()
        spec = resolve(path, leaf)
        if spec is not None:
            return spec
        if strict:
            raise ValueError(
                "no partition rule matched leaf "
                f"{jax.tree_util.keystr(path)!r}"
            )
        return default

    return jax.tree_util.tree_map_with_path(assign, tree)


def _rule_resolver(rules: Optional[Sequence[Tuple[str, "P"]]]):
    """Compile ``rules`` into ``path -> spec | None`` (None = no match)."""
    if not rules:
        return lambda path, leaf: None
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def resolve(path, leaf):
        if getattr(leaf, "ndim", None) == 0:
            return P()
        name = jax.tree_util.keystr(path)
        for pat, spec in compiled:
            if pat.search(name) is not None:
                return spec
        return None

    return resolve


def _prefix_spec(spec: "P", leaf: Any, axis_prefix: Optional[str]) -> "P":
    """Shift ``spec`` one axis right under ``axis_prefix`` (the stacked-
    state law: ``P("pop")`` -> ``P(prefix, "pop")``, ``P()`` ->
    ``P(prefix)``); leaves too narrow for the inner spec fall back to
    prefix-only (or fully replicated for scalars)."""
    if axis_prefix is None or axis_prefix in spec:
        return spec
    if getattr(leaf, "ndim", 0) < 1 + len(spec):
        return P(axis_prefix) if getattr(leaf, "ndim", 0) >= 1 else P()
    return P(axis_prefix, *spec)


def state_sharding(
    state: Any,
    mesh: Mesh,
    default: Optional["P"] = None,
    rules: Optional[Sequence[Tuple[str, "P"]]] = None,
    axis_prefix: Optional[str] = None,
) -> Any:
    """A pytree of ``NamedSharding`` matching ``state``, driven by the
    ``field(sharding=...)`` annotations on its dataclasses (unannotated
    fields get ``default``, replicated unless overridden).

    This is the consumer the reference's sharding metadata never had
    (reference state.py:304-334 ``get_state_sharding`` exists but
    StdWorkflow ignores it): feed the result to ``jax.device_put``,
    ``with_sharding_constraint`` or jit's ``in_shardings``.

    ``rules`` / ``axis_prefix``: same semantics as
    :func:`constrain_state` — regex rules override annotations per leaf
    path, and every resolved spec is shifted under ``axis_prefix``
    (tenant-stacked fleet states, :mod:`evox_tpu.workflows.tenancy`).
    """
    default = P() if default is None else default
    rule_spec = _rule_resolver(rules)

    def resolve(path, leaf):
        spec = rule_spec(path, leaf)
        if spec is None:
            spec = _spec_for_path(state, path, default)
        return NamedSharding(mesh, _prefix_spec(spec, leaf, axis_prefix))

    return jax.tree_util.tree_map_with_path(resolve, state)


def constrain_state(
    state: Any,
    mesh: Optional[Mesh],
    policy: Any = None,
    rules: Optional[Sequence[Tuple[str, "P"]]] = None,
    axis_prefix: Optional[str] = None,
) -> Any:
    """Tracing-time: constrain ANNOTATED leaves to their declared sharding.

    Unannotated leaves are left to GSPMD's propagation (constraining them
    to replicated would pessimize algorithms whose working arrays are
    naturally population-sharded).

    ``policy``: an optional :class:`~evox_tpu.core.dtype_policy.
    DtypePolicy`. When active, ``field(storage=True)``-annotated float
    leaves are additionally cast to the policy's *storage* dtype in the
    same tree walk — this is the workflow's end-of-step boundary, so the
    loop-carried state leaves HBM at half width while every in-step
    reduction already ran in the compute dtype (see core/dtype_policy.py).
    ``policy=None`` (or a no-op policy) changes nothing, and a policy
    applies even without a mesh (single-device bf16 storage is the same
    bytes win).

    ``rules``: optional ``[(regex, PartitionSpec), ...]`` matched against
    leaf key paths BEFORE the field annotations (first match wins; see
    :func:`match_partition_rules`) — the escape hatch for layouts the
    annotations don't describe.

    ``axis_prefix``: prepend a mesh axis to every resolved spec —
    ``P(POP_AXIS)`` becomes ``P(axis_prefix, POP_AXIS)`` and ``P()``
    becomes ``P(axis_prefix)``. This is how a TENANT-stacked state (every
    leaf grew a leading tenant axis, :mod:`evox_tpu.workflows.tenancy`)
    reuses the per-field annotations unchanged on a (TENANT, POP) 2-D
    mesh: the stacking axis shards over ``axis_prefix`` while each
    field's own layout shifts one axis right — no per-state annotation
    churn. Ignored for specs already naming the prefix axis."""
    from .dtype_policy import _castable, _storage_flag_for_path

    active = policy is not None and not policy.is_noop
    if mesh is None and not active:
        return state
    rule_spec = _rule_resolver(rules)

    def constrain(path, x):
        if active and _castable(x) and _storage_flag_for_path(state, path):
            x = jax.lax.convert_element_type(x, policy.storage)
        if mesh is None:
            return x
        spec = rule_spec(path, x)
        if spec is None:
            spec = _spec_for_path(state, path, None)
        if spec is None:
            return x
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, _prefix_spec(spec, x, axis_prefix))
        )

    return jax.tree_util.tree_map_with_path(constrain, state)


def place_state(
    state: Any,
    mesh: Optional[Mesh],
    rules: Optional[Sequence[Tuple[str, "P"]]] = None,
    axis_prefix: Optional[str] = None,
) -> Any:
    """Eager: ``device_put`` every leaf onto its annotated sharding
    (``rules``/``axis_prefix`` as :func:`state_sharding` — the restore
    path for tenant-stacked fleet snapshots)."""
    if mesh is None:
        return state
    shardings = state_sharding(
        state, mesh, rules=rules, axis_prefix=axis_prefix
    )
    return jax.tree.map(jax.device_put, state, shardings)


def place_pop(tree: Any, mesh: Optional[Mesh], axis_name: str = POP_AXIS) -> Any:
    """EAGER placement: ``device_put`` every leaf with its leading axis
    sharded over ``axis_name``. Use when loading host data or a restored
    checkpoint into a mesh layout (``shard_pop`` is the tracing-time
    constraint form and only works inside jit)."""
    if mesh is None:
        return tree
    s = pop_sharding(mesh, axis_name)
    return jax.tree.map(lambda x: jax.device_put(x, s), tree)


def all_gather(x: jax.Array, axis_name: str = POP_AXIS, tiled: bool = True) -> jax.Array:
    """``lax.all_gather`` for use *inside* shard_map islands."""
    return jax.lax.all_gather(x, axis_name, axis=0, tiled=tiled)


def tree_all_gather(tree: Any, axis_name: str = POP_AXIS, tiled: bool = True) -> Any:
    return jax.tree.map(lambda x: all_gather(x, axis_name, tiled), tree)


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    **kwargs: Any,
) -> None:
    """Initialize multi-host JAX (call once per host before building meshes).

    On TPU pods the arguments are auto-detected from the environment, so a
    bare ``init_distributed()`` suffices.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )


def process_id() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_dist_initialized() -> bool:
    return jax.process_count() > 1
