"""Mesh-native distributed primitives.

Replaces the reference's pmap + Ray stack (reference: src/evox/core/
distributed.py, src/evox/workflows/distributed.py) with the modern JAX
sharding model: one global ``jax.sharding.Mesh`` whose default axis is
``"pop"``; population arrays are sharded along ``"pop"``; algorithm state is
replicated; collectives (all_gather / psum over fitness) ride ICI within a
TPU slice and DCN across slices, inserted either automatically by GSPMD from
sharding constraints or explicitly inside ``shard_map`` islands.

Multi-host: call :func:`init_distributed` (an idempotency-guarded wrapper
over ``jax.distributed.initialize``) on every process FIRST, build the
global mesh with :func:`create_pod_mesh` (pod-ordered devices: each
process's local devices contiguous along the sharded axis), assemble
eager states into global arrays with :func:`ensure_global_state` — the
same single-program step then runs SPMD across the whole pod, which is
the TPU-native equivalent of the reference's ``jax.distributed`` + NCCL
path and entirely replaces its Ray RPC path for jittable problems.
Host-side rendezvous (checkpoint commits) rides :func:`process_barrier`;
cross-process host readbacks ride :func:`host_value`. The whole layer is
exercised end to end by ``__graft_entry__.dryrun_multihost(n)``
(real coordinator + n worker processes; GUIDE.md §6 "going multi-host").
"""

from __future__ import annotations

import functools
import re
import warnings
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

POP_AXIS = "pop"
# Second mesh axis for multi-tenant fleets (workflows/tenancy.py): N
# independent runs vmap-stacked on a leading tenant axis lay out on a
# (TENANT, POP) 2-D mesh — tenant-leading leaves sharded over "tenant",
# per-individual leaves over ("tenant", "pop").
TENANT_AXIS = "tenant"

__all__ = [
    "POP_AXIS",
    "TENANT_AXIS",
    "create_mesh",
    "pop_sharding",
    "replicated_sharding",
    "shard_pop",
    "place_pop",
    "replicate",
    "match_partition_rules",
    "state_sharding",
    "annotation_specs",
    "constrain_state",
    "place_state",
    "all_gather",
    "tree_all_gather",
    "ShardedES",
    "sharded_es_tell",
    "init_distributed",
    "shutdown_distributed",
    "process_id",
    "process_count",
    "is_dist_initialized",
    "BarrierTimeoutError",
    "pod_devices",
    "create_pod_mesh",
    "mesh_spans_processes",
    "process_barrier",
    "assemble_global_array",
    "host_value",
    "tree_host_value",
    "ensure_global_state",
]


def create_mesh(
    axis_names: Sequence[str] = (POP_AXIS,),
    devices: Optional[Sequence[jax.Device]] = None,
    shape: Optional[Sequence[int]] = None,
) -> Mesh:
    """Build a device mesh. Default: 1-D mesh named ``"pop"`` over all devices."""
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices)
    if shape is None:
        shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    return Mesh(devices.reshape(shape), axis_names)


def pop_sharding(mesh: Mesh, axis_name: str = POP_AXIS) -> NamedSharding:
    """Sharding that splits the leading (population) axis across the mesh."""
    return NamedSharding(mesh, P(axis_name))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated sharding over the mesh."""
    return NamedSharding(mesh, P())


def _constrain(tree: Any, sharding: NamedSharding) -> Any:
    return jax.tree.map(lambda x: jax.lax.with_sharding_constraint(x, sharding), tree)


def shard_pop(tree: Any, mesh: Optional[Mesh], axis_name: str = POP_AXIS) -> Any:
    """Constrain every leaf's leading axis to be sharded over ``axis_name``.

    No-op when ``mesh`` is None (single-device path compiles identically).
    """
    if mesh is None:
        return tree
    return _constrain(tree, pop_sharding(mesh, axis_name))


def replicate(tree: Any, mesh: Optional[Mesh]) -> Any:
    """Constrain every leaf to be replicated over the mesh (no-op sans mesh)."""
    if mesh is None:
        return tree
    return _constrain(tree, replicated_sharding(mesh))


def _spec_for_path(state: Any, path: tuple, default: "P") -> "P":
    """Resolve the deepest ``field(sharding=...)`` annotation along a pytree
    key path (inner annotations override outer ones)."""
    import dataclasses

    obj, spec = state, default
    for key in path:
        if isinstance(key, jax.tree_util.GetAttrKey) and dataclasses.is_dataclass(obj):
            f = obj.__dataclass_fields__.get(key.name)
            if f is not None and f.metadata.get("sharding") is not None:
                spec = f.metadata["sharding"]
            obj = getattr(obj, key.name)
        elif isinstance(key, jax.tree_util.SequenceKey):
            obj = obj[key.idx]
        elif isinstance(key, jax.tree_util.DictKey):
            obj = obj[key.key]
        else:
            break
    return spec


def match_partition_rules(
    rules: Sequence[Tuple[str, "P"]],
    tree: Any,
    default: Optional["P"] = None,
    strict: bool = False,
) -> Any:
    """A pytree of ``PartitionSpec`` assigned by REGEX RULES over leaf key
    paths — the rule-driven alternative to per-field annotations (the
    ``match_partition_rules`` pattern of LLM sharding stacks, SNIPPETS.md
    [2]), for states whose layout the annotations don't (or shouldn't)
    describe: tenant-stacked fleets, externally defined pytrees, one-off
    layout experiments.

    ``rules``: ``[(pattern, spec), ...]`` tried in order against each
    leaf's ``jax.tree_util.keystr`` path (``re.search`` semantics, so
    ``r"\\.population$"`` anchors a suffix and ``r"algo"`` matches
    anywhere); the FIRST match wins. Scalar (0-d) leaves always resolve
    to ``P()`` — there is nothing to partition. Unmatched leaves get
    ``default`` (``None`` keeps them unconstrained / GSPMD-propagated);
    ``strict=True`` raises on an unmatched leaf instead, the
    exhaustiveness check of the exemplar.

    Returns a pytree of ``PartitionSpec``/``None`` matching ``tree`` —
    feed it to :func:`constrain_state` (``rules=`` takes the raw rule
    list directly), ``jax.device_put`` via ``NamedSharding``, or jit's
    ``in_shardings``."""
    resolve = _rule_resolver(rules)

    def assign(path, leaf):
        if getattr(leaf, "ndim", None) == 0:
            return P()
        spec = resolve(path, leaf)
        if spec is not None:
            return spec
        if strict:
            raise ValueError(
                "no partition rule matched leaf "
                f"{jax.tree_util.keystr(path)!r}"
            )
        return default

    return jax.tree_util.tree_map_with_path(assign, tree)


def _rule_resolver(rules: Optional[Sequence[Tuple[str, "P"]]]):
    """Compile ``rules`` into ``path -> spec | None`` (None = no match)."""
    if not rules:
        return lambda path, leaf: None
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def resolve(path, leaf):
        if getattr(leaf, "ndim", None) == 0:
            return P()
        name = jax.tree_util.keystr(path)
        for pat, spec in compiled:
            if pat.search(name) is not None:
                return spec
        return None

    return resolve


def _prefix_spec(spec: "P", leaf: Any, axis_prefix: Optional[str]) -> "P":
    """Shift ``spec`` one axis right under ``axis_prefix`` (the stacked-
    state law: ``P("pop")`` -> ``P(prefix, "pop")``, ``P()`` ->
    ``P(prefix)``); leaves too narrow for the inner spec fall back to
    prefix-only (or fully replicated for scalars)."""
    if axis_prefix is None or axis_prefix in spec:
        return spec
    if getattr(leaf, "ndim", 0) < 1 + len(spec):
        return P(axis_prefix) if getattr(leaf, "ndim", 0) >= 1 else P()
    return P(axis_prefix, *spec)


def state_sharding(
    state: Any,
    mesh: Mesh,
    default: Optional["P"] = None,
    rules: Optional[Sequence[Tuple[str, "P"]]] = None,
    axis_prefix: Optional[str] = None,
) -> Any:
    """A pytree of ``NamedSharding`` matching ``state``, driven by the
    ``field(sharding=...)`` annotations on its dataclasses (unannotated
    fields get ``default``, replicated unless overridden).

    This is the consumer the reference's sharding metadata never had
    (reference state.py:304-334 ``get_state_sharding`` exists but
    StdWorkflow ignores it): feed the result to ``jax.device_put``,
    ``with_sharding_constraint`` or jit's ``in_shardings``.

    ``rules`` / ``axis_prefix``: same semantics as
    :func:`constrain_state` — regex rules override annotations per leaf
    path, and every resolved spec is shifted under ``axis_prefix``
    (tenant-stacked fleet states, :mod:`evox_tpu.workflows.tenancy`).
    """
    default = P() if default is None else default
    rule_spec = _rule_resolver(rules)

    def resolve(path, leaf):
        spec = rule_spec(path, leaf)
        if spec is None:
            spec = _spec_for_path(state, path, default)
        return NamedSharding(mesh, _prefix_spec(spec, leaf, axis_prefix))

    return jax.tree_util.tree_map_with_path(resolve, state)


def constrain_state(
    state: Any,
    mesh: Optional[Mesh],
    policy: Any = None,
    rules: Optional[Sequence[Tuple[str, "P"]]] = None,
    axis_prefix: Optional[str] = None,
) -> Any:
    """Tracing-time: constrain ANNOTATED leaves to their declared sharding.

    Unannotated leaves are left to GSPMD's propagation (constraining them
    to replicated would pessimize algorithms whose working arrays are
    naturally population-sharded).

    ``policy``: an optional :class:`~evox_tpu.core.dtype_policy.
    DtypePolicy`. When active, ``field(storage=True)``-annotated float
    leaves are additionally cast to the policy's *storage* dtype in the
    same tree walk — this is the workflow's end-of-step boundary, so the
    loop-carried state leaves HBM at half width while every in-step
    reduction already ran in the compute dtype (see core/dtype_policy.py).
    ``policy=None`` (or a no-op policy) changes nothing, and a policy
    applies even without a mesh (single-device bf16 storage is the same
    bytes win).

    ``rules``: optional ``[(regex, PartitionSpec), ...]`` matched against
    leaf key paths BEFORE the field annotations (first match wins; see
    :func:`match_partition_rules`) — the escape hatch for layouts the
    annotations don't describe.

    ``axis_prefix``: prepend a mesh axis to every resolved spec —
    ``P(POP_AXIS)`` becomes ``P(axis_prefix, POP_AXIS)`` and ``P()``
    becomes ``P(axis_prefix)``. This is how a TENANT-stacked state (every
    leaf grew a leading tenant axis, :mod:`evox_tpu.workflows.tenancy`)
    reuses the per-field annotations unchanged on a (TENANT, POP) 2-D
    mesh: the stacking axis shards over ``axis_prefix`` while each
    field's own layout shifts one axis right — no per-state annotation
    churn. Ignored for specs already naming the prefix axis."""
    from .dtype_policy import _castable, _storage_flag_for_path

    active = policy is not None and not policy.is_noop
    if mesh is None and not active:
        return state
    rule_spec = _rule_resolver(rules)

    def constrain(path, x):
        if active and _castable(x) and _storage_flag_for_path(state, path):
            x = jax.lax.convert_element_type(x, policy.storage)
        if mesh is None:
            return x
        spec = rule_spec(path, x)
        if spec is None:
            spec = _spec_for_path(state, path, None)
        if spec is None:
            return x
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, _prefix_spec(spec, x, axis_prefix))
        )

    return jax.tree_util.tree_map_with_path(constrain, state)


def place_state(
    state: Any,
    mesh: Optional[Mesh],
    rules: Optional[Sequence[Tuple[str, "P"]]] = None,
    axis_prefix: Optional[str] = None,
) -> Any:
    """Eager: ``device_put`` every leaf onto its annotated sharding
    (``rules``/``axis_prefix`` as :func:`state_sharding` — the restore
    path for tenant-stacked fleet snapshots). On a mesh spanning
    processes this routes through :func:`ensure_global_state` — each
    process assembles only its addressable shards from the full host
    value (the process-count-portable checkpoint-restore path)."""
    if mesh is None:
        return state
    if mesh_spans_processes(mesh):
        return ensure_global_state(
            state, mesh, rules=rules, axis_prefix=axis_prefix
        )
    shardings = state_sharding(
        state, mesh, rules=rules, axis_prefix=axis_prefix
    )
    return jax.tree.map(jax.device_put, state, shardings)


def annotation_specs(state: Any, default: "P" = P()) -> Any:
    """A pytree of ``PartitionSpec`` matching ``state``, resolved purely
    from the per-field ``field(sharding=...)`` annotations (the mesh-free
    sibling of :func:`state_sharding`) — e.g. the ``in_specs`` of a
    ``shard_map`` island over an annotated state (:class:`ShardedES`)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: _spec_for_path(state, path, default), state
    )


def place_pop(tree: Any, mesh: Optional[Mesh], axis_name: str = POP_AXIS) -> Any:
    """EAGER placement: ``device_put`` every leaf with its leading axis
    sharded over ``axis_name``. Use when loading host data or a restored
    checkpoint into a mesh layout (``shard_pop`` is the tracing-time
    constraint form and only works inside jit). Pod meshes assemble the
    per-process shards (:func:`assemble_global_array`)."""
    if mesh is None:
        return tree
    s = pop_sharding(mesh, axis_name)
    if mesh_spans_processes(mesh):
        return jax.tree.map(lambda x: assemble_global_array(x, s), tree)
    return jax.tree.map(lambda x: jax.device_put(x, s), tree)


def all_gather(x: jax.Array, axis_name: str = POP_AXIS, tiled: bool = True) -> jax.Array:
    """``lax.all_gather`` for use *inside* shard_map islands."""
    return jax.lax.all_gather(x, axis_name, axis=0, tiled=tiled)


def tree_all_gather(tree: Any, axis_name: str = POP_AXIS, tiled: bool = True) -> Any:
    return jax.tree.map(lambda x: all_gather(x, axis_name, tiled), tree)


# --------------------------------------------------------------------------
# Gather-free POP-sharded large-population ES (PR 10, ROADMAP item 4).
#
# "Massively parallel CMA-ES with increasing population" (PAPERS.md) shows
# the CMA family keeps improving at pop ~ 1e4..1e6 on parallel hardware —
# but a naive mesh run still materializes the full (pop, dim) sample matrix
# on every device: jax.random's default threefry is non-partitionable (each
# device generates the FULL matrix and slices its shard), and the
# sort-select-recombine tell gathers the population to apply `z[order][:mu]`.
# The two pieces below close both holes for the low-memory CMA track
# (SepCMAES / LMMAES / RMES — diagonal / low-rank covariance):
#
# - sampling: each device draws only its own (pop/n_dev, dim) block from a
#   fold_in-derived per-shard stream inside a shard_map island
#   (`ShardedES.ask`);
# - recombination: "sort, select mu, dot with weights" is reformulated as
#   "weight every candidate by its global fitness RANK and sum" — ranks are
#   fitness-sized (pop floats, cheap to replicate), the weighted sums are
#   (dim,)-sized moments accumulated per shard and `psum`-reduced
#   (`sharded_es_tell`), and the weight table lookup is bitwise-identical
#   to the sorted-selection weights, so sharded == replicated up to
#   summation order (documented tolerance, tests/test_state_contracts.py).
#
# Per-device peak memory therefore scales as pop/n_dev, verified by AOT
# `memory_analysis()` + compiled-HLO inspection (tests/test_large_pop.py).


def _require_shard_protocol(algorithm: Any) -> None:
    missing = [
        name
        for name in ("ask_rows", "rank_weights", "pop_moments", "tell_with_moments")
        if not callable(getattr(algorithm, name, None))
    ]
    if missing or not getattr(algorithm, "pop_shard_capable", False):
        raise TypeError(
            f"{type(algorithm).__name__} does not implement the POP-sharded "
            "low-memory ES protocol (pop_shard_capable + ask_rows/"
            "rank_weights/pop_moments/tell_with_moments); capable "
            "algorithms: the low-memory CMA track (SepCMAES, LMMAES, RMES)"
            + (f"; missing: {missing}" if missing else "")
        )


def sharded_es_tell(
    algorithm: Any,
    state: Any,
    fitness: jax.Array,
    mesh: Mesh,
    axis_name: str = POP_AXIS,
) -> Any:
    """One gather-free ``tell`` over a POP-sharded sample matrix.

    Global fitness ranks are computed in the surrounding (GSPMD) program —
    fitness is ``(pop,)``-sized, cheap to gather/replicate — then a
    ``shard_map`` island turns each device's ``(pop/n_dev, dim)`` artifact
    shard into weighted partial moments and ``psum``s them; the small
    replicated strategy-state update (``tell_with_moments``) runs on the
    reduced ``(dim,)``/``(k, dim)`` moments. No collective ever moves a
    ``(pop, dim)`` operand. Works unchanged on a (TENANT, POP) 2-D mesh
    (PR 7): specs name only the ``pop`` axis, so tenant rows replicate."""
    if fitness.ndim != 1:
        raise ValueError(
            f"sharded_es_tell is single-objective; got fitness {fitness.shape}"
        )
    from ..utils.compat import shard_map  # deferred: utils import cycle-safe

    fields = tuple(algorithm.sharded_pop_fields)
    rows = {name: getattr(state, name) for name in fields}
    # global 0-based ranks as the scatter-inverse of ONE stable argsort
    # (identical to the classic double argsort — ties break by index,
    # exactly like the replicated z[argsort(fitness)][:mu] selection —
    # but one pop-sized sort cheaper)
    order = jnp.argsort(fitness)
    ranks = jnp.zeros_like(order).at[order].set(
        jnp.arange(order.shape[0], dtype=order.dtype)
    )

    def island(rows_local, ranks_local):
        w_local = algorithm.rank_weights(ranks_local)
        return jax.lax.psum(
            algorithm.pop_moments(rows_local, w_local), axis_name
        )

    moments = shard_map(
        island,
        mesh=mesh,
        in_specs=(
            {name: P(axis_name) for name in fields},
            P(axis_name),
        ),
        out_specs=P(),
        check_vma=False,
    )(rows, ranks)
    # reuse the rank sort for the top-mu SORTED fitness (fitness-sized
    # gather, no second pop-sized sort): RMES's PSR consumes it via the
    # same `f_sel` key the replicated tell threads; algorithms that don't
    # read it cost nothing (XLA dead-code-eliminates the gather)
    moments = dict(moments, f_sel=fitness[order][: algorithm.mu])
    return algorithm.tell_with_moments(state, moments, fitness)


class ShardedES:
    """Wrap a low-memory ES (SepCMAES / LMMAES / RMES) so every
    per-candidate array stays POP-sharded: per-shard sampling in ``ask``,
    psum-of-moments recombination in ``tell`` (:func:`sharded_es_tell`).

    Drop-in :class:`~evox_tpu.core.algorithm.Algorithm`: state type, field
    annotations and hyperparameter attributes are the wrapped algorithm's
    (attribute reads forward), so it composes with ``StdWorkflow`` (pass
    the same ``mesh``), :class:`~evox_tpu.core.guardrail.GuardedAlgorithm`
    (wrap OUTSIDE: ``GuardedAlgorithm(ShardedES(algo, mesh))``),
    ``DtypePolicy`` bf16 storage, donated fused runs, the
    ``GenerationExecutor``, and IPOP handoff
    (``IPOPRestarts(handoff_factory=...)``).

    Sampling law: ``ask`` splits the state key once, then shard ``s`` draws
    its block from ``fold_in(k, s)`` — on the mesh each device computes
    only its own block inside a ``shard_map`` island (jax's default
    threefry is NOT partitionable, so constraining a plain
    ``jax.random.normal`` would still materialize the full matrix per
    device). ``mesh=None`` with ``n_shards=N`` runs the SAME law
    replicated (concatenated blocks) — the reference the sharded path is
    tested against (bitwise-equal samples, psum-order-only differences).
    ``mesh=None, n_shards=1`` is the wrapped algorithm's legacy stream,
    bit-identical to the bare algorithm.

    Args:
        algorithm: a ``pop_shard_capable`` algorithm (the low-memory CMA
            track). Population size must divide ``n_shards``.
        mesh: mesh with a ``axis_name`` axis — 1-D ``(POP,)`` or the
            (TENANT, POP) 2-D mesh of workflows/tenancy.py (tenant rows
            replicate the strategy state; specs name only the pop axis).
        axis_name: mesh axis to shard the population over.
        n_shards: sampling-law shard count; defaults to the mesh's
            ``axis_name`` size (or 1 without a mesh). Pass explicitly on
            ``mesh=None`` to build the replicated reference of an n-device
            sharded run. May be any positive MULTIPLE of the mesh's
            ``axis_name`` size: each device then draws
            ``n_shards / n_dev`` consecutive sample blocks from its
            global block indices — the SAME sampling law on fewer
            devices, which is what makes a pod run topology-portable
            (an 8-shard trajectory killed mid-flight resumes on a
            4-device survivor mesh with ``n_shards=8`` and reproduces
            the uninjured law up to psum order; the pod-supervisor
            shrink-and-resume path, ISSUE 14).
    """

    is_pop_sharded = False  # overridden per instance when a mesh is given

    def __init__(
        self,
        algorithm: Any,
        mesh: Optional[Mesh] = None,
        axis_name: str = POP_AXIS,
        n_shards: Optional[int] = None,
    ):
        _require_shard_protocol(algorithm)
        if getattr(algorithm, "has_init_ask", False) or getattr(
            algorithm, "has_init_tell", False
        ):
            raise TypeError(
                "ShardedES supports steady-state ask/tell algorithms only "
                f"({type(algorithm).__name__} declares init_ask/init_tell)"
            )
        self.algorithm = algorithm
        self.mesh = mesh
        self.axis_name = axis_name
        if n_shards is None:
            n_shards = int(mesh.shape[axis_name]) if mesh is not None else 1
        self.n_shards = int(n_shards)
        if self.n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {self.n_shards}")
        if mesh is not None and self.n_shards % int(mesh.shape[axis_name]):
            raise ValueError(
                f"n_shards={self.n_shards} is not a multiple of the mesh's "
                f"'{axis_name}' axis ({int(mesh.shape[axis_name])}); the "
                "per-shard sampling law needs whole blocks per device"
            )
        pop = int(algorithm.pop_size)
        if pop % self.n_shards != 0:
            raise ValueError(
                f"pop_size {pop} is not divisible by n_shards={self.n_shards}"
            )
        self.is_pop_sharded = mesh is not None

    def __getattr__(self, name: str) -> Any:
        # only reached when normal lookup fails: forward hyperparameter
        # reads (pop_size, dim, mu, weights, ...) to the wrapped algorithm
        if name.startswith("__") or name == "algorithm":
            raise AttributeError(name)
        return getattr(object.__getattribute__(self, "algorithm"), name)

    # the steady-state-only contract asserted in __init__
    @property
    def has_init_ask(self) -> bool:
        return False

    @property
    def has_init_tell(self) -> bool:
        return False

    def _rename_axis(self, spec: "P") -> "P":
        """Field annotations name the canonical ``POP_AXIS``; substitute
        this wrapper's ``axis_name`` when the mesh calls it differently."""
        if self.axis_name == POP_AXIS:
            return spec
        return P(*(self.axis_name if ax == POP_AXIS else ax for ax in spec))

    def _state_shardings(self, state: Any) -> Any:
        """Per-leaf ``NamedSharding`` from the field annotations, with the
        pop axis renamed to ``axis_name`` (the placement twin of
        :meth:`_state_specs`)."""
        return jax.tree_util.tree_map(
            lambda sp: NamedSharding(self.mesh, sp),
            self._state_specs(state),
            is_leaf=lambda x: isinstance(x, P),
        )

    # ------------------------------------------------------------------ api
    def init(self, key: jax.Array) -> Any:
        if self.mesh is None:
            return self.algorithm.init(key)
        if isinstance(key, jax.core.Tracer):
            # inside a trace (e.g. GuardedAlgorithm's on-device restart):
            # constrain instead of placing — GSPMD lays the fresh state out
            state = self.algorithm.init(key)
            return jax.tree.map(
                jax.lax.with_sharding_constraint,
                state,
                self._state_shardings(state),
            )
        # eager: compile init with its OUTPUT shardings pinned to the field
        # annotations, so the (pop, dim) buffers are born sharded — never
        # materialized on one device and re-placed. On a pod mesh the key
        # operand must itself be a GLOBAL (replicated) array first — a
        # process-local committed array is not a legal global-jit operand
        if mesh_spans_processes(self.mesh):
            rep = NamedSharding(self.mesh, P())
            if _is_typed_key(key):
                key = jax.random.wrap_key_data(
                    assemble_global_array(
                        np.asarray(jax.device_get(jax.random.key_data(key))),
                        rep,
                    ),
                    impl=jax.random.key_impl(key),
                )
            else:
                key = assemble_global_array(
                    np.asarray(jax.device_get(key)), rep
                )
        sds = jax.eval_shape(self.algorithm.init, key)
        shardings = self._state_shardings(sds)
        return jax.jit(self.algorithm.init, out_shardings=shardings)(key)

    def ask(self, state: Any) -> Tuple[Any, Any]:
        if self.mesh is None and self.n_shards == 1:
            return self.algorithm.ask(state)  # legacy stream, bare-identical
        key, k = jax.random.split(state.key)
        shard = int(self.algorithm.pop_size) // self.n_shards
        fields = tuple(self.algorithm.sharded_pop_fields)
        if self.mesh is None:
            # replicated reference of the per-shard sampling law
            pops, arts = [], []
            for s in range(self.n_shards):
                p, a = self.algorithm.ask_rows(
                    state, jax.random.fold_in(k, s), shard
                )
                pops.append(p)
                arts.append(a)
            pop = jnp.concatenate(pops)
            art = {
                name: jnp.concatenate([a[name] for a in arts])
                for name in fields
            }
        else:
            from ..utils.compat import shard_map  # deferred (cycle-safe)

            axis = self.axis_name
            # n_shards may exceed the device count (shrunken survivor
            # mesh resuming a wider run's sampling law): device d owns
            # the consecutive global blocks [d*bpd, (d+1)*bpd) and
            # concatenates them — identical draws to the wider mesh,
            # just fewer devices holding more blocks each
            bpd = self.n_shards // int(self.mesh.shape[axis])

            def island(st, k_op):
                d = jax.lax.axis_index(axis)
                if bpd == 1:
                    return self.algorithm.ask_rows(
                        st, jax.random.fold_in(k_op, d), shard
                    )
                pops_b, arts_b = [], []
                for b in range(bpd):
                    p_b, a_b = self.algorithm.ask_rows(
                        st, jax.random.fold_in(k_op, d * bpd + b), shard
                    )
                    pops_b.append(p_b)
                    arts_b.append(a_b)
                return (
                    jnp.concatenate(pops_b),
                    {
                        name: jnp.concatenate([a[name] for a in arts_b])
                        for name in fields
                    },
                )

            pop, art = shard_map(
                island,
                mesh=self.mesh,
                # the state rides in under its own field annotations (the
                # (pop, dim) artifact enters as a local shard, unused by
                # ask_rows; the small strategy fields replicate), with the
                # annotations' POP_AXIS renamed to this wrapper's axis
                in_specs=(self._state_specs(state), P()),
                out_specs=(P(axis), {name: P(axis) for name in fields}),
                check_vma=False,
            )(state, k)
        return pop, state.replace(key=key, **art)

    def _state_specs(self, state: Any) -> Any:
        """Per-leaf shard_map specs from the field annotations
        (:func:`annotation_specs`), with ``POP_AXIS`` substituted by this
        wrapper's ``axis_name`` (the annotations name the canonical axis;
        the mesh may not)."""
        return jax.tree_util.tree_map(
            self._rename_axis,
            annotation_specs(state),
            is_leaf=lambda x: isinstance(x, P),
        )

    def tell(self, state: Any, fitness: jax.Array) -> Any:
        if self.mesh is None:
            return self.algorithm.tell(state, fitness)
        return sharded_es_tell(
            self.algorithm, state, fitness, self.mesh, self.axis_name
        )


# --------------------------------------------------------------------------
# Multi-process (pod-style) execution (PR 13, ROADMAP item 3).
#
# jax's multi-controller model: every process runs the SAME program over a
# mesh built from the GLOBAL device list (`jax.devices()` spans processes
# once `jax.distributed` is initialized); each process physically owns only
# its local devices, GSPMD inserts the cross-host collectives. Three host-
# side obligations fall out, owned by the helpers below:
#
# - mesh construction must put each process's local devices in a CONTIGUOUS
#   block of the sharded axis (`create_pod_mesh` sorts by (process_index,
#   id)), so a per-process data shard is a contiguous slice;
# - eager values (fresh inits, restored checkpoints) must become GLOBAL
#   arrays before a global-mesh jit may consume them — each process builds
#   its addressable shards from the full host value with
#   ``jax.make_array_from_single_device_arrays`` (`assemble_global_array` /
#   `ensure_global_state`); a plain ``device_put`` onto a cross-process
#   sharding is not legal;
# - host readbacks of a cross-process-sharded array must all-gather first
#   (`host_value`: a jitted identity with replicated out_shardings), and
#   host-side rendezvous (checkpoint commit) goes through the coordinator's
#   KV store (`process_barrier`) — no XLA collective, so it works even
#   where the backend cannot run one.
#
# `constrain_state` itself is already collective-aware: it is a TRACE-time
# constraint, and on a pod mesh GSPMD lowers the declared layouts to
# ICI/DCN collectives exactly as on a single host. The eager twin
# `place_state` routes through the assembly path on pod meshes.

# what THIS process passed to init_distributed (guards a second call even
# on jax builds whose global_state exposes nothing)
_INIT_RECORD: Optional[dict] = None


#: sentinel: the jax build exposes no distributed introspection at all
#: (distinct from "introspection works and there is no client")
_INTROSPECT_FAILED = object()


def _dist_client():
    """The live distributed-runtime client, None when introspection works
    and none is active, or :data:`_INTROSPECT_FAILED` on jax builds
    without `jax._src.distributed.global_state` (the only introspection
    point jax exposes)."""
    try:
        from jax._src import distributed as _jd

        return _jd.global_state.client
    except Exception:  # pragma: no cover - exotic jax builds
        return _INTROSPECT_FAILED


def _dist_process_info() -> Tuple[int, int]:
    """(process_id, num_processes) of the ACTIVE jax.distributed runtime
    WITHOUT touching the backend: ``jax.process_count()`` initializes
    the backend, and a multiprocess CPU backend init BLOCKS until every
    peer initializes too — so a barrier called before the backend is up
    (the pod supervisor's join/warmup rendezvous, a coordination-only
    worker) would wedge exactly where it must not. Falls back to the
    backend-derived counts only when the runtime exposes nothing."""
    try:
        from jax._src import distributed as _jd

        gs = _jd.global_state
        pid, n = gs.process_id, gs.num_processes
        if pid is not None and n is not None:
            return int(pid), int(n)
    except Exception:  # pragma: no cover - exotic jax builds
        pass
    return int(jax.process_index()), int(jax.process_count())


def _current_dist_config() -> dict:
    """Best-effort record of the ACTIVE jax.distributed configuration."""
    cfg: dict = dict(_INIT_RECORD or {})
    try:
        from jax._src import distributed as _jd

        gs = _jd.global_state
        for ours, theirs in (
            ("coordinator_address", "coordinator_address"),
            ("num_processes", "num_processes"),
            ("process_id", "process_id"),
        ):
            val = getattr(gs, theirs, None)
            if val is not None:
                cfg[ours] = val
    except Exception:  # pragma: no cover
        pass
    return cfg


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    **kwargs: Any,
) -> None:
    """Initialize multi-host JAX (call once per process, BEFORE any jax
    backend use, then build meshes over ``jax.devices()``).

    On TPU pods the arguments are auto-detected from the environment, so a
    bare ``init_distributed()`` suffices.

    Idempotency: ``jax.distributed.initialize`` raises an opaque jaxlib
    error on a second call ("must be called before any JAX computations"
    — true but useless when the real cause is double-init). This wrapper
    makes the second call explicit: a re-call whose arguments agree with
    the active configuration (or constrain nothing) is a WARNED NO-OP —
    the idempotent shape library/driver layers need — while a re-call
    naming a DIFFERENT coordinator/process layout raises a
    ``RuntimeError`` that says exactly which argument conflicts
    (tests/test_multihost.py regression-tests both through the
    ``dryrun_multihost`` harness)."""
    global _INIT_RECORD
    requested = {
        "coordinator_address": coordinator_address,
        "num_processes": num_processes,
        "process_id": process_id,
        **kwargs,
    }
    if is_dist_initialized():
        current = _current_dist_config()
        conflicts = {
            name: (req, current[name])
            for name, req in requested.items()
            if req is not None
            and current.get(name) is not None
            and req != current[name]
        }
        if conflicts:
            detail = ", ".join(
                f"{k}: requested {req!r} != active {cur!r}"
                for k, (req, cur) in sorted(conflicts.items())
            )
            raise RuntimeError(
                "init_distributed: jax.distributed is already initialized "
                f"with a CONFLICTING configuration ({detail}). One process "
                "belongs to one coordinator for its lifetime — restart the "
                "process to join a different one."
            )
        # arguments whose active value is unknowable (the first init ran
        # outside this wrapper, or jax's global_state doesn't expose the
        # field) cannot be verified as matching — say so instead of
        # claiming a match that was never checked
        unverified = sorted(
            name for name, req in requested.items()
            if req is not None and current.get(name) is None
        )
        note = (
            f" (arguments not verifiable against the active config and "
            f"IGNORED: {unverified})" if unverified else ""
        )
        warnings.warn(
            "init_distributed: jax.distributed is already initialized "
            f"(coordinator {current.get('coordinator_address')!r}, "
            f"{current.get('num_processes')} process(es)); this matching "
            f"call is a no-op{note}",
            stacklevel=2,
        )
        return
    # cache hardening (ISSUE 14 satellite): any jitted-replicate closure
    # cached for a PREVIOUS topology (a pod this process left via
    # shutdown_distributed, or a pre-distributed backend) must never run
    # on the re-formed pod — it was compiled for the dead device set
    _replicate_program.cache_clear()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )
    _INIT_RECORD = {k: v for k, v in requested.items() if v is not None}


def shutdown_distributed() -> None:
    """Tear down this process's ``jax.distributed`` membership (no-op
    when none is active) and invalidate every cross-process-compiled
    host-readback program.

    The ``host_value``/``tree_host_value`` replicate closures are cached
    per ``NamedSharding`` (:func:`_replicate_program`); a pod that
    re-forms after a failure builds a NEW mesh, but a sharding that
    hashes equal to a dead pod's (same spec, revived device objects on
    exotic backends) would silently reuse a program compiled for the
    dead topology and wedge the first readback of the healed run. The
    cache is therefore dropped on BOTH edges — here at shutdown and in
    :func:`init_distributed`'s real-init path — so a re-formed pod
    always compiles its gathers against the live topology
    (regression-tested via the re-init guard path, tests/
    test_pod_supervisor.py::
    test_replicate_cache_invalidated_on_shutdown_and_reinit)."""
    global _INIT_RECORD
    _replicate_program.cache_clear()
    _INIT_RECORD = None
    client = _dist_client()
    if client is not _INTROSPECT_FAILED and client is None:
        return
    try:
        jax.distributed.shutdown()
    except Exception as e:  # pragma: no cover - backend-dependent teardown
        warnings.warn(
            f"shutdown_distributed: jax.distributed.shutdown raised "
            f"{type(e).__name__}: {e} (caches were still invalidated)",
            stacklevel=2,
        )


def process_id() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_dist_initialized() -> bool:
    """True iff ``jax.distributed`` has been initialized in THIS process.

    Reads the distributed-runtime CLIENT, not ``jax.process_count() > 1``:
    a 1-process ``jax.distributed`` run (a pod job launched at n=1, a
    coordinator smoke test) is initialized but has one process, and the
    old count-based predicate misread it as uninitialized
    (ISSUE 13 satellite; regression-tested via the 1-process leg of the
    ``dryrun_multihost`` harness). The count check survives only as a
    last-ditch fallback for jax builds whose ``global_state`` is
    unreadable — a multi-process device list cannot exist without an
    initialized runtime. Never touches an UNinitialized backend: probing
    ``jax.process_count()`` would initialize it, which is precisely what
    callers checking "may I still init_distributed?" must not do.

    The live client is authoritative whenever introspection works: after
    ``jax.distributed.shutdown()`` the client is gone and this reads
    False again (so a re-``init_distributed`` actually re-initializes —
    the wrapper's own ``_INIT_RECORD`` must never shadow a shutdown)."""
    client = _dist_client()
    if client is not _INTROSPECT_FAILED:
        return client is not None
    # introspection unavailable: fall back to what THIS wrapper did,
    # then to the (backend-safe) process count
    if _INIT_RECORD is not None:  # pragma: no cover - exotic jax builds
        return True
    try:  # pragma: no cover - exotic jax builds
        from jax._src import xla_bridge as _xb

        backend_up = bool(getattr(_xb, "_backends", None))
    except Exception:
        backend_up = True
    return backend_up and jax.process_count() > 1  # pragma: no cover


def pod_devices() -> list:
    """The global device list in POD ORDER: sorted by ``(process_index,
    id)`` so each process's local devices form one contiguous block —
    the device order `create_pod_mesh` lays axes over."""
    return sorted(jax.devices(), key=lambda d: (d.process_index, d.id))


def create_pod_mesh(
    axis_names: Sequence[str] = (POP_AXIS,),
    shape: Optional[Sequence[int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a global mesh spanning every process's devices.

    The multi-host twin of :func:`create_mesh`: devices come from
    ``jax.devices()`` (the GLOBAL list once ``init_distributed`` ran on
    every process) sorted into pod order, so with the default C-order
    reshape each process's local devices occupy a contiguous block of the
    LEADING axis — a ``P("pop")``-sharded array then stores each
    process's population slice on that process, and the (TENANT, POP)
    2-D fleet mesh (``axis_names=(TENANT_AXIS, POP_AXIS), shape=(t,
    p)``) keeps whole tenant rows process-local whenever ``t`` is a
    multiple of the process count. Single-process it degenerates to
    exactly :func:`create_mesh`. Validates that every process
    contributes the same device count (jax requires symmetric
    processes) and that the mesh consumes the whole pod."""
    if devices is None:
        devices = pod_devices()
    devices = list(devices)
    n = len(devices)
    counts = {}
    for d in devices:
        counts[d.process_index] = counts.get(d.process_index, 0) + 1
    if len(set(counts.values())) > 1:
        raise ValueError(
            "create_pod_mesh: processes contribute unequal device counts "
            f"({counts}); a pod mesh needs symmetric processes"
        )
    if shape is None:
        shape = (n,) + (1,) * (len(axis_names) - 1)
    total = 1
    for s in shape:
        total *= int(s)
    if total != n:
        raise ValueError(
            f"create_pod_mesh: shape {tuple(shape)} does not consume the "
            f"{n} pod devices"
        )
    return Mesh(np.asarray(devices, dtype=object).reshape(shape), axis_names)


def mesh_spans_processes(mesh: Optional[Mesh]) -> bool:
    """True when ``mesh`` places devices of more than one process — the
    gate for the eager global-assembly paths below (a single-process mesh
    keeps the plain ``device_put`` fast path)."""
    if mesh is None:
        return False
    return len({d.process_index for d in mesh.devices.flat}) > 1


_BARRIER_SEQ = [0]

#: KV prefix under which every process records its barrier arrival — the
#: census the timeout path reads to NAME the processes that never came
_BARRIER_KV_PREFIX = "evox_tpu/barrier_arrival"


class BarrierTimeoutError(RuntimeError):
    """A :func:`process_barrier` deadline expired with peers missing —
    the cross-process twin of the dispatch-deadline error (ISSUE 14
    satellite: a barrier with a dead peer must raise a CLASSIFIED
    deadline naming the processes that never arrived, not block forever
    or die with an opaque coordination-service string).
    ``classify_error`` folds it into the ``deadline`` class; the pod
    supervisor refines it into worker-dead / hung-collective via the
    heartbeat census. ``arrived``/``missing`` are sorted process-id
    lists reconstructed from the barrier's KV arrival records."""

    def __init__(
        self,
        name: str,
        timeout_s: float,
        arrived: Sequence[int],
        missing: Sequence[int],
        cause: str = "",
    ):
        self.barrier_name = name
        self.timeout_s = timeout_s
        self.arrived = sorted(int(p) for p in arrived)
        self.missing = sorted(int(p) for p in missing)
        detail = f" [{cause}]" if cause else ""
        super().__init__(
            f"process_barrier '{name}' timed out after {timeout_s:g} s: "
            f"processes {self.missing or '<unknown>'} never arrived "
            f"(arrived: {self.arrived}){detail}"
        )


def process_barrier(name: Optional[str] = None, timeout_s: float = 120.0) -> None:
    """Block until every process reached this barrier.

    Rides the coordinator's KV store (``wait_at_barrier``), NOT an XLA
    collective — so it works during startup, between dispatches, and on
    backends that cannot run a cross-process computation at all. No-op
    single-process. SPMD discipline applies: every process must call the
    same barriers in the same order (auto-generated names are a per-
    process counter). The checkpoint commit protocol is the canonical
    user: non-zero processes must not proceed past a save point before
    process 0's manifest is durable.

    Deadline discipline (ISSUE 14): each process records its arrival in
    the coordinator KV store before waiting, so when the wait times out
    — a peer was SIGKILLed, wedged, or preempted — the survivor raises
    :class:`BarrierTimeoutError` NAMING the processes that never
    arrived instead of surfacing the coordination service's opaque
    deadline string (regression-tested with a real non-arriving child,
    tests/test_pod_supervisor.py::
    test_process_barrier_timeout_names_missing_process). Process 0
    deletes the arrival records after a successful pass so long runs
    don't accrete KV garbage."""
    client = _dist_client()
    if client is None:
        return
    # process identity from the distributed runtime, NOT the backend:
    # jax.process_count() would initialize the backend, and multiprocess
    # CPU backend init blocks on every peer — a barrier must stay a
    # pure coordination-service operation (it is what startup code and
    # the pod supervisor rendezvous on)
    pid, nprocs = _dist_process_info()
    if nprocs <= 1:
        return
    if client is _INTROSPECT_FAILED:  # pragma: no cover - exotic builds
        # multi-process with no readable client: a silent no-op here
        # would turn the checkpoint COMMIT barrier into a data race
        # (a non-writer could resume a manifest that is not yet
        # durable) — fail loudly instead
        raise RuntimeError(
            "process_barrier: this jax build exposes no distributed-"
            "runtime client introspection, so a multi-process rendezvous "
            "cannot be performed safely"
        )
    if name is None:
        _BARRIER_SEQ[0] += 1
        name = f"evox_tpu_barrier_{_BARRIER_SEQ[0]}"
    kv_dir = f"{_BARRIER_KV_PREFIX}/{name}"
    try:
        client.key_value_set(f"{kv_dir}/{pid}", "1")
    except Exception:  # arrival bookkeeping must never fail the barrier
        pass
    try:
        client.wait_at_barrier(name, int(timeout_s * 1000))
    except Exception as e:
        msg = str(e)
        low = msg.lower()
        if "barrier timed out" in low or "deadline_exceeded" in low:
            arrived: list = []
            try:
                arrived = [
                    int(k.rsplit("/", 1)[-1])
                    for k, _ in client.key_value_dir_get(kv_dir + "/")
                ]
            except Exception:
                pass  # census unavailable (coordinator dying): keep []
            missing = sorted(set(range(nprocs)) - set(arrived))
            raise BarrierTimeoutError(
                name, timeout_s, arrived, missing, cause=msg.splitlines()[0]
            ) from e
        raise
    if pid == 0:
        try:
            for k, _ in client.key_value_dir_get(kv_dir + "/"):
                client.key_value_delete(k)
        except Exception:
            pass


def _is_typed_key(x: Any) -> bool:
    dt = getattr(x, "dtype", None)
    return dt is not None and jnp.issubdtype(dt, jax.dtypes.prng_key)


def assemble_global_array(host_arr: Any, sharding: NamedSharding) -> jax.Array:
    """Build a GLOBAL ``jax.Array`` on ``sharding`` from a full host
    value every process holds (deterministic init, restored snapshot):
    each process ``device_put``s only the index slices its own devices
    own and stitches them with
    ``jax.make_array_from_single_device_arrays`` — the per-process
    assembly step a cross-process sharding requires (an eager
    ``device_put`` onto it is not addressable-complete and raises).
    Single-process shardings take the plain ``device_put`` fast path."""
    if not mesh_spans_processes(getattr(sharding, "mesh", None)):
        return jax.device_put(host_arr, sharding)
    arr = np.asarray(host_arr)
    shards = [
        jax.device_put(arr[idx], d)
        for d, idx in sharding.addressable_devices_indices_map(
            arr.shape
        ).items()
    ]
    return jax.make_array_from_single_device_arrays(
        arr.shape, sharding, shards
    )


@functools.lru_cache(maxsize=64)
def _replicate_program(sharding: NamedSharding):
    """One cached jitted identity-with-allgather per target sharding: a
    fresh ``jax.jit(lambda ...)`` per call would defeat the dispatch
    cache and recompile the gather for every leaf of every pod
    checkpoint/fetch (NamedSharding hashes by (mesh, spec), so the
    steady-state hot path hits this cache)."""
    return jax.jit(lambda a: a, out_shardings=sharding)


def host_value(x: Any) -> Any:
    """The FULL host (numpy) value of ``x``, even when it is sharded
    across processes: fully-addressable arrays are a plain
    ``device_get``; a cross-process-sharded array is first replicated
    through a jitted identity (``out_shardings=P()`` — GSPMD inserts the
    all-gather) and read from the local replica. Every process receives
    the same value and every process must call this collectively for
    cross-process operands (it dispatches a computation there)."""
    if not isinstance(x, jax.Array):
        return np.asarray(x)
    if x.is_fully_addressable:
        return np.asarray(jax.device_get(x))
    if getattr(x, "is_fully_replicated", False):
        # replicated global array: the local replica IS the value — no
        # collective needed (the common case for every strategy-state
        # scalar in a pod checkpoint gather)
        return np.asarray(jax.device_get(x.addressable_data(0)))
    sharding = x.sharding
    mesh = getattr(sharding, "mesh", None)
    if mesh is None:  # pragma: no cover - non-named cross-process layout
        raise ValueError(
            "host_value: cannot all-gather a cross-process array without "
            "a named-sharding mesh"
        )
    rep = _replicate_program(NamedSharding(mesh, P()))(x)
    return np.asarray(jax.device_get(rep.addressable_data(0)))


def tree_host_value(tree: Any) -> Any:
    """:func:`host_value` over a pytree (typed PRNG-key leaves pass
    through ``key_data`` and come back typed)."""

    def fetch(leaf):
        if _is_typed_key(leaf):
            return jax.random.wrap_key_data(
                jnp.asarray(host_value(jax.random.key_data(leaf))),
                impl=jax.random.key_impl(leaf),
            )
        return host_value(leaf)

    return jax.tree.map(fetch, tree)


def ensure_global_state(
    state: Any,
    mesh: Optional[Mesh],
    default: Optional["P"] = None,
    rules: Optional[Sequence[Tuple[str, "P"]]] = None,
    axis_prefix: Optional[str] = None,
) -> Any:
    """Per-process GLOBAL-state assembly: place every leaf of an
    eagerly-built (process-local) state onto its annotation-resolved
    sharding over a pod mesh via :func:`assemble_global_array`, so the
    state a global-mesh jit consumes is made of global arrays on every
    process. This is the init/restore boundary of multi-process runs —
    ``StdWorkflow.init`` et al. call it after their eager ``init`` (which
    computes the same host value on every process from the same key), and
    ``place_state`` routes restored snapshots through it.

    No-op when ``mesh`` does not span processes. Leaves that are already
    global (non-fully-addressable) pass through untouched. Typed PRNG-key
    leaves are assembled REPLICATED via ``key_data`` (strategy-level
    keys; a pod layout for key leaves comes from ``constrain_state``
    inside the step)."""
    if not mesh_spans_processes(mesh):
        return state
    shardings = state_sharding(
        state, mesh, default=default, rules=rules, axis_prefix=axis_prefix
    )

    def place(leaf, sh):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            return leaf  # already a global array
        if _is_typed_key(leaf):
            data = assemble_global_array(
                np.asarray(jax.device_get(jax.random.key_data(leaf))),
                NamedSharding(mesh, P()),
            )
            return jax.random.wrap_key_data(
                data, impl=jax.random.key_impl(leaf)
            )
        return assemble_global_array(
            np.asarray(jax.device_get(leaf)), sh
        )

    return jax.tree.map(place, state, shardings)
