from .struct import PyTreeNode, field, static_field, pytree_dataclass, replace
from .algorithm import Algorithm
from .problem import Problem
from .monitor import Monitor, HOOK_NAMES
from .distributed import (
    POP_AXIS,
    TENANT_AXIS,
    ShardedES,
    annotation_specs,
    match_partition_rules,
    create_mesh,
    pop_sharding,
    replicated_sharding,
    shard_pop,
    sharded_es_tell,
    replicate,
    all_gather,
    tree_all_gather,
    init_distributed,
    shutdown_distributed,
    process_id,
    process_count,
    is_dist_initialized,
    BarrierTimeoutError,
)
from .executor import GenerationExecutor
from .pod_supervisor import (
    CollectiveDeadlineError,
    PodFailureError,
    PodSupervisor,
)
from .exec_cache import (
    ExecCacheError,
    ExecCacheMissError,
    ExecutableCache,
    topology_fingerprint,
)
from .instrument import (
    DispatchRecorder,
    RetraceError,
    instrument,
    run_report,
    write_chrome_trace,
    write_report_jsonl,
)
from .xla_cost import CHIP_CEILINGS, CostAnalyzer
from .dtype_policy import (
    BF16_STORAGE,
    DtypePolicy,
    apply_compute,
    apply_storage,
    policy_report,
)
from .guardrail import (
    GuardedAlgorithm,
    GuardedState,
    IPOPRestarts,
    recenter_state,
)
from . import state_io

__all__ = [
    "GuardedAlgorithm",
    "GuardedState",
    "IPOPRestarts",
    "recenter_state",
    "GenerationExecutor",
    "ExecutableCache",
    "ExecCacheError",
    "ExecCacheMissError",
    "topology_fingerprint",
    "DispatchRecorder",
    "RetraceError",
    "CHIP_CEILINGS",
    "CostAnalyzer",
    "instrument",
    "run_report",
    "write_chrome_trace",
    "write_report_jsonl",
    "PyTreeNode",
    "field",
    "static_field",
    "pytree_dataclass",
    "replace",
    "Algorithm",
    "Problem",
    "Monitor",
    "HOOK_NAMES",
    "POP_AXIS",
    "TENANT_AXIS",
    "ShardedES",
    "annotation_specs",
    "sharded_es_tell",
    "match_partition_rules",
    "create_mesh",
    "pop_sharding",
    "replicated_sharding",
    "shard_pop",
    "replicate",
    "all_gather",
    "tree_all_gather",
    "init_distributed",
    "shutdown_distributed",
    "process_id",
    "process_count",
    "is_dist_initialized",
    "BarrierTimeoutError",
    "CollectiveDeadlineError",
    "PodFailureError",
    "PodSupervisor",
    "state_io",
]
