"""GenerationExecutor — the one async generation loop behind every driver.

Before this module, five drivers each hand-rolled their own generation
loop: ``run_host_pipelined`` (double-buffered host evals),
``StdWorkflow.run`` (host-problem path), ``IslandWorkflow.run``,
``RunSupervisor``'s chunked ladder, and the ``RunQueue``'s serving
chunks. Each serialized some mix of device dispatch, host evaluation,
checkpoint fsync, and telemetry fetch. This module owns that loop once
(the behavioral analog of the reference Ray workflow's async dispatch
queue, reference workflows/distributed.py:361-369 — see PARITY row 54),
and the five drivers become thin policies over it:

- **Double-buffered dispatch** (``run_host``): the device half of
  generation ``k+1`` (``pipeline_tell`` + ``pipeline_ask``, async
  dispatch — PR 4 proved durations don't scale without
  ``block_dispatch``, so the dispatch is free on the tunnel) overlaps
  the host evaluation of generation ``k`` on a worker thread, and both
  overlap the user's ``on_generation`` host work — the
  ``run_host_pipelined`` structure, now owned here.
- **Background I/O lanes**: checkpoint pickles+fsyncs, ``on_generation``
  hooks, and telemetry-ring fetches run on dedicated single-thread
  lanes with a bounded in-flight queue (backpressure, never unbounded
  growth); errors are surfaced at the next drain point, and the
  checkpoint lane is always drained before anything reads
  ``checkpointer.latest()`` (the supervisor's restore rung) and before
  the run returns.
- **Bounded-staleness tells** (``max_staleness=K``, opt-in): the loop
  may keep up to ``K+1`` evaluations in flight and admit a tell whose
  candidates were asked up to ``K`` tells ago — stale-gradient ES
  (Fiber; "Distributed ES with Multi-Level Learning", PAPERS.md). Each
  tell keeps its OWN matched (ask-artifacts, fitness) pair: the
  executor detects the ask's artifact leaves (key, noise, candidate
  buffers — the leaves a probe ask changes) once, and grafts them onto
  the newest told state, so updates accumulate while the sampling
  distribution lags by at most ``K`` tells. ``K=0`` (default) is
  BIT-identical to the legacy loops — the repo's run==step laws stay
  the referee; ``K>0`` is a throughput/quality trade documented in
  GUIDE.md §6 and gated by a convergence test, not an equivalence law.
- **Supervision as hooks**: when a supervisor (duck-typed:
  ``call``/``min_eval_chunk``/``checkpointer``) is attached, every
  chunk dispatch runs under its deadline watchdog + retry ladder, the
  restore rung replays from the newest (drained) snapshot, and the
  OOM/413 degrade rung halves the host eval chunk — the
  ``RunSupervisor`` keeps the policy (classification, backoff, ladder),
  the executor owns the loop.

Observability: counters (generations, stale tells, background tasks,
queue high-water) and overlap spans (device dispatch vs host eval vs
background I/O vs wall) land in ``run_report()["executor"]`` (schema
v4, validated by tools/check_report.py) and as an "generation executor"
process in ``write_chrome_trace`` (span slices + queue-depth/stale-lag
counter tracks). Entirely host-side — no callbacks, axon-safe
(pinned by tests/test_no_host_callbacks.py).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .attest import IntegrityError

# Layering note: this module lives in core/ (it is workflow-shape-agnostic
# infrastructure: any object with pipeline_ask/pipeline_tell or run(state,
# n) drives), but the checkpoint/resume helpers it consults belong to the
# workflows package (ISSUE 9 satellite: enter_run/chunk_to_boundary are
# hoisted into workflows/checkpoint.py and "called from the executor").
# Those imports are deliberately deferred to call time — workflows imports
# core at module level, so eager imports here would be circular; core
# stays importable without workflows, and only executor RUNS need it.

__all__ = ["GenerationExecutor"]

# ask-side monitor hooks: in stale mode an admitted tell's monitor chain
# comes from the newest told state (ctx branches fork), so monitors whose
# state advances in these hooks would silently lose generations
_ASK_SIDE_HOOKS = ("pre_step", "pre_ask", "post_ask", "pre_eval")

_MAX_TRACE_SPANS = 20_000
_MAX_COUNTER_SAMPLES = 20_000


class _IoLane:
    """One ordered background I/O lane: a single worker thread (so saves
    land in submission order) plus a bounded in-flight deque. ``submit``
    applies backpressure by joining the oldest task when the lane is
    full — the queue can never grow without bound behind a slow disk.
    Errors are re-raised at the next ``submit``/``drain`` (a background
    fsync failure must fail the run, not vanish)."""

    def __init__(self, name: str, max_inflight: int):
        self.name = name
        self.max_inflight = max(1, int(max_inflight))
        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"executor-{name}"
        )
        self._pending: deque = deque()
        self.submitted = 0
        self.busy_s = 0.0
        self.high_water = 0

    def submit(self, fn: Callable[[], Any]) -> Future:
        while len(self._pending) >= self.max_inflight:
            self._pending.popleft().result()  # backpressure + error surface

        def timed():
            t0 = time.perf_counter()
            try:
                return fn()
            finally:
                self.busy_s += time.perf_counter() - t0

        fut = self._pool.submit(timed)
        self._pending.append(fut)
        self.submitted += 1
        self.high_water = max(self.high_water, len(self._pending))
        return fut

    def depth(self) -> int:
        return sum(1 for f in self._pending if not f.done())

    def drain(self) -> None:
        """Join every pending task, re-raising the first error."""
        while self._pending:
            self._pending.popleft().result()

    def close(self) -> None:
        self._pool.shutdown(wait=False)


class _InflightEval:
    """One generation's in-flight evaluation: its loop index, the ask's
    ctx (astate branch, monitor branch, candidates), the future of the
    host evaluation, and ``base_told`` — how many tells the base state
    had absorbed when this ask sampled from it. A tell admitted after
    further tells landed (``told > base_told``) is STALE: its candidates
    came from a distribution that many updates behind."""

    __slots__ = ("g", "ctx", "fut", "base_told")

    def __init__(self, g: int, ctx: Any, fut: Future, base_told: int):
        self.g = g
        self.ctx = ctx
        self.fut = fut
        self.base_told = base_told


def _is_key_path(path) -> bool:
    name = jax.tree_util.keystr(path)
    return name.endswith(".key") or name.endswith("['key']")


def _ask_artifact_mask(pre_algo: Any, post_algo: Any) -> List[bool]:
    """Which algorithm-state leaves does ``ask`` write? Compared leaf-wise
    between the pre-ask and post-ask state of ONE probe generation:
    unequal leaves (plus every ``key`` leaf, which must always follow the
    ask chain) are ask-artifacts — the leaves a stale tell grafts from
    its own ctx onto the newest told state so the (noise, fitness)
    pairing the algorithm's ``tell`` math assumes stays matched.

    All per-leaf equality scalars are fetched in ONE ``device_get``: on
    the tunneled axon backend every blocking round trip costs 45-100 ms
    (CLAUDE.md), and a per-leaf fetch would stall the first steady ask
    by seconds in the very module built to hide that latency."""
    pre = jax.tree_util.tree_flatten_with_path(pre_algo)[0]
    post = jax.tree.leaves(post_algo)
    forced: List[Optional[bool]] = []
    comparisons = []
    for (path, a), b in zip(pre, post):
        if _is_key_path(path):
            forced.append(True)
            continue
        try:
            comparisons.append(jnp.array_equal(a, b, equal_nan=True))
            forced.append(None)
        except TypeError:
            forced.append(True)  # exotic leaf (no ==): treat as artifact
    same_flags = iter(jax.device_get(comparisons) if comparisons else [])
    return [
        f if f is not None else not bool(next(same_flags)) for f in forced
    ]


def _merge_artifacts(base_algo: Any, ask_algo: Any, mask: List[bool]) -> Any:
    base_leaves, treedef = jax.tree.flatten(base_algo)
    ask_leaves = jax.tree.leaves(ask_algo)
    return jax.tree.unflatten(
        treedef,
        [a if m else b for b, a, m in zip(base_leaves, ask_leaves, mask)],
    )


def _rekey(algo: Any, entry_key: Any, g: int) -> Any:
    """A deterministic fresh PRNG stream for an ask issued while earlier
    tells are still pending (two asks from the same told state would
    otherwise replay the same key split)."""
    return algo.replace(key=jax.random.fold_in(entry_key, g))


class GenerationExecutor:
    """The unified async generation loop (module docstring for the full
    design). One instance may drive many runs; counters and spans
    accumulate and ``report()`` is the ``run_report()["executor"]``
    section.

    Args:
        max_staleness: default tell-staleness bound ``K`` for
            :meth:`run_host` (overridable per run). ``0`` (default) is
            bit-identical to the legacy drive loops. ``K>0`` keeps up to
            ``K+1`` host evaluations in flight and admits each tell at a
            lag of at most ``K`` tells (stale-gradient semantics;
            requires an algorithm state with a ``key`` field, no
            ``dtype_policy``, no ``donate_carries``, and monitors
            without ask-side hooks — TelemetryMonitor qualifies; the
            host ``evaluate`` must tolerate concurrent calls).
        io_inflight: bound on in-flight background tasks PER LANE
            (checkpoint / hook / fetch); submission past it blocks on
            the oldest task (backpressure).
        supervisor: default supervisor hook (a
            :class:`~evox_tpu.workflows.supervisor.RunSupervisor` or
            anything duck-typing its ``call``/``checkpointer``/
            ``min_eval_chunk``); overridable per run.
        fetch_monitors_every: when set, every N admitted generations the
            executor background-fetches ``state.monitors`` (the small
            telemetry rings — never the population) and keeps the newest
            host copy in ``last_monitor_fetch`` — live telemetry that
            never blocks the loop.
        clock: monotonic seconds source (``time.perf_counter`` — the
            same clock DispatchRecorder and RunSupervisor stamp with, so
            trace tracks align).
    """

    def __init__(
        self,
        max_staleness: int = 0,
        io_inflight: int = 4,
        supervisor: Any = None,
        pod_supervisor: Any = None,
        fetch_monitors_every: Optional[int] = None,
        clock: Callable[[], float] = time.perf_counter,
        metrics: Any = None,
    ):
        if max_staleness < 0:
            raise ValueError(f"max_staleness must be >= 0, got {max_staleness}")
        if io_inflight < 1:
            raise ValueError(f"io_inflight must be >= 1, got {io_inflight}")
        if fetch_monitors_every is not None and fetch_monitors_every < 1:
            raise ValueError("fetch_monitors_every must be >= 1")
        self.max_staleness = int(max_staleness)
        self.io_inflight = int(io_inflight)
        self.supervisor = supervisor
        # pod fault domain (core/pod_supervisor.py, ISSUE 14): when
        # attached, run_fused puts every SPMD-lockstep collective point
        # (chunk dispatch, pod checkpoint gather) under the pod deadline
        # + classification, rendezvouses at chunk boundaries, and honors
        # the coordinated SIGTERM drain. None (default) changes nothing.
        self.pod_supervisor = pod_supervisor
        self.fetch_monitors_every = fetch_monitors_every
        # serving-plane flight recorder (PR 16, workflows/flightrec.py):
        # when attached (constructor or `executor.metrics = rec` — the
        # RunQueue auto-threads its recorder), _sample mirrors the
        # counter tracks as gauges and _timed_dispatch observes dispatch
        # latency into a histogram. None (default) changes nothing.
        self.metrics = metrics
        self._clock = clock
        self._created = clock()
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {
            "runs": 0,
            "chunks": 0,
            "supervised_chunks": 0,
            "generations": 0,
            "asks": 0,
            "tells": 0,
            "stale_tells": 0,
            "max_lag": 0,
            "bg_checkpoint": 0,
            "bg_hook": 0,
            "bg_fetch": 0,
            # surrogate refits dispatched between tells (ISSUE 15,
            # workflows/surrogate.py refit_due/dispatch_refit hooks)
            "bg_refit": 0,
            # compute-integrity rung (ISSUE 20, core/attest.py): extra
            # dispatches spent re-running chunks for verification, chunks
            # whose digests agreed, digest mismatches detected, and
            # mismatches healed by the 2-of-3 vote. Coherence law:
            # verify_dispatches == verified_chunks + 2 * mismatches
            # (one re-dispatch per rung, one more per mismatch).
            "verify_dispatches": 0,
            "verified_chunks": 0,
            "integrity_mismatches": 0,
            "integrity_healed": 0,
        }
        # rung configuration/outcome state the counters can't carry:
        # newest run's cadence (None = rung off) and no-majority aborts
        self.integrity: Dict[str, Any] = {"verify_every": None, "aborts": 0}
        self.queue_stats: Dict[str, int] = {
            "io_inflight_limit": self.io_inflight,
            "io_inflight_max": 0,
            "stale_window_max": 0,
        }
        # overlap accounting (seconds): device dispatch time (host-side
        # cost of the jitted calls — async dispatch, the PR-1 semantics),
        # host evaluation busy time (inside the eval workers; may exceed
        # wall when K>0 runs evals concurrently), background-I/O busy
        # time, and the wall window covered by executor runs
        self.overlap: Dict[str, float] = {
            "device_dispatch_s": 0.0,
            "host_eval_s": 0.0,
            "io_s": 0.0,
            "wall_s": 0.0,
        }
        self.last_monitor_fetch: Optional[Tuple[int, Any]] = None
        # largest per-run max_staleness override actually driven — the
        # report's bound must cover every run's admitted lag, not just
        # the constructor default
        self._max_k_seen = 0
        self._trace_spans: List[dict] = []
        self._dropped_spans = 0
        self._counter_samples: Dict[str, List[Tuple[float, float]]] = {
            "executor/io_queue_depth": [],
            "executor/stale_lag": [],
        }

    # ------------------------------------------------------------- recording
    def _span(self, track: str, name: str, t0: float, dt: float, **args) -> None:
        with self._lock:
            if len(self._trace_spans) >= _MAX_TRACE_SPANS:
                self._dropped_spans += 1
                return
            span = {"track": track, "name": name, "t_abs": t0, "dur": dt}
            if args:
                span["args"] = args
            self._trace_spans.append(span)

    def _sample(self, track: str, value: float) -> None:
        with self._lock:
            samples = self._counter_samples[track]
            if len(samples) < _MAX_COUNTER_SAMPLES:
                samples.append((self._clock(), float(value)))
        if self.metrics is not None:
            # "executor/io_queue_depth" -> "executor.io_queue_depth":
            # metric names are dotted, trace tracks slash-separated
            self.metrics.set(track.replace("/", "."), float(value))

    def _timed_dispatch(self, name: str, fn: Callable[[], Any]) -> Any:
        t0 = self._clock()
        try:
            return fn()
        finally:
            dt = self._clock() - t0
            self.overlap["device_dispatch_s"] += dt
            self._span("device", name, t0, dt)
            if self.metrics is not None:
                self.metrics.count("executor.dispatches")
                self.metrics.observe("executor.dispatch_ms", dt * 1e3)

    # ---------------------------------------------------------------- report
    def report(self) -> dict:
        """The ``executor`` section of ``run_report()`` — strict JSON."""
        device = self.overlap["device_dispatch_s"]
        host = self.overlap["host_eval_s"]
        wall = self.overlap["wall_s"]
        bound = max(device, host)
        out = {
            # the EFFECTIVE bound: per-run max_staleness= overrides widen it
            "max_staleness": max(self.max_staleness, self._max_k_seen),
            "counters": dict(self.counters),
            "queue": dict(self.queue_stats),
            "overlap": {
                "device_dispatch_s": round(device, 6),
                "host_eval_s": round(host, 6),
                "io_s": round(self.overlap["io_s"], 6),
                "wall_s": round(wall, 6),
                # wall / max(device, host): 1.0 = perfect overlap, 2.0 =
                # fully serialized equal halves (the pre-executor shape)
                "overlap_efficiency": (
                    round(wall / bound, 4) if bound > 1e-9 and wall > 0 else None
                ),
            },
        }
        if self._dropped_spans:
            out["dropped_spans"] = self._dropped_spans
        return out

    def trace_spans(self) -> List[dict]:
        """Recorded spans (absolute ``perf_counter`` timestamps) for
        :func:`~evox_tpu.core.instrument.write_chrome_trace`'s
        "generation executor" process."""
        with self._lock:
            return list(self._trace_spans)

    def counter_samples(self) -> Dict[str, List[Tuple[float, float]]]:
        """(t_abs, value) samples per counter track (queue depth, stale
        lag) for the trace exporter."""
        with self._lock:
            return {k: list(v) for k, v in self._counter_samples.items()}

    # ------------------------------------------------------------ fused runs
    def run_fused(
        self,
        wf: Any,
        state: Any,
        n_steps: int,
        checkpointer: Any = None,
        chunk: Optional[int] = None,
        resume_from: Any = None,
        supervisor: Any = None,
        pod_supervisor: Any = None,
        entry: str = "run",
        attest: Any = None,
        verify_every: Optional[int] = None,
    ) -> Any:
        """Drive ``wf.run(state, n)``-shaped fused dispatches in cadence
        chunks: the loop previously hand-rolled by ``checkpointed_run``,
        ``RunSupervisor.run``, and the ``RunQueue``. Chunking a
        ``fori_loop`` does not change its math, so the final state is
        identical to one straight dispatch; snapshots run on the
        background checkpoint lane (bounded, drained before return and
        before any restore), and with a supervisor every chunk dispatch
        runs under its deadline + classified-retry ladder with the
        restore rung replaying from the newest drained snapshot.
        ``n_steps`` counts REMAINING generations (``resume_from``
        reinterprets it as the TOTAL target, exactly ``wf.run``'s law).

        ``pod_supervisor`` (a :class:`~evox_tpu.core.pod_supervisor.
        PodSupervisor`, ISSUE 14): every SPMD-lockstep collective point
        — the chunk dispatch and, on pod meshes, the synchronous
        checkpoint gather — runs under the pod's disposable-watchdog
        deadline with census-refined failure classification, each chunk
        ends in the classified :meth:`chunk_boundary` rendezvous, and a
        coordinated drain (SIGTERM preemption) finishes the in-flight
        chunk, fsyncs a FINAL barrier checkpoint even off-cadence,
        drains the background lane, and returns early — the caller then
        exits 0. Pod faults surface as :class:`~evox_tpu.core.
        pod_supervisor.PodFailureError` (fatal to the in-process ladder
        by design; re-formation happens in the respawn driver). ``None``
        (default) leaves this loop bit-identical to the pre-pod tree.

        ``verify_every=K`` (with ``attest``, a :class:`~evox_tpu.core.
        attest.StateAttestor`; a default one is built if omitted) is the
        compute-integrity rung (ISSUE 20): every K-th completed chunk is
        re-dispatched from its immutable pre-chunk entry state (the PR-6
        snapshot-before-donate guarantee makes the entry state free) and
        the two results' layout-invariant digests compared. On mismatch a
        third dispatch votes 2-of-3: the majority state proceeds, the
        dissent is journaled against the pod (quarantine via the PR-14
        re-formation path, not a whole-run abort); no majority raises
        :class:`~evox_tpu.core.attest.IntegrityError` (classified
        ``integrity`` — never retried). ``attest=None`` with
        ``verify_every=None`` (default) is the established no-op
        discipline: zero extra dispatches, bit-identical to pre-PR.
        """
        from ..workflows.checkpoint import chunk_to_boundary, enter_run

        supervisor = self.supervisor if supervisor is None else supervisor
        pod = (
            self.pod_supervisor if pod_supervisor is None else pod_supervisor
        )
        wf._run_executor = self
        if supervisor is not None:
            wf._run_supervisor = supervisor
        if pod is not None:
            wf._pod_supervisor = pod
        state, n_steps, ckpt = enter_run(
            state, n_steps, checkpointer, resume_from, expect_like=state
        )
        if ckpt is None and supervisor is not None:
            ckpt = getattr(supervisor, "checkpointer", None)
        self.counters["runs"] += 1
        if verify_every is not None:
            if verify_every < 1:
                raise ValueError(f"verify_every must be >= 1, got {verify_every}")
            if attest is None:
                from .attest import StateAttestor

                attest = StateAttestor()
            self.integrity["verify_every"] = int(verify_every)
        total = n_steps + int(state.generation)
        chunk_i = 0  # completed chunks THIS run — the verify-rung cadence
        budget = {"used": 0}  # restores bounded per RUN, not per chunk
        restore = self._restore_thunk(supervisor, ckpt, wf, state)
        lane = _IoLane("checkpoint", self.io_inflight)
        # registered so the restore rung's _drain_checkpoint_lanes sees
        # THIS run's in-flight snapshots too (not only pipelined segments')
        lanes = getattr(self, "_active_ckpt_lanes", None)
        if lanes is None:
            lanes = self._active_ckpt_lanes = []
        lanes.append(lane)
        t_run0 = self._clock()
        try:
            while int(state.generation) < total:
                remaining = total - int(state.generation)
                step = min(remaining, chunk_to_boundary(state, ckpt, chunk))
                attempted = state
                chunk_fn = lambda: wf.run(attempted, step)  # noqa: E731
                if pod is not None:
                    # innermost: the pod watchdog wraps the raw lockstep
                    # dispatch so a hung collective is classified before
                    # any in-process ladder sees it
                    chunk_fn = lambda: pod.supervised(  # noqa: E731
                        lambda: wf.run(attempted, step), entry=entry
                    )
                dispatch = lambda: self._timed_dispatch(  # noqa: E731
                    entry, chunk_fn
                )
                if supervisor is not None:
                    self.counters["supervised_chunks"] += 1
                    state = supervisor.call(
                        dispatch,
                        entry=entry,
                        restore=restore,
                        restore_budget=budget,
                    )
                else:
                    state = dispatch()
                chunk_i += 1
                if (
                    attest is not None
                    and verify_every is not None
                    and chunk_i % verify_every == 0
                    # only a chunk that truly ran to completion can be
                    # re-dispatched for comparison — a restore-rung result
                    # is an older snapshot, not this chunk's output
                    and int(state.generation)
                    == int(attempted.generation) + step
                ):
                    state = self._verify_chunk(
                        wf,
                        attempted,
                        state,
                        step,
                        attest,
                        entry=entry,
                        supervisor=supervisor,
                        pod=pod,
                    )
                self.counters["chunks"] += 1
                gen = int(state.generation)
                progressed = gen > int(attempted.generation)
                if progressed:
                    self.counters["generations"] += gen - int(
                        attempted.generation
                    )
                # pod rendezvous BEFORE the snapshot decision: the drain
                # law's final checkpoint must be the newest barrier, so
                # a drain decided here forces an (off-cadence) save below
                drain = (
                    progressed
                    and pod is not None
                    and pod.chunk_boundary(gen) == "drain"
                )
                if (
                    ckpt is not None
                    and progressed
                    and (gen % ckpt.every == 0 or gen >= total or drain)
                ):
                    # only snapshot forward progress — the restore rung
                    # hands back an OLDER state that is already durable
                    self._submit_checkpoint(lane, ckpt, state, pod=pod)
                if drain:
                    # preemption-graceful stop: in-flight chunk finished,
                    # final barrier checkpoint submitted — make it (and
                    # every earlier snapshot) durable, record, hand back.
                    # checkpointer-less runs drain too (the process must
                    # still stop cleanly) but the record says no final
                    # snapshot exists — nothing claims resumability
                    lane.drain()
                    pod.note_drained(gen, checkpointed=ckpt is not None)
                    return state
            lane.drain()  # every snapshot durable before the run returns
            return state
        except BaseException:
            try:  # flush what we can without masking the real failure
                lane.drain()
            except Exception:
                pass
            raise
        finally:
            if lane in lanes:
                lanes.remove(lane)
            lane.close()
            self._account_lane(lane)
            self.overlap["wall_s"] += self._clock() - t_run0

    # ------------------------------------------------------- integrity rung
    def _verify_chunk(
        self,
        wf: Any,
        attempted: Any,
        state: Any,
        step: int,
        attest: Any,
        *,
        entry: str,
        supervisor: Any,
        pod: Any,
    ) -> Any:
        """ISSUE 20 voted re-dispatch: re-run the chunk from its immutable
        entry state and compare layout-invariant digests. Agreement
        verifies the chunk; a mismatch escalates to a third dispatch and
        the 2-of-3 majority wins, with the dissent noted against the pod
        (journaled ``pod_failure`` classification ``integrity_dissent`` —
        the PodManager re-formation driver quarantines the pod, the run
        itself proceeds on the majority state). No majority is an
        :class:`IntegrityError`: three mutually disagreeing results leave
        nothing trustworthy to continue from."""

        def _dispatch_again() -> Any:
            fn = lambda: wf.run(attempted, step)  # noqa: E731
            if pod is not None:
                raw = fn
                fn = lambda: pod.supervised(raw, entry=f"{entry}:verify")  # noqa: E731
            dispatch = lambda: self._timed_dispatch(  # noqa: E731
                f"{entry}:verify", fn
            )
            if supervisor is not None:
                # transient dispatch faults during verification retry as
                # usual; no restore rung — the entry state IS the snapshot
                return supervisor.call(dispatch, entry=f"{entry}:verify")
            return dispatch()

        def _digest(s: Any) -> tuple:
            return tuple(
                int(v) for v in np.asarray(jax.device_get(attest.digest(s)))
            )

        gen = int(state.generation)
        self.counters["verify_dispatches"] += 1
        redo = _dispatch_again()
        d0, d1 = _digest(state), _digest(redo)
        if d0 == d1:
            self.counters["verified_chunks"] += 1
            return state
        self.counters["integrity_mismatches"] += 1
        if supervisor is not None:
            supervisor._event(
                "integrity_mismatch", entry=entry, generation=gen
            )
        if self.metrics is not None:
            self.metrics.count("executor.integrity_mismatches")
            self.metrics.event(
                "integrity.mismatch", entry=entry, generation=gen
            )
        self.counters["verify_dispatches"] += 1
        third = _dispatch_again()
        d2 = _digest(third)
        if d2 == d1:
            winner, dissent = redo, "first"
        elif d2 == d0:
            winner, dissent = state, "redo"
        else:
            self.integrity["aborts"] += 1
            raise IntegrityError(
                f"no 2-of-3 majority at generation {gen}: three dispatches "
                f"of the same chunk produced three distinct digests — "
                f"nothing trustworthy to continue from",
                generation=gen,
                where=f"{entry}:verify",
            )
        self.counters["integrity_healed"] += 1
        if supervisor is not None:
            supervisor._event(
                "integrity_heal", entry=entry, generation=gen, dissent=dissent
            )
        if self.metrics is not None:
            self.metrics.count("executor.integrity_healed")
            self.metrics.event(
                "integrity.heal", entry=entry, generation=gen, dissent=dissent
            )
        if pod is not None and hasattr(pod, "note_integrity_dissent"):
            pod.note_integrity_dissent(
                generation=gen, entry=entry, dissent=dissent
            )
        return winner

    def integrity_counters(self) -> Dict[str, Any]:
        """The executor's contribution to run_report v14 ``integrity``
        (``None`` when the verify rung never armed — the no-op form)."""
        if self.integrity["verify_every"] is None:
            return None
        return {
            "verify_every": self.integrity["verify_every"],
            "redispatches": self.counters["verify_dispatches"],
            "verified_chunks": self.counters["verified_chunks"],
            "mismatches": self.counters["integrity_mismatches"],
            "healed": self.counters["integrity_healed"],
            "aborted": self.integrity["aborts"],
        }

    # ---------------------------------------------------------- host-eval runs
    def run_host(
        self,
        wf: Any,
        state: Any,
        n_steps: int,
        on_generation: Optional[Callable[[int, Any, Any], None]] = None,
        checkpointer: Any = None,
        resume_from: Any = None,
        eval_chunk: Optional[int] = None,
        chunk: Optional[int] = None,
        max_staleness: Optional[int] = None,
        supervisor: Any = None,
    ) -> Any:
        """The double-buffered host-evaluation loop (external problems):
        generation ``k``'s host ``evaluate`` runs on a worker thread
        while the device halves of ``k+1`` dispatch and the previous
        generation's ``on_generation`` hook runs on the background hook
        lane — the ``run_host_pipelined`` contract, owned here. With a
        supervisor the loop is chunked and each chunk runs under the
        ladder with the OOM/413 degrade rung halving ``eval_chunk``
        (floored at ``supervisor.min_eval_chunk``). ``max_staleness=K``
        opts into stale tells (see the class docstring); ``K=0`` is
        bit-identical to a ``wf.step`` loop."""
        from ..workflows.checkpoint import chunk_to_boundary, enter_run

        supervisor = self.supervisor if supervisor is None else supervisor
        if not getattr(wf, "external", False):
            raise ValueError(
                "run_host is for external (host) problems; jittable "
                "problems should use run_fused / wf.run's fused device loop"
            )
        K = self.max_staleness if max_staleness is None else int(max_staleness)
        if K < 0:
            raise ValueError(f"max_staleness must be >= 0, got {K}")
        self._max_k_seen = max(self._max_k_seen, K)
        if K > 0:
            self._check_stale_support(wf)
            if getattr(state.algo, "key", None) is None:
                raise ValueError(
                    "max_staleness > 0 needs an algorithm state with a "
                    "'key' field (the rekeyed ask streams fold from it); "
                    f"{type(state.algo).__name__} has none"
                )
        wf._run_executor = self
        if supervisor is not None:
            wf._run_supervisor = supervisor
        state, n_steps, ckpt = enter_run(
            state, n_steps, checkpointer, resume_from, expect_like=state
        )
        if ckpt is None and supervisor is not None:
            ckpt = getattr(supervisor, "checkpointer", None)
        if n_steps <= 0:
            # nothing left (e.g. resuming a complete run) — return BEFORE
            # dispatching ask/eval: a stray background evaluate would
            # waste a generation and race the caller on the problem
            return state
        self.counters["runs"] += 1
        t_run0 = self._clock()
        try:
            if supervisor is None and chunk is None:
                return self._pipeline_segment(
                    wf, state, n_steps, on_generation, ckpt, eval_chunk, K
                )
            # chunked path: the supervisor ladder (or an explicit chunk
            # grid) wraps each pipelined segment; the degrade rung
            # mutates the eval-chunk cell the next attempt closes over
            total = n_steps + int(state.generation)
            cell = {"eval_chunk": eval_chunk}
            degrade = (
                self._degrade_thunk(supervisor, wf, cell)
                if supervisor is not None
                else None
            )
            budget = {"used": 0}
            restore = self._restore_thunk(supervisor, ckpt, wf, state)
            while int(state.generation) < total:
                remaining = total - int(state.generation)
                step = min(remaining, chunk_to_boundary(state, ckpt, chunk))
                attempted = state
                segment = lambda: self._pipeline_segment(  # noqa: E731
                    wf, attempted, step, on_generation, ckpt,
                    cell["eval_chunk"], K,
                )
                if supervisor is not None:
                    self.counters["supervised_chunks"] += 1
                    state = supervisor.call(
                        segment,
                        entry="pipelined",
                        restore=restore,
                        degrade=degrade,
                        restore_budget=budget,
                    )
                else:
                    state = segment()
                self.counters["chunks"] += 1
            return state
        finally:
            self.overlap["wall_s"] += self._clock() - t_run0

    # ------------------------------------------------------------- internals
    def _check_stale_support(self, wf: Any) -> None:
        if getattr(wf, "dtype_policy", None) is not None:
            raise ValueError(
                "max_staleness > 0 cannot compose with a dtype_policy: the "
                "stale-tell graft splices storage- and compute-dtype state "
                "branches; run stale tells at full precision"
            )
        if getattr(wf, "donate_carries", False):
            raise ValueError(
                "max_staleness > 0 cannot compose with donate_carries: a "
                "donated pipeline_tell ctx aliases the base state's buffers, "
                "which stale tells must keep reusing"
            )
        table = getattr(wf, "_hook_table", None)
        if table is not None:
            ask_side = [n for n in _ASK_SIDE_HOOKS if table.get(n)]
            if ask_side:
                raise ValueError(
                    "max_staleness > 0 skips ask-side monitor hooks "
                    f"({ask_side} are implemented by attached monitors): "
                    "stale tells chain monitor state through tells only. "
                    "Use tell-side monitors (TelemetryMonitor) with stale "
                    "runs."
                )

    def _degrade_thunk(self, supervisor: Any, wf: Any, cell: dict):
        """The OOM/HTTP-413 degrade rung: halve the host eval chunk,
        floored at the supervisor's ``min_eval_chunk`` (the policy knob
        stays on the supervisor; the loop it degrades lives here)."""
        floor = max(1, int(getattr(supervisor, "min_eval_chunk", 1)))

        def degrade() -> bool:
            cur = cell["eval_chunk"]
            if cur is None:
                pop = getattr(
                    getattr(wf, "algorithm", None), "pop_size", None
                )
                if pop is None:
                    return False
                nxt = max(int(pop) // 2, floor)
            elif cur <= floor:
                return False
            else:
                nxt = max(cur // 2, floor)
            if nxt == cur:
                return False
            cell["eval_chunk"] = nxt
            return True

        return degrade

    def _restore_thunk(self, supervisor: Any, ckpt: Any, wf: Any, expect_like: Any):
        """The supervisor's replay rung, with one executor addition: any
        in-flight background snapshot is drained before ``latest()`` is
        read, so the restore can never race a half-landed save."""
        if supervisor is None or ckpt is None:
            return None
        restorer = getattr(supervisor, "_restorer", None)
        if restorer is None:
            return None
        inner = restorer(ckpt, wf, expect_like)
        if inner is None:
            return None

        def restore():
            self._drain_checkpoint_lanes()
            return inner()

        return restore

    # the lanes of the CURRENTLY running segments, for the restore rung
    _active_ckpt_lanes: List[_IoLane]

    # ------------------------------------------------- named background lanes
    def background_lane(self, name: str) -> _IoLane:
        """A PERSISTENT ordered background I/O lane owned by this
        executor (created lazily, one worker thread, bounded in-flight
        with backpressure). Unlike the per-run checkpoint lanes, a named
        lane survives across chunks/runs — the serving layer's
        fleet-snapshot and journal traffic lives here, so every chunk's
        snapshot pickle+fsync overlaps the next chunk's dispatch.
        Registered with the restore-drain set: a supervisor restore rung
        waits for these writes too before reading ``latest()``."""
        lanes = getattr(self, "_named_lanes", None)
        if lanes is None:
            lanes = self._named_lanes = {}
        lane = lanes.get(name)
        if lane is None:
            lane = lanes[name] = _IoLane(name, self.io_inflight)
            active = getattr(self, "_active_ckpt_lanes", None)
            if active is None:
                active = self._active_ckpt_lanes = []
            active.append(lane)
        return lane

    def submit_background(
        self, name: str, fn: Callable[[], Any], counter: str = "bg_task"
    ) -> None:
        """Submit ``fn`` to the named persistent lane (ordered within the
        lane; errors re-raise at the next submit/drain), counting it
        under ``counter`` and recording a span for the trace."""
        lane = self.background_lane(name)
        self.counters[counter] = self.counters.get(counter, 0) + 1
        t0 = self._clock()

        def task():
            try:
                return fn()
            finally:
                self._span(f"io:{name}", counter, t0, self._clock() - t0)

        lane.submit(task)
        self._sample("executor/io_queue_depth", lane.depth())

    def drain_lane(self, name: str) -> None:
        """Join every pending task of a named lane (no-op for a name
        that was never used), re-raising the first error — the serving
        layer calls this at sweep completion so a failed background
        fsync fails the sweep instead of vanishing."""
        lane = getattr(self, "_named_lanes", {}).get(name)
        if lane is not None:
            lane.drain()
            # fold the lane's busy time into overlap accounting as it
            # quiesces (idempotent: busy_s is consumed and reset)
            self.overlap["io_s"] += lane.busy_s
            lane.busy_s = 0.0
            self.queue_stats["io_inflight_max"] = max(
                self.queue_stats["io_inflight_max"], lane.high_water
            )

    def close(self) -> None:
        """Quiesce the executor: drain every named background lane (so a
        failed fsync still surfaces), then shut their worker threads
        down and forget them. A lane thread alive at interpreter exit
        races the jax atexit backend teardown the same way a live
        deserialized executable does (PERF_NOTES §23) — pod drains and
        the multi-pod gateway call this before letting the process exit.
        Idempotent; a closed executor lazily re-creates lanes if used
        again."""
        lanes = getattr(self, "_named_lanes", None) or {}
        first_err: Optional[BaseException] = None
        for name in list(lanes):
            try:
                self.drain_lane(name)
            except Exception as e:  # keep closing the rest
                if first_err is None:
                    first_err = e
            lanes[name].close()
        self._named_lanes = {}
        active = getattr(self, "_active_ckpt_lanes", None)
        if active is not None:
            active.clear()
        if first_err is not None:
            raise first_err

    def _drain_checkpoint_lanes(self) -> None:
        for lane in list(getattr(self, "_active_ckpt_lanes", [])):
            try:
                lane.drain()
            except Exception:
                # the restore rung is already on an error path; a failed
                # background save must not mask the restore itself (the
                # snapshot set on disk is still consistent — save is
                # atomic), so the drain error is dropped HERE only
                pass

    def _submit_checkpoint(
        self, lane: _IoLane, ckpt: Any, state: Any, pod: Any = None
    ) -> None:
        self.counters["bg_checkpoint"] += 1
        t0 = self._clock()
        if jax.process_count() > 1:
            # pod meshes: WorkflowCheckpointer.save gathers collectively
            # and barriers across processes — both must run in SPMD
            # lockstep on the admitting thread, never interleaved from a
            # background lane (each process's lanes drain independently,
            # which would reorder the collectives and deadlock the pod).
            # Under a pod supervisor the gather+barrier is itself a
            # supervised collective point: a peer dying mid-save raises
            # a classified PodFailureError instead of wedging the pod.
            # The save gets its OWN (larger) deadline — a full host
            # gather legitimately outlasts a chunk dispatch, and the
            # chunk bound would abort a healthy pod at every cadence
            if pod is not None:
                pod.supervised(
                    lambda: ckpt.save(state),
                    entry="checkpoint",
                    deadline_s=getattr(pod, "checkpoint_deadline_s", None),
                )
            else:
                ckpt.save(state)
            self._span("io:checkpoint", "save", t0, self._clock() - t0,
                       generation=int(state.generation))
            return

        def save():
            ckpt.save(state)
            self._span("io:checkpoint", "save", t0, self._clock() - t0,
                       generation=int(state.generation))

        lane.submit(save)
        self._sample("executor/io_queue_depth", lane.depth())

    def _account_lane(self, lane: _IoLane) -> None:
        self.overlap["io_s"] += lane.busy_s
        self.queue_stats["io_inflight_max"] = max(
            self.queue_stats["io_inflight_max"], lane.high_water
        )

    def _pipeline_segment(
        self,
        wf: Any,
        state: Any,
        n_steps: int,
        on_generation: Optional[Callable],
        checkpointer: Any,
        eval_chunk: Optional[int],
        K: int,
    ) -> Any:
        """One uninterrupted pipelined stretch of ``n_steps`` generations.
        ``K=0`` reproduces the legacy ``run_host_pipelined`` loop exactly
        (same dispatch/tell/hook ordering ⇒ bit-identical states); ``K>0``
        widens the in-flight window to ``K+1`` evaluations with
        artifact-grafted stale tells."""
        from ..workflows.pipelined import chunked_evaluate

        if n_steps <= 0:
            return state
        gen0 = int(state.generation)
        eval_pool = ThreadPoolExecutor(
            max_workers=K + 1, thread_name_prefix="executor-eval"
        )
        ckpt_lane = _IoLane("checkpoint", self.io_inflight)
        hook_lane = _IoLane("hook", self.io_inflight)
        fetch_lane = _IoLane("fetch", self.io_inflight)
        lanes = getattr(self, "_active_ckpt_lanes", None)
        if lanes is None:
            lanes = self._active_ckpt_lanes = []
        lanes.append(ckpt_lane)
        # stale bookkeeping: the entry key seeds rekeyed ask streams, the
        # artifact mask is probed at the first STEADY ask (init asks can
        # write a different leaf set than steady asks)
        entry_key = getattr(state.algo, "key", None)
        artifact_mask: Optional[List[bool]] = None
        pending: deque = deque()
        hook_fut: Optional[Future] = None
        asked = 0
        told = 0
        base = state
        # surrogate hooks (ISSUE 15, workflows/surrogate.py — duck-typed
        # so core stays decoupled): host_evaluate slices the screened
        # batch to its truly evaluated rows before the expensive host
        # problem sees it; refit_due/dispatch_refit refit the surrogate
        # between tells as a SEPARATE async-dispatched program — the
        # loop never blocks on it, and the model an ask consumes lags
        # the archive by at most the workflow's refit cadence (the
        # bounded-staleness discipline applied to the model)
        host_eval = getattr(wf, "host_evaluate", None)
        refit_due = getattr(wf, "refit_due", None)
        dispatch_refit = getattr(wf, "dispatch_refit", None)

        def submit_eval(cand, pstate):
            def run_eval():
                t0 = self._clock()
                try:
                    if host_eval is not None:
                        return host_eval(pstate, cand, eval_chunk)
                    return chunked_evaluate(wf.problem, pstate, cand, eval_chunk)
                finally:
                    dt = self._clock() - t0
                    with self._lock:
                        self.overlap["host_eval_s"] += dt
                    self._span("host_eval", "evaluate", t0, dt)

            return eval_pool.submit(run_eval)

        try:
            while told < n_steps:
                # ---------------------------------------------- issue asks
                while asked < n_steps and (asked - told) <= K:
                    ask_state = base
                    if pending:
                        # an ask with tells still pending must not replay
                        # the base state's key split — fold a fresh
                        # deterministic stream per generation
                        ask_state = base.replace(
                            algo=_rekey(base.algo, entry_key, gen0 + asked)
                        )
                    probe_pre = ask_state.algo if (
                        K > 0
                        and artifact_mask is None
                        and not ask_state.first_step
                    ) else None
                    cand, ctx = self._timed_dispatch(
                        "pipeline_ask", lambda: wf.pipeline_ask(ask_state)
                    )
                    if probe_pre is not None:
                        artifact_mask = _ask_artifact_mask(probe_pre, ctx[0])
                    self.counters["asks"] += 1
                    pending.append(
                        _InflightEval(
                            asked, ctx, submit_eval(cand, base.prob), told
                        )
                    )
                    asked += 1
                    self.queue_stats["stale_window_max"] = max(
                        self.queue_stats["stale_window_max"], len(pending)
                    )
                    if artifact_mask is None and K > 0:
                        # mask not probed yet (first_step peel): hold the
                        # window at depth 1 until the steady shape is known
                        break
                # ------------------------------------------------ admit tell
                ev = pending.popleft()
                fitness, _ = ev.fut.result()
                if hook_fut is not None:
                    # surface on_generation errors from the previous
                    # generation BEFORE advancing the state (legacy law)
                    hook_fut.result()
                    hook_fut = None
                # staleness in TELLS: how many updates landed after this
                # generation's candidates were sampled (== K in the steady
                # stale window, including the final drain tells)
                lag = told - ev.base_told
                self._sample("executor/stale_lag", lag)
                if lag > 0:
                    self.counters["stale_tells"] += 1
                    self.counters["max_lag"] = max(
                        self.counters["max_lag"], lag
                    )
                    # graft the admitted generation's ask-artifacts (key,
                    # noise, candidate buffers) onto the newest told state:
                    # tell sees its own matched (noise, fitness) pair while
                    # every earlier tell's update — and the newest monitor
                    # chain — is kept
                    hybrid = _merge_artifacts(
                        base.algo, ev.ctx[0], artifact_mask
                    )
                    ctx = (hybrid, tuple(base.monitors), ev.ctx[2])
                else:
                    ctx = ev.ctx
                tell_state = base
                base = self._timed_dispatch(
                    "pipeline_tell",
                    lambda: wf.pipeline_tell(tell_state, ctx, fitness, tell_state.prob),
                )
                told += 1
                self.counters["tells"] += 1
                self.counters["generations"] += 1
                if (
                    refit_due is not None
                    and dispatch_refit is not None
                    and refit_due(gen0 + told)
                ):
                    # BEFORE the snapshot decision: a checkpoint at this
                    # boundary must embed the refit, so a resumed run
                    # reproduces the schedule (pure in the absolute
                    # generation). Async dispatch — no host block.
                    self.counters["bg_refit"] += 1
                    base = self._timed_dispatch(
                        "surrogate_refit",
                        lambda: dispatch_refit(base, gen0 + told),
                    )
                if checkpointer is not None:
                    if int(base.generation) % checkpointer.every == 0:
                        self._submit_checkpoint(ckpt_lane, checkpointer, base)
                if on_generation is not None:
                    self.counters["bg_hook"] += 1
                    snapshot, fit_snapshot, g_abs = base, fitness, gen0 + ev.g
                    hook_fut = hook_lane.submit(
                        lambda: on_generation(g_abs, snapshot, fit_snapshot)
                    )
                if (
                    self.fetch_monitors_every
                    and told % self.fetch_monitors_every == 0
                    and getattr(base, "monitors", None)
                ):
                    self._submit_monitor_fetch(fetch_lane, base)
            if hook_fut is not None:
                hook_fut.result()
            hook_lane.drain()
            if checkpointer is not None:
                if int(base.generation) % checkpointer.every != 0:
                    # final state is always durable, even off-cadence
                    self._submit_checkpoint(ckpt_lane, checkpointer, base)
            ckpt_lane.drain()
            fetch_lane.drain()
            return base
        except BaseException:
            try:  # flush snapshots without masking the real failure
                ckpt_lane.drain()
            except Exception:
                pass
            raise
        finally:
            if ckpt_lane in lanes:
                lanes.remove(ckpt_lane)
            eval_pool.shutdown(wait=False)
            for lane in (ckpt_lane, hook_lane, fetch_lane):
                lane.close()
                self._account_lane(lane)

    def _submit_monitor_fetch(self, lane: _IoLane, state: Any) -> None:
        self.counters["bg_fetch"] += 1
        gen = int(state.generation)
        monitors = state.monitors
        cross_process = any(
            isinstance(leaf, jax.Array) and not leaf.is_fully_addressable
            for leaf in jax.tree_util.tree_leaves(monitors)
        )
        if cross_process:
            # pod meshes: the ring all-gather is a COLLECTIVE and must be
            # dispatched in SPMD lockstep on every process — run it here
            # on the admitting thread (a background thread interleaving
            # its own collectives with the main loop's dispatches would
            # deadlock the pod); only the host bookkeeping rides the lane
            from .distributed import tree_host_value

            monitors = tree_host_value(monitors)

        def fetch():
            t0 = self._clock()
            host = jax.device_get(monitors)
            self.last_monitor_fetch = (gen, host)
            dt = self._clock() - t0
            self._span("io:fetch", "monitors", t0, dt, generation=gen)
            if self.metrics is not None:
                # the telemetry lane is the axon-legal path from the
                # on-device rings into the metrics plane: the fetch just
                # completed on a background thread (registry is
                # thread-safe), so the gauges carry the newest ring
                # values without any callback or extra round-trip
                self.metrics.count("executor.monitor_fetches")
                self.metrics.observe("executor.monitor_fetch_ms", dt * 1e3)
                self.metrics.set("executor.monitor_fetch_gen", gen)

        lane.submit(fetch)
        self._sample("executor/io_queue_depth", lane.depth())
