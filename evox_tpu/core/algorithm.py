"""Abstract Algorithm: the ask–evaluate–tell contract.

Mirrors the capability of the reference's ``Algorithm`` (reference:
src/evox/core/algorithm.py:10-96) with a purely functional, TPU-idiomatic
signature: the algorithm object holds only *static* hyperparameters; all
mutable data (population, strategy parameters, PRNG key) lives in a typed
pytree state returned by ``init`` and threaded through ``ask``/``tell``.

Optional ``init_ask``/``init_tell`` support algorithms whose first
generation differs from steady state (e.g. GA-style algorithms that evaluate
a full parent population once before producing offspring) — same duck-typed
detection idea as reference algorithm.py:52-96, implemented via method
override detection.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax

AlgorithmState = Any


class Algorithm:
    """Base class for every optimization algorithm.

    Contract::

        state = algo.init(key)                 # build initial state
        pop, state = algo.ask(state)           # propose candidates
        state = algo.tell(state, fitness)      # ingest fitness of `pop`

    ``ask`` must return a ``(pop_size, ...)`` candidate array (or pytree with
    leading pop axis). ``tell`` receives fitness with shape ``(pop_size,)``
    for single-objective or ``(pop_size, n_objectives)`` for multi-objective.

    First-generation overrides: implement ``init_ask``/``init_tell`` when the
    initial evaluation differs (different pop size or bookkeeping). Workflows
    dispatch them on generation 0 when present.
    """

    def init(self, key: jax.Array) -> AlgorithmState:
        raise NotImplementedError

    def ask(self, state: AlgorithmState) -> Tuple[Any, AlgorithmState]:
        raise NotImplementedError

    def tell(self, state: AlgorithmState, fitness: jax.Array) -> AlgorithmState:
        raise NotImplementedError

    # -- optional first-generation hooks ------------------------------------
    def init_ask(self, state: AlgorithmState) -> Tuple[Any, AlgorithmState]:
        """Candidates for the very first evaluation. Default: ``ask``."""
        return self.ask(state)

    def init_tell(self, state: AlgorithmState, fitness: jax.Array) -> AlgorithmState:
        """Ingest the very first fitness batch. Default: ``tell``."""
        return self.tell(state, fitness)

    @property
    def has_init_ask(self) -> bool:
        return type(self).init_ask is not Algorithm.init_ask

    @property
    def has_init_tell(self) -> bool:
        return type(self).init_tell is not Algorithm.init_tell
