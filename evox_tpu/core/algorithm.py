"""Abstract Algorithm: the ask–evaluate–tell contract.

Mirrors the capability of the reference's ``Algorithm`` (reference:
src/evox/core/algorithm.py:10-96) with a purely functional, TPU-idiomatic
signature: the algorithm object holds only *static* hyperparameters; all
mutable data (population, strategy parameters, PRNG key) lives in a typed
pytree state returned by ``init`` and threaded through ``ask``/``tell``.

Optional ``init_ask``/``init_tell`` support algorithms whose first
generation differs from steady state (e.g. GA-style algorithms that evaluate
a full parent population once before producing offspring) — same duck-typed
detection idea as reference algorithm.py:52-96, implemented via method
override detection.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

AlgorithmState = Any


class Algorithm:
    """Base class for every optimization algorithm.

    Contract::

        state = algo.init(key)                 # build initial state
        pop, state = algo.ask(state)           # propose candidates
        state = algo.tell(state, fitness)      # ingest fitness of `pop`

    ``ask`` must return a ``(pop_size, ...)`` candidate array (or pytree with
    leading pop axis). ``tell`` receives fitness with shape ``(pop_size,)``
    for single-objective or ``(pop_size, n_objectives)`` for multi-objective.

    First-generation overrides: implement ``init_ask``/``init_tell`` when the
    initial evaluation differs (different pop size or bookkeeping). Workflows
    dispatch them on generation 0 when present.
    """

    # Opt-in mesh for algorithms whose internal O(n²) machinery (e.g. MO
    # environmental selection) can shard across a device mesh. None =
    # replicated computation; GAMOAlgorithm exposes it as a constructor
    # argument, any other algorithm accepts plain attribute assignment.
    mesh = None

    def init(self, key: jax.Array) -> AlgorithmState:
        raise NotImplementedError

    def ask(self, state: AlgorithmState) -> Tuple[Any, AlgorithmState]:
        raise NotImplementedError

    def tell(self, state: AlgorithmState, fitness: jax.Array) -> AlgorithmState:
        raise NotImplementedError

    # -- optional first-generation hooks ------------------------------------
    def init_ask(self, state: AlgorithmState) -> Tuple[Any, AlgorithmState]:
        """Candidates for the very first evaluation. Default: ``ask``."""
        return self.ask(state)

    def init_tell(self, state: AlgorithmState, fitness: jax.Array) -> AlgorithmState:
        """Ingest the very first fitness batch. Default: ``tell``."""
        return self.tell(state, fitness)

    @property
    def has_init_ask(self) -> bool:
        return type(self).init_ask is not Algorithm.init_ask

    @property
    def has_init_tell(self) -> bool:
        return type(self).init_tell is not Algorithm.init_tell

    # -- optional migration hook --------------------------------------------
    def migrate(
        self, state: AlgorithmState, pop: Any, fitness: jax.Array
    ) -> AlgorithmState:
        """Ingest foreign individuals (island migration / human-in-the-loop;
        the slot behind ``StdWorkflow(migrate_helper=...)`` and
        ``IslandWorkflow`` — reference std_workflow.py:230-244).

        ``fitness`` is in the internal minimization convention. The default
        offers each migrant to the worst rows of ``state.population`` /
        ``state.fitness``, accepting only migrants that beat the row they
        would displace (elitist acceptance — an unconditional overwrite
        would let a bad migrant clobber e.g. a PSO pbest row and break its
        monotonicity invariant). Enough for every population-based
        single-objective state carrying those two fields; algorithms with
        extra per-individual bookkeeping (personal bests, archives) or
        multi-objective selection should override.
        """
        pop_arr = getattr(state, "population", None)
        fit_arr = getattr(state, "fitness", None)
        if pop_arr is None or fit_arr is None or fit_arr.ndim != 1:
            raise NotImplementedError(
                f"{type(self).__name__} has no (population, 1-d fitness) "
                "state fields; override migrate() to support migration"
            )
        k = fitness.shape[0]
        worst = jnp.argsort(-fit_arr)[:k]
        accept = fitness < fit_arr[worst]  # (k,) per-row elitism
        new_rows = jnp.where(accept[:, None], pop, pop_arr[worst])
        new_fit = jnp.where(accept, fitness, fit_arr[worst])
        return state.replace(
            population=pop_arr.at[worst].set(new_rows),
            fitness=fit_arr.at[worst].set(new_fit),
        )
