"""Monitor hook specification (reference: src/evox/core/monitor.py:11-47).

Same 8-hook surface as the reference, redesigned functionally so monitor
state is an on-device pytree threaded through the jitted workflow step —
no host round-trip needed for elite tracking or Pareto archives. Monitors
that want unbounded host-side history additionally use ``jax.experimental
.io_callback`` internally (see monitors/eval_monitor.py).

Each hook is pure: it receives the monitor state plus step data and returns
an updated monitor state. A monitor declares which hooks it implements via
``hooks()`` so the workflow only wires what is needed.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax

MonitorState = Any

HOOK_NAMES = (
    "pre_step",
    "pre_ask",
    "post_ask",
    "pre_eval",
    "post_eval",
    "pre_tell",
    "post_tell",
    "post_step",
)


class Monitor:
    """Base monitor. Subclasses override ``init``, ``hooks`` and hook methods.

    Hook signatures (all return the new monitor state):

    - ``pre_step(mstate)``
    - ``pre_ask(mstate)``
    - ``post_ask(mstate, cand)``
    - ``pre_eval(mstate, cand)``
    - ``post_eval(mstate, cand, fitness)`` — fitness already in the
      *user's* direction convention (workflows un-flip ``opt_direction``
      before calling, so maximization problems see positive-is-better).
    - ``pre_tell(mstate, transformed_fitness)``
    - ``post_tell(mstate)``
    - ``post_step(mstate, workflow_state)``
    """

    def init(self, key: Optional[jax.Array] = None) -> MonitorState:
        return None

    def hooks(self) -> Sequence[str]:
        """Names of the hooks this monitor implements."""
        raise NotImplementedError

    def set_opt_direction(self, opt_direction: jax.Array) -> None:
        """Called once by the workflow with the ±1 direction vector."""
        self.opt_direction = opt_direction

    # -- hooks (default: identity) ------------------------------------------
    def pre_step(self, mstate: MonitorState) -> MonitorState:
        return mstate

    def pre_ask(self, mstate: MonitorState) -> MonitorState:
        return mstate

    def post_ask(self, mstate: MonitorState, cand: Any) -> MonitorState:
        return mstate

    def pre_eval(self, mstate: MonitorState, cand: Any) -> MonitorState:
        return mstate

    def post_eval(self, mstate: MonitorState, cand: Any, fitness: jax.Array) -> MonitorState:
        return mstate

    def pre_tell(self, mstate: MonitorState, fitness: jax.Array) -> MonitorState:
        return mstate

    def post_tell(self, mstate: MonitorState) -> MonitorState:
        return mstate

    def post_step(self, mstate: MonitorState, wf_state: Any) -> MonitorState:
        return mstate

    def flush(self) -> None:
        """Block until any async host callbacks have landed."""
        jax.effects_barrier()
