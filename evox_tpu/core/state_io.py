"""Checkpoint save / load (reference: src/evox/core/state.py:264-301).

Because every evox_tpu state is a plain pytree, checkpointing is direct
orbax ``StandardCheckpointer`` save/restore (sharding-aware: restore can
target a ``NamedSharding`` layout for a different mesh than the one that
saved), with a pickle fallback for quick local snapshots.
"""

from __future__ import annotations

import pickle
from pathlib import Path
from typing import Any, Optional

import jax

_ASYNC_CKPT = None


def _checkpointer():
    """Process-wide orbax checkpointer (its save path is async-capable)."""
    global _ASYNC_CKPT
    if _ASYNC_CKPT is None:
        import atexit

        import orbax.checkpoint as ocp

        _ASYNC_CKPT = ocp.StandardCheckpointer()
        # a process exiting right after save(wait=False) must not leave a
        # truncated/uncommitted checkpoint behind
        atexit.register(wait_for_saves)
    return _ASYNC_CKPT


def wait_for_saves() -> None:
    """Block until every ``save(..., wait=False)`` has committed to disk."""
    if _ASYNC_CKPT is not None:
        _ASYNC_CKPT.wait_until_finished()


def save(
    state: Any,
    path: str,
    backend: str = "orbax",
    wait: bool = True,
    overwrite: bool = False,
) -> None:
    """Save a state pytree to ``path``.

    ``wait=False`` (orbax only) returns as soon as the state is staged:
    serialization and the filesystem commit proceed in orbax's background
    thread while training continues (SURVEY.md §5.4's async-checkpoint
    recommendation). Call :func:`wait_for_saves` before reading the
    checkpoint or exiting the process. An existing destination raises
    unless ``overwrite=True`` (orbax's guard against clobbering the only
    good snapshot).
    """
    path = Path(path).resolve()
    if backend == "orbax":
        ckpt = _checkpointer()
        ckpt.save(path, state, force=overwrite)
        if wait:
            ckpt.wait_until_finished()
    elif backend == "pickle":
        path.parent.mkdir(parents=True, exist_ok=True)
        with open(path, "wb") as f:
            pickle.dump(jax.device_get(state), f)
    else:
        raise ValueError(f"unknown checkpoint backend: {backend!r}")


def load(path: str, target: Optional[Any] = None, backend: str = "orbax") -> Any:
    """Load a state pytree from ``path``.

    ``target``: an abstract or concrete pytree of the same structure (required
    for orbax; leaves may carry ``sharding`` to restore directly into a mesh
    layout different from the saving run).
    """
    path = Path(path).resolve()
    if backend == "orbax":
        import orbax.checkpoint as ocp

        if target is None:
            raise ValueError("orbax restore requires a `target` pytree template")
        template = jax.tree.map(ocp.utils.to_shape_dtype_struct, target)
        wait_for_saves()  # a pending async save of `path` must land first
        return _checkpointer().restore(path, template)
    elif backend == "pickle":
        with open(path, "rb") as f:
            return pickle.load(f)
    else:
        raise ValueError(f"unknown checkpoint backend: {backend!r}")
