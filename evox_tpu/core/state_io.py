"""Checkpoint save / load (reference: src/evox/core/state.py:264-301).

Because every evox_tpu state is a plain pytree, checkpointing is direct
orbax ``StandardCheckpointer`` save/restore (sharding-aware: restore can
target a ``NamedSharding`` layout for a different mesh than the one that
saved), with a pickle fallback for quick local snapshots.
"""

from __future__ import annotations

import pickle
from pathlib import Path
from typing import Any, Optional

import jax


def save(state: Any, path: str, backend: str = "orbax") -> None:
    """Save a state pytree to ``path``."""
    path = Path(path).resolve()
    if backend == "orbax":
        import orbax.checkpoint as ocp

        with ocp.StandardCheckpointer() as ckpt:
            ckpt.save(path, state)
    elif backend == "pickle":
        path.parent.mkdir(parents=True, exist_ok=True)
        with open(path, "wb") as f:
            pickle.dump(jax.device_get(state), f)
    else:
        raise ValueError(f"unknown checkpoint backend: {backend!r}")


def load(path: str, target: Optional[Any] = None, backend: str = "orbax") -> Any:
    """Load a state pytree from ``path``.

    ``target``: an abstract or concrete pytree of the same structure (required
    for orbax; leaves may carry ``sharding`` to restore directly into a mesh
    layout different from the saving run).
    """
    path = Path(path).resolve()
    if backend == "orbax":
        import orbax.checkpoint as ocp

        if target is None:
            raise ValueError("orbax restore requires a `target` pytree template")
        template = jax.tree.map(ocp.utils.to_shape_dtype_struct, target)
        with ocp.StandardCheckpointer() as ckpt:
            return ckpt.restore(path, template)
    elif backend == "pickle":
        with open(path, "rb") as f:
            return pickle.load(f)
    else:
        raise ValueError(f"unknown checkpoint backend: {backend!r}")
