"""AOT XLA cost/memory analysis and roofline attribution — all host-side.

PR 1's :mod:`~evox_tpu.core.instrument` answers *how long* each dispatch
took; this module answers *why*: is an entry point compute-bound
(MXU-limited), memory-bound (HBM-limited), or dispatch-bound (the
45-100 ms axon round-trip dwarfs the useful work)? The machinery is
deliberately callback-free and trace-free:

- **AOT analysis**: ``jit(fn).lower(*args).compile()`` once per entry
  point and harvest ``compiled.cost_analysis()`` (FLOPs, bytes accessed)
  and ``compiled.memory_analysis()`` (argument/output/temp bytes). Both
  are host-side XLA queries — nothing runs on the device, no
  ``io_callback``/``pure_callback`` anywhere, so the analysis works
  identically on the 8-device CPU mesh and the tunneled axon TPU.
- **Roofline merge**: static FLOPs/bytes divided by the *differenced*
  measured seconds (``DispatchRecorder``'s slope over distinct trip
  counts — bench.py's latency-cancelling discipline) give achieved TF/s
  and GB/s, compared against the measured chip ceilings below.
- **Dynamic trip counts**: XLA's HLO cost analysis counts a
  dynamic-trip-count ``fori_loop`` body ONCE (verified empirically: a
  10-iteration loop of a 528 kFLOP body reports 528 kFLOPs), so the
  static cost of a ``make_run_loop`` program is the PER-GENERATION cost
  — exactly the unit the differenced slope measures. The two merge
  without any trip-count bookkeeping.

Dependency direction: this module imports only jax/numpy; it must never
import :mod:`~evox_tpu.core.instrument` (which imports *it*), monitors,
or workflows. Workflows opt in by exposing ``analysis_targets(state)``
(duck-typed — see :meth:`CostAnalyzer.analyze_workflow`).
"""

from __future__ import annotations

import hashlib
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

__all__ = [
    "CHIP_CEILINGS",
    "CostAnalyzer",
    "abstract_signature",
    "analyze_callable",
    "roofline_section",
]

# Measured ceilings of the target chip, NOT spec-sheet numbers: the
# differenced probes of bench.py (slope of t(n2)-t(n1) over two trip
# counts, cancelling the per-dispatch tunnel latency) measured HBM triad
# at ~607 GB/s and bf16 matmul at ~206 TF/s on the tunneled v5e-1 chip
# (spec: ~819 GB/s / ~197 TF/s bf16 — the matmul probe exceeds the
# bf16 spec figure because XLA fuses toward the int8/bf16 MXU path).
# "Fraction of peak" below therefore means fraction of what THIS chip
# demonstrably delivers through the same harness that timed the entry.
CHIP_CEILINGS: Dict[str, Any] = {
    "mxu_bf16_tflops": 206.0,
    "hbm_gbps": 607.0,
    "provenance": (
        "differenced probes through the axon tunnel (bench.py protocol, "
        "PERF_NOTES): bf16 matmul ~206 TF/s, HBM triad ~607 GB/s on the "
        "tunneled v5e-1; ratios against these are achieved-vs-measured, "
        "not achieved-vs-spec"
    ),
}

# measured >= factor * ideal  =>  the entry spends most of its time on
# neither FLOPs nor HBM traffic: per-dispatch overhead (tunnel
# round-trip, host Python, XLA launch) dominates -> "dispatch-bound"
DISPATCH_BOUND_FACTOR = 4.0

CLASSIFICATIONS = ("compute-bound", "memory-bound", "dispatch-bound")


# --------------------------------------------------------------- signatures


def _leaf_sig(leaf: Any) -> str:
    if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
        return f"{np.dtype(leaf.dtype).name}[{','.join(map(str, leaf.shape))}]"
    # python scalars trace to weak-typed scalar avals: any int is the same
    # aval as any other int, so the VALUE must not enter the signature
    # (wf.run(state, 100) vs run(state, 200) is NOT a retrace)
    return type(leaf).__name__


def abstract_signature(args: tuple, kwargs: Optional[dict] = None) -> Tuple[str, str]:
    """``(aval_sig, static_sig)`` of a call's arguments.

    ``aval_sig`` keys the abstract values jit actually specializes on —
    leaf shapes/dtypes (python scalars collapse to their type: they trace
    to weak-typed scalar avals). A *new* ``aval_sig`` for an
    already-compiled entry is the classic silent retrace (a shape or
    dtype changed). ``static_sig`` hashes the pytree structure including
    static fields; it changes on benign, designed recompiles too — e.g.
    ``StdWorkflowState.first_step`` flipping after the init-generation
    peel — so the two are reported separately and only aval changes are
    flagged.
    """
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs or {}))
    aval_sig = ";".join(_leaf_sig(leaf) for leaf in leaves)
    static_sig = hashlib.sha1(
        (str(treedef) + "|" + aval_sig).encode()
    ).hexdigest()[:16]
    return aval_sig, static_sig


# ------------------------------------------------------------- AOT analysis


def _cost_dict(compiled: Any) -> Optional[dict]:
    """Normalize ``compiled.cost_analysis()`` across jax versions: some
    return a one-element list of dicts, newer ones the dict itself."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # backend without HLO cost analysis
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    return ca if isinstance(ca, dict) else None


_MEMORY_ATTRS = (
    ("argument_size_in_bytes", "argument_bytes"),
    ("output_size_in_bytes", "output_bytes"),
    ("temp_size_in_bytes", "temp_bytes"),
    ("alias_size_in_bytes", "alias_bytes"),
    ("generated_code_size_in_bytes", "generated_code_bytes"),
)


def _memory_dict(compiled: Any) -> Optional[dict]:
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out: dict = {}
    for attr, key in _MEMORY_ATTRS:
        v = getattr(ma, attr, None)
        if v is not None:
            out[key] = int(v)
    if not out:
        return None
    # arguments alias in place, temps live alongside outputs: the sum is
    # the standard upper estimate of live bytes during execution
    out["peak_bytes_estimate"] = (
        out.get("argument_bytes", 0)
        + out.get("output_bytes", 0)
        + out.get("temp_bytes", 0)
    )
    return out


def analyze_callable(fn: Callable, *args: Any, **kwargs: Any) -> dict:
    """AOT-lower and compile ``fn(*args, **kwargs)`` once, harvesting XLA's
    static cost and memory analysis. ``fn`` may be a ``jax.jit`` wrapper
    (lowered directly — the same program the workflow dispatches) or any
    traceable callable (jitted ad hoc). ``args`` may be concrete arrays
    or ``jax.ShapeDtypeStruct`` pytrees — lowering never executes the
    program, so this is safe and side-effect-free on every backend.

    Returns ``{"flops", "bytes_accessed", "memory": {...}, "signature"}``
    with ``None`` for quantities the backend does not report, or
    ``{"error": ...}`` when lowering/compilation fails (analysis must
    never sink the run it describes).
    """
    try:
        lowerable = fn if hasattr(fn, "lower") else jax.jit(fn)
        compiled = lowerable.lower(*args, **kwargs).compile()
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}
    cost = _cost_dict(compiled)

    def _metric(key: str) -> Optional[float]:
        v = cost.get(key) if cost else None
        # XLA reports -1/absent for metrics a backend doesn't model
        return float(v) if v is not None and v >= 0 else None

    return {
        "flops": _metric("flops"),
        "bytes_accessed": _metric("bytes accessed"),
        "memory": _memory_dict(compiled),
        "signature": abstract_signature(args, kwargs)[0],
    }


class CostAnalyzer:
    """Per-entry-point AOT analysis cache.

    One lower+compile per ``(entry, aval_signature)`` — re-analysis with
    the same abstract arguments is free, so :func:`~evox_tpu.core.
    instrument.run_report` can call :meth:`analyze_workflow` on every
    report without recompiling anything.
    """

    def __init__(self, ceilings: Optional[dict] = None):
        self.ceilings = dict(ceilings if ceilings is not None else CHIP_CEILINGS)
        self.analyses: Dict[str, dict] = {}
        self._cache: Dict[Tuple[str, str], dict] = {}

    def analyze(self, name: str, fn: Callable, *args: Any, **kwargs: Any) -> dict:
        key = (name, abstract_signature(args, kwargs)[0])
        if key not in self._cache:
            self._cache[key] = analyze_callable(fn, *args, **kwargs)
        self.analyses[name] = self._cache[key]
        return self.analyses[name]

    def analyze_workflow(self, workflow: Any, state: Any) -> Dict[str, dict]:
        """Analyze every entry point the workflow advertises through
        ``analysis_targets(state)`` (duck-typed: workflows without the
        method contribute nothing). Targets map entry names to
        ``(jitted_callable, example_args)`` — the exact programs the
        workflow dispatches, so the analysis covers what actually runs."""
        targets = getattr(workflow, "analysis_targets", None)
        if targets is None:
            return {}
        for name, (fn, args) in targets(state).items():
            self.analyze(name, fn, *args)
        return self.analyses


# ----------------------------------------------------------------- roofline


def roofline_section(
    analyses: Dict[str, dict],
    dispatch_summary: Optional[dict] = None,
    ceilings: Optional[dict] = None,
    dispatch_bound_factor: float = DISPATCH_BOUND_FACTOR,
) -> dict:
    """Merge static AOT analyses with measured per-unit dispatch timings
    into the ``roofline`` section of ``run_report()``.

    Per entry: static FLOPs/bytes/memory, the measured seconds per work
    unit (differenced slope when the recorder saw two trip counts, else
    the steady-state median — flagged ``latency_confounded`` because a
    single-trip-count timing still contains the full per-dispatch
    round-trip), achieved TF/s and GB/s, fractions of the measured chip
    ceilings, and a bound-ness classification:

    - ``dispatch-bound``: measured time exceeds ``dispatch_bound_factor``
      x the roofline-ideal time — per-dispatch overhead dominates.
    - ``compute-bound`` / ``memory-bound``: whichever of the FLOP and HBM
      ideal times is larger when the measurement is near the roofline.

    Entries with an analysis error or no recorded timing keep their
    static half and classify ``None`` — the report never invents rates.
    """
    ceilings = dict(ceilings if ceilings is not None else CHIP_CEILINGS)
    peak_flops = float(ceilings["mxu_bf16_tflops"]) * 1e12
    peak_bytes = float(ceilings["hbm_gbps"]) * 1e9
    entry_stats = (dispatch_summary or {}).get("entry_points", {})
    entries: Dict[str, dict] = {}
    for name, analysis in sorted(analyses.items()):
        entry: dict = {"static": analysis, "classification": None}
        if "error" in analysis:
            entries[name] = entry
            continue
        per_work = (entry_stats.get(name) or {}).get("per_work_s") or {}
        t = per_work.get("seconds")
        flops = analysis.get("flops")
        nbytes = analysis.get("bytes_accessed")
        if not t or t <= 0:
            entries[name] = entry
            continue
        if flops is None and nbytes is None:
            # the backend reported no static metrics at all: a verdict
            # here would be invented — keep the measurement, classify None
            entry.update(
                measured_s_per_unit=t,
                timing_method=per_work.get("method"),
                latency_confounded=bool(per_work.get("latency_confounded")),
            )
            entries[name] = entry
            continue
        ideal_compute_s = (flops or 0.0) / peak_flops
        ideal_memory_s = (nbytes or 0.0) / peak_bytes
        ideal_s = max(ideal_compute_s, ideal_memory_s)
        if ideal_s <= 0 or t > dispatch_bound_factor * ideal_s:
            classification = "dispatch-bound"
        elif ideal_compute_s >= ideal_memory_s:
            classification = "compute-bound"
        else:
            classification = "memory-bound"
        entry.update(
            measured_s_per_unit=t,
            timing_method=per_work.get("method"),
            latency_confounded=bool(per_work.get("latency_confounded")),
            achieved_tflops=(
                round(flops / t / 1e12, 6) if flops is not None else None
            ),
            achieved_gbps=(
                round(nbytes / t / 1e9, 6) if nbytes is not None else None
            ),
            frac_peak_compute=(
                round(flops / t / peak_flops, 6) if flops is not None else None
            ),
            frac_peak_bandwidth=(
                round(nbytes / t / peak_bytes, 6)
                if nbytes is not None
                else None
            ),
            ideal_s=round(ideal_s, 9),
            dispatch_overhead_frac=round(max(0.0, 1.0 - ideal_s / t), 6),
            classification=classification,
        )
        entries[name] = entry
    return {
        "ceilings": ceilings,
        "dispatch_bound_factor": dispatch_bound_factor,
        "entries": entries,
    }
