"""Host-side serving metrics registry: counters, gauges, histograms.

Everything observability-wise before PR 16 was post-hoc and per-run —
``run_report()`` summarizes one workflow AFTER it returns. The ROADMAP
north star is a long-lived multi-pod *service* (item 4's control plane)
whose operators need tenant-gens/sec, deadline hit rate, cache hits, and
pod health **while it runs**. Fiber (arXiv 2003.11164, PAPERS.md)
centers exactly this: a monitoring plane is what turns a framework into
an operable service.

This module is the registry half of that plane: a tiny, dependency-free
map of named metrics the serving stack increments at its existing host
boundaries. Three kinds, the Prometheus trinity:

- :class:`Counter` — monotonically non-decreasing totals (dispatches,
  cache hits, preemptions). The stream validator
  (tools/check_report.py) enforces the monotonicity across samples.
- :class:`Gauge` — last-write-wins levels (queue depth, pod census).
- :class:`Histogram` — fixed-bucket distributions (dispatch
  milliseconds, compile milliseconds). Buckets are FIXED at creation —
  a dynamic-bucket histogram would make two samples of one stream
  incomparable, so the registry refuses to re-create a histogram with
  different buckets.

Axon rule (CLAUDE.md): the whole registry is host-side Python on data
already outside traced code — values arrive from dispatch boundaries
and from telemetry rings the executor's ``fetch_monitors_every`` lane
already fetched. No io_callback / pure_callback / jax.debug anywhere
(pinned by tests/test_no_host_callbacks.py); nothing here ever touches
a live jax value.

The registry deliberately knows nothing about files or streams —
:class:`~evox_tpu.workflows.flightrec.FlightRecorder` owns durability
(the PR-11 journal discipline) and samples this registry at chunk
barriers. ``snapshot()`` is the hand-off: a plain strict-JSON dict.

Note: this is ``evox_tpu.core.metrics`` — the *serving* metrics plane.
The top-level ``evox_tpu.metrics`` package (IGD/HV quality indicators,
EvoX parity) is unrelated; the name collision mirrors Prometheus vs
sklearn.metrics and is resolved by the package path.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "METRIC_KINDS",
    "DEFAULT_MS_BUCKETS",
]

#: the closed set of metric kinds a stream may carry; the stream
#: validator rejects anything else (the EVENT_KINDS discipline)
METRIC_KINDS = ("counter", "gauge", "histogram")

#: default latency buckets (milliseconds): sub-ms host work through the
#: 45-100 ms tunnel round-trip up to multi-second compiles
DEFAULT_MS_BUCKETS = (1.0, 5.0, 20.0, 50.0, 100.0, 250.0, 1000.0, 5000.0)


def _finite(value: Any) -> float:
    v = float(value)
    if not math.isfinite(v):
        raise ValueError(f"metrics are strict-JSON: non-finite value {value!r}")
    return v


class Counter:
    """Monotonically non-decreasing total. ``inc`` rejects negative
    deltas — a counter that can go down is a gauge wearing the wrong
    uniform, and the stream validator's monotonicity law would flag the
    decrease as corruption."""

    kind = "counter"

    def __init__(self, name: str):
        self.name = name
        self.value: float = 0.0

    def inc(self, n: float = 1.0) -> None:
        n = _finite(n)
        if n < 0:
            raise ValueError(
                f"counter {self.name!r} cannot decrease (inc({n})) — use a "
                "gauge for levels"
            )
        self.value += n

    def snapshot(self) -> float:
        # ints stay ints through JSON (counters are almost always counts)
        return int(self.value) if self.value == int(self.value) else self.value


class Gauge:
    """Last-write-wins level (queue depth, live process count)."""

    kind = "gauge"

    def __init__(self, name: str):
        self.name = name
        self.value: float = 0.0

    def set(self, v: float) -> None:
        self.value = _finite(v)

    def snapshot(self) -> float:
        return int(self.value) if self.value == int(self.value) else self.value


class Histogram:
    """Fixed-bucket cumulative histogram (Prometheus semantics: bucket i
    counts observations ``<= le[i]``; a final implicit +Inf bucket is
    ``count``). ``sum``/``count`` ride along so rates and means are
    derivable from any single sample."""

    kind = "histogram"

    def __init__(self, name: str, buckets: Sequence[float] = DEFAULT_MS_BUCKETS):
        if not buckets:
            raise ValueError(f"histogram {name!r} needs at least one bucket")
        le = [float(b) for b in buckets]
        if le != sorted(le) or len(set(le)) != len(le):
            raise ValueError(
                f"histogram {name!r} buckets must be strictly increasing, "
                f"got {buckets}"
            )
        self.name = name
        self.le: Tuple[float, ...] = tuple(le)
        self.counts: List[int] = [0] * len(le)
        self.count = 0
        self.sum = 0.0

    def observe(self, v: float) -> None:
        v = _finite(v)
        self.count += 1
        self.sum += v
        for i, bound in enumerate(self.le):
            if v <= bound:
                self.counts[i] += 1

    def snapshot(self) -> Dict[str, Any]:
        return {
            "le": list(self.le),
            "counts": list(self.counts),
            "count": self.count,
            "sum": round(self.sum, 6),
        }


class MetricsRegistry:
    """Thread-safe named-metric registry.

    ``counter(name)`` / ``gauge(name)`` / ``histogram(name, buckets)``
    get-or-create (the Prometheus client idiom, so producers never
    coordinate creation); re-creating under a different kind — or a
    histogram under different buckets — raises, because one name must
    mean one thing for the life of a stream. Shorthand mutators
    (:meth:`count` / :meth:`set` / :meth:`observe`) keep producer call
    sites one line.

    Thread safety matters here: the executor's background lanes
    (checkpoint, monitor fetch) and the queue's caller thread all
    produce into one registry.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, Any] = {}

    # ------------------------------------------------------------ get/create
    def _get(self, name: str, cls, *args) -> Any:
        if not name or not isinstance(name, str):
            raise ValueError(f"metric name must be a non-empty str, got {name!r}")
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, *args)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}"
                )
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(
        self, name: str, buckets: Sequence[float] = DEFAULT_MS_BUCKETS
    ) -> Histogram:
        h = self._get(name, Histogram, buckets)
        if h.le != tuple(float(b) for b in buckets):
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{h.le}, requested {tuple(buckets)} — fixed buckets are "
                "what keep two samples of one stream comparable"
            )
        return h

    # ------------------------------------------------------------- shorthand
    def count(self, name: str, n: float = 1.0) -> None:
        self.counter(name).inc(n)

    def set(self, name: str, v: float) -> None:
        self.gauge(name).set(v)

    def observe(
        self, name: str, v: float, buckets: Sequence[float] = DEFAULT_MS_BUCKETS
    ) -> None:
        self.histogram(name, buckets).observe(v)

    def get(self, name: str) -> Optional[Any]:
        with self._lock:
            return self._metrics.get(name)

    def value(self, name: str, default: float = 0) -> float:
        """Current scalar value of a counter/gauge (0 when absent) —
        producers use this for read-modify checks, tests for asserts."""
        m = self.get(name)
        return default if m is None or isinstance(m, Histogram) else m.snapshot()

    def values(self, prefix: str) -> Dict[str, float]:
        """Every counter/gauge scalar under a dotted-name prefix, e.g.
        ``values("control.")`` -> the multi-pod gateway's own family.
        Histograms are skipped (their snapshot is a dict, not a scalar);
        consumers wanting them take the full :meth:`snapshot`."""
        with self._lock:
            metrics = list(self._metrics.values())
        return {
            m.name: m.snapshot()
            for m in metrics
            if m.name.startswith(prefix) and not isinstance(m, Histogram)
        }

    # -------------------------------------------------------------- snapshot
    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """The registry as one strict-JSON dict, kinds separated so a
        consumer (stream sample, validator, evoxtail) never guesses:
        ``{"counters": {...}, "gauges": {...}, "histograms": {...}}``."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: Dict[str, Dict[str, Any]] = {
            "counters": {},
            "gauges": {},
            "histograms": {},
        }
        for m in metrics:
            out[m.kind + "s"][m.name] = m.snapshot()
        return out

    def to_openmetrics(self) -> str:
        """Prometheus/OpenMetrics text exposition of the current state
        (dots in names become underscores — the Prometheus charset).
        Host-side string building only; `evoxtail --prometheus` and any
        scrape endpoint share this one serializer."""
        lines: List[str] = []
        snap = self.snapshot()
        for name, v in sorted(snap["counters"].items()):
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} counter")
            lines.append(f"{pn}_total {_prom_num(v)}")
        for name, v in sorted(snap["gauges"].items()):
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} gauge")
            lines.append(f"{pn} {_prom_num(v)}")
        for name, h in sorted(snap["histograms"].items()):
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} histogram")
            for le, c in zip(h["le"], h["counts"]):
                lines.append(f'{pn}_bucket{{le="{_prom_num(le)}"}} {c}')
            lines.append(f'{pn}_bucket{{le="+Inf"}} {h["count"]}')
            lines.append(f"{pn}_sum {_prom_num(h['sum'])}")
            lines.append(f"{pn}_count {h['count']}")
        lines.append("# EOF")
        return "\n".join(lines) + "\n"


def _prom_name(name: str) -> str:
    return "".join(
        c if (c.isalnum() or c == "_") else "_" for c in name
    ).strip("_")


def _prom_num(v: Any) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)
