"""Host-side dispatch instrumentation for workflow entry points.

The device half of observability (TelemetryMonitor) lives inside the
jitted step; this module is the host half. It wraps a workflow's jitted
entry points (``init`` / ``step`` / ``run`` / ``pipeline_ask`` /
``pipeline_tell``) with plain wall-clock timing *around the dispatch* —
never inside traced code, so it is safe on every backend including the
axon-tunneled TPU, and on that backend it directly measures the
45-100 ms per-dispatch tunnel round-trip that bench.py documents.

Semantics under JAX's async dispatch: a warm call returns once the work
is *dispatched*, so its duration is the host-side dispatch cost (on the
tunneled chip: the round-trip latency). The first call of an entry point
additionally pays trace + compile, which dominates it — the summary
reports that first call separately (``first_call_s``) plus an estimated
``compile_s`` (first call minus the steady-state median) alongside the
steady-state dispatch statistics. Host fetches go through
:meth:`DispatchRecorder.fetch`, which accounts bytes and seconds per
fetch site (a big-array fetch costs real tunnel time, ~6.6 s/256 MB —
the reason bench.py fetches a small leaf).

Beyond timing, the recorder is the host half of the roofline analytics
layer (core/xla_cost.py):

- **Work-normalized timing**: each call carries a work count (``run``'s
  ``n_steps``; 1 elsewhere). When an entry was called at two distinct
  trip counts, the per-generation time is the *differenced slope*
  ``(t(n2) - t(n1)) / (n2 - n1)`` — bench.py's latency-cancelling
  discipline — otherwise the steady median is used and flagged
  ``latency_confounded`` (a single-trip-count timing still contains the
  whole per-dispatch round-trip).
- **Retrace detection**: every call's abstract argument signature is
  recorded. A new *aval* signature (leaf shapes/dtypes changed) after an
  entry's first call is the classic silent TPU perf killer — flagged in
  the summary (``retrace_flags``) and escalated to :class:`RetraceError`
  under ``DispatchRecorder(strict_retrace=True)``. Static-only structure
  changes (e.g. the designed ``first_step`` peel recompile) are counted
  separately and never flagged.
- **Span recording**: every timed call and fetch keeps its
  ``(start, duration)`` so :func:`write_chrome_trace` can export the run
  as a Chrome trace-event JSON timeline (Perfetto / chrome://tracing),
  with TelemetryMonitor rings and farm health counters as counter tracks.

``run_report`` merges this host-side summary with the device counters of
any attached monitor exposing ``report(mstate)`` (TelemetryMonitor) into
one JSON-serializable dict — plus, when a :class:`~evox_tpu.core.
xla_cost.CostAnalyzer` is attached (``instrument(wf, analyze=True)``), a
``roofline`` section attributing each entry point compute-bound /
memory-bound / dispatch-bound; ``write_report_jsonl`` appends it to a
JSON-lines file.
"""

from __future__ import annotations

import contextlib
import json
import math
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from .xla_cost import CostAnalyzer, abstract_signature, roofline_section

__all__ = [
    "DispatchRecorder",
    "RetraceError",
    "instrument",
    "run_report",
    "sanitize_json",
    "write_chrome_trace",
    "write_report_jsonl",
]


def sanitize_json(obj: Any) -> Any:
    """Recursively replace non-finite floats with ``None`` so the result
    is STRICT (RFC 8259) JSON — ``json.dumps`` would otherwise emit bare
    ``Infinity``/``NaN`` tokens that ``jq``/``JSON.parse`` reject. Inf/NaN
    legitimately appear in telemetry (the +inf best before any finite
    generation, inf-padded ring slots of an all-poison generation)."""
    if isinstance(obj, dict):
        return {k: sanitize_json(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [sanitize_json(v) for v in obj]
    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    return obj

# bound methods wrapped on the workflow INSTANCE, so instrumentation is
# per-workflow and never leaks into other workflows of the same class
DEFAULT_ENTRY_POINTS = (
    "init",
    "step",
    "run",
    "pipeline_ask",
    "pipeline_tell",
)


class RetraceError(RuntimeError):
    """An instrumented entry point is about to recompile because a call's
    abstract argument signature (leaf shapes/dtypes) changed — raised
    instead of silently paying the compile when
    ``DispatchRecorder(strict_retrace=True)``."""


def _run_work(args: tuple, kwargs: dict) -> int:
    """Work units of a ``run(state, n_steps, ...)`` call. Restart/resume
    drivers may run fewer generations than requested; n_steps is still the
    honest per-call upper bound and exact for plain fused runs."""
    n = kwargs.get("n_steps", args[1] if len(args) > 1 else 1)
    try:
        return max(int(n), 1)
    except (TypeError, ValueError):
        return 1


DEFAULT_WORK_EXTRACTORS: Dict[str, Callable[[tuple, dict], int]] = {
    "run": _run_work,
}


class _EntryStats:
    __slots__ = ("times", "works", "spans", "sigs", "aval_sigs", "retraces")

    def __init__(self) -> None:
        self.times: list = []  # call durations, [0] is the cold call
        self.works: list = []  # work units per call (run: n_steps)
        self.spans: list = []  # (abs_start_s, duration_s, work)
        self.sigs: Dict[str, int] = {}  # full (aval|static) sig -> calls
        self.aval_sigs: Dict[str, int] = {}  # aval sig -> calls
        self.retraces: list = []  # {"call", "kind", "t"} events

    # ------------------------------------------------------------ retrace
    def observe_signature(self, sig: Tuple[str, str], t: float) -> Optional[str]:
        """Record a call's (aval, static) signature; returns the retrace
        kind (``"aval"``/``"static"``) when this call will recompile an
        already-compiled entry, else None. The FIRST signature is the
        initial compile, never a retrace."""
        aval, static = sig
        full = aval + "|" + static
        kind = None
        if self.sigs and full not in self.sigs:
            kind = "aval" if aval not in self.aval_sigs else "static"
            self.retraces.append(
                {"call": len(self.times) + 1, "kind": kind, "t": t}
            )
        self.sigs[full] = self.sigs.get(full, 0) + 1
        self.aval_sigs[aval] = self.aval_sigs.get(aval, 0) + 1
        return kind

    @property
    def aval_retraces(self) -> int:
        return sum(1 for r in self.retraces if r["kind"] == "aval")

    # ------------------------------------------------------------- timing
    def _per_work(self) -> Optional[dict]:
        """Seconds per work unit. Differenced slope over the two extreme
        distinct work counts when available (per-dispatch latency cancels
        exactly, bench.py's protocol); else the steady median divided by
        its median work, flagged latency-confounded. The cold call (index
        0, trace+compile) is excluded whenever warmer data exists."""
        if not self.times:
            return None
        steady = (self.times[1:], self.works[1:]) if len(self.times) > 1 else None
        for source, cold_included in ((steady, False), ((self.times, self.works), True)):
            if source is None:
                continue
            times, works = source
            best: Dict[int, float] = {}
            for w, t in zip(works, times):
                best[w] = min(t, best.get(w, math.inf))
            if len(best) < 2:
                continue
            w1, w2 = min(best), max(best)
            slope = (best[w2] - best[w1]) / (w2 - w1)
            # noise (or a compile inside the smaller-work call) can invert
            # the pair — fall through to the median rather than report it
            if slope > 0:
                out = {
                    "seconds": round(slope, 9),
                    "method": "differenced",
                    "latency_confounded": False,
                    "work_pair": [w1, w2],
                }
                if cold_included:
                    # one end of the slope still contains trace+compile —
                    # warm both trip counts (bench.py discipline) to clear
                    out["cold_call_included"] = True
                return out
        times, works = (self.times, self.works) if steady is None else steady
        med_t = float(np.median(times))
        med_w = max(float(np.median(works)), 1.0)
        return {
            "seconds": round(med_t / med_w, 9),
            "method": "median_per_work",
            # a single trip count cannot cancel the per-dispatch
            # round-trip: the rate below under-reports on the tunnel
            "latency_confounded": True,
        }

    def summary(self) -> dict:
        first = self.times[0]
        steady = self.times[1:]
        out = {
            "calls": len(self.times),
            "first_call_s": round(first, 6),
            "total_s": round(sum(self.times), 6),
            "work_total": int(sum(self.works)),
        }
        if steady:
            p50 = float(np.percentile(steady, 50))
            out["dispatch_s"] = {
                "mean": round(float(np.mean(steady)), 6),
                "p50": round(p50, 6),
                "min": round(float(np.min(steady)), 6),
                "max": round(float(np.max(steady)), 6),
            }
            # the cold call = trace + compile + one dispatch; subtracting
            # the steady median leaves a compile estimate (floored: noise
            # can invert it for trivially small programs)
            out["compile_s"] = round(max(first - p50, 0.0), 6)
        else:
            out["dispatch_s"] = None
            out["compile_s"] = round(first, 6)
        out["per_work_s"] = self._per_work()
        out["signatures"] = {
            "aval": len(self.aval_sigs),
            "static": len(self.sigs),
            "retraces": len(self.retraces),
            "aval_retraces": self.aval_retraces,
            # static-only recompiles (e.g. the designed first_step peel)
            # are recorded above but only AVAL changes flag: a new leaf
            # shape/dtype is the silent perf killer
            "flagged": self.aval_retraces > 0,
        }
        return out


class DispatchRecorder:
    """Per-entry-point wall-clock registry; all accounting host-side.

    Args:
        clock: monotonic seconds source (default ``time.perf_counter``).
        strict_retrace: raise :class:`RetraceError` *before* dispatching a
            call whose abstract argument signature (leaf shapes/dtypes)
            would recompile an already-compiled entry point. Static-only
            structure changes (the designed ``first_step`` peel) never
            raise.
        max_spans: cap on retained ``(start, duration)`` spans across all
            entries+fetches (timeline export memory bound for very long
            runs); beyond it spans are dropped (counted) while the
            aggregate statistics keep accumulating.
        block_dispatch: block on the returned pytree
            (``jax.block_until_ready``) INSIDE the timed region. Default
            off: a warm call's duration is then the host-side dispatch
            cost (JAX async dispatch — the PR-1 semantics). Turn it ON
            to measure roofline rates: the differenced per-work slope
            needs durations that scale with the work, which async
            dispatch times do not. Axon caveat (CLAUDE.md):
            ``block_until_ready`` can return before the tunneled compute
            ran, so on that backend the blocked timing under-measures —
            end the measured region with a small :meth:`fetch` as
            bench.py does and prefer its slope.
    """

    def __init__(
        self,
        clock: Callable[[], float] = time.perf_counter,
        strict_retrace: bool = False,
        max_spans: int = 100_000,
        block_dispatch: bool = False,
    ):
        self._clock = clock
        self._entries: Dict[str, _EntryStats] = {}
        self._fetches: Dict[str, dict] = {}
        self._fetch_spans: List[dict] = []
        self._created = clock()
        self.strict_retrace = strict_retrace
        self.max_spans = max_spans
        self.block_dispatch = block_dispatch
        self._span_count = 0
        self._dropped_spans = 0
        self.analyzer: Optional[CostAnalyzer] = None

    def _keep_span(self) -> bool:
        if self._span_count >= self.max_spans:
            self._dropped_spans += 1
            return False
        self._span_count += 1
        return True

    # ------------------------------------------------------------- recording
    @contextlib.contextmanager
    def record(self, name: str, work: int = 1):
        """Time a host-side block as one call of entry point ``name``
        covering ``work`` units (generations) of progress."""
        t0 = self._clock()
        try:
            yield
        finally:
            dt = self._clock() - t0
            stats = self._entries.setdefault(name, _EntryStats())
            stats.times.append(dt)
            stats.works.append(work)
            if self._keep_span():
                stats.spans.append((t0, dt, work))

    def wrap(
        self,
        name: str,
        fn: Callable,
        work_fn: Optional[Callable[[tuple, dict], int]] = None,
    ) -> Callable:
        """Wrap ``fn`` so every call is recorded under ``name``, with
        signature tracking for retrace detection."""

        def wrapped(*args: Any, **kwargs: Any):
            stats = self._entries.setdefault(name, _EntryStats())
            sig = abstract_signature(args, kwargs)
            # strict mode raises BEFORE the signature is recorded, so a
            # retried call with the same bad shape raises again instead of
            # silently passing a now-"known" signature to the compiler
            if (
                self.strict_retrace
                and stats.sigs
                and sig[0] not in stats.aval_sigs
            ):
                raise RetraceError(
                    f"entry point '{name}' would retrace: abstract argument "
                    f"signature changed to {sig[0][:200]} after "
                    f"{len(stats.times)} call(s) — a leaf shape or dtype "
                    "changed between calls (the classic silent TPU compile "
                    "cost). Fix the shape instability, or drop "
                    "strict_retrace to record it instead."
                )
            stats.observe_signature(sig, self._clock() - self._created)
            work = work_fn(args, kwargs) if work_fn is not None else 1
            with self.record(name, work=work):
                out = fn(*args, **kwargs)
                if self.block_dispatch:
                    # jax.block_until_ready skips non-array leaves itself;
                    # anything it raises is a REAL device execution error
                    # and must propagate, not be timed as a fast success
                    jax.block_until_ready(out)
                return out

        wrapped._dispatch_recorder = self  # idempotence marker for attach
        wrapped.__wrapped__ = fn
        return wrapped

    def attach(
        self,
        workflow: Any,
        entry_points: Sequence[str] = DEFAULT_ENTRY_POINTS,
    ) -> Any:
        """Wrap the workflow's entry points in place (instance attributes
        shadow the class methods; other instances are untouched). Note
        that ``run`` internally peels its first generation through
        ``step``, so one ``run`` call also records one ``step`` call —
        that peeled dispatch is real and reported where it happens.
        Re-attaching the same recorder is a no-op per entry point."""
        for name in entry_points:
            fn = getattr(workflow, name, None)
            if fn is None or not callable(fn):
                continue
            if getattr(fn, "_dispatch_recorder", None) is self:
                continue
            setattr(
                workflow,
                name,
                self.wrap(name, fn, DEFAULT_WORK_EXTRACTORS.get(name)),
            )
        return workflow

    def fetch(self, tree: Any, name: str = "fetch") -> Any:
        """Bring ``tree`` to host, accounting bytes and seconds under
        ``name``. Returns the numpy-leaved tree. This is the ONLY place
        instrumented code should materialize device data — fetch bytes
        are the tunnel-cost currency on the axon backend."""
        t0 = self._clock()
        host = jax.device_get(tree)
        dt = self._clock() - t0
        nbytes = int(
            sum(
                x.nbytes
                for x in jax.tree.leaves(host)
                if hasattr(x, "nbytes")
            )
        )
        agg = self._fetches.setdefault(
            name, {"calls": 0, "bytes": 0, "seconds": 0.0}
        )
        agg["calls"] += 1
        agg["bytes"] += nbytes
        agg["seconds"] += dt
        if self._keep_span():
            self._fetch_spans.append(
                {"name": name, "t0": t0, "dt": dt, "bytes": nbytes}
            )
        return host

    # --------------------------------------------------------------- summary
    def summary(self) -> dict:
        out = {
            "entry_points": {
                name: stats.summary()
                for name, stats in sorted(self._entries.items())
            },
            "fetches": {
                name: {
                    "calls": agg["calls"],
                    "bytes": agg["bytes"],
                    "seconds": round(agg["seconds"], 6),
                }
                for name, agg in sorted(self._fetches.items())
            },
            "wall_s": round(self._clock() - self._created, 6),
            "retrace_flags": sorted(
                name
                for name, stats in self._entries.items()
                if stats.aval_retraces > 0
            ),
        }
        if self._dropped_spans:
            out["dropped_spans"] = self._dropped_spans
        return out


def instrument(
    workflow: Any,
    recorder: Optional[DispatchRecorder] = None,
    entry_points: Sequence[str] = DEFAULT_ENTRY_POINTS,
    analyze: bool = False,
    strict_retrace: bool = False,
    block_dispatch: bool = False,
) -> DispatchRecorder:
    """Attach (or create) a :class:`DispatchRecorder` to ``workflow``.

    ``analyze=True`` additionally attaches a :class:`~evox_tpu.core.
    xla_cost.CostAnalyzer`: the first ``run_report`` AOT-lowers and
    compiles the workflow's advertised entry points once (host-side, no
    callbacks) and the report gains a ``roofline`` section.
    ``strict_retrace=True`` makes any aval-signature retrace of an
    instrumented entry raise :class:`RetraceError` instead of silently
    recompiling. ``block_dispatch=True`` makes timed calls wait for
    their result (required for meaningful roofline rates — see
    :class:`DispatchRecorder`).

    Usage::

        rec = instrument(wf, analyze=True, block_dispatch=True)
        state = wf.init(key)
        state = wf.run(state, 100)   # warm
        state = wf.run(state, 300)   # second trip count -> differenced
        report = run_report(wf, state, recorder=rec)
    """
    recorder = recorder if recorder is not None else DispatchRecorder(
        strict_retrace=strict_retrace, block_dispatch=block_dispatch
    )
    if strict_retrace:
        recorder.strict_retrace = True
    if block_dispatch:
        recorder.block_dispatch = True
    recorder.attach(workflow, entry_points)
    if analyze and recorder.analyzer is None:
        recorder.analyzer = CostAnalyzer()
    return recorder


def _sharding_subsection(
    workflow: Any, state: Any, analyses: Dict[str, dict]
) -> Optional[dict]:
    """The roofline ``sharding`` subsection (schema v5): for a workflow
    driving a POP-sharded algorithm (``core.distributed.ShardedES``,
    duck-typed via ``is_pop_sharded``), compare the AOT PER-DEVICE peak
    bytes of the steady entry point against the FULL-POP artifact bytes of
    the algorithm state — a gather-free compiled step must keep the former
    strictly below the latter (``memory_analysis()`` reports per-device
    sizes for SPMD programs; verified in tests/test_large_pop.py)."""
    algo = getattr(workflow, "algorithm", None)
    if not getattr(algo, "is_pop_sharded", False):
        return None
    n_dev = int(getattr(algo, "n_shards", 1) or 1)
    if n_dev < 4:
        # the inequality is meaningful only when the shard is a small
        # fraction of the population: per-device peak carries a constant
        # factor (z in+out, candidates, temps) of roughly 2-4x one shard,
        # so at n_dev < 4 even a perfectly gather-free program can sit at
        # or above full-pop bytes — no claim is attached rather than a
        # false "not gather-free" rejection
        return None
    pop = int(getattr(algo, "pop_size", 0) or 0)
    astate = getattr(state, "algo", None)
    full = 0
    for leaf in jax.tree_util.tree_leaves(astate):
        shape = getattr(leaf, "shape", ())
        if pop and len(shape) >= 1 and shape[0] == pop:
            # count float artifacts at the COMPUTE width (>= 4 bytes):
            # under a bf16 storage policy the leaves REST at half width
            # but the in-step temps the peak actually measures are f32
            # (apply_compute upcasts at step entry) — comparing an f32
            # peak against a bf16-sized reference would falsely fail
            # legitimate gather-free bf16 runs
            itemsize = np.dtype(leaf.dtype).itemsize
            if np.issubdtype(np.dtype(leaf.dtype), np.floating):
                itemsize = max(itemsize, 4)
            full += int(np.prod(shape)) * itemsize
    if full < 4 * 1024 * 1024:
        # the inequality discriminates only when the full-pop artifacts
        # dominate the per-device FIXED footprint (replicated strategy
        # fields, monitor rings, program temps); a small-pop sharded run
        # is legitimate but proves nothing either way — no claim attached
        # rather than a false "not gather-free" rejection
        return None
    for entry in ("step", "run"):
        analysis = analyses.get(entry)
        if not isinstance(analysis, dict) or "error" in analysis:
            continue
        peak = (analysis.get("memory") or {}).get("peak_bytes_estimate")
        if peak:
            return {
                "axis": str(getattr(algo, "axis_name", "pop")),
                "n_devices": int(getattr(algo, "n_shards", 1) or 1),
                "pop_size": pop,
                "entry": entry,
                "per_device_peak_bytes": int(peak),
                "full_pop_bytes": int(full),
                "gather_free": int(peak) < int(full),
            }
    return None


def _multihost_subsection(
    workflow: Any, state: Any, analyses: Dict[str, dict]
) -> Optional[dict]:
    """The roofline ``multihost`` subsection (schema v8, ISSUE 13):
    attached when THIS process is part of a multi-process
    ``jax.distributed`` run. Cites the per-PROCESS peak (``memory_
    analysis`` reports per-device stats for SPMD programs — PR 10 — so a
    process's peak is its local devices' sum), the full-population
    artifact bytes it must stay gather-free against per device, and a
    collective-bytes-per-generation estimate over the ``cost_analysis``
    shapes: the pop-sized fitness/rank traffic every sharded tell
    replicates plus (for the ShardedES protocol) the psum-reduced moment
    tree, sized via ``eval_shape`` of ``pop_moments``."""
    if jax.process_count() <= 1:
        return None
    algo = getattr(workflow, "algorithm", None)
    pop = int(getattr(algo, "pop_size", 0) or 0)
    n_local = jax.local_device_count()
    peak = entry_used = None
    for entry in ("step", "run"):
        analysis = analyses.get(entry)
        if not isinstance(analysis, dict) or "error" in analysis:
            continue
        p = (analysis.get("memory") or {}).get("peak_bytes_estimate")
        if p:
            peak, entry_used = int(p), entry
            break
    if peak is None:
        return None
    full = 0
    astate = getattr(state, "algo", None)
    for leaf in jax.tree_util.tree_leaves(astate):
        shape = getattr(leaf, "shape", ())
        if pop and len(shape) >= 1 and shape[0] == pop:
            itemsize = np.dtype(leaf.dtype).itemsize
            if np.issubdtype(np.dtype(leaf.dtype), np.floating):
                itemsize = max(itemsize, 4)  # compute-width (PR-10 rule)
            full += int(np.prod(shape)) * itemsize
    # collective traffic model per generation: fitness + ranks are
    # replicated pop-sized operands; the ShardedES tell additionally
    # psums its (dim,)-sized moment tree
    collective = 2 * pop * 4
    if getattr(algo, "is_pop_sharded", False):
        try:
            inner = getattr(algo, "algorithm", algo)
            shard = pop // max(int(getattr(algo, "n_shards", 1) or 1), 1)
            rows = {
                name: jax.ShapeDtypeStruct(
                    getattr(astate, name).shape[:0]
                    + (shard,)
                    + getattr(astate, name).shape[1:],
                    jnp.float32,
                )
                for name in getattr(inner, "sharded_pop_fields", ())
            }
            w_sds = jax.ShapeDtypeStruct((shard,), jnp.float32)
            moments = jax.eval_shape(inner.pop_moments, rows, w_sds)
            collective += sum(
                int(np.prod(m.shape)) * 4
                for m in jax.tree_util.tree_leaves(moments)
            )
        except Exception:
            pass  # the base fitness/rank model stands
    return {
        "process_count": int(jax.process_count()),
        "n_local_devices": int(n_local),
        "entry": entry_used,
        "per_device_peak_bytes": peak,
        "per_process_peak_bytes": peak * int(n_local),
        "full_pop_bytes": int(full),
        "collective_bytes_estimate": int(collective),
        "collective_model": (
            "2*pop*4 fitness/rank replication + psum moment tree "
            "(eval_shape over pop_moments); per-process peak = "
            "per-device peak * local device count"
        ),
    }


def run_report(
    workflow: Any = None,
    state: Any = None,
    recorder: Optional[DispatchRecorder] = None,
    extra: Optional[dict] = None,
    analyzer: Optional[CostAnalyzer] = None,
    supervisor: Any = None,
    executor: Any = None,
    pod_supervisor: Any = None,
    metrics: Any = None,
    control_plane: Any = None,
) -> dict:
    """Merge device telemetry and host dispatch timings into ONE
    JSON-serializable dict.

    Device side: every monitor on ``workflow`` exposing ``report(mstate)``
    (duck-typed, so core never imports monitors) is called with its slot
    of ``state.monitors``. Host side: ``recorder.summary()``. Either half
    may be absent — a report can cover a bare recorder or a bare
    workflow+state.

    Roofline: when ``analyzer`` is given (or the recorder carries one —
    ``instrument(wf, analyze=True)``), the workflow's entry points are
    AOT-analyzed (cached; one compile per entry+signature) and merged
    with the measured per-work timings into a ``roofline`` section (see
    :func:`~evox_tpu.core.xla_cost.roofline_section`). With no analyzer
    the report is exactly the pre-roofline shape — a no-op.

    Supervisor: when ``supervisor`` is given — or the workflow was driven
    by a :class:`~evox_tpu.workflows.supervisor.RunSupervisor`, which
    advertises itself as ``workflow._run_supervisor`` — the report gains
    a ``supervisor`` section (deadline/retry/restore/degradation events
    and counters, ``RunSupervisor.report()``). Duck-typed: anything with
    a zero-arg ``report()`` works, and core stays decoupled from the
    workflows package.
    """
    # v2: roofline sections carry dtype_policy + donation provenance
    # (tools/check_report.py enforces them for v2+, exempting the
    # historical v1 captures). v3 adds the optional `tenancy` section
    # (multi-tenant fleets, workflows/tenancy.py). v4 adds the optional
    # `executor` section (core/executor.py GenerationExecutor: queue
    # depth, overlap spans, staleness counters) — validated when present.
    # v5 adds the optional roofline `sharding` subsection (POP-sharded
    # large-pop runs: per-device peak bytes vs the full-pop bytes — the
    # gather-free acceptance signal) and `guardrail.ipop` (host-boundary
    # doubling/handoff events) — both validated when present. v6 adds
    # the serving fault-domain sections (workflows/journal.py +
    # fleet_health.py): `tenancy.queue.journal` (hash-chained WAL event
    # counters, recovered flag) and `tenancy.fleet_health` (per-tenant
    # freeze/evict/restart action log) — validated when present. v7 adds
    # the optional `serving` section (core/exec_cache.py +
    # workflows/elastic.py): the AOT executable cache's hit/miss/compile
    # accounting (`serving.cache`) and the bucket lattice the workflow
    # serves (`serving.buckets`) — validated when present. v8 adds the
    # optional roofline `multihost` subsection (ISSUE 13: multi-process
    # runs cite their per-process AOT peak and a collective-bytes
    # estimate next to the sharding evidence) — validated when present.
    # v9 adds the optional `pod_supervisor` section (ISSUE 14,
    # core/pod_supervisor.py: heartbeat censuses, collective-deadline
    # failures with worker_dead/hung_collective/coordinator_loss
    # classification, coordinated drains, re-formation/resume events) —
    # validated when present, incl. the monotonic-census and
    # reform↔resume coherence rules. v10 adds the optional `surrogate`
    # section (ISSUE 15, workflows/surrogate.py: archive fill, refit
    # count/staleness, the screened-vs-true eval ledger, health
    # readings, chronological fallback events) — validated when present,
    # incl. the counter-sum and event-ordering coherence rules. v11 adds
    # the top-level `schema_version` int (PR 16 satellite: the version
    # is grep-able without parsing the schema string; check_report
    # --schema prints the validated range) and the optional `metrics` +
    # `slo` sections (workflows/flightrec.py FlightRecorder: the
    # serving-plane registry snapshot, stream accounting, and the SLO
    # ledger) — validated when present, incl. slo↔tenancy.queue
    # counter coherence. v12 adds the optional `control_plane` section
    # (ISSUE 18, workflows/control_plane.py: the multi-pod gateway's pod
    # census, ledger event counts, tenant accounting with the
    # exactly-once admission audit, and the steal/autoscale event
    # streams) — validated when present, incl. the ledger↔counter
    # coherence and empty-duplicate-admissions rules. v13 adds the
    # optional `search` section (ISSUE 19, monitors/lineage.py
    # LineageMonitor: the operator-attribution credit ledger, best-
    # ancestry traceback, restart-epoch counter, per-generation
    # best/delta trajectory, and the MO front-size/churn rings) —
    # validated when present, incl. the successes≤attempts ledger rule,
    # ancestry-indices-in-range, and churn non-negativity. v14 adds the
    # optional `integrity` section (ISSUE 20, core/attest.py
    # StateAttestor + core/executor.py voted re-dispatch): the on-device
    # attestation ring (generation-stamped state digests at a cadence),
    # the verify rung's dispatch/mismatch/heal counters, any
    # bisect_divergence() forensics report, and a one-word verdict
    # (clean/detected/healed/aborted) — validated when present, incl.
    # the cadence-monotone ring, verdict-set, bisection-in-window, and
    # redispatch-counter coherence rules.
    report: dict = {
        "schema": "evox_tpu.run_report/v14",
        "schema_version": 14,
    }
    if state is not None and hasattr(state, "generation"):
        report["generation"] = int(state.generation)
    if workflow is not None and state is not None:
        telemetry = []
        # a fleet state (VectorizedWorkflowState) has no top-level
        # .monitors — its per-tenant monitor states live tenant-stacked
        # under .tenants and are reported through the tenancy section
        mstates = getattr(state, "monitors", None)
        if mstates is not None:
            for i, mon in enumerate(getattr(workflow, "monitors", ())):
                if hasattr(mon, "report"):
                    entry = mon.report(mstates[i])
                    entry["monitor"] = type(mon).__name__
                    entry["monitor_index"] = i
                    telemetry.append(entry)
        report["telemetry"] = telemetry
        # multi-tenant fleets (duck-typed, core never imports workflows):
        # per-tenant telemetry rings, fleet shape, and — when a RunQueue
        # drives the fleet — the queue's admission/eviction counters
        if hasattr(workflow, "tenancy_report"):
            try:
                report["tenancy"] = workflow.tenancy_report(state)
            except Exception as e:  # report decoration must never sink it
                report["tenancy"] = {"error": f"{type(e).__name__}: {e}"}
        # guarded runs (core/guardrail.py): surface the wrapper's health
        # counters as a first-class section (duck-typed — core stays
        # decoupled from the concrete GuardedAlgorithm class)
        algo = getattr(workflow, "algorithm", None)
        astate = getattr(state, "algo", None)
        if hasattr(algo, "health_report") and hasattr(astate, "restarts"):
            report["guardrail"] = algo.health_report(astate)
        # host-boundary IPOP history (workflows/ipop.py): doubling and
        # low-memory handoff events recorded on the caller's workflow
        # object (clones share the list) — duck-typed like _run_supervisor
        ipop_events = getattr(workflow, "_ipop_events", None)
        if ipop_events:
            report.setdefault("guardrail", {})["ipop"] = list(ipop_events)
        # surrogate pre-screening (schema v10, workflows/surrogate.py):
        # the archive/refit/eval-count ledger proving how many TRUE
        # evaluations the run spent — duck-typed, core never imports the
        # workflows package
        if hasattr(workflow, "surrogate_report"):
            try:
                report["surrogate"] = workflow.surrogate_report(state)
            except Exception as e:  # decoration must never sink the report
                report["surrogate"] = {"error": f"{type(e).__name__}: {e}"}
        # search-dynamics lineage (schema v13, monitors/lineage.py): the
        # first attached monitor exposing `search_report` contributes the
        # top-level `search` section — attribution ledger, best-ancestry
        # traceback, epoch counter, trajectory window (duck-typed: core
        # never imports the monitors package)
        if mstates is not None:
            for i, mon in enumerate(getattr(workflow, "monitors", ())):
                if hasattr(mon, "search_report"):
                    try:
                        report["search"] = mon.search_report(mstates[i])
                    except Exception as e:  # must never sink the report
                        report["search"] = {
                            "error": f"{type(e).__name__}: {e}"
                        }
                    break
        # compute-integrity attestation (schema v14, core/attest.py):
        # the first attached monitor exposing `integrity_report` (a
        # StateAttestor) contributes the generation-stamped digest ring;
        # the executor's verify counters and any forensics report join
        # it below, after the executor pickup
        if mstates is not None:
            for i, mon in enumerate(getattr(workflow, "monitors", ())):
                if hasattr(mon, "integrity_report"):
                    try:
                        report["integrity"] = mon.integrity_report(
                            mstates[i]
                        )
                    except Exception as e:  # must never sink the report
                        report["integrity"] = {
                            "error": f"{type(e).__name__}: {e}"
                        }
                    break
    summary = recorder.summary() if recorder is not None else None
    if summary is not None:
        report["dispatch"] = summary
    if analyzer is None and recorder is not None:
        analyzer = recorder.analyzer
    if analyzer is not None:
        if workflow is not None and state is not None:
            try:
                analyzer.analyze_workflow(workflow, state)
            except Exception as e:
                # analysis must never sink the report it decorates:
                # analyze_callable degrades per entry, but the workflow's
                # analysis_targets itself (eval_shape, fit_shape hooks)
                # can raise — keep telemetry/dispatch, note the loss
                report["roofline"] = {"error": f"{type(e).__name__}: {e}"}
        if "roofline" not in report and analyzer.analyses:
            report["roofline"] = roofline_section(
                analyzer.analyses, summary, analyzer.ceilings
            )
        if (
            isinstance(report.get("roofline"), dict)
            and "entries" in report["roofline"]
        ):
            # precision/donation provenance (PR 6): rates are only
            # interpretable next to the dtype the state was stored at and
            # whether the run carry was donated (alias_bytes per entry
            # live in entries[*].static.memory.alias_bytes). Attached for
            # EVERY v2 roofline — a workflow-less (bare-analyzer) report
            # falls back to the explicit f32/undonated defaults via
            # policy_report(None)/getattr, keeping the v2 schema coherent
            # with tools/check_report.py's required fields
            from .dtype_policy import policy_report

            report["roofline"]["dtype_policy"] = policy_report(workflow)
            report["roofline"]["donation"] = {
                "donate_carries": bool(
                    getattr(workflow, "donate_carries", False)
                ),
                "alias_bytes": {
                    name: (a.get("memory") or {}).get("alias_bytes", 0)
                    for name, a in analyzer.analyses.items()
                    if isinstance(a, dict) and "error" not in a
                },
            }
            # POP-sharded large-pop provenance (schema v5, PR 10): when the
            # workflow drives a ShardedES-backed algorithm, record the AOT
            # per-device peak next to the full-pop artifact bytes — the
            # "per-device memory scales as pop/n_dev, not pop" acceptance
            # signal (tools/check_report.py asserts peak < full-pop bytes)
            sharding = _sharding_subsection(
                workflow, state, analyzer.analyses
            )
            if sharding is not None:
                report["roofline"]["sharding"] = sharding
            # multi-process provenance (schema v8, ISSUE 13): a pod run
            # cites its per-process peak + collective-traffic estimate
            multihost = _multihost_subsection(
                workflow, state, analyzer.analyses
            )
            if multihost is not None:
                report["roofline"]["multihost"] = multihost
    # elastic serving (schema v7, duck-typed — core never imports the
    # workflows package): a bucket workflow warmed through the AOT
    # executable cache advertises it as `_exec_cache`
    # (workflows/elastic.py warm_fleet_cache) and its lattice as
    # `_bucket_table`; the cache's hit/miss/compile-seconds accounting
    # is how a serving process proves its cold path never recompiled
    cache = getattr(workflow, "_exec_cache", None)
    if cache is not None and hasattr(cache, "report"):
        serving: dict = {"cache": cache.report()}
        table = getattr(workflow, "_bucket_table", None)
        if table is not None and hasattr(table, "report"):
            serving["buckets"] = table.report()
        report["serving"] = serving
    if supervisor is None and workflow is not None:
        supervisor = getattr(workflow, "_run_supervisor", None)
    if supervisor is not None and hasattr(supervisor, "report"):
        report["supervisor"] = supervisor.report()
    # pod supervisor (core/pod_supervisor.py, schema v9): a pod-
    # supervised run advertises itself as `_pod_supervisor` — heartbeat
    # censuses, classified failures, drains, and reform/resume events
    # become the `pod_supervisor` section (duck-typed like the others)
    if pod_supervisor is None and workflow is not None:
        pod_supervisor = getattr(workflow, "_pod_supervisor", None)
    if pod_supervisor is not None and hasattr(pod_supervisor, "report"):
        report["pod_supervisor"] = pod_supervisor.report()
    # generation executor (core/executor.py): the workflow's most recent
    # executor-backed run advertises itself as `_run_executor` — queue
    # depth, overlap spans, and staleness counters become the `executor`
    # section (duck-typed: anything with a zero-arg report() works)
    if executor is None and workflow is not None:
        executor = getattr(workflow, "_run_executor", None)
    if executor is not None and hasattr(executor, "report"):
        report["executor"] = executor.report()
    # serving-plane flight recorder (schema v11, workflows/flightrec.py):
    # a metrics-instrumented serving stack advertises its recorder as
    # `_flight_recorder` (the RunQueue backref) — the registry snapshot
    # and stream accounting become the `metrics` section and the SLO
    # ledger a first-class top-level `slo` section (duck-typed like the
    # supervisor pickups; core never imports the workflows package)
    if metrics is None and workflow is not None:
        metrics = getattr(workflow, "_flight_recorder", None)
    if metrics is not None and hasattr(metrics, "report"):
        report["metrics"] = metrics.report()
        if hasattr(metrics, "slo_ledger"):
            report["slo"] = metrics.slo_ledger()
    # multi-pod control plane (schema v12, workflows/control_plane.py):
    # a workflow served through the gateway advertises it as
    # `_control_plane` (duck-typed like every pickup above — core never
    # imports the workflows package); its report() — pod census, ledger
    # event counts, exactly-once admission audit, steal/autoscale
    # streams — becomes the `control_plane` section
    if control_plane is None and workflow is not None:
        control_plane = getattr(workflow, "_control_plane", None)
    if control_plane is not None and hasattr(control_plane, "report"):
        report["control_plane"] = control_plane.report()
    # compute-integrity verify/forensics (schema v14, ISSUE 20): the
    # executor's voted re-dispatch counters (None until the verify rung
    # was armed) and any bisect_divergence() report — advertised as
    # `workflow._integrity_forensics` — join the attestor ring picked up
    # above; the verdict folds the layer's whole story into one word
    verify = (
        executor.integrity_counters()
        if executor is not None and hasattr(executor, "integrity_counters")
        else None
    )
    forensics = (
        getattr(workflow, "_integrity_forensics", None)
        if workflow is not None
        else None
    )
    integ = report.get("integrity")
    if (
        isinstance(integ, dict) and "error" in integ
    ):  # ring pickup failed — leave the error section as-is
        pass
    elif integ is not None or verify is not None or forensics is not None:
        if integ is None:
            integ = {"enabled": True, "attestations": 0, "ring": []}
        if verify is not None:
            integ["verify"] = verify
        if forensics is not None:
            integ["bisection"] = dict(forensics)
        v = integ.get("verify") or {}
        if v.get("aborted"):
            integ["verdict"] = "aborted"
        elif v.get("healed"):
            integ["verdict"] = "healed"
        elif v.get("mismatches") or (
            forensics is not None
            and forensics.get("first_divergent_generation") is not None
        ):
            integ["verdict"] = "detected"
        else:
            integ["verdict"] = "clean"
        report["integrity"] = integ
    if extra:
        report["extra"] = dict(extra)
    return sanitize_json(report)


def write_report_jsonl(report: dict, path: str) -> None:
    """Append ``report`` as one strict-JSON line to a JSON-lines file."""
    with open(path, "a") as f:
        f.write(json.dumps(sanitize_json(report), allow_nan=False) + "\n")


# ------------------------------------------------------------ chrome trace

_US = 1e6  # trace-event timestamps are microseconds


#: trace pids are ``PID_STRIDE * jax_process_index + local track``:
#: track 0 = host dispatch, 1 = device telemetry, 2 = host counters,
#: 3 = run supervisor, 4 = generation executor, 5 = pod supervisor.
#: workflows/flightrec.py shares the stride (its metrics tracks start at
#: the same base), so per-process traces from ``dryrun_multihost`` land
#: on disjoint, deterministic pid ranges and can be concatenated or
#: merged without collision.
PID_STRIDE = 100


def _counter_events(
    track: str, samples: Sequence[Tuple[float, Any]], pid: int
) -> List[dict]:
    """One ``ph: "C"`` event per finite sample; ``samples`` carry
    already-relative timestamps in seconds."""
    short = track.rsplit("/", 1)[-1]
    events = []
    for t, v in samples:
        v = float(v)
        if not math.isfinite(v) or not math.isfinite(t):
            continue
        events.append(
            {
                "ph": "C",
                "name": track,
                "pid": pid,
                "ts": round(max(t, 0.0) * _US, 3),
                "args": {short: v},
            }
        )
    return events


def write_chrome_trace(
    path: str,
    recorder: Optional[DispatchRecorder] = None,
    workflow: Any = None,
    state: Any = None,
    extra_counters: Optional[Dict[str, Sequence[Tuple[float, Any]]]] = None,
    supervisor: Any = None,
    executor: Any = None,
    pod_supervisor: Any = None,
    process_index: Optional[int] = None,
) -> dict:
    """Export a run as Chrome trace-event JSON (open in Perfetto or
    chrome://tracing) and return the trace dict.

    - Recorder spans become complete (``ph: "X"``) slices: one thread per
      entry point under the "host dispatch" process, fetches on their own
      thread with byte counts in ``args``; retrace events appear as
      instant markers on the entry's thread.
    - TelemetryMonitor rings (any monitor on ``workflow`` exposing
      ``counter_tracks(mstate)``) become counter (``ph: "C"``) tracks.
      The rings are generation-indexed — the callback-free design has no
      per-generation host timestamps — so samples are spread uniformly
      across the recorder's observed span window (or 1 ms/generation
      without a recorder): counter shapes are exact, their time axis is
      approximate by construction.
    - ``extra_counters`` maps track names to ``(timestamp, value)``
      samples stamped with the recorder's clock (``time.perf_counter``),
      e.g. :meth:`ProcessRolloutFarm.counter_tracks` worker-health
      samples — these land at their true host times.
    - Supervisor events (``supervisor=`` a :class:`~evox_tpu.workflows.
      supervisor.RunSupervisor`, or picked up duck-typed from
      ``workflow._run_supervisor``) become instant (``ph: "i"``) markers
      — ``supervisor:retry`` / ``supervisor:deadline`` /
      ``supervisor:restore`` / ``supervisor:degrade`` /
      ``supervisor:abort`` — on their own "run supervisor" process at
      their true host timestamps (same ``perf_counter`` clock as the
      recorder).
    - Executor activity (``executor=`` a :class:`~evox_tpu.core.executor.
      GenerationExecutor`, or picked up duck-typed from
      ``workflow._run_executor``) lands on a "generation executor"
      process: overlap spans (device dispatch / host eval / background
      checkpoint+fetch I/O, one thread per track) as complete slices at
      their true host timestamps, plus queue-depth and stale-lag counter
      tracks.

    Every process gets ``process_name``/``thread_name`` metadata events
    and a deterministic pid: ``pid = PID_STRIDE * jax_process_index +
    track`` (track 0-5 per the :data:`PID_STRIDE` table).
    ``process_index`` defaults to the active ``jax.distributed`` process
    id (0 outside a pod), so per-worker traces from ``dryrun_multihost``
    land on disjoint pid ranges with names like ``"p1: host dispatch"``
    instead of colliding anonymously.

    Entirely host-side (no callbacks, axon-safe): everything exported was
    already recorded outside traced code.
    """
    events: List[dict] = []
    t0 = recorder._created if recorder is not None else 0.0
    t_end = t0

    if process_index is None:
        try:
            from .distributed import _dist_process_info

            process_index, _ = _dist_process_info()
        except Exception:
            process_index = 0
    process_index = int(process_index)
    pid_base = PID_STRIDE * process_index
    # process 0 keeps unprefixed names (the single-process common case
    # reads cleanly); workers carry their index so merged traces name
    # every track's owner
    prefix = f"p{process_index}: " if process_index else ""

    def meta(track: int, name: str, tid: Optional[int] = None) -> dict:
        e = {
            "ph": "M",
            "pid": pid_base + track,
            "name": "process_name" if tid is None else "thread_name",
            "args": {"name": (name if tid is not None else prefix + name)},
        }
        if tid is not None:
            e["tid"] = tid
        return e

    if recorder is not None:
        events.append(meta(0, "host dispatch"))
        names = sorted(recorder._entries)
        for tid, name in enumerate(names, start=1):
            stats = recorder._entries[name]
            events.append(meta(0, name, tid))
            for start, dur, work in stats.spans:
                t_end = max(t_end, start + dur)
                ev = {
                    "ph": "X",
                    "name": name,
                    "cat": "dispatch",
                    "pid": pid_base,
                    "tid": tid,
                    "ts": round((start - t0) * _US, 3),
                    "dur": round(dur * _US, 3),
                }
                if work != 1:
                    ev["args"] = {"work": work}
                events.append(ev)
            for r in stats.retraces:
                events.append(
                    {
                        "ph": "i",
                        "name": f"retrace:{r['kind']}",
                        "cat": "retrace",
                        "pid": pid_base,
                        "tid": tid,
                        "ts": round(max(r["t"], 0.0) * _US, 3),
                        "s": "t",
                    }
                )
        if recorder._fetch_spans:
            tid = len(names) + 1
            events.append(meta(0, "fetch", tid))
            for span in recorder._fetch_spans:
                t_end = max(t_end, span["t0"] + span["dt"])
                events.append(
                    {
                        "ph": "X",
                        "name": span["name"],
                        "cat": "fetch",
                        "pid": pid_base,
                        "tid": tid,
                        "ts": round((span["t0"] - t0) * _US, 3),
                        "dur": round(span["dt"] * _US, 3),
                        "args": {"bytes": span["bytes"]},
                    }
                )

    window_s = max(t_end - t0, 0.0)
    if (
        workflow is not None
        and state is not None
        and getattr(state, "monitors", None) is not None
    ):
        events.append(meta(1, "device telemetry"))
        for i, mon in enumerate(getattr(workflow, "monitors", ())):
            tracks_fn = getattr(mon, "counter_tracks", None)
            if tracks_fn is None:
                continue
            for track, samples in tracks_fn(state.monitors[i]).items():
                if not samples:
                    continue
                gens = [g for g, _ in samples]
                lo, hi = min(gens), max(gens)
                span = max(hi - lo, 1)
                scale = (window_s / span) if window_s > 0 else 1e-3
                rel = [((g - lo) * scale, v) for g, v in samples]
                events.extend(_counter_events(track, rel, pid=pid_base + 1))

    if extra_counters:
        events.append(meta(2, "host counters"))
        for track, samples in extra_counters.items():
            rel = [(t - t0, v) for t, v in samples]
            events.extend(_counter_events(track, rel, pid=pid_base + 2))

    if supervisor is None and workflow is not None:
        supervisor = getattr(workflow, "_run_supervisor", None)
    if supervisor is not None and hasattr(supervisor, "markers"):
        markers = supervisor.markers()
        if markers:
            events.append(meta(3, "run supervisor"))
            for m in markers:
                events.append(
                    {
                        "ph": "i",
                        "name": m["name"],
                        "cat": "supervisor",
                        "pid": pid_base + 3,
                        "tid": 1,
                        "ts": round(max(m["t_abs"] - t0, 0.0) * _US, 3),
                        "s": "p",
                        "args": sanitize_json(m.get("args", {})),
                    }
                )

    # pod supervisor events (ISSUE 14, duck-typed from
    # ``workflow._pod_supervisor``): ``supervisor:pod:*`` instant markers
    # — join / census / barrier_timeout / failure / drain / reform /
    # resume — on their own "pod supervisor" process, same clock
    if pod_supervisor is None and workflow is not None:
        pod_supervisor = getattr(workflow, "_pod_supervisor", None)
    if pod_supervisor is not None and hasattr(pod_supervisor, "markers"):
        markers = pod_supervisor.markers()
        if markers:
            events.append(meta(5, "pod supervisor"))
            for m in markers:
                events.append(
                    {
                        "ph": "i",
                        "name": m["name"],
                        "cat": "supervisor",
                        "pid": pid_base + 5,
                        "tid": 1,
                        "ts": round(max(m["t_abs"] - t0, 0.0) * _US, 3),
                        "s": "p",
                        "args": sanitize_json(m.get("args", {})),
                    }
                )

    if executor is None and workflow is not None:
        executor = getattr(workflow, "_run_executor", None)
    if executor is not None and hasattr(executor, "trace_spans"):
        spans = executor.trace_spans()
        samples = (
            executor.counter_samples()
            if hasattr(executor, "counter_samples")
            else {}
        )
        if spans or any(samples.values()):
            events.append(meta(4, "generation executor"))
            tids: Dict[str, int] = {}
            for span in spans:
                tids.setdefault(span["track"], len(tids) + 1)
            for track, tid in sorted(tids.items(), key=lambda kv: kv[1]):
                events.append(meta(4, track, tid))
            for span in spans:
                ev = {
                    "ph": "X",
                    "name": span["name"],
                    "cat": "executor",
                    "pid": pid_base + 4,
                    "tid": tids[span["track"]],
                    "ts": round(max(span["t_abs"] - t0, 0.0) * _US, 3),
                    "dur": round(max(span["dur"], 0.0) * _US, 3),
                }
                if span.get("args"):
                    ev["args"] = sanitize_json(span["args"])
                events.append(ev)
            for track, track_samples in samples.items():
                rel = [(t - t0, v) for t, v in track_samples]
                events.extend(_counter_events(track, rel, pid=pid_base + 4))

    trace = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "exporter": "evox_tpu.core.instrument.write_chrome_trace",
            "time_origin": "DispatchRecorder creation",
        },
    }
    with open(path, "w") as f:
        json.dump(trace, f, allow_nan=False)
    return trace
