"""Host-side dispatch instrumentation for workflow entry points.

The device half of observability (TelemetryMonitor) lives inside the
jitted step; this module is the host half. It wraps a workflow's jitted
entry points (``init`` / ``step`` / ``run`` / ``pipeline_ask`` /
``pipeline_tell``) with plain wall-clock timing *around the dispatch* —
never inside traced code, so it is safe on every backend including the
axon-tunneled TPU, and on that backend it directly measures the
45-100 ms per-dispatch tunnel round-trip that bench.py documents.

Semantics under JAX's async dispatch: a warm call returns once the work
is *dispatched*, so its duration is the host-side dispatch cost (on the
tunneled chip: the round-trip latency). The first call of an entry point
additionally pays trace + compile, which dominates it — the summary
reports that first call separately (``first_call_s``) plus an estimated
``compile_s`` (first call minus the steady-state median) alongside the
steady-state dispatch statistics. Host fetches go through
:meth:`DispatchRecorder.fetch`, which accounts bytes and seconds per
fetch site (a big-array fetch costs real tunnel time, ~6.6 s/256 MB —
the reason bench.py fetches a small leaf).

``run_report`` merges this host-side summary with the device counters of
any attached monitor exposing ``report(mstate)`` (TelemetryMonitor) into
one JSON-serializable dict; ``write_report_jsonl`` appends it to a
JSON-lines file.
"""

from __future__ import annotations

import contextlib
import json
import math
import time
from typing import Any, Callable, Dict, Optional, Sequence

import jax
import numpy as np

__all__ = [
    "DispatchRecorder",
    "instrument",
    "run_report",
    "sanitize_json",
    "write_report_jsonl",
]


def sanitize_json(obj: Any) -> Any:
    """Recursively replace non-finite floats with ``None`` so the result
    is STRICT (RFC 8259) JSON — ``json.dumps`` would otherwise emit bare
    ``Infinity``/``NaN`` tokens that ``jq``/``JSON.parse`` reject. Inf/NaN
    legitimately appear in telemetry (the +inf best before any finite
    generation, inf-padded ring slots of an all-poison generation)."""
    if isinstance(obj, dict):
        return {k: sanitize_json(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [sanitize_json(v) for v in obj]
    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    return obj

# bound methods wrapped on the workflow INSTANCE, so instrumentation is
# per-workflow and never leaks into other workflows of the same class
DEFAULT_ENTRY_POINTS = (
    "init",
    "step",
    "run",
    "pipeline_ask",
    "pipeline_tell",
)


class _EntryStats:
    __slots__ = ("times",)

    def __init__(self) -> None:
        self.times: list = []  # call durations, [0] is the cold call

    def summary(self) -> dict:
        first = self.times[0]
        steady = self.times[1:]
        out = {
            "calls": len(self.times),
            "first_call_s": round(first, 6),
            "total_s": round(sum(self.times), 6),
        }
        if steady:
            p50 = float(np.percentile(steady, 50))
            out["dispatch_s"] = {
                "mean": round(float(np.mean(steady)), 6),
                "p50": round(p50, 6),
                "min": round(float(np.min(steady)), 6),
                "max": round(float(np.max(steady)), 6),
            }
            # the cold call = trace + compile + one dispatch; subtracting
            # the steady median leaves a compile estimate (floored: noise
            # can invert it for trivially small programs)
            out["compile_s"] = round(max(first - p50, 0.0), 6)
        else:
            out["dispatch_s"] = None
            out["compile_s"] = round(first, 6)
        return out


class DispatchRecorder:
    """Per-entry-point wall-clock registry; all accounting host-side."""

    def __init__(self, clock: Callable[[], float] = time.perf_counter):
        self._clock = clock
        self._entries: Dict[str, _EntryStats] = {}
        self._fetches: Dict[str, dict] = {}
        self._created = clock()

    # ------------------------------------------------------------- recording
    @contextlib.contextmanager
    def record(self, name: str):
        """Time a host-side block as one call of entry point ``name``."""
        t0 = self._clock()
        try:
            yield
        finally:
            dt = self._clock() - t0
            self._entries.setdefault(name, _EntryStats()).times.append(dt)

    def wrap(self, name: str, fn: Callable) -> Callable:
        """Wrap ``fn`` so every call is recorded under ``name``."""

        def wrapped(*args: Any, **kwargs: Any):
            with self.record(name):
                return fn(*args, **kwargs)

        wrapped._dispatch_recorder = self  # idempotence marker for attach
        wrapped.__wrapped__ = fn
        return wrapped

    def attach(
        self,
        workflow: Any,
        entry_points: Sequence[str] = DEFAULT_ENTRY_POINTS,
    ) -> Any:
        """Wrap the workflow's entry points in place (instance attributes
        shadow the class methods; other instances are untouched). Note
        that ``run`` internally peels its first generation through
        ``step``, so one ``run`` call also records one ``step`` call —
        that peeled dispatch is real and reported where it happens.
        Re-attaching the same recorder is a no-op per entry point."""
        for name in entry_points:
            fn = getattr(workflow, name, None)
            if fn is None or not callable(fn):
                continue
            if getattr(fn, "_dispatch_recorder", None) is self:
                continue
            setattr(workflow, name, self.wrap(name, fn))
        return workflow

    def fetch(self, tree: Any, name: str = "fetch") -> Any:
        """Bring ``tree`` to host, accounting bytes and seconds under
        ``name``. Returns the numpy-leaved tree. This is the ONLY place
        instrumented code should materialize device data — fetch bytes
        are the tunnel-cost currency on the axon backend."""
        t0 = self._clock()
        host = jax.device_get(tree)
        dt = self._clock() - t0
        nbytes = int(
            sum(
                x.nbytes
                for x in jax.tree.leaves(host)
                if hasattr(x, "nbytes")
            )
        )
        agg = self._fetches.setdefault(
            name, {"calls": 0, "bytes": 0, "seconds": 0.0}
        )
        agg["calls"] += 1
        agg["bytes"] += nbytes
        agg["seconds"] += dt
        return host

    # --------------------------------------------------------------- summary
    def summary(self) -> dict:
        return {
            "entry_points": {
                name: stats.summary()
                for name, stats in sorted(self._entries.items())
            },
            "fetches": {
                name: {
                    "calls": agg["calls"],
                    "bytes": agg["bytes"],
                    "seconds": round(agg["seconds"], 6),
                }
                for name, agg in sorted(self._fetches.items())
            },
            "wall_s": round(self._clock() - self._created, 6),
        }


def instrument(
    workflow: Any,
    recorder: Optional[DispatchRecorder] = None,
    entry_points: Sequence[str] = DEFAULT_ENTRY_POINTS,
) -> DispatchRecorder:
    """Attach (or create) a :class:`DispatchRecorder` to ``workflow``.

    Usage::

        rec = instrument(wf)
        state = wf.init(key)
        state = wf.run(state, 100)
        report = run_report(wf, state, recorder=rec)
    """
    recorder = recorder if recorder is not None else DispatchRecorder()
    recorder.attach(workflow, entry_points)
    return recorder


def run_report(
    workflow: Any = None,
    state: Any = None,
    recorder: Optional[DispatchRecorder] = None,
    extra: Optional[dict] = None,
) -> dict:
    """Merge device telemetry and host dispatch timings into ONE
    JSON-serializable dict.

    Device side: every monitor on ``workflow`` exposing ``report(mstate)``
    (duck-typed, so core never imports monitors) is called with its slot
    of ``state.monitors``. Host side: ``recorder.summary()``. Either half
    may be absent — a report can cover a bare recorder or a bare
    workflow+state.
    """
    report: dict = {"schema": "evox_tpu.run_report/v1"}
    if state is not None and hasattr(state, "generation"):
        report["generation"] = int(state.generation)
    if workflow is not None and state is not None:
        telemetry = []
        for i, mon in enumerate(getattr(workflow, "monitors", ())):
            if hasattr(mon, "report"):
                entry = mon.report(state.monitors[i])
                entry["monitor"] = type(mon).__name__
                entry["monitor_index"] = i
                telemetry.append(entry)
        report["telemetry"] = telemetry
        # guarded runs (core/guardrail.py): surface the wrapper's health
        # counters as a first-class section (duck-typed — core stays
        # decoupled from the concrete GuardedAlgorithm class)
        algo = getattr(workflow, "algorithm", None)
        astate = getattr(state, "algo", None)
        if hasattr(algo, "health_report") and hasattr(astate, "restarts"):
            report["guardrail"] = algo.health_report(astate)
    if recorder is not None:
        report["dispatch"] = recorder.summary()
    if extra:
        report["extra"] = dict(extra)
    return sanitize_json(report)


def write_report_jsonl(report: dict, path: str) -> None:
    """Append ``report`` as one strict-JSON line to a JSON-lines file."""
    with open(path, "a") as f:
        f.write(json.dumps(sanitize_json(report), allow_nan=False) + "\n")
