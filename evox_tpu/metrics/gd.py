"""Generational Distance (+ GD+). Capability parity with reference
src/evox/metrics/gd.py."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..utils.common import pairwise_euclidean_dist


def gd(objs: jax.Array, pf: jax.Array, p: float = 1.0) -> jax.Array:
    """Mean distance from each solution to its nearest true-front point."""
    d = pairwise_euclidean_dist(objs, pf)
    return jnp.mean(jnp.min(d, axis=1) ** p) ** (1.0 / p)


def gd_plus(objs: jax.Array, pf: jax.Array) -> jax.Array:
    diff = jnp.maximum(objs[:, None, :] - pf[None, :, :], 0.0)
    d = jnp.linalg.norm(diff, axis=-1)
    return jnp.mean(jnp.min(d, axis=1))


class GD:
    def __init__(self, pf: jax.Array, p: float = 1.0):
        self.pf = pf
        self.p = p

    def __call__(self, objs: jax.Array) -> jax.Array:
        return gd(objs, self.pf, self.p)


class GDPlus:
    def __init__(self, pf: jax.Array):
        self.pf = pf

    def __call__(self, objs: jax.Array) -> jax.Array:
        return gd_plus(objs, self.pf)
