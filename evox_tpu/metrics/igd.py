"""Inverted Generational Distance (+ IGD+ variant). Capability parity with
reference src/evox/metrics/igd.py."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..utils.common import pairwise_euclidean_dist


def igd(objs: jax.Array, pf: jax.Array, p: float = 1.0) -> jax.Array:
    """Mean distance from each true-front point to its nearest solution."""
    d = pairwise_euclidean_dist(pf, objs)
    return jnp.mean(jnp.min(d, axis=1) ** p) ** (1.0 / p)


def igd_plus(objs: jax.Array, pf: jax.Array) -> jax.Array:
    """IGD+ (Ishibuchi et al. 2015): only dominated directions count."""
    diff = jnp.maximum(objs[None, :, :] - pf[:, None, :], 0.0)
    d = jnp.linalg.norm(diff, axis=-1)
    return jnp.mean(jnp.min(d, axis=1))


class IGD:
    def __init__(self, pf: jax.Array, p: float = 1.0):
        self.pf = pf
        self.p = p

    def __call__(self, objs: jax.Array) -> jax.Array:
        return igd(objs, self.pf, self.p)


class IGDPlus:
    def __init__(self, pf: jax.Array):
        self.pf = pf

    def __call__(self, objs: jax.Array) -> jax.Array:
        return igd_plus(objs, self.pf)
